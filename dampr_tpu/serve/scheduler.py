"""Per-tenant fair scheduling for the serve daemon.

Admission and dispatch are two separate gates:

- **Admission** (at submit) charges the job's estimated input bytes
  (:func:`.wire.estimate_input_bytes`) against the tenant's byte budget
  — the same figure ``RunStore`` budgets spill admission with, applied
  one level up: a tenant whose queued + running jobs already reserve
  the budget is rejected with a coded event instead of queued forever.
  The reservation is held until the job reaches a terminal state, so a
  *cancelled job releases its budget reservation* immediately.

- **Dispatch** (when a worker slot frees) is deficit round-robin in
  bytes: each pass over the tenants with queued work funds every
  tenant's deficit counter by ``quantum`` and dispatches a tenant's
  head job once the deficit covers its cost.  Byte-fair, not job-fair:
  a tenant flooding small jobs cannot starve a tenant with one large
  job, and vice versa — each gets the same byte allowance per round.
  The rotation pointer survives across calls so one tenant's luck with
  slot timing does not reset the round order.

In-flight dedupe rides the submission fingerprint: a non-volatile
fingerprint matching a queued/running job attaches the new submission
as a *follower* of that primary — no queue entry, no reservation, one
run, both clients read the same result bytes.

The scheduler is plain state + transitions; the daemon serializes all
calls under its own lock (one lock, no internal locking here).
"""

import collections
import time

#: Job lifecycle.  ``coalesced`` is terminal-by-proxy: the follower's
#: outcome IS its primary's (resolved through ``Job.primary``).
STATES = ("queued", "running", "done", "failed", "cancelled", "rejected",
          "coalesced")
TERMINAL = ("done", "failed", "cancelled", "rejected")


class AdmissionError(Exception):
    """Submission refused at the door.  ``reason`` is the machine field
    the coded ``serve-reject`` event and the HTTP response carry."""

    def __init__(self, reason, message):
        super(AdmissionError, self).__init__(message)
        self.reason = reason


class Job(object):
    """One submission's full record (the /jobs row)."""

    def __init__(self, job_id, tenant, fingerprint, cost, payload=None,
                 options=None):
        self.id = job_id
        self.tenant = tenant
        self.fingerprint = fingerprint
        self.cost = int(cost)
        self.payload = payload          # wire bytes until dispatched
        self.options = dict(options or {})
        self.state = "queued"
        self.primary = None             # job id this one coalesced onto
        self.followers = []
        self.submitted_at = time.time()
        self.started_at = None
        self.finished_at = None
        self.error = None
        self.diagnostics = []
        self.exit_code = None
        self.run_name = None
        self.job_dir = None
        self.crashdump = None
        self.result_meta = {}
        self.cancel_requested = False

    @property
    def queue_wait_s(self):
        if self.started_at is not None:
            return self.started_at - self.submitted_at
        if self.state == "queued":
            return time.time() - self.submitted_at
        return None

    @property
    def wall_s(self):
        if self.started_at is None:
            return None
        return (self.finished_at or time.time()) - self.started_at

    def to_row(self):
        meta = self.result_meta or {}
        reuse = meta.get("reuse") or {}
        row = {
            "job": self.id,
            "tenant": self.tenant,
            "state": self.state,
            "fingerprint": (self.fingerprint or "")[:16],
            "cost_bytes": self.cost,
            "submitted_at": self.submitted_at,
            "queue_wait_s": self.queue_wait_s,
            "wall_s": self.wall_s,
            "reuse_hits": reuse.get("hits"),
            "records": meta.get("records"),
            "primary": self.primary,
            "coalesced": len(self.followers),
            "error": self.error,
            "exit_code": self.exit_code,
            "crashdump": self.crashdump,
        }
        if self.diagnostics:
            row["diagnostics"] = list(self.diagnostics)
        return row


class _Tenant(object):
    def __init__(self, name, budget):
        self.name = name
        self.budget = int(budget)
        self.queue = collections.deque()
        self.deficit = 0
        self.reserved = 0
        self.counts = collections.Counter()


class Scheduler(object):
    def __init__(self, tenant_budget, quantum, queue_depth):
        self.tenant_budget = int(tenant_budget)
        self.quantum = max(1, int(quantum))
        self.queue_depth = int(queue_depth)
        self.tenants = {}
        self._rotation = []    # tenant visit order (stable)
        self._cursor = 0       # DRR pointer, survives across dispatches
        self._active_fp = {}   # fingerprint -> primary Job (queued/running)

    def tenant(self, name):
        st = self.tenants.get(name)
        if st is None:
            st = self.tenants[name] = _Tenant(name, self.tenant_budget)
            self._rotation.append(name)
        return st

    # -- admission ----------------------------------------------------------
    def coalesce_target(self, fingerprint):
        """The in-flight primary an identical submission coalesces onto,
        or None.  Volatile fingerprints never match (the caller checks)."""
        job = self._active_fp.get(fingerprint)
        if job is not None and job.state in ("queued", "running"):
            return job
        return None

    def admit(self, job):
        """Queue ``job``, reserving its cost against the tenant budget.
        Raises :class:`AdmissionError` when the budget or queue depth is
        exhausted."""
        st = self.tenant(job.tenant)
        if len(st.queue) >= self.queue_depth:
            raise AdmissionError(
                "queue-full",
                "tenant {!r} already has {} queued job(s) (limit {})"
                .format(job.tenant, len(st.queue), self.queue_depth))
        if st.reserved + job.cost > st.budget:
            raise AdmissionError(
                "budget",
                "tenant {!r} byte budget exhausted: {} reserved + {} "
                "requested > {} budget".format(
                    job.tenant, st.reserved, job.cost, st.budget))
        st.reserved += job.cost
        st.queue.append(job)
        st.counts["admitted"] += 1
        if job.fingerprint and job.fingerprint not in self._active_fp:
            self._active_fp[job.fingerprint] = job
        return st

    def attach_follower(self, primary, follower):
        follower.state = "coalesced"
        follower.primary = primary.id
        primary.followers.append(follower.id)
        self.tenant(follower.tenant).counts["coalesced"] += 1

    # -- dispatch -----------------------------------------------------------
    def next_job(self):
        """Deficit-round-robin pick: the next dispatchable job, or None
        when every queue is empty.  Terminates because each full pass
        funds every live deficit by ``quantum`` > 0."""
        live = [n for n in self._rotation if self.tenants[n].queue]
        if not live:
            return None
        n = len(self._rotation)
        while True:
            name = self._rotation[self._cursor % n]
            self._cursor += 1
            st = self.tenants[name]
            if not st.queue:
                continue
            st.deficit += self.quantum
            head = st.queue[0]
            if st.deficit >= head.cost:
                st.queue.popleft()
                st.deficit -= head.cost
                if not st.queue:
                    # classic DRR: an emptied queue forfeits its credit —
                    # idle tenants must not bank allowance.
                    st.deficit = 0
                return head

    # -- terminal transitions -----------------------------------------------
    def remove_queued(self, job):
        """Drop a still-queued job (cancellation path).  Returns True
        when it was found in its tenant's queue."""
        st = self.tenant(job.tenant)
        try:
            st.queue.remove(job)
        except ValueError:
            return False
        return True

    def release(self, job):
        """Return ``job``'s reservation to its tenant and retire its
        fingerprint from the dedupe index.  Idempotent per job."""
        st = self.tenant(job.tenant)
        if job.cost > 0:
            st.reserved = max(0, st.reserved - job.cost)
            job.cost = 0  # released exactly once
        if self._active_fp.get(job.fingerprint) is job:
            del self._active_fp[job.fingerprint]
        st.counts[job.state] += 1

    # -- telemetry ----------------------------------------------------------
    def stats(self):
        """Per-tenant counters for /jobs and /metrics."""
        out = {}
        for name in self._rotation:
            st = self.tenants[name]
            out[name] = {
                "queued": len(st.queue),
                "reserved_bytes": st.reserved,
                "budget_bytes": st.budget,
                "deficit_bytes": st.deficit,
                "counts": dict(st.counts),
            }
        return out
