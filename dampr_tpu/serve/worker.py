"""Per-job worker: ``python -m dampr_tpu.serve.worker <job_dir>``.

One job = one process = one run scope.  The daemon gets real isolation
for free from this shape: a poison record, a per-job timeout, a client
cancellation, or an operator SIGTERM all land on *this* process — the
PR 10 fault layer classifies and retries inside it (``resume="auto"``),
the runner's SIGTERM handler walks the crashdump path (the job dies
with a schema-valid ``crashdump.json``, exit 143), and the daemon
merely reaps an exit code.  Nothing a tenant ships can take the daemon
down.

Contract with the daemon (all paths inside ``job_dir``):

- ``job.json`` (read): run name, resume mode, daemon-assigned options;
- ``payload.bin`` (read): the :mod:`.wire` envelope;
- ``result.pkl`` (written on success, atomically): pickled list of the
  output's ``(key, value)`` records — the bytes the daemon streams
  back verbatim to every client of this run (byte-exactness is
  end-to-end: the daemon never re-serializes results);
- ``result.json`` (written on success): small JSON meta — wall
  seconds, record count, the run's reuse section, artifact paths;
- ``error.json`` (written on failure, best-effort): classified error.

Environment is the daemon's doing (see ``daemon._spawn``): the shared
scratch root and reuse cache directory, ``DAMPR_TPU_SERVE_ACTIVE=1``
(which resolves ``settings.reuse`` "auto" ON — the whole point of
serving: shared-prefix materializations amortize across tenants), and
a per-job trace dir so crash artifacts land under the job's directory.
"""

import json
import os
import pickle
import sys
import time


def _write_json(path, doc):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, sort_keys=True, default=str)
    os.replace(tmp, path)


def run_job(job_dir):
    """Execute the job under ``job_dir``; returns the process exit code."""
    with open(os.path.join(job_dir, "job.json")) as f:
        spec = json.load(f)
    with open(os.path.join(job_dir, "payload.bin"), "rb") as f:
        payload = f.read()

    from . import wire
    from .. import dampr as _dampr

    started = time.time()
    try:
        graph, source = wire.decode(payload)
        handle = _dampr.PBase(source, _dampr.Dampr(graph))
        kwargs = {}
        resume = spec.get("resume", "auto")
        if resume:
            kwargs["resume"] = resume
        em = handle.run(name=spec["run_name"], **kwargs)
        records = list(em.dataset.read())
        tmp = os.path.join(job_dir, "result.pkl.tmp")
        with open(tmp, "wb") as f:
            pickle.dump(records, f, protocol=pickle.HIGHEST_PROTOCOL)
        os.replace(tmp, os.path.join(job_dir, "result.pkl"))
        summary = em.stats() or {}
        _write_json(os.path.join(job_dir, "result.json"), {
            "wall_seconds": round(time.time() - started, 6),
            "records": len(records),
            "reuse": summary.get("reuse"),
            "trace_file": summary.get("trace_file"),
            "stats_file": summary.get("stats_file"),
            "run_name": spec["run_name"],
        })
        return 0
    except BaseException as e:
        from .. import faults as _faults

        try:
            import traceback

            _write_json(os.path.join(job_dir, "error.json"), {
                "type": type(e).__name__,
                "message": str(e)[:2000],
                "kind": _faults.classify(e),
                "wall_seconds": round(time.time() - started, 6),
                "traceback": traceback.format_exc()[-4000:],
            })
        except Exception:
            pass
        if isinstance(e, SystemExit):
            raise  # the runner's SIGTERM path already chose the code
        if isinstance(e, KeyboardInterrupt):
            return 130
        return 1


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 1:
        print("usage: python -m dampr_tpu.serve.worker <job_dir>",
              file=sys.stderr)
        return 2
    return run_job(argv[0])


if __name__ == "__main__":
    sys.exit(main())
