"""``dampr_tpu.serve`` — the disaggregated multi-tenant pipeline service.

One long-running daemon (``dampr-tpu-serve``) accepts composed plan IR
from many concurrent clients over HTTP, the tf.data-service argument
(arXiv 2210.14826) applied to this engine: input processing is a
*service*, not a per-caller batch process, so compiled/cached stage
materializations amortize across submissions instead of dying with each
process.

The package splits along the daemon's own seams:

- :mod:`.wire` — the validated, fingerprinted plan wire-form: a
  stdlib-only by-value serializer for composed graphs (lambdas ship by
  code), the submission fingerprint (``resume.stage_fingerprints``
  chained to the requested output), and the input-byte cost estimate
  the scheduler charges against tenant budgets.
- :mod:`.scheduler` — per-tenant job queues with deficit-round-robin
  fair sharing over byte budgets, reservation accounting, and in-flight
  fingerprint dedupe (identical submissions coalesce onto one run).
- :mod:`.worker` — the per-job subprocess entry point: one job = one
  process = one run scope, so the PR 10 fault layer (classified
  retries, quarantine, SIGTERM crashdumps) isolates tenants from each
  other and from the daemon.
- :mod:`.daemon` — the HTTP service itself: ``/submit``, ``/jobs``,
  ``/result``, ``/cancel``, ``/metrics``, ``/healthz``, ``/drain``,
  plus the dispatch loop, per-job timeouts, graceful SIGTERM drain,
  and the coded event stream (``serve-*`` in ``obs.log.EVENT_CODES``).
- :mod:`.client` — the stdlib client (``ServeClient`` / ``RemoteJob``)
  behind the ``PBase.submit(url)`` DSL hook.

See ``docs/serve.md`` for the protocol, the fairness/admission
contract, and the isolation guarantees.
"""

from .client import RemoteJob, ServeClient, SubmitError
from .daemon import ServeDaemon
from .wire import WireError

__all__ = ["RemoteJob", "ServeClient", "ServeDaemon", "SubmitError",
           "WireError"]
