"""Stdlib client for the serve daemon: :class:`ServeClient` /
:class:`RemoteJob`, the machinery behind ``pipeline.submit(url)``.

A submission is validated twice — once HERE, before any bytes travel
(the same ``analyze.validate`` pre-flight the daemon's admission gate
runs, with the same multi-process promotion, so a plan with an
unpicklable capture fails fast client-side with the coded ``DTA401``
diagnostic), and once at the daemon's door.  Either way the coded
diagnostic reaches the author; a worker never sees an invalid plan.

``RemoteJob.result()`` unpickles the exact bytes the worker wrote
(``result.pkl`` streamed verbatim through the daemon), so a served
run's records are byte-for-byte what a local ``run()`` of the same
plan produces.
"""

import json
import pickle
import time
import urllib.error
import urllib.request

from . import wire as _wire


class SubmitError(RuntimeError):
    """A submission the daemon (or the client-side pre-flight) refused.
    ``reason`` is the machine-readable rejection class (``wire``,
    ``invalid``, ``budget``, ``queue-full``, ``draining``, ...);
    ``diagnostics`` carries the coded pre-flight records when the
    rejection was an admission-gate validation failure."""

    def __init__(self, message, reason=None, diagnostics=None):
        super(SubmitError, self).__init__(message)
        self.reason = reason
        self.diagnostics = diagnostics or []


class RemoteJob(object):
    """Handle onto one submitted job (or a coalesced follower)."""

    def __init__(self, client, job_id, state, primary=None,
                 fingerprint=None):
        self.client = client
        self.id = job_id
        self.state = state
        self.primary = primary
        self.fingerprint = fingerprint
        self._row = None

    def poll(self):
        """Refresh and return this job's /jobs row."""
        self._row = self.client._get_json("/jobs/" + self.id)
        self.state = self._row.get("state", self.state)
        return self._row

    def wait(self, timeout_s=300.0, interval_s=0.1):
        """Block until the job reaches a terminal state; returns the
        final row.  Raises :class:`TimeoutError` at the deadline."""
        deadline = time.time() + timeout_s
        while True:
            row = self.poll()
            if self.state in ("done", "failed", "cancelled", "rejected"):
                return row
            if time.time() >= deadline:
                raise TimeoutError(
                    "job {} still {!r} after {:.1f}s".format(
                        self.id, self.state, timeout_s))
            time.sleep(interval_s)

    def result_bytes(self, timeout_s=300.0):
        """The worker's result.pkl bytes, verbatim.  Waits for
        completion; raises :class:`SubmitError` when the job failed."""
        self.wait(timeout_s=timeout_s)
        status, body, ctype = self.client._get_raw("/result/" + self.id)
        if status == 200:
            return body
        try:
            doc = json.loads(body.decode("utf-8"))
        except ValueError:
            doc = {"error": body[:200].decode("utf-8", "replace")}
        raise SubmitError(
            "job {} {}: {}".format(self.id, self.state,
                                   doc.get("error", "no result")),
            reason=doc.get("state") or self.state)

    def result(self, timeout_s=300.0):
        """The job's output records: the list of ``(key, value)`` pairs
        a local ``run().read()`` of the same plan yields."""
        return pickle.loads(self.result_bytes(timeout_s=timeout_s))

    def read(self, timeout_s=300.0):
        """Values only (mirrors ``ValueEmitter.stream`` ordering)."""
        return [v for _k, v in self.result(timeout_s=timeout_s)]

    def cancel(self):
        doc = self.client._post_json("/cancel/" + self.id, b"")
        self.state = doc.get("state", self.state)
        return doc


class ServeClient(object):
    """HTTP client onto one daemon.  ``url`` is the base, e.g.
    ``http://127.0.0.1:9400``."""

    def __init__(self, url, timeout_s=30.0):
        self.url = url.rstrip("/")
        self.timeout_s = timeout_s

    # -- transport -----------------------------------------------------------
    def _request(self, method, path, body=None):
        req = urllib.request.Request(
            self.url + path, data=body, method=method,
            headers={"Content-Type": "application/json"} if body is not None
            else {})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
                return (resp.status, resp.read(),
                        resp.headers.get("Content-Type", ""))
        except urllib.error.HTTPError as e:
            return e.code, e.read(), e.headers.get("Content-Type", "")

    def _get_raw(self, path):
        return self._request("GET", path)

    def _get_json(self, path):
        status, body, _ctype = self._request("GET", path)
        doc = json.loads(body.decode("utf-8"))
        if status != 200:
            raise SubmitError(doc.get("error", "HTTP {}".format(status)),
                              reason=doc.get("reason"))
        return doc

    def _post_json(self, path, body):
        status, raw, _ctype = self._request("POST", path, body=body)
        doc = json.loads(raw.decode("utf-8"))
        if status != 200:
            raise SubmitError(doc.get("error", "HTTP {}".format(status)),
                              reason=doc.get("reason"),
                              diagnostics=doc.get("diagnostics"))
        return doc

    # -- protocol ------------------------------------------------------------
    def submit(self, pipeline, tenant="default", reuse="auto",
               timeout_s=None, label=None, validate=True):
        """Ship a composed pipeline (a DSL handle, or a raw
        ``(graph, source)`` pair) to the daemon; returns a
        :class:`RemoteJob`.

        ``validate=True`` (default) runs the admission pre-flight
        client-side first — same checks, same coded diagnostics, no
        network round-trip for a plan the daemon would bounce anyway.
        ``reuse="off"`` opts this job out of the materialization cache
        AND of in-flight coalescing (it always gets its own run).
        """
        graph, source = self._plan_of(pipeline)
        if validate:
            from ..analyze import validate as _validate

            diags = _validate.validate_graph(
                graph, num_processes=2, probe_traceable=False,
                probe_assoc=True, probe_pickle=True)
            errors = [d for d in diags if d.severity == "error"]
            if errors:
                raise SubmitError(
                    "plan failed pre-flight validation: " + "; ".join(
                        "{}: {}".format(d.code, d.message)
                        for d in errors),
                    reason="invalid",
                    diagnostics=[d.to_dict() for d in errors])
        try:
            payload = _wire.encode(graph, source)
        except _wire.WireError as e:
            raise SubmitError(str(e), reason="wire")
        import base64

        request = {"tenant": tenant, "plan":
                   base64.b64encode(payload).decode("ascii"),
                   "reuse": reuse}
        if timeout_s is not None:
            request["timeout_s"] = timeout_s
        if label:
            request["label"] = label
        doc = self._post_json(
            "/submit", json.dumps(request).encode("utf-8"))
        return RemoteJob(self, doc["job"], doc.get("state", "queued"),
                         primary=doc.get("primary"),
                         fingerprint=doc.get("fingerprint"))

    @staticmethod
    def _plan_of(pipeline):
        graph = getattr(getattr(pipeline, "pmer", None), "graph", None)
        source = getattr(pipeline, "source", None)
        if graph is not None and source is not None:
            return graph, source
        try:
            graph, source = pipeline
            return graph, source
        except (TypeError, ValueError):
            raise SubmitError(
                "cannot submit {!r}: expected a composed pipeline handle "
                "or a (graph, source) pair".format(type(pipeline).__name__),
                reason="wire")

    # -- telemetry -----------------------------------------------------------
    def jobs(self):
        return self._get_json("/jobs")

    def health(self):
        return self._get_json("/healthz")

    def metrics(self):
        _status, body, _ctype = self._get_raw("/metrics")
        return body.decode("utf-8")

    def drain(self):
        return self._post_json("/drain", b"")
