"""Fault injection, failure classification, and quarantine plumbing.

The engine's only fault story used to be a bare in-place retry loop —
no transient-vs-deterministic distinction, no backoff, and no way to
*test* any of it short of monkeypatching internals.  This module makes
failure a first-class, tested code path, in two halves:

**Injection** — a registry of named fault sites threaded through the
hot paths (spill frame write/read, UDF invocation, exchange steps,
device dispatch, checkpoint persist, observability tick loops, and a
rank-kill site for multi-process tests), driven by a seeded,
schedule-based plan so chaos runs are exactly reproducible::

    DAMPR_TPU_FAULTS="spill_write:p=0.01;exchange_step:nth=3"

Each entry names a site plus firing rules (``p=`` per-invocation
probability from a per-site seeded RNG, ``nth=`` the 1-based invocation
that faults, ``every=`` a period, ``times=`` a budget, ``match=`` a
substring content key so a *specific record* fails deterministically,
``rank=`` a process-rank filter) and an action (raise a classified
fault — ``kind=transient|deterministic|fatal``, default transient —
or ``sleep_ms=`` a stall, or ``exit=`` an ``os._exit`` code: the
rank-kill used by the multi-process chaos tests, which flushes the
flight recorder first so the killed rank still leaves a crashdump).

Zero overhead when disabled: every site is one module-global None-check
(:func:`check` / :func:`check_records`), the same contract as
:mod:`dampr_tpu.obs.trace`.

**Classification** — :func:`classify` buckets any exception for the
retry layers:

- ``transient`` (flaky IO: ``OSError`` and friends, plus injected
  transients): worth an in-place retry, *with* exponential backoff +
  jitter (:func:`backoff`);
- ``deterministic`` (everything else — a UDF bug, a poison record):
  retried without backoff for legacy compatibility by the job loop,
  and the batched-UDF path first tries to *bisect and quarantine* the
  offending records (:class:`Quarantine`, ``settings.max_quarantined``);
- ``fatal`` (``MemoryError``, ``KeyboardInterrupt``, ``SystemExit``,
  quarantine-budget overflow, injected fatals): never retried — not by
  the job loop, not by ``run(resume="auto")``.

**Fault events** — cross-run memory for failures that kill the process
before stats can land (the exchange watchdog): one JSONL sidecar per
run name (``<scratch_root>/<run>/faults.jsonl``, bounded, O_APPEND
crash-safe like the history corpus).  ``plan/lower.apply_shuffle``
reads it so a stage whose collective exchange timed out degrades to the
host shuffle on the next run.

See ``docs/robustness.md`` for the full site catalog and semantics.
"""

import json
import logging
import os
import random
import threading
import time

from . import settings

log = logging.getLogger("dampr_tpu.faults")

EVENTS_FILE = "faults.jsonl"
QUARANTINE_FILE = "quarantine.jsonl"

#: Cap on retained fault-event lines per run (oldest rewritten away).
EVENTS_CAP = 256

#: The documented fault-site catalog (docs/robustness.md keeps the
#: prose table; tests assert the two stay in sync).  An unknown site in
#: a plan is tolerated with a one-time warning — forward compatibility
#: beats a hard failure in a chaos harness.
SITES = (
    "spill_write",      # io/writer.py worker + storage sync spill
    "spill_read",       # io/frames.py frame read/decompress
    "udf",              # runner batched-UDF chain (match= keys records)
    "fold",             # runner map-side partial/final folds
    "exchange_step",    # parallel/exchange.py per collective step
    "device_dispatch",  # ops/lower.py program dispatch
    "checkpoint_persist",  # resume.py manifest/block persistence
    "rank_kill",        # exchange step entry; exit= kills the process
    "sampler_tick",     # obs/sampler.py loop (slow-stop shutdown tests)
    "progress_tick",    # obs/progress.py loop
    "overlap_produce",  # runner._overlap_stream producer (race widener)
    "cache_read",       # plan/reuse.py manifest/block reads (degrade path)
    "stream_publish",   # runner pipelined publish hook (streamed edges)
)


# -- injected fault types ----------------------------------------------------

class InjectedFault(Exception):
    """Base of every injected fault (site name on ``.site``)."""

    site = None


class TransientInjectedFault(InjectedFault, OSError):
    """Injected flaky-IO failure: classified ``transient`` (retryable
    with backoff) by construction — it subclasses OSError so code that
    catches real IO errors treats it identically."""


class DeterministicInjectedFault(InjectedFault):
    """Injected poison failure: same inputs always fail (the quarantine
    path's test vehicle)."""


class FatalInjectedFault(InjectedFault):
    """Injected unrecoverable failure: no retry layer may absorb it."""


class QuarantineOverflow(Exception):
    """More poison records than ``settings.max_quarantined`` allows —
    classified fatal (retrying re-bisects into the same wall)."""


_KIND_EXC = {
    "transient": TransientInjectedFault,
    "deterministic": DeterministicInjectedFault,
    "fatal": FatalInjectedFault,
}


# -- classification ----------------------------------------------------------

def classify(exc):
    """``"transient"`` | ``"deterministic"`` | ``"fatal"`` for any
    exception.  Transient = flaky-IO shaped (worth an in-place retry
    with backoff); fatal = never retried by any layer; everything else
    is deterministic (a UDF/data failure — the job retry loop still
    retries it for legacy compatibility, but without backoff, and the
    quarantine path handles it first where it applies)."""
    if isinstance(exc, FatalInjectedFault):
        return "fatal"
    if isinstance(exc, (MemoryError, KeyboardInterrupt, SystemExit,
                        GeneratorExit, QuarantineOverflow)):
        return "fatal"
    if isinstance(exc, TransientInjectedFault):
        return "transient"
    if isinstance(exc, (OSError, TimeoutError, ConnectionError,
                        InterruptedError)):
        # IOError == OSError on py3; TimeoutError/ConnectionError are
        # OSError subclasses but named for readers grepping the policy.
        return "transient"
    return "deterministic"


def backoff(attempt, rng=random):
    """Retry delay (seconds) for the given 0-based attempt: full-jitter
    exponential backoff — uniform over ``[0, min(cap, base * 2^n)]``
    (the AWS-architecture-blog scheme: decorrelates retry storms while
    keeping the expected delay half the deterministic ladder)."""
    base = max(1, settings.retry_backoff_ms)
    cap = max(base, settings.retry_backoff_max_ms)
    span = min(cap, base * (1 << min(int(attempt), 20)))
    return rng.uniform(0.0, span) / 1000.0


# -- the injection plan ------------------------------------------------------

class FaultSpecError(ValueError):
    """Malformed DAMPR_TPU_FAULTS spec."""


class SiteRule(object):
    """Firing rules + action for one site.  Thread-safe: invocation
    counting and the seeded RNG sit behind one lock (fault checks are
    off the per-record hot path, so the lock cost is irrelevant)."""

    __slots__ = ("site", "p", "nth", "every", "times", "kind", "match",
                 "rank", "sleep_ms", "exit_code", "duration_ms",
                 "invocations", "injected", "_t0", "_rng", "_lock")

    def __init__(self, site, seed=0, p=None, nth=None, every=None,
                 times=None, kind="transient", match=None, rank=None,
                 sleep_ms=None, exit_code=None, duration_ms=None):
        self.site = site
        self.p = p
        self.nth = nth
        self.every = every
        self.times = times
        if times is None:
            # nth fires once by default; p/every/match keep firing.
            self.times = 1 if nth is not None else None
        if kind not in _KIND_EXC:
            raise FaultSpecError(
                "site {}: unknown kind {!r} (transient/deterministic/"
                "fatal)".format(site, kind))
        self.kind = kind
        self.match = match
        self.rank = rank
        self.sleep_ms = sleep_ms
        self.exit_code = exit_code
        # Windowed firing (the `slow` duty-cycle modeling a rank that is
        # slow for a while then RECOVERS — the straggler-mitigation
        # disengage test vehicle): the rule only fires within
        # ``duration_ms`` of its first invocation; past the window the
        # site goes quiet (invocations still count).
        self.duration_ms = duration_ms
        self._t0 = None
        self.invocations = 0
        self.injected = 0
        # Per-site seeded stream: the schedule replays exactly under the
        # same seed regardless of which other sites fired.
        self._rng = random.Random(
            "{}:{}".format(seed, site).encode("utf-8"))
        self._lock = threading.Lock()

    def _matches(self, record):
        if self.match is None:
            return True
        if record is None:
            return False
        try:
            return self.match in repr(record)
        except Exception:
            return False

    def should_fire(self, record=None):
        """Count one invocation and decide.  ``match=`` rules are
        content-keyed (the invocation counter still advances, but only
        matching records can fire — and they ALWAYS fire while the
        ``times`` budget lasts, so a poison record fails
        deterministically on every re-execution/bisect probe)."""
        with self._lock:
            if self.rank is not None and self.rank != _process_rank():
                return False
            self.invocations += 1
            if self.duration_ms is not None:
                now = time.monotonic()
                if self._t0 is None:
                    self._t0 = now
                if (now - self._t0) * 1000.0 > self.duration_ms:
                    return False  # slow window over: the site recovered
            if self.times is not None and self.injected >= self.times:
                return False
            if self.match is not None:
                fire = self._matches(record)
            elif self.nth is not None:
                fire = self.invocations == self.nth
            elif self.every is not None:
                fire = self.invocations % max(1, self.every) == 0
            elif self.p is not None:
                fire = self._rng.random() < self.p
            else:
                fire = True
            if fire:
                self.injected += 1
            return fire

    def describe(self):
        out = {"site": self.site, "kind": self.kind}
        for k in ("p", "nth", "every", "times", "match", "rank",
                  "sleep_ms", "exit_code", "duration_ms"):
            v = getattr(self, k)
            if v is not None:
                out[k] = v
        return out


def _process_rank():
    """This process's rank (env-derived; never initializes a backend)."""
    try:
        from .parallel.mesh import rank_info

        return rank_info()[0]
    except Exception:
        return 0


def _parse_value(key, val):
    if key == "p":
        return float(val)
    if key in ("nth", "every", "times", "rank", "sleep_ms", "exit",
               "duration_ms"):
        return int(val)
    return val


class FaultPlan(object):
    """Parsed injection schedule: ``{site: SiteRule}`` plus the seed.

    Spec grammar (fully deterministic under one seed)::

        spec  := entry (';' entry)*
        entry := 'seed=' INT | SITE ':' kv (',' kv)*
        kv    := ('p'|'nth'|'every'|'times'|'rank'|'sleep_ms'|'exit'
                  |'duration_ms') '=' NUM
               | 'kind' '=' ('transient'|'deterministic'|'fatal')
               | 'match' '=' TEXT

    ``duration_ms`` windows any rule to the first N ms after its first
    invocation — with ``sleep_ms`` it models a rank that is slow for a
    while then recovers (the straggler-mitigation disengage vehicle).
    """

    def __init__(self, spec, seed=None):
        self.spec = spec
        self.seed = 0 if seed is None else int(seed)
        self._from_settings = False  # set by configure_for_run
        self.rules = {}
        entries = [e.strip() for e in (spec or "").split(";") if e.strip()]
        # Pass 1: the seed entry applies to every site regardless of
        # position (a trailing ';seed=7' must not reseed half the plan).
        body = []
        for entry in entries:
            if entry.startswith("seed=") and ":" not in entry:
                self.seed = int(entry.split("=", 1)[1])
                continue
            body.append(entry)
        for entry in body:
            if ":" not in entry:
                raise FaultSpecError(
                    "fault entry {!r}: expected 'site:key=val,...'"
                    .format(entry))
            site, _colon, rest = entry.partition(":")
            site = site.strip()
            kwargs = {}
            for kv in rest.split(","):
                kv = kv.strip()
                if not kv:
                    continue
                if "=" not in kv:
                    raise FaultSpecError(
                        "fault entry {!r}: bad rule {!r}".format(entry, kv))
                k, _eq, v = kv.partition("=")
                k = k.strip()
                try:
                    kwargs[k] = _parse_value(k, v.strip())
                except ValueError:
                    raise FaultSpecError(
                        "fault entry {!r}: bad value for {!r}".format(
                            entry, k))
            exit_code = kwargs.pop("exit", None)
            if site not in SITES:
                log.warning("fault plan names unknown site %r (known: %s)"
                            " — kept anyway", site, ", ".join(SITES))
            try:
                self.rules[site] = SiteRule(
                    site, seed=self.seed, exit_code=exit_code, **kwargs)
            except TypeError as e:
                raise FaultSpecError(
                    "fault entry {!r}: {}".format(entry, e))

    # -- firing --------------------------------------------------------------
    def _fire(self, rule, record=None):
        count_injected(rule.site)
        from .obs import trace as _trace

        _trace.instant("fault", "inject:{}".format(rule.site),
                       site=rule.site, kind=rule.kind)
        if rule.exit_code is not None:
            # Rank-kill: flush the flight recorder so the killed process
            # still leaves a schema-valid crashdump, then die hard — the
            # whole point is an abrupt, unannounced death.
            from .obs import flightrec as _flightrec

            log.error("fault injection: killing process (site=%s, "
                      "exit=%d)", rule.site, rule.exit_code)
            _flightrec.flush_active(
                "fault-injected-kill",
                FatalInjectedFault("rank kill at {}".format(rule.site)))
            os._exit(rule.exit_code)
        if rule.sleep_ms is not None:
            log.warning("fault injection: stalling %s for %d ms",
                        rule.site, rule.sleep_ms)
            time.sleep(rule.sleep_ms / 1000.0)
            return
        exc = _KIND_EXC[rule.kind](
            "injected {} fault at site {!r} (injection #{})".format(
                rule.kind, rule.site, rule.injected))
        exc.site = rule.site
        raise exc

    def check(self, site, record=None):
        rule = self.rules.get(site)
        if rule is not None and rule.should_fire(record):
            self._fire(rule, record)

    def check_records(self, site, keys, values):
        """Batch form for record-keyed sites: a ``match=`` rule scans
        the batch and fires on the first poisoned record; rules without
        ``match`` count the call as ONE invocation (batch granularity)."""
        rule = self.rules.get(site)
        if rule is None:
            return
        if rule.match is None:
            if rule.should_fire():
                self._fire(rule)
            return
        for k, v in zip(keys, values):
            if rule.should_fire((k, v)):
                self._fire(rule, (k, v))

    def counts(self):
        return {site: r.injected for site, r in self.rules.items()
                if r.injected}

    def describe(self):
        return {"spec": self.spec, "seed": self.seed,
                "sites": [r.describe() for r in self.rules.values()]}


# -- module-level lifecycle (mirrors obs.trace) ------------------------------

_active = None


def configure(spec=None):
    """Install a plan from ``spec`` (default: ``settings.faults`` /
    env ``DAMPR_TPU_FAULTS``).  Empty spec clears.  Returns the active
    plan or None."""
    global _active
    if spec is None:
        spec = settings.faults
    if not spec:
        _active = None
        return None
    _active = FaultPlan(spec)
    log.warning("fault injection ACTIVE: %s", spec)
    return _active


def configure_for_run():
    """Per-run (re)installation: when ``settings.faults`` carries a
    spec, every run starts a FRESH plan — per-run invocation counters
    make each run's schedule identical, which is what lets the chaos CI
    pin byte-identical results.  When ``settings.faults`` is cleared, a
    previously settings-installed plan is cleared with it (the
    documented "empty = injection fully disabled" contract); a plan a
    test installed directly via :func:`install` is left alone."""
    global _active
    if settings.faults:
        plan = configure(settings.faults)
        plan._from_settings = True
    elif _active is not None and getattr(_active, "_from_settings",
                                         False):
        _active = None


def install(plan):
    global _active
    _active = plan


def clear():
    global _active
    _active = None


def active():
    return _active


def enabled():
    return _active is not None


def check(site, record=None):
    """One-None-check fault site.  No-op unless a plan is installed."""
    p = _active
    if p is not None:
        p.check(site, record)


def check_records(site, keys, values):
    p = _active
    if p is not None:
        p.check_records(site, keys, values)


# -- retry / injection counters (process-cumulative; runner snapshots) -------

_counter_lock = threading.Lock()
injected_counts = {}
io_retry_counts = {}
io_backoff_seconds = 0.0


def count_injected(site):
    with _counter_lock:
        injected_counts[site] = injected_counts.get(site, 0) + 1


def count_io_retry(kind, delay=0.0):
    """One transient IO retry (``spill_write`` / ``spill_read`` /
    ``checkpoint_persist``) absorbed by an in-place retry loop, plus
    the backoff it is about to sleep — IO-only retry storms must show
    their cost in ``backoff_seconds``, not just a count."""
    global io_backoff_seconds
    with _counter_lock:
        io_retry_counts[kind] = io_retry_counts.get(kind, 0) + 1
        io_backoff_seconds += delay


def counters_snapshot():
    with _counter_lock:
        return dict(injected_counts), dict(io_retry_counts), \
            io_backoff_seconds


def counters_delta(snap):
    """(injected, io_retries, io_backoff_seconds) deltas since ``snap``
    — THIS run's share of the process-cumulative counters."""
    if snap is None:
        return {}, {}, 0.0
    inj0, io0, bk0 = snap
    with _counter_lock:
        inj = {k: v - inj0.get(k, 0) for k, v in injected_counts.items()
               if v - inj0.get(k, 0) > 0}
        io = {k: v - io0.get(k, 0) for k, v in io_retry_counts.items()
              if v - io0.get(k, 0) > 0}
        bk = max(0.0, io_backoff_seconds - bk0)
    return inj, io, bk


def retry_io(fn, kind, retries=None):
    """Run ``fn()`` retrying TRANSIENT failures in place with backoff
    (``settings.io_retries`` by default).  Deterministic and fatal
    failures propagate immediately — a corrupt frame or a dead disk is
    not healed by retrying.  Counts absorbed retries (and the backoff
    seconds slept) per ``kind``."""
    budget = settings.io_retries if retries is None else retries
    attempt = 0
    while True:
        try:
            return fn()
        except Exception as e:
            if classify(e) != "transient" or attempt >= budget:
                raise
            delay = backoff(attempt)
            count_io_retry(kind, delay)
            from .obs import trace as _trace

            _trace.instant("fault", "retry:{}".format(kind),
                           attempt=attempt + 1, kind="transient")
            log.warning("transient %s failure (attempt %d/%d), retrying "
                        "in %.0f ms: %s", kind, attempt + 1, budget + 1,
                        delay * 1000, e)
            time.sleep(delay)
            attempt += 1


# -- run context (exchange watchdog attribution) -----------------------------

#: Display/attribution-only view of the run the CURRENT process is
#: executing (single-writer: the runner's sequential stage walk).  The
#: exchange watchdog reads it to tag fault events with run + stage.
run_context = {"run": None, "stage": None}


def set_context(run=None, stage=None):
    run_context["run"] = run
    run_context["stage"] = stage


# -- shared JSONL sidecar plumbing -------------------------------------------

def _safe_run_dir(run_name):
    return os.path.join(settings.scratch_root,
                        str(run_name).replace("/", "_"))


def _append_jsonl(path, lines):
    """Crash-safe line appends: one ``O_APPEND`` fd, one write per line
    (a process dying mid-write corrupts at most its own line).  The
    caller owns any locking and pre-serialized the lines."""
    os.makedirs(os.path.dirname(path), exist_ok=True)
    fd = os.open(path, os.O_WRONLY | os.O_APPEND | os.O_CREAT, 0o644)
    try:
        for line in lines:
            os.write(fd, (line + "\n").encode("utf-8", "backslashreplace"))
    finally:
        os.close(fd)


def _load_jsonl(path, keep=None):
    """Tolerant line-validated load: unparsable lines are skipped,
    never fatal; ``keep`` filters parsed dicts."""
    out = []
    try:
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict) and (keep is None or keep(rec)):
                    out.append(rec)
    except OSError:
        return []
    return out


# -- quarantine sink ---------------------------------------------------------


def quarantine_path(run_name):
    return os.path.join(_safe_run_dir(run_name), QUARANTINE_FILE)


class Quarantine(object):
    """Per-run poison-record sink, bounded by ``settings.max_quarantined``.

    Accounting is **attempt-scoped**: each job collects the records its
    bisect isolated in a local :class:`QuarantineAttempt` and commits
    them only when the attempt SUCCEEDS.  A retried job (its first
    attempt's outputs rolled back by ``store.attempt()``) re-encounters
    the same poison records and re-records them from scratch — the
    failed attempt never committed, so nothing double-counts — while
    *genuinely duplicate* poison records (same bytes, distinct record
    instances) each count and each land in the sink, so the budget
    bounds real data loss, not distinct reprs.

    Over-budget at record time or commit time raises
    :class:`QuarantineOverflow` (fatal; the run fails fast with the
    original failure chained).  A fresh run under the same name
    truncates the previous run's sink (the file describes THIS run)."""

    def __init__(self, run_name, limit):
        self.run = run_name
        self.limit = max(0, int(limit))
        self.path = quarantine_path(run_name)
        self.count = 0  # committed records (successful attempts only)
        self.records = []  # committed record dicts (bounded by limit):
        #                    lets an auto-resume retry adopt this state
        self._lock = threading.Lock()
        try:
            os.makedirs(os.path.dirname(self.path), exist_ok=True)
            if os.path.exists(self.path):
                os.unlink(self.path)
        except OSError:
            pass

    def rewrite_sink(self):
        """Re-materialize the sink file from the committed in-memory
        records — the ``run(resume="auto")`` path: a fresh retry
        runner's Quarantine.__init__ truncated the file, but the prior
        attempt's committed quarantines (whose stages may now restore
        from checkpoints without re-running) must survive in both the
        audit trail and the budget."""
        with self._lock:
            try:
                tmp = self.path + ".tmp"
                with open(tmp, "w", encoding="utf-8",
                          errors="backslashreplace") as f:
                    for rec in self.records:
                        f.write(json.dumps(rec, default=str) + "\n")
                os.replace(tmp, self.path)
            except OSError:
                log.warning("quarantine sink rewrite failed",
                            exc_info=True)

    def attempt(self):
        """A fresh per-job-attempt recorder."""
        return QuarantineAttempt(self)

    def precheck(self, local_pending, stage, exc):
        """Budget gate at record time (optimistic: concurrent jobs'
        uncommitted records are invisible; commit re-checks)."""
        with self._lock:
            if self.count + local_pending >= self.limit:
                raise QuarantineOverflow(
                    "stage {}: quarantine budget exhausted "
                    "(settings.max_quarantined={}) — failing fast with "
                    "the original error".format(stage, self.limit)) from exc

    def commit(self, records):
        """Land one successful attempt's quarantined records: count
        them, append the sink lines, re-check the budget (two jobs may
        have raced under ``precheck``'s optimistic gate)."""
        if not records:
            return
        with self._lock:
            if self.count + len(records) > self.limit:
                raise QuarantineOverflow(
                    "quarantine budget exhausted at commit "
                    "(settings.max_quarantined={}, {} committed, {} "
                    "landing)".format(self.limit, self.count,
                                      len(records)))
            self.count += len(records)
            self.records.extend(records)
            n = self.count
            try:
                _append_jsonl(self.path,
                              [json.dumps(rec, default=str)
                               for rec in records])
            except OSError:
                log.warning("quarantine sink write failed", exc_info=True)
        log.warning(
            "quarantined %d poison record(s) (%d/%d total) -> %s",
            len(records), n, self.limit, self.path)


class QuarantineAttempt(object):
    """One job attempt's local quarantine recorder (single-threaded:
    owned by the job closure)."""

    __slots__ = ("_q", "records")

    def __init__(self, quarantine):
        self._q = quarantine
        self.records = []

    def add(self, stage, key, value, exc):
        self._q.precheck(len(self.records), stage, exc)
        self.records.append({
            "stage": stage,
            "key": repr(key)[:500],
            "value": repr(value)[:500],
            "error": type(exc).__name__,
            "message": str(exc)[:500],
            "ts": round(time.time(), 3),
        })
        from .obs import trace as _trace

        _trace.instant("fault", "quarantine", stage=stage,
                       error=type(exc).__name__)
        log.warning(
            "stage %s: isolated poison record (%s: %s) — lands in the "
            "sink when this job attempt commits", stage,
            type(exc).__name__, str(exc)[:200])

    def commit(self):
        self._q.commit(self.records)
        self.records = []


def load_quarantine(run_name):
    """Every quarantined-record line for a run (empty on none)."""
    return _load_jsonl(quarantine_path(run_name))


# -- fault-event sidecar (cross-run memory for process-killing faults) -------

def events_path(run_name):
    return os.path.join(_safe_run_dir(run_name), EVENTS_FILE)


_events_lock = threading.Lock()


class _events_file_lock(object):
    """Cross-PROCESS exclusive lock for the events sidecar: surviving
    ranks on one machine share a scratch root, and the cap compaction's
    read-truncate-rewrite would otherwise discard a sibling's freshly
    appended line (the exact event the shuffle degrade depends on).
    flock on a sidecar lockfile — not the data file, whose inode
    ``os.replace`` swaps — released on process death.  Degrades to a
    no-op where flock is unsupported (same policy as resume.RunGuard)."""

    def __init__(self, path):
        self._path = path + ".lock"
        self._fd = None

    def __enter__(self):
        try:
            import fcntl

            os.makedirs(os.path.dirname(self._path), exist_ok=True)
            self._fd = os.open(self._path, os.O_CREAT | os.O_RDWR, 0o644)
            fcntl.flock(self._fd, fcntl.LOCK_EX)
        except OSError:
            if self._fd is not None:
                os.close(self._fd)
                self._fd = None
        return self

    def __exit__(self, *exc):
        if self._fd is not None:
            try:
                import fcntl

                fcntl.flock(self._fd, fcntl.LOCK_UN)
            finally:
                os.close(self._fd)
                self._fd = None
        return False


def record_event(run_name, kind, **fields):
    """Append one fault event for ``run_name`` (O_APPEND, bounded,
    best-effort — this runs on paths that are already dying and must
    never mask the original failure).  Returns the path or None."""
    if not run_name:
        return None
    try:
        rec = {"kind": kind, "ts": round(time.time(), 3)}
        rec.update(fields)
        line = json.dumps(rec, sort_keys=True, default=str)
        if "\n" in line:
            return None
        path = events_path(run_name)
        with _events_lock, _events_file_lock(path):
            _append_jsonl(path, [line])
            _compact_events(path)
        return path
    except Exception:
        log.warning("fault event append failed for %r", run_name,
                    exc_info=True)
        return None


def _compact_events(path):
    try:
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            lines = f.readlines()
    except OSError:
        return
    if len(lines) <= EVENTS_CAP:
        return
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        f.writelines(lines[-EVENTS_CAP:])
    os.replace(tmp, path)


def load_events(run_name):
    """Every valid fault event for a run name, oldest -> newest."""
    if not run_name:
        return []
    return _load_jsonl(events_path(run_name),
                       keep=lambda rec: bool(rec.get("kind")))


def clear_events(run_name):
    try:
        os.unlink(events_path(run_name))
    except OSError:
        pass


def stages_with_exchange_timeouts(run_name):
    """Stage ids whose collective exchange timed out in a PREVIOUS run
    under this name — the plan layer degrades those stages to the host
    shuffle until the operator clears ``faults.jsonl`` (a hung gloo
    collective is catastrophic; host-until-told-otherwise is the safe
    direction)."""
    sids = set()
    for ev in load_events(run_name):
        if ev.get("kind") == "exchange_timeout" and isinstance(
                ev.get("stage"), int):
            sids.add(ev["stage"])
    return sids
