"""Console entry points (pyproject [project.scripts]).

The reference installs as a plain library with ``test_suite`` wiring only
(reference setup.py:1-20); these go further: the benchmark and the two
canonical workloads run from an installed package without a repo checkout.

- ``dampr-tpu-bench``  — the TF-IDF headline benchmark (same code path the
  repo-root ``bench.py`` driver hook runs; DAMPR_BENCH_MB sizes the corpus).
- ``dampr-tpu-wc``     — word count over a file/dir, top-20 to stdout
  (``--stats`` appends the run summary).
- ``dampr-tpu-tfidf``  — TF-IDF over a file/dir, TSV parts to --out
  (``--stats`` appends the run summary).
- ``dampr-tpu-stats``  — pretty-print a completed run's ``stats.json``
  and locate its Perfetto-loadable trace (see ``settings.trace``).
"""

import argparse
import math
import operator
import os
import sys


def bench():
    from .bench_tfidf import main
    main()


def _print_stats(emitter):
    from .obs import export

    print()
    print(export.format_summary(emitter.stats()))


def wc():
    ap = argparse.ArgumentParser(description="word count (top 20)")
    ap.add_argument("path")
    ap.add_argument("--chunk-mb", type=int, default=16)
    ap.add_argument("--stats", action="store_true",
                    help="print the run's stage/spill/devtime summary")
    args = ap.parse_args()

    from . import Dampr

    counts = (Dampr.text(args.path, chunk_size=args.chunk_mb * 1024 ** 2)
              .flat_map(lambda line: line.split())
              .fold_by(lambda w: w, binop=operator.add, value=lambda w: 1)
              .run("wc-cli"))
    for word, count in sorted(counts, key=lambda kv: kv[1],
                              reverse=True)[:20]:
        print("{}: {}".format(word, count))
    if args.stats:
        _print_stats(counts)
    counts.delete()


def tf_idf():
    ap = argparse.ArgumentParser(description="TF-IDF -> TSV parts")
    ap.add_argument("path")
    ap.add_argument("--out", default="/tmp/dampr_tpu_idfs")
    ap.add_argument("--stats", action="store_true",
                    help="print the run's stage/spill/devtime summary")
    args = ap.parse_args()

    from . import Dampr
    from .ops.text import DocFreq

    chunk = (os.path.getsize(args.path) + 1
             if os.path.isfile(args.path) else 16 * 1024 ** 2)
    docs = Dampr.text(args.path, chunk)
    df = (docs.custom_mapper(DocFreq(mode="word", lower=True))
          .fold_by(lambda kv: kv[0], operator.add, lambda kv: kv[1]))
    idf = df.cross_right(
        docs.len(),
        lambda d, total: (d[0], d[1], math.log(1 + float(total) / d[1])),
        memory=True)
    em = idf.sink_tsv(args.out).run("tfidf-cli")
    print("TSV parts in {}".format(args.out))
    if args.stats:
        _print_stats(em)


def stats():
    """Locate and pretty-print a run's persisted stats.json (written when
    ``settings.trace`` / DAMPR_TPU_TRACE=1 was on for the run)."""
    ap = argparse.ArgumentParser(
        description="pretty-print a run's stats.json + trace location")
    ap.add_argument("run", help="run name (as passed to run(name=...)), a "
                                "run scratch directory, or a stats.json "
                                "path")
    ap.add_argument("--json", action="store_true",
                    help="dump the raw stats.json instead of formatting")
    args = ap.parse_args()

    from .obs import export

    summary, path = export.load_stats(args.run)
    if summary is None:
        print("no stats.json found for {!r} (searched under {}); traced "
              "runs write one — enable settings.trace / DAMPR_TPU_TRACE=1"
              .format(args.run, export.run_trace_dir(args.run)),
              file=sys.stderr)
        raise SystemExit(2)
    if args.json:
        import json

        print(json.dumps(summary, indent=2, sort_keys=True))
    else:
        print("stats: {}".format(path))
        print(export.format_summary(summary))
