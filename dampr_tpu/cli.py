"""Console entry points (pyproject [project.scripts]).

The reference installs as a plain library with ``test_suite`` wiring only
(reference setup.py:1-20); these go further: the benchmark and the two
canonical workloads run from an installed package without a repo checkout.

- ``dampr-tpu-bench``  — the TF-IDF headline benchmark (same code path the
  repo-root ``bench.py`` driver hook runs; DAMPR_BENCH_MB sizes the corpus).
- ``dampr-tpu-wc``     — word count over a file/dir, top-20 to stdout
  (``--stats`` appends the run summary).
- ``dampr-tpu-tfidf``  — TF-IDF over a file/dir, TSV parts to --out
  (``--stats`` appends the run summary).
- ``dampr-tpu-stats``  — pretty-print a completed run's ``stats.json``
  and locate its Perfetto-loadable trace (see ``settings.trace``);
  ``--series`` renders the sampled metric time series, ``--prom`` dumps
  Prometheus text exposition, ``--fleet`` merges a multi-process run's
  per-rank traces into one timeline and prints the fleet section
  (per-rank totals, exchange matrices, skew/straggler), and a run
  directory containing any rank's crashdump (``crashdump.json`` /
  ``crashdump.rank<k>.json`` — the flight recorder's death artifacts)
  makes the command exit 3 so scripts detect failed runs.
- ``dampr-tpu-doctor`` — ranked bottleneck diagnosis for a completed run
  (critical-path verdicts + per-op profile + history corpus -> concrete
  settings suggestions); ``--diff A B`` compares two runs, ``--json``
  emits the machine report (``docs/doctor_schema.json``).  See
  :mod:`dampr_tpu.obs.doctor`.
- ``dampr-tpu-lint``   — static pre-flight diagnostics for pipeline
  modules without executing them (UDF purity/determinism, dispatch
  serialization, fold associativity, jax traceability); ``--json``
  emits the machine report (``docs/lint_schema.json``).  See
  :mod:`dampr_tpu.analyze.lint` and ``docs/analysis.md``.
- ``dampr-tpu-sentry`` — regression sentry over the long-horizon
  telemetry store (MAD anomaly detection per plan fingerprint);
  ``--strict`` exits nonzero on a detected regression — the perf-gate
  CI contract.  See :mod:`dampr_tpu.obs.sentry`.
- ``dampr-tpu-top``    — live terminal dashboard polling every rank's
  ``/metrics`` endpoint (``settings.metrics_port``); ``--once --json``
  for scripts.  See :mod:`dampr_tpu.obs.top`.
- ``dampr-tpu-history`` — inspect/GC/vacuum the run-history corpora
  under the scratch root.  See :mod:`dampr_tpu.obs.history`.

``dampr-tpu-wc`` / ``dampr-tpu-tfidf`` take ``--progress`` for the live
in-run status line (``settings.progress``) and ``--explain`` to print the
optimized logical plan (dampr_tpu.plan; docs/plan.md) without running.
"""

import argparse
import math
import operator
import os
import sys


def _join_deployment():
    """Env-gated multi-process join (no-op without a coordinator env):
    any CLI dropped onto a pod rank with DAMPR_TPU_COORDINATOR /
    JAX_COORDINATOR_ADDRESS wired joins the jax.distributed process
    group before its first jax use — the same pipelines then span every
    rank's devices with no other changes (docs/parallel.md)."""
    from .parallel.mesh import maybe_init_distributed

    maybe_init_distributed()


def bench():
    _join_deployment()
    from .bench_tfidf import main
    main()


def _print_stats(emitter):
    from .obs import export

    print()
    print(export.format_summary(emitter.stats()))


def _enable_progress():
    from . import settings

    settings.progress = True


def wc():
    ap = argparse.ArgumentParser(description="word count (top 20)")
    ap.add_argument("path")
    ap.add_argument("--chunk-mb", type=int, default=16)
    ap.add_argument("--stats", action="store_true",
                    help="print the run's stage/spill/devtime summary")
    ap.add_argument("--progress", action="store_true",
                    help="live per-stage status line while the run "
                         "executes (records/s, MB/s, spill backlog, ETA)")
    ap.add_argument("--explain", action="store_true",
                    help="print the optimized logical plan (stage fusion, "
                         "dead stages, adaptive sizing) and exit without "
                         "running — see docs/plan.md")
    args = ap.parse_args()
    if args.progress:
        _enable_progress()
    _join_deployment()

    from . import Dampr

    pipe = (Dampr.text(args.path, chunk_size=args.chunk_mb * 1024 ** 2)
            .flat_map(lambda line: line.split())
            .fold_by(lambda w: w, binop=operator.add, value=lambda w: 1))
    if args.explain:
        print(pipe.explain(name="wc-cli"))
        return
    counts = pipe.run("wc-cli")
    for word, count in sorted(counts, key=lambda kv: kv[1],
                              reverse=True)[:20]:
        print("{}: {}".format(word, count))
    if args.stats:
        _print_stats(counts)
    counts.delete()


def tf_idf():
    ap = argparse.ArgumentParser(description="TF-IDF -> TSV parts")
    ap.add_argument("path")
    ap.add_argument("--out", default="/tmp/dampr_tpu_idfs")
    ap.add_argument("--stats", action="store_true",
                    help="print the run's stage/spill/devtime summary")
    ap.add_argument("--progress", action="store_true",
                    help="live per-stage status line while the run "
                         "executes (records/s, MB/s, spill backlog, ETA)")
    ap.add_argument("--explain", action="store_true",
                    help="print the optimized logical plan (stage fusion, "
                         "dead stages, adaptive sizing) and exit without "
                         "running — see docs/plan.md")
    args = ap.parse_args()
    if args.progress:
        _enable_progress()
    _join_deployment()

    from . import Dampr
    from .ops.text import DocFreq

    chunk = (os.path.getsize(args.path) + 1
             if os.path.isfile(args.path) else 16 * 1024 ** 2)
    docs = Dampr.text(args.path, chunk)
    df = (docs.custom_mapper(DocFreq(mode="word", lower=True))
          .fold_by(lambda kv: kv[0], operator.add, lambda kv: kv[1]))
    idf = df.cross_right(
        docs.len(),
        lambda d, total: (d[0], d[1], math.log(1 + float(total) / d[1])),
        memory=True)
    pipe = idf.sink_tsv(args.out)
    if args.explain:
        print(pipe.explain(name="tfidf-cli"))
        return
    em = pipe.run("tfidf-cli")
    print("TSV parts in {}".format(args.out))
    if args.stats:
        _print_stats(em)


def doctor():
    """Ranked bottleneck diagnosis for a completed run (see
    dampr_tpu.obs.doctor)."""
    from .obs.doctor import main

    raise SystemExit(main())


def lint():
    """Static pre-flight diagnostics for pipeline modules (see
    dampr_tpu.analyze.lint; docs/analysis.md)."""
    from .analyze.lint import main

    raise SystemExit(main())


def sentry():
    """Regression sentry over the telemetry store (see
    dampr_tpu.obs.sentry)."""
    from .obs.sentry import main

    raise SystemExit(main())


def top():
    """Live fleet dashboard over per-rank /metrics endpoints (see
    dampr_tpu.obs.top)."""
    from .obs.top import main

    raise SystemExit(main())


def history_cli():
    """Run-history corpus inspection/maintenance (see
    dampr_tpu.obs.history)."""
    from .obs.history import main

    raise SystemExit(main())


def serve():
    """Multi-tenant pipeline service daemon (see dampr_tpu.serve and
    docs/serve.md): accepts validated plan submissions over HTTP, runs
    each in an isolated per-job worker, drains gracefully on SIGTERM."""
    from .serve.daemon import main

    raise SystemExit(main())


def _report_crashdump(dump):
    """Describe a flight-recorder crash dump on stderr (the non-zero
    exit's why).  Rank-attributed: a fleet run's dump names which rank
    died."""
    import json

    line = "CRASHED RUN: crashdump at {}".format(dump)
    try:
        with open(dump) as f:
            other = json.load(f).get("otherData") or {}
        crash = other.get("crash") or {}
        proc = other.get("process") or crash or {}
        if (proc.get("num_processes") or 1) > 1:
            line += "  [rank {}/{}]".format(proc.get("process_id", "?"),
                                            proc.get("num_processes"))
        if crash.get("reason"):
            line += "  (reason: {}".format(crash["reason"])
            if crash.get("exception"):
                line += ", {}: {}".format(crash["exception"],
                                          crash.get("message", ""))
            line += ")"
    except (OSError, ValueError):
        pass
    print(line, file=sys.stderr)


def stats():
    """Locate and pretty-print a run's persisted stats.json (written when
    ``settings.trace`` / DAMPR_TPU_TRACE=1 was on for the run).  Exits 3
    when the run left a flight-recorder ``crashdump.json`` — scripts use
    the exit code to detect failed runs."""
    ap = argparse.ArgumentParser(
        description="pretty-print a run's stats.json + trace location")
    ap.add_argument("run", help="run name (as passed to run(name=...)), a "
                                "run scratch directory, or a stats.json "
                                "path")
    ap.add_argument("--json", action="store_true",
                    help="dump the raw stats.json instead of formatting")
    ap.add_argument("--series", action="store_true",
                    help="render the sampled metric time series (counter "
                         "events from the run's trace.json/crashdump.json)")
    ap.add_argument("--prom", action="store_true",
                    help="dump the run's metrics in Prometheus text "
                         "exposition format")
    ap.add_argument("--fleet", action="store_true",
                    help="merge a multi-process run's per-rank traces "
                         "into one Perfetto timeline and print the fleet "
                         "section (per-rank totals, exchange matrices, "
                         "per-step skew, straggler)")
    ap.add_argument("--log", nargs="?", const=20, type=int, default=None,
                    metavar="N",
                    help="render the newest N structured log events "
                         "(default 20) from the run's events.jsonl "
                         "(settings.log_level / DAMPR_TPU_LOG)")
    args = ap.parse_args()

    from .obs import export, flightrec

    summary, path = export.load_stats(args.run)
    # Scan EVERY rank's crashdump: a clean rank 0 must not mask a killed
    # sibling (exit 3 names each dead rank).
    dumps = flightrec.locate_all_crashdumps(args.run)
    dump = dumps[0] if dumps else None
    if summary is None:
        if dump is not None:
            # A run that died before stats landed still has its crash
            # timeline — surface it and fail the invocation.
            for d in dumps:
                _report_crashdump(d)
            raise SystemExit(3)
        print("no stats.json found for {!r} (searched under {}); traced "
              "runs write one — enable settings.trace / DAMPR_TPU_TRACE=1"
              .format(args.run, export.run_trace_dir(args.run)),
              file=sys.stderr)
        raise SystemExit(2)
    if args.fleet and summary.get("fleet") is None:
        # Post-hoc merge BEFORE any output mode renders: the run may
        # predate the finalize-time merge, or rank artifacts may have
        # landed after rank 0 finished — merging is idempotent, and
        # --json must embed the section instead of appending text to a
        # machine-readable stream.
        from .obs import fleet

        section = fleet.merge_run(os.path.dirname(path) if path
                                  else args.run)
        if section is not None:
            summary["fleet"] = section
    log_tail = None
    if args.log is not None:
        from .obs import log as obslog

        # The stream lives next to stats.json; fall back to resolving
        # the run name when the stats path came from elsewhere.
        cand = (os.path.join(os.path.dirname(path), obslog.FILE)
                if path else None)
        log_tail = obslog.tail(cand if cand and os.path.isfile(cand)
                               else args.run, n=args.log)
        if args.json:
            summary = dict(summary, log_tail=log_tail)
    if args.prom:
        from .obs import promtext

        out = promtext.render_summary(summary)
        if not out:
            print("no metrics section in {} (enable the metrics plane: "
                  "settings.metrics_interval_ms / DAMPR_TPU_METRICS_MS)"
                  .format(path), file=sys.stderr)
        else:
            sys.stdout.write(out)
    elif args.json:
        import json

        print(json.dumps(summary, indent=2, sort_keys=True))
    else:
        print("stats: {}".format(path))
        print(export.format_summary(summary))
    if (not args.prom and not args.json and dump is None
            and summary.get("critpath")):
        run_verdict = (summary["critpath"].get("run") or {}).get("verdict")
        if run_verdict:
            print("bottleneck: {}  (run `dampr-tpu-doctor {}` for the "
                  "full diagnosis)".format(run_verdict, args.run))
    if args.fleet and not args.json and not args.prom:
        from .obs import fleet

        print()
        print(fleet.format_fleet(summary.get("fleet")))
    if args.series:
        tf = summary.get("trace_file")
        if not tf or not os.path.isfile(tf):
            # Fall back to the trace (or crash dump) sitting next to the
            # stats file — trace_dir may have moved since the run.
            for cand in ("trace.json", "crashdump.json"):
                c = os.path.join(os.path.dirname(path), cand)
                if os.path.isfile(c):
                    tf = c
                    break
        if not tf or not os.path.isfile(tf):
            print("no trace.json for {!r}: the time series live there as "
                  "counter events".format(args.run), file=sys.stderr)
        else:
            print()
            print(export.format_series(export.load_series(tf)))
        pipe_view = export.format_pipeline_series(summary)
        if pipe_view:
            print()
            print(pipe_view)
    if args.log is not None and not args.json and not args.prom:
        from .obs import log as obslog

        print()
        print(obslog.format_tail(log_tail))
    if dump is not None:
        for d in dumps:
            _report_crashdump(d)
        raise SystemExit(3)
