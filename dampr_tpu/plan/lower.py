"""The device-lowering pass: assign each executed stage an explicit
execution target (``host`` | ``device`` | ``mesh``).

Runs after the rewrite passes (and on the literal graph when the
optimizer is off — lowering is a placement decision, not a graph-shape
rewrite), inspecting each stage:

- a **map** stage lowers when its fused chain is a native-vocabulary
  scanner (:func:`dampr_tpu.ops.lower.claims` — the tokenize/hash
  scanners) optionally followed by identity, its map-side combiner (if
  any) is a device-foldable ``sum``, and its output feeds a keyed
  associative fold — the fused map->fold shape the jitted programs
  compile.  Everything else stays host with a recorded reason (opaque
  UDFs are the guaranteed fallback: the lowerer never claims a stage it
  cannot prove equivalent).
- a **reduce** stage lowers when it is a device-foldable associative
  fold (``sum``/``min``/``max``) — executed through the existing exact
  segment kernels, which still fall back per block when 32-bit lanes
  would truncate.
- a redistribution stage that stays host — a general (non-associative)
  reduce, a join, or a ``sort_by`` re-key map whose materialization is
  read back key-sorted — additionally gets a **shuffle** target
  (``mesh`` | ``host``) from :func:`cost.shuffle_choice`: explicit
  ``settings.mesh_exchange`` modes win, auto mode decides from the
  run-history corpus (shuffle input bytes, record sizes, partition
  counts).  ``mesh`` routes the stage's redistribution through the
  HBM-budgeted collective byte exchange
  (:mod:`dampr_tpu.parallel.exchange`); results are byte-identical
  either way.  The decision map rides the runner (``_shuffle_targets``
  — a dispatch hint, deliberately NOT stage options, so resume/cache
  fingerprints never depend on accumulated history) and lands in the
  plan report's ``shuffle`` section.

Placement is stats-driven (the tf.data-service argument, arXiv
2210.14826): a prior run's history showing a stage emitted fewer than
``settings.lower_min_records`` records pins it to host — program
dispatch overhead dominates tiny stages.  Per-stage kill switch: pass
``lower=False`` in the stage's options (``custom_mapper(m,
lower=False)``).  Master switch: ``settings.lower``
(``DAMPR_TPU_LOWER``; results are byte-identical either way).

Device-targeted stages gain ``options["exec_target"] = "device"`` on a
fresh clone (shared nodes are never mutated); the full target map with
reasons lands in the plan report's ``lowering`` section, rendered by
``explain()`` and shipped in ``stats()["plan"]``.
"""

import logging

from .. import base, settings
from ..graph import GMap, GReduce
from . import ir

log = logging.getLogger("dampr_tpu.plan.lower")


def _fold_kind(stage):
    """The device-foldable combiner kind a stage carries, or None."""
    op = None
    if isinstance(getattr(stage, "combiner", None),
                  base.PartialReduceCombiner):
        op = stage.combiner.op
    elif "binop" in (stage.options or {}):
        from ..ops import segment

        op = segment.as_assoc_op(stage.options["binop"])
    return getattr(op, "kind", None)


def _consumers_all_sum_folds(graph, output, protected, _depth=0):
    """Does EVERY consumer of ``output`` (looking through bare
    checkpoints) fold it with a keyed associative ``sum``?

    The device programs emit partial counts at batch granularity where
    the host scanner emits them at window granularity — only a summing
    fold is invariant to that regrouping.  Any other consumer (an opaque
    UDF branch, a min/max fold, a direct read of a requested output)
    would OBSERVE the partial grouping, so the stage must stay host for
    the legs to stay byte-identical."""
    if _depth > len(graph.stages) or output in protected:
        return False
    consumers = [s for s in graph.stages
                 if output in getattr(s, "inputs", ())]
    if not consumers:
        return False
    for stage in consumers:
        if isinstance(stage, GReduce):
            red = getattr(stage, "reducer", None)
            if (isinstance(red, base.AssocFoldReducer)
                    and red.op.kind == "sum"):
                continue
            return False
        if isinstance(stage, GMap) and ir.is_identity_mapper(stage.mapper):
            kind = _fold_kind(stage)
            if kind == "sum":
                continue
            if kind is None and not ir.has_combiner(stage):
                # bare checkpoint: its consumers decide
                if _consumers_all_sum_folds(graph, stage.output, protected,
                                            _depth + 1):
                    continue
            return False
        return False
    return True


def _map_decision(stage, graph, protected):
    """(target, reason) for a GMap stage.  ``protected`` holds the
    requested output Sources — a directly-read output exposes partial
    granularity and never lowers without a combiner."""
    from ..ops import lower as ops_lower

    if (stage.options or {}).get("lower") is False:
        return "host", "killed by stage option lower=False"
    if len(stage.inputs) != 1:
        return "host", "multi-input map (join shapes stay host)"
    leaves = ir.flatten_mapper(stage.mapper)
    head, tail = leaves[0], leaves[1:]
    params = ops_lower.claims(head)
    if params is None:
        # Widened vocabulary (ROADMAP 5a): a chain the static analyzer
        # certifies jax-traceable (pure deterministic ValueMap/Filter
        # lane ops that abstract-eval cleanly) lowers as a vectorized
        # lane program — exactness-gated per block at dispatch, the
        # per-record path the guaranteed fallback.  A certified chain's
        # record multiplicity and grouping are identical to the host
        # path, so no combiner/consumer granularity constraints apply.
        if settings.analyze:
            from ..analyze import jaxtrace

            spec, why = jaxtrace.chain_claims(stage.mapper)
            if spec is not None:
                return "device", why + " (verified-per-block lane program)"
        name = ir._part_name(head)
        return "host", "no device lowering for {} (opaque UDF)".format(name)
    bad = [p for p in tail if not (type(p) is base.Map
                                   and p.mapper is base._identity)]
    if bad:
        return "host", "post-scan ops not in the device vocabulary: " + \
            ", ".join(ir._part_name(p) for p in bad)
    kind = _fold_kind(stage)
    if ir.has_combiner(stage) and kind != "sum":
        # A non-sum combiner folds partials whose grouping differs
        # between the host (per window) and device (per batch) scans.
        return "host", "combiner kind {!r} not sum — partial-count " \
            "granularity would be observable".format(kind)
    if kind != "sum" and not _consumers_all_sum_folds(
            graph, stage.output, protected):
        return "host", "not every consumer is a keyed sum fold — " \
            "partial-count granularity would be observable"
    return "device", "scanner {} + keyed sum fold compile to one jitted " \
        "program".format(type(head).__name__)


def _reduce_decision(stage):
    if (stage.options or {}).get("lower") is False:
        return "host", "killed by stage option lower=False"
    red = getattr(stage, "reducer", None)
    if not isinstance(red, base.AssocFoldReducer):
        name = ir._part_name(red) if red is not None else "?"
        return "host", "non-associative reducer {} (opaque UDF)".format(name)
    if red.op.kind not in ("sum", "min", "max"):
        return "host", "fold binop has no device kind (opaque Python binop)"
    return "device", "assoc {} fold runs the device segment kernels " \
        "(exact 32-bit-lane gate per block)".format(red.op.kind)


def analyze(graph, history=None, outputs=()):
    """Per-executed-stage target decisions: [{sid, kind, target, reason}].

    ``history`` (a prior run's stats.json summary, shape-matched by the
    caller) drives the stats placement gate; ``outputs`` are the Sources
    the caller will read directly."""
    by_sid = {}
    if history:
        by_sid = {s.get("stage"): s for s in history.get("stages", [])}
    protected = set(outputs)
    decisions = []
    for sid, stage in enumerate(graph.stages):
        kind = ir.stage_kind(stage)
        if kind == "input":
            continue
        if kind == "map":
            target, reason = _map_decision(stage, graph, protected)
        elif kind == "reduce":
            target, reason = _reduce_decision(stage)
        else:
            target, reason = "host", "sinks drain through the normal " \
                "spill/store machinery"
        if target == "device":
            st = by_sid.get(sid) or {}
            recs = st.get("records_out")
            if recs is not None and recs < settings.lower_min_records:
                target, reason = "host", (
                    "history: {} records < lower_min_records={} — dispatch "
                    "overhead dominates".format(
                        recs, settings.lower_min_records))
        decisions.append({"sid": sid, "kind": kind, "target": target,
                          "reason": reason})
    return decisions


def empty_section(enabled):
    return {"enabled": enabled, "targets": [], "device_stages": 0,
            "handoff": []}


def handoff_analyze(graph, decisions, run_name=None):
    """Cross-stage fusion pass: per producer->consumer edge, may the
    producer's program outputs stay HBM-resident for the consumer
    (``handoff="device"``) or must they spill through the host tier
    (``handoff="spill"``)?  An edge qualifies when BOTH endpoints
    lowered: the producer is a device map (native scanner or certified
    lane chain) and the consumer is a device-lowered associative fold —
    then the runner threads the producer's outputs as HBM-resident
    BlockRefs straight into the collective fold, skipping d2h, pickle,
    frame encode/decode, and h2d on that edge.  Every decline carries a
    reason; results are byte-identical either way (runtime degrades fall
    back to the spill path per batch or per edge)."""
    from ..ops import lower as ops_lower

    targets = {d["sid"]: d for d in decisions}
    edges = []
    if not any(d["target"] == "device" for d in decisions):
        return edges
    priced = None
    for sid, stage in enumerate(graph.stages):
        d = targets.get(sid)
        if d is None or d["target"] != "device" or d["kind"] != "map":
            continue
        consumers = [(cid, c) for cid, c in enumerate(graph.stages)
                     if stage.output in getattr(c, "inputs", ())]
        for cid, cons in consumers:
            cd = targets.get(cid)
            edge = {"src": sid, "dst": cid}
            if (not isinstance(cons, GReduce) or cd is None
                    or cd["target"] != "device"):
                edge["handoff"] = "spill"
                edge["kind"] = "no-device-consumer"
                edge["reason"] = ("consumer is not a device-lowered "
                                  "fold — outputs drain through the "
                                  "host tier")
                edges.append(edge)
                continue
            if not settings.handoff_enabled():
                edge["handoff"] = "spill"
                edge["kind"] = "settings"
                edge["reason"] = (
                    "handoff off (settings.handoff={!r}; hbm budget {} "
                    "on this backend)".format(
                        settings.handoff, settings.effective_hbm_budget()))
                edges.append(edge)
                continue
            params = ops_lower.claims(stage.mapper)
            if params is not None and params.get("pair_values"):
                edge["handoff"] = "spill"
                edge["kind"] = "object-lane"
                edge["reason"] = ("pair-values scanner emits an object "
                                  "lane — no device tier for it")
                edges.append(edge)
                continue
            if not settings.handoff_forced() and run_name:
                if priced is None:
                    from . import cost

                    priced = cost.handoff_choice(run_name, graph)
                choice, why = priced
                if choice == "spill":
                    edge["handoff"] = "spill"
                    edge["kind"] = "priced"
                    edge["reason"] = why
                    edges.append(edge)
                    continue
            edge["handoff"] = "device"
            edge["kind"] = "resident"
            edge["via"] = ("scanner-program" if params is not None
                           else "lane-program")
            edge["reason"] = (
                "producer program outputs stay HBM-resident into the "
                "collective fold — d2h/spill/h2d skipped on this edge"
                + ("" if priced is None or priced[0] is None
                   else " ({})".format(priced[1])))
            edges.append(edge)
    return edges


def empty_shuffle_section(enabled):
    return {"enabled": enabled, "targets": [], "mesh_stages": 0}


def _is_sort_stage(stage):
    """A GMap whose chain re-keys for a global sort (``sort_by``'s Rekey
    op): its materialization is read back key-sorted, and the sorted
    read's range redistribution is the shuffle being routed."""
    if not isinstance(stage, GMap):
        return False
    return any(isinstance(p, base.Rekey)
               for p in ir.flatten_mapper(stage.mapper))


def shuffle_analyze(graph, history, n_dev, n_partitions,
                    device_sids=(), model=None):
    """Per-redistribution-stage shuffle decisions:
    [{sid, kind, target, reason}].  Candidates are every GReduce (the
    group_by/fold_by/join exchange) and every sort re-key GMap (the
    sorted read's range exchange); device-lowered reduces are recorded
    but not routed — their redistribution rides the collective fold
    program, not the byte exchange."""
    from . import cost

    by_sid = {}
    if history:
        by_sid = {s.get("stage"): s for s in history.get("stages", [])}
    decisions = []
    for sid, stage in enumerate(graph.stages):
        if isinstance(stage, GReduce):
            kind = "reduce"
        elif _is_sort_stage(stage):
            # A Rekey chain feeding a reduce is a group_by's key-assign
            # pass — its redistribution happens at the consuming reduce,
            # which gets its own row.  Only reduce-free rekeys (sort_by
            # materializations read back key-sorted) exchange at read.
            if any(isinstance(c, GReduce) for c in graph.stages
                   if stage.output in getattr(c, "inputs", ())):
                continue
            kind = "sort"
        else:
            continue
        if sid in device_sids:
            decisions.append({
                "sid": sid, "kind": kind, "target": "device",
                "reason": "device-lowered fold — redistribution rides "
                          "the collective fold program, not the byte "
                          "exchange"})
            continue
        target, reason = cost.shuffle_choice(
            by_sid.get(sid), n_dev, n_partitions, model=model)
        decisions.append({"sid": sid, "kind": kind, "target": target,
                          "reason": reason})
    return decisions


def apply_shuffle(runner, report):
    """Record host-vs-mesh shuffle decisions in ``report["shuffle"]`` and
    ride the routing map on the runner (``runner._shuffle_targets``:
    {sid: "mesh"|"host"}) for its target-aware redistribution dispatch.
    Runs on BOTH optimizer legs and independently of device lowering —
    the exchange is a redistribution transport, not a stage program.  The
    map is a runtime dispatch hint, never stage options, so checkpoint /
    cache fingerprints stay independent of accumulated history."""
    graph = getattr(runner, "graph", None)
    report["shuffle"] = empty_shuffle_section(False)
    if graph is None or not hasattr(graph, "stages"):
        return
    mode = str(settings.mesh_exchange).lower()
    section = report["shuffle"]
    if mode in ("off", "0", "false") or not settings.use_device:
        section["reason"] = (
            "off (settings.mesh_exchange={!r}; every redistribution "
            "stays on the host shuffle)".format(settings.mesh_exchange))
        return
    from . import cost

    n_dev = (settings.device_count_for_auto()
             if mode not in ("on", "1", "true") else None)
    history = cost.matched_history(getattr(runner, "name", None), graph)
    device_sids = {
        d["sid"] for d in (report.get("lowering") or {}).get("targets", [])
        if d["target"] == "device" and d["kind"] == "reduce"}
    decisions = shuffle_analyze(
        graph, history, n_dev if n_dev is not None else 2,
        getattr(runner, "n_partitions", settings.partitions), device_sids,
        model=cost.current_model(getattr(runner, "name", None), graph))
    # Fault-history degrade: a stage whose collective exchange TIMED OUT
    # in a previous run under this name (a dead rank wedged the gloo
    # collective; the watchdog recorded the event before aborting) pins
    # to the host shuffle — a hung collective is catastrophic, so
    # host-until-the-operator-clears-it is the safe direction.  Explicit
    # ``mesh_exchange="on"`` still wins (the operator asked).
    if mode not in ("on", "1", "true"):
        try:
            from .. import faults as _faults

            timed_out = _faults.stages_with_exchange_timeouts(
                getattr(runner, "name", None))
        except Exception:
            timed_out = ()
        for d in decisions:
            if d["target"] == "mesh" and d["sid"] in timed_out:
                d["target"] = "host"
                d["reason"] = (
                    "fault-history: a previous run's collective exchange "
                    "timed out at this stage (exchange_timeout_ms) — "
                    "degraded to the host shuffle; delete the run's "
                    "faults.jsonl to re-try the mesh")
                log.warning("plan: stage %d shuffle degraded to host "
                            "after a recorded exchange timeout", d["sid"])
    section["enabled"] = True
    section["targets"] = decisions
    section["mesh_stages"] = sum(
        1 for d in decisions if d["target"] == "mesh")
    if settings.exchange_coding_enabled():
        # Coded aggregation (parallel.replan / runner._code_exchange_batch):
        # sum-combinable keyed folds routed over the byte exchange
        # pre-fold each window per destination partition — the run
        # summary's mesh.exchange.coding section carries the measured
        # raw-vs-coded bytes this mode traded.
        section["coding"] = str(settings.exchange_coding)
    routing = {d["sid"]: d["target"] for d in decisions
               if d["target"] in ("mesh", "host")}
    try:
        runner._shuffle_targets = routing
    except AttributeError:
        pass
    if section["mesh_stages"]:
        log.info("plan: %d redistribution stage(s) routed over the mesh "
                 "exchange", section["mesh_stages"])


def apply(runner, outputs, report):
    """Annotate ``runner.graph`` with execution targets and record the
    decision map in ``report["lowering"]`` (+ ``report["device_stages"]``,
    the count the stats section surfaces).  Value-semantic: only stages
    that lower get fresh clones; with lowering off (or nothing eligible)
    the graph object is untouched.  History loads lazily — the disabled
    path (CPU default) never touches the stats file."""
    graph = getattr(runner, "graph", None)
    report["lowering"] = empty_section(False)
    report["device_stages"] = 0
    report["handoff_edges"] = 0
    if graph is None or not hasattr(graph, "stages"):
        return
    if not settings.lower_enabled():
        report["lowering"]["reason"] = (
            "off (settings.lower={!r}; DAMPR_TPU_LOWER forces it)"
            .format(settings.lower))
        return
    from . import cost

    # Stats-driven placement is an AUTO-mode behavior: when the master
    # switch is explicitly forced ("1"/"on"), the operator asked for
    # device execution and the run-history floor (lower_min_records)
    # must not silently pin eligible stages back to host — forced legs
    # (CI's lower-on matrix, a user's DAMPR_TPU_LOWER=1) stay
    # deterministic regardless of accumulated corpus state.
    history = (None if settings.lower_forced()
               else cost.matched_history(getattr(runner, "name", None),
                                         graph))
    decisions = analyze(graph, history, outputs)
    section = report["lowering"]
    section["enabled"] = True
    section["targets"] = decisions
    lowered = {d["sid"] for d in decisions if d["target"] == "device"}
    section["device_stages"] = len(lowered)
    report["device_stages"] = len(lowered)
    if not lowered:
        return
    # Cross-stage fusion: which device->device edges keep their dataflow
    # HBM-resident (the handoff tier) instead of spilling through host.
    edges = handoff_analyze(graph, decisions,
                            run_name=getattr(runner, "name", None))
    section["handoff"] = edges
    dev_edges = [e for e in edges if e["handoff"] == "device"]
    report["handoff_edges"] = len(dev_edges)
    hand_sids = {e["src"] for e in dev_edges}
    try:
        runner._handoff_sids = hand_sids
    except AttributeError:
        pass
    store = getattr(runner, "store", None)
    if store is not None and hand_sids:
        # Arms the store's handoff budget (on forced CPU-JAX legs the
        # plain HBM budget resolves to 0 and would instantly evict the
        # refs the handoff just kept resident).  Runs without handoff
        # edges keep the classic budget untouched.
        store.handoff_active = True
    stages = list(graph.stages)
    for sid in lowered:
        opts = dict(stages[sid].options or {})
        opts["exec_target"] = "device"
        stages[sid] = ir.clone_with_options(stages[sid], opts)
    runner.graph = ir.rebuilt(stages)
    log.info("plan: %d stage(s) lowered to device programs, %d "
             "device-handoff edge(s)", len(lowered), len(dev_edges))
