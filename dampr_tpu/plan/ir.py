"""Plan-level views over the logical :class:`~dampr_tpu.graph.Graph`.

The graph layer stays a dumb ordered stage list (its value semantics are
what make handles shareable); everything the optimizer needs to reason
about it — who consumes which Source, what a mapper chain is made of,
which stages are rewrite barriers — lives here as pure functions, so the
passes in :mod:`.passes` never poke at node internals directly.
"""

from .. import base
from ..graph import GInput, GMap, GReduce, GSink, Graph

#: Record ops whose presence makes a stage a fusion barrier.  ``Sample``
#: draws from a per-thread RNG in stream order, so moving it across a
#: materialization boundary changes which records each RNG stream sees
#: (seeded runs must stay reproducible across optimize on/off);
#: ``Inspect`` is the user asking to SEE the records at that exact point.
BARRIER_OPS = (base.Sample, base.Inspect)


# -- mapper chains -----------------------------------------------------------

def flatten_mapper(m):
    """A (possibly fused) mapper -> its leaf parts in stream order."""
    if type(m) in (base.ComposedMapper, base.ComposedStreamable):
        return flatten_mapper(m.left) + flatten_mapper(m.right)
    return [m]


def _is_identity_leaf(p):
    return type(p) is base.Map and p.mapper is base._identity


def is_identity_mapper(m):
    """True when the mapper chain is pure identity (a checkpoint head)."""
    return all(_is_identity_leaf(p) for p in flatten_mapper(m))


def is_record_chain(m):
    """Fusable mapper: a pure per-record chain (Map / typed RecordOps,
    composed) with no barrier ops.  Anything with per-chunk or
    whole-partition semantics (BlockMapper lifecycle, StreamMapper,
    map-side joins) transforms at a granularity fusion would change."""
    if not base.is_pure_record_stream(m):
        return False
    return not any(isinstance(p, BARRIER_OPS) for p in flatten_mapper(m))


def compose_mappers(*mappers):
    """Compose mapper chains into one fused mapper, dropping identity
    leaves (they contribute nothing to the stream)."""
    parts = []
    for m in mappers:
        parts.extend(p for p in flatten_mapper(m) if not _is_identity_leaf(p))
    if not parts:
        return base.Map(base._identity)
    return base.fuse(parts)


# -- stage predicates --------------------------------------------------------

def has_barrier_ops(stage):
    """Does the stage's mapper chain contain a granularity-sensitive op
    (Sample/Inspect)?  Such stages neither absorb their producer nor
    dissolve into their consumer: fusing in EITHER direction changes the
    record grouping their op observes (a sampler's per-thread RNG
    streams, an inspect's print points)."""
    m = getattr(stage, "mapper", None)
    return m is not None and any(isinstance(p, BARRIER_OPS)
                                 for p in flatten_mapper(m))


def stage_is_barrier(stage):
    """Must this stage's OUTPUT stay materialized exactly as constructed?

    Explicit user checkpoints carry ``options["barrier"]``; ``cached()``
    pins carry ``memory``; Sample/Inspect chains are barriers by op type.
    A barrier stage never dissolves into its consumer — the checkpoint
    boundary the user asked for survives — but a plain checkpoint/cached
    tail may still ABSORB its producer: that removes the producer's
    materialization, not the checkpoint's own.
    """
    opts = getattr(stage, "options", None) or {}
    if opts.get("barrier") or opts.get("memory"):
        return True
    return has_barrier_ops(stage)


def has_combiner(stage):
    return (getattr(stage, "combiner", None) is not None
            or "binop" in (getattr(stage, "options", None) or {}))


def merge_options(head_opts, tail_opts):
    """Fused-stage options: the tail's semantic options win (binop,
    n_reducers, the shuffle shape belongs to the tail); ``n_maps`` takes
    the most restrictive of the two (a stage that asked to serialize
    stays serialized when fused — same rule as runtime scan sharing)."""
    out = dict(head_opts or {})
    out.update(tail_opts or {})
    if head_opts and tail_opts and "n_maps" in head_opts \
            and "n_maps" in tail_opts:
        out["n_maps"] = min(head_opts["n_maps"], tail_opts["n_maps"])
    return out


# -- graph views -------------------------------------------------------------

def consumer_counts(stages, outputs=()):
    """{Source: consumer count} over every stage input list, with every
    requested output charged one extra consumer (the final read) so a
    requested Source never looks private to its one graph consumer."""
    counts = {}
    for stage in stages:
        for src in stage.inputs:
            counts[src] = counts.get(src, 0) + 1
    for src in outputs:
        counts[src] = counts.get(src, 0) + 1
    return counts


def producer_index(stages):
    """{output Source: stage index}."""
    return {stage.output: i for i, stage in enumerate(stages)}


def executed_stage_count(graph):
    """Stages the runner actually executes (GInput taps are free)."""
    return sum(1 for s in graph.stages if not isinstance(s, GInput))


def stage_kind(stage):
    if isinstance(stage, GInput):
        return "input"
    if isinstance(stage, GMap):
        return "map"
    if isinstance(stage, GReduce):
        return "reduce"
    if isinstance(stage, GSink):
        return "sink"
    return type(stage).__name__


def _part_name(p):
    fn = None
    for attr in ("mapper", "f", "key_f", "streamer_f", "reducer",
                 "stream_f", "crosser", "sinker"):
        fn = getattr(p, attr, None)
        if fn is not None:
            break
    label = type(p).__name__
    name = getattr(fn, "__name__", None)
    if name and name != "<lambda>":
        return "{}({})".format(label, name)
    return label


def describe_stage(stage):
    """Human-readable one-liner for explain() output."""
    if isinstance(stage, GInput):
        return "input[{}]".format(type(stage.tap).__name__)
    if isinstance(stage, GMap):
        parts = " . ".join(_part_name(p) for p in flatten_mapper(stage.mapper))
        extra = ""
        if has_combiner(stage):
            extra += " +combiner"
        if stage.options.get("memory"):
            extra += " +pinned"
        if stage.options.get("barrier"):
            extra += " +barrier"
        return "map[{}]{}".format(parts, extra)
    if isinstance(stage, GReduce):
        return "reduce[{}]".format(_part_name(stage.reducer))
    if isinstance(stage, GSink):
        return "sink[{} -> {}]".format(_part_name(stage.sinker), stage.path)
    return repr(stage)


def stage_shape(stage):
    """Cheap structural key for matching a stage against a prior run's
    stats history (cost.py): kind plus the operator chain's class names.
    Deliberately ignores captured values — two runs of the same pipeline
    code produce identical shapes."""
    if isinstance(stage, GInput):
        return "input:" + type(stage.tap).__name__
    if isinstance(stage, GMap):
        names = ".".join(type(p).__name__
                         for p in flatten_mapper(stage.mapper))
        if has_combiner(stage):
            names += "+c"
        return "map:" + names
    if isinstance(stage, GReduce):
        return "reduce:" + type(stage.reducer).__name__
    if isinstance(stage, GSink):
        return "sink:" + type(stage.sinker).__name__
    return "other:" + type(stage).__name__


def stage_shapes(graph):
    """Per-executed-stage shape records, keyed the way the runner numbers
    stages (sid = index in the full stage list, GInputs included)."""
    return [{"sid": i, "shape": stage_shape(s)}
            for i, s in enumerate(graph.stages) if not isinstance(s, GInput)]


def graph_signature(graph):
    """Structural signature for idempotence checks: stage kinds, operator
    identities, and input wiring (as producer positions)."""
    pos = {s.output: i for i, s in enumerate(graph.stages)}
    sig = []
    for stage in graph.stages:
        ops = ()
        if isinstance(stage, GMap):
            ops = tuple(id(p) for p in flatten_mapper(stage.mapper))
            ops += (id(stage.combiner), id(stage.shuffler))
        elif isinstance(stage, GReduce):
            ops = (id(stage.reducer),)
        elif isinstance(stage, GSink):
            ops = tuple(id(p) for p in flatten_mapper(stage.sinker))
            ops += (stage.path,)
        sig.append((stage_kind(stage),
                    tuple(pos.get(s, -1) for s in stage.inputs),
                    ops,
                    tuple(sorted((k, repr(v)) for k, v in
                                 (stage.options or {}).items()))))
    return tuple(sig)


def stage_provenance(stage):
    """The original-stage descriptions a fused node was built from (ridden
    onto fused nodes by :mod:`.passes` for the per-operator profiler), or
    None for never-fused stages."""
    return getattr(stage, "_provenance", None)


def clone_with_options(stage, options):
    """Fresh node with replaced options — shared StageNodes are never
    mutated (graphs are copy-on-write; a node may live in other handles'
    graphs).  Fusion provenance survives the clone."""
    if isinstance(stage, GMap):
        out = GMap(stage.inputs, stage.output, stage.mapper,
                   stage.combiner, stage.shuffler, options)
    elif isinstance(stage, GReduce):
        out = GReduce(stage.inputs, stage.output, stage.reducer, options)
    elif isinstance(stage, GSink):
        out = GSink(stage.inputs, stage.output, stage.sinker, stage.path,
                    options)
    else:
        raise TypeError("cannot clone {!r}".format(stage))
    prov = stage_provenance(stage)
    if prov is not None:
        out._provenance = prov
    return out


def rebuilt(stages):
    return Graph(stages)
