"""The pass pipeline: Graph -> Graph rewrites.

Rules (each with a kill switch in :mod:`dampr_tpu.settings`):

- **dead-stage elimination** (``settings.plan_dead``): stages unreachable
  from any requested output or durable sink are dropped — the stage walk
  otherwise executes every stage in construction order, reachable or not.
- **map fusion** (``settings.plan_fuse``): ``A -> B`` GMap pairs where
  A's output has exactly one consumer (B), A carries no combiner, and
  neither side is a barrier collapse into one stage.  Two sub-rules:
  pure per-record chains on both sides compose into one fused mapper;
  an identity tail (checkpoint head) dissolves into ANY producer mapper
  (block mappers keep their vectorized ``map_blocks`` path untouched).
  The tail's combiner/shuffler/output always survive on the fused stage.
- **combiner hoisting** (``settings.plan_hoist``): the identity-dissolve
  sub-rule applied to a combiner-carrying tail — the map-side fold the
  DSL plants as a separate identity stage runs inside the producer's map
  jobs, deleting a full materialize boundary.
- **sink fusion** (``settings.plan_fuse_sinks``): a pure record chain
  whose single consumer is a GSink composes into the sinker, so the sink
  streams transformed records straight off its input.

Barriers (boundaries fusion never erases): explicit ``checkpoint()``
stages (``options["barrier"]``) and ``cached()`` pins (``memory``) never
dissolve into their consumer — their materialization point survives
(they may still absorb a private producer, which removes the producer's
boundary, not theirs); stages whose chain contains ``Sample`` or
``Inspect`` fuse in neither direction (their ops observe record
grouping); and any Source with more than one consumer stays put —
including the shared prefixes ``Graph.union`` dedupes and every
requested output.  See ``docs/plan.md``.

All rewrites build fresh StageNodes; nodes of the input graph are never
mutated (they may be shared with other live handles).
"""

import logging

from .. import settings
from ..graph import GMap, GSink
from . import ir

log = logging.getLogger("dampr_tpu.plan")


def _dead_stage_elimination(stages, outputs, report):
    """Keep only stages reachable (via inputs) from a requested output or
    a durable sink."""
    needed = set(outputs)
    keep = [False] * len(stages)
    for i in range(len(stages) - 1, -1, -1):
        stage = stages[i]
        if isinstance(stage, GSink) or stage.output in needed:
            keep[i] = True
            needed.update(stage.inputs)
    dropped = [i for i, k in enumerate(keep) if not k]
    if not dropped:
        return stages
    report["rules"]["dead_stages"] += len(dropped)
    report["dead"].extend(
        "s{}:{}".format(i, ir.describe_stage(stages[i])) for i in dropped)
    return [s for i, s in enumerate(stages) if keep[i]]


def _impure_blocks_compose(*stages):
    """Does the static analyzer (settings.analyze) veto composing these
    stages' record chains into one stage?  An evidence-impure UDF keeps
    its own stage: fusing it would move its side effects into another
    stage's job/retry/checkpoint scope (a retried fused job replays the
    OTHER stage's side effects too, and a checkpoint alias over the
    fused node may skip them entirely).  ``assume_pure=True`` stage
    options suppress (honored inside stage_verdict).  Identity
    dissolves never consult this — they leave the surviving mapper
    untouched."""
    if not settings.analyze:
        return False
    from ..analyze import props

    for s in stages:
        try:
            if not props.stage_verdict(s).pure:
                return True
        except Exception:  # noqa: BLE001 - analysis never fails a plan
            continue  # unclassifiable stage: benefit of the doubt,
            #           but keep checking the OTHER stages
    return False


def _fusable_pair(a, b, counts, protected):
    """May GMap ``b`` absorb its producer GMap ``a``?  Returns the rule
    name ('fuse_maps' / 'hoist_combiners') or None.

    The head must not be a barrier (its output is the materialization the
    user asked for); the tail only blocks on granularity-sensitive ops —
    a checkpoint()/cached() tail absorbing its producer keeps its own
    boundary (and pin) intact while deleting the producer's."""
    if ir.stage_is_barrier(a) or ir.has_barrier_ops(b):
        return None
    if a.output in protected or counts.get(a.output, 0) != 1:
        return None
    if ir.has_combiner(a):
        # A combiner head is a shuffle boundary: its folded output IS the
        # stage contract its reduce consumer folds again.
        return None
    if ir.is_identity_mapper(b.mapper):
        # Identity tail dissolves into any producer (checkpoint elision /
        # combiner hoist); the producer's mapper — and with it the
        # vectorized map_blocks / window_sink paths — is untouched.
        return "hoist_combiners" if ir.has_combiner(b) else "fuse_maps"
    if ir.is_record_chain(a.mapper) and ir.is_record_chain(b.mapper):
        if _impure_blocks_compose(a, b):
            return None
        return "fuse_maps"
    return None


def _fuse_maps(stages, protected, report):
    """Fixed-point fusion sweep over GMap->GMap (and GMap->GSink) pairs."""
    do_maps = settings.plan_fuse
    do_hoist = settings.plan_hoist
    do_sinks = settings.plan_fuse_sinks
    if not (do_maps or do_hoist or do_sinks):
        return stages
    stages = list(stages)
    changed = True
    while changed:
        changed = False
        counts = ir.consumer_counts(stages, protected)
        producer = ir.producer_index(stages)
        for bi, b in enumerate(stages):
            if len(getattr(b, "inputs", ())) < 1:
                continue
            ai = producer.get(b.inputs[0])
            if ai is None:
                continue
            a = stages[ai]
            if not isinstance(a, GMap):
                continue
            if isinstance(b, GMap) and len(b.inputs) == 1:
                rule = _fusable_pair(a, b, counts, protected)
                if rule is None:
                    continue
                if rule == "fuse_maps" and not do_maps:
                    continue
                if rule == "hoist_combiners" and not do_hoist:
                    continue
                if ir.is_identity_mapper(b.mapper):
                    mapper = a.mapper
                else:
                    mapper = ir.compose_mappers(a.mapper, b.mapper)
                fused = GMap(a.inputs, b.output, mapper,
                             b.combiner, b.shuffler,
                             ir.merge_options(a.options, b.options))
            elif (isinstance(b, GSink) and do_sinks
                    and len(b.inputs) == 1
                    and not ir.stage_is_barrier(a)
                    and a.output not in protected
                    and counts.get(a.output, 0) == 1
                    and not ir.has_combiner(a)
                    and ir.is_record_chain(a.mapper)
                    and ir.is_record_chain(b.sinker)
                    and not _impure_blocks_compose(a)):
                rule = "fuse_sinks"
                fused = GSink(a.inputs, b.output,
                              ir.compose_mappers(a.mapper, b.sinker),
                              b.path, ir.merge_options(a.options, b.options))
            else:
                continue
            report["rules"][rule] += 1
            report["fused"].append({
                "rule": rule,
                "into": ir.describe_stage(fused),
                "members": [ir.describe_stage(a), ir.describe_stage(b)],
            })
            # Provenance rides the fused node (an attribute, not options —
            # options feed resume fingerprints): the ordered descriptions
            # of the ORIGINAL user stages this node absorbed, so the
            # per-operator profiler (obs.profile) can attribute the fused
            # stage's time back to the ops the user actually wrote.
            fused._provenance = (
                (ir.stage_provenance(a) or [ir.describe_stage(a)])
                + (ir.stage_provenance(b) or [ir.describe_stage(b)]))
            # The fused node takes the producer's slot (its inputs'
            # producers all precede it); the tail's slot disappears.
            stages[ai] = fused
            del stages[bi]
            changed = True
            break
    return stages


def optimize(graph, outputs):
    """Rewrite ``graph`` for the requested ``outputs``.

    Returns ``(graph, report)``.  When no rule fires the ORIGINAL graph
    object comes back (so ``optimize(optimize(g)) is optimize(g)`` — the
    idempotence the property tests pin).  ``outputs`` are the Sources the
    caller will read; they are never fused away or eliminated.
    """
    from . import empty_report

    report = empty_report(graph, enabled=True)
    protected = set(outputs)
    stages = list(graph.stages)
    if settings.plan_dead:
        stages = _dead_stage_elimination(stages, protected, report)
    stages = _fuse_maps(stages, protected, report)
    fired = sum(report["rules"].values())
    if not fired:
        report["stages_after"] = report["stages_before"]
        return graph, report
    out = ir.rebuilt(stages)
    report["stages_after"] = ir.executed_stage_count(out)
    log.info("plan: %d -> %d stages (%s)", report["stages_before"],
             report["stages_after"],
             ", ".join("{}={}".format(k, v)
                       for k, v in sorted(report["rules"].items()) if v))
    return out, report
