"""Stats-driven adaptive execution.

Every finalized run under a name appends one record to the run-history
corpus (:mod:`dampr_tpu.obs.history`) carrying per-stage records/bytes
in and out plus the plan's stage shapes; traced runs additionally leave
a ``stats.json`` (the pre-corpus source, still honored as a fallback).
When the CURRENT optimized plan has the same shape sequence, those
measurements — the newest record when the corpus holds fewer than three
matching runs, per-stage medians over the recent window otherwise — size
this run:

- **partition count**: the run's ``n_partitions`` is re-derived from the
  largest observed reduce input (``plan_partition_bytes`` per partition,
  clamped) — tiny workloads stop paying 64 partitions' worth of fixed
  per-partition numpy cost, huge ones fan out wider.  Skipped when the
  caller pinned ``n_partitions`` explicitly or the run is resumable
  (changing the partition count would invalidate every checkpoint).
- **block batch size**: map stages whose observed bytes/record is large
  get a per-stage ``batch_size`` option so blocks target
  ``plan_block_bytes`` instead of ``settings.batch_size`` records of
  unknown width (bounds per-block memory on fat records).
- **reducer job width**: reduce stages whose observed input was tiny run
  their partition jobs on one worker (``n_reducers=1``) — pool fan-out
  costs more than it buys under ``small_stage_bytes``.

No history, a shape mismatch, or ``settings.plan_adapt`` off -> static
defaults, untouched.  Every decision lands in the plan report's
``adaptive`` section (visible via ``explain()`` and ``em.stats()``).
"""

import logging
import os

from .. import settings
from ..graph import GMap, GReduce
from . import ir

log = logging.getLogger("dampr_tpu.plan.cost")


def empty_cost_section(reason=None):
    from . import model as _model

    return _model.empty_section(False, reason=reason)


def load_history(run_name):
    """The prior run's stats.json summary for this run name, or None.
    Never raises: adaptation is best-effort by design."""
    if not run_name:
        return None
    try:
        from ..obs import export

        summary, _path = export.load_stats(run_name)
        return summary
    except Exception:
        log.debug("stats history unreadable for %r", run_name, exc_info=True)
        return None


def corpus_history(run_name, graph):
    """(history, reason) for this run name from the run-history corpus
    (:mod:`dampr_tpu.obs.history`).

    The corpus accumulates one record per finalized run; only records
    whose stage-shape sequence matches ``graph`` count (per-sid
    measurements are meaningless across shapes).  One or two matching
    records behave exactly like the old single-stats.json path (the
    newest record verbatim — equivalence-pinned); three or more feed
    per-stage MEDIANS over the ``settings.history_window`` most recent,
    so one outlier run stops steering the sizing.  Runs that predate the
    corpus fall back to their stats.json.  Returns ``(None, reason)``
    when nothing usable exists; never raises."""
    if not run_name:
        return None, "no-history"
    shapes_now = ir.stage_shapes(graph)
    try:
        from ..obs import history

        records = history.load(run_name)
        if records:
            matched = history.matching(records, shapes_now)
            if not matched:
                return None, "shape-mismatch"
            window = max(1, settings.history_window)
            return history.synthesize(matched[-window:]), None
    except Exception:
        log.debug("history corpus unreadable for %r", run_name,
                  exc_info=True)
    hist = load_history(run_name)
    if hist is None:
        return None, "no-history"
    shapes_prev = (hist.get("plan") or {}).get("stage_shapes") or []
    if ([s.get("shape") for s in shapes_prev]
            != [s["shape"] for s in shapes_now]):
        return None, "shape-mismatch"
    return hist, None


def matched_history(run_name, graph):
    """The shape-matched history for ``run_name`` (corpus-backed), or
    None.  Used by the lowering pass's stats-driven placement and by
    explain()."""
    hist, _reason = corpus_history(run_name, graph)
    return hist


def current_model(run_name, graph):
    """The fitted :class:`~dampr_tpu.plan.model.CostModel` for a run
    name (knob-variance tables scoped to ``graph``'s fingerprint), or
    None — model disabled (``DAMPR_TPU_COST_MODEL=0``), no corpus, or
    any read failure (the model layer is best-effort by design)."""
    if not settings.cost_model_enabled() or not run_name:
        return None
    try:
        from ..obs import history
        from . import model as _model

        records = history.load(run_name)
        if not records:
            return None
        fp = history.plan_fingerprint(ir.stage_shapes(graph))
        return _model.build(records, fp)
    except Exception:
        log.debug("cost model unavailable for %r", run_name,
                  exc_info=True)
        return None


def handoff_choice(run_name, graph):
    """Corpus-priced handoff-vs-spill decision for this plan's
    device->device edges (``plan.lower.handoff_analyze``, auto mode
    only).  Returns (decision, reason): ``"device"``/``"spill"``, or
    None when there is no evidence — auto then keeps the edge resident
    and the recorded reason says what a measurement would add."""
    if not settings.cost_model_enabled() or not run_name:
        return None, ("no corpus pricing (cost model off or unnamed "
                      "run) — auto keeps the edge resident")
    try:
        from ..obs import history
        from . import model as _model

        records = history.load(run_name)
        if not records:
            return None, ("empty corpus — no handoff-vs-spill evidence "
                          "yet")
        fp = history.plan_fingerprint(ir.stage_shapes(graph))
        return _model.price_handoff(records, fp)
    except Exception:
        log.debug("handoff pricing unavailable for %r", run_name,
                  exc_info=True)
        return None, "corpus pricing unavailable"


def load_tuned(run_name):
    """The persisted autotune winner for a run name
    (``<scratch_root>/<run>/tuned.json``, written by
    :mod:`dampr_tpu.obs.autotune`), or None.  Never raises."""
    if not run_name:
        return None
    try:
        import json

        safe = str(run_name).replace("/", "_")
        path = os.path.join(settings.scratch_root, safe, "tuned.json")
        if not os.path.isfile(path):
            return None
        with open(path) as f:
            doc = json.load(f)
        return doc if isinstance(doc, dict) else None
    except Exception:
        log.debug("tuned.json unreadable for %r", run_name,
                  exc_info=True)
        return None


def shuffle_choice(hist_stage, n_dev, n_partitions, mode=None,
                   model=None):
    """(target, reason) — route one redistribution stage's shuffle over
    the ``host`` threadpool path or the ``mesh`` collective byte exchange
    (:mod:`dampr_tpu.parallel.exchange`).

    Explicit ``settings.mesh_exchange`` modes always win; ``auto`` decides
    from the run-history corpus — a stage whose recorded shuffle input is
    under ``settings.exchange_min_bytes`` keeps the host path (the D*D
    window pack/unpack fixed cost dominates tiny exchanges), anything
    larger (or unmeasured) rides the budgeted collective schedule.  The
    reason string carries the evidence (bytes, record sizes, partition
    counts) into ``explain()`` and the plan report.
    """
    if mode is None:
        mode = settings.mesh_exchange
    m = str(mode).lower()
    if m in ("off", "0", "false") or not settings.use_device:
        return "host", "settings.mesh_exchange={!r} pins the host " \
            "shuffle".format(mode)
    if m in ("on", "1", "true"):
        return "mesh", "settings.mesh_exchange={!r} forces the " \
            "collective exchange".format(mode)
    if n_dev < 2:
        return "host", "single visible device — nothing to exchange over"
    st = hist_stage or {}
    bytes_in = st.get("bytes_in")
    if not bytes_in:
        return "mesh", "{} devices visible, no shuffle history — the " \
            "budgeted collective engages by availability".format(n_dev)
    if model is not None:
        # Learned placement: when the corpus has fit BOTH the exchange
        # and host-fold operator classes, modeled seconds decide the
        # route instead of the static byte floor.  Unfit classes fall
        # through to the heuristic below (and DAMPR_TPU_COST_MODEL=0
        # never reaches here) — the kill switch reproduces the
        # pre-model decisions byte-identically.
        pred = model.shuffle_prediction(bytes_in / 1e6)
        if pred is not None:
            return pred
    if bytes_in < settings.exchange_min_bytes:
        return "host", (
            "history: {} B shuffle input < exchange_min_bytes={} — the "
            "D*D collective window pack/unpack overhead dominates; host "
            "shuffle is cheaper".format(
                bytes_in, settings.exchange_min_bytes))
    recs = st.get("records_in") or st.get("records_out") or 0
    rec_bytes = (bytes_in / float(recs)) if recs else None
    detail = "~{:.0f} B/record, ".format(rec_bytes) if rec_bytes else ""
    return "mesh", (
        "history: {} B shuffle input across {} partitions on {} devices "
        "({}windowed under exchange_hbm_budget={}){}".format(
            bytes_in, n_partitions, n_dev, detail,
            settings.exchange_hbm_budget,
            "; coded aggregation armed (exchange_coding={}) for "
            "sum-combinable folds".format(settings.exchange_coding)
            if settings.exchange_coding_enabled() else ""))


def _clamped_partitions(reduce_bytes):
    want = max(1, -(-int(reduce_bytes) // settings.plan_partition_bytes))
    floor = max(4, min(settings.max_processes, settings.partitions))
    ceil_ = max(settings.partitions, 4 * settings.partitions)
    return max(floor, min(want, ceil_))


def _batch_for(rec_bytes):
    """Records per block so a block targets plan_block_bytes: the largest
    power of two at or under the target, floored at 16 so degenerate
    histories (multi-MB records) still batch a handful at a time instead
    of overshooting the byte bound by orders of magnitude."""
    if rec_bytes <= 0:
        return None
    want = max(16, int(settings.plan_block_bytes // rec_bytes))
    b = 16
    while b * 2 <= want:
        b *= 2
    return b


def adapt(runner, graph, report):
    """Apply history-driven sizing to ``runner`` (n_partitions) and
    ``runner.graph`` (per-stage options).  Mutates nothing shared: stages
    that gain options are fresh clones."""
    info = {"applied": False, "reason": None, "history": None, "changes": []}
    report["adaptive"] = info
    if not settings.plan_adapt:
        info["reason"] = "disabled"
        return
    if getattr(runner, "resume", False):
        # Checkpoint fingerprints are salted with the partition count and
        # hash per-stage options: re-sizing would orphan every checkpoint.
        info["reason"] = "resumable-run"
        return
    hist, reason = corpus_history(getattr(runner, "name", None), graph)
    if hist is None:
        info["reason"] = reason
        return
    info["history"] = hist.get("stats_file") or hist.get("run")
    info["history_entries"] = hist.get("history_entries", 1)
    by_sid = {s.get("stage"): s for s in hist.get("stages", [])}

    # -- run-level partition count ------------------------------------------
    reduce_bytes = 0
    for i, stage in enumerate(graph.stages):
        if isinstance(stage, GReduce):
            st = by_sid.get(i) or {}
            reduce_bytes = max(reduce_bytes, st.get("bytes_in") or 0)
    if (reduce_bytes > 0
            and not getattr(runner, "_explicit_partitions", True)):
        want = _clamped_partitions(reduce_bytes)
        if want != runner.n_partitions:
            info["changes"].append({
                "what": "n_partitions", "from": runner.n_partitions,
                "to": want, "reduce_bytes_in": reduce_bytes})
            runner.n_partitions = want

    # -- per-stage options ---------------------------------------------------
    new_stages = None
    for i, stage in enumerate(graph.stages):
        st = by_sid.get(i) or {}
        opts = None
        if (isinstance(stage, GMap)
                and "batch_size" not in (stage.options or {})):
            recs, nbytes = st.get("records_out") or 0, st.get("bytes_out") or 0
            if recs and nbytes:
                batch = _batch_for(nbytes / float(recs))
                if batch and batch < settings.batch_size:
                    opts = dict(stage.options or {})
                    opts["batch_size"] = batch
                    info["changes"].append({
                        "what": "batch_size", "stage": i, "to": batch,
                        "record_bytes": round(nbytes / float(recs), 1)})
        elif (isinstance(stage, GReduce)
                and "n_reducers" not in (stage.options or {})):
            nbytes = st.get("bytes_in") or 0
            if 0 < nbytes <= settings.small_stage_bytes:
                opts = dict(stage.options or {})
                opts["n_reducers"] = 1
                info["changes"].append({
                    "what": "n_reducers", "stage": i, "to": 1,
                    "bytes_in": nbytes})
        if opts is not None:
            if new_stages is None:
                new_stages = list(graph.stages)
            new_stages[i] = ir.clone_with_options(stage, opts)
    if new_stages is not None:
        runner.graph = ir.rebuilt(new_stages)
    if info["changes"]:
        info["applied"] = True
        report["rules"]["adaptive"] = len(info["changes"])
        log.info("plan: adaptive sizing applied %d change(s) from %s",
                 len(info["changes"]), info["history"])
    else:
        info["reason"] = "within-defaults"


def _hist_stage_rows(hist, graph):
    """Shape-matched history stages annotated with op class and MB —
    the feature rows the model search prices this plan with."""
    from . import model as _model

    shape_by_sid = {s["sid"]: s["shape"] for s in ir.stage_shapes(graph)}
    rows = []
    for st in (hist or {}).get("stages") or ():
        row = dict(st)
        row["op_class"] = _model.op_class(
            st, shape_by_sid.get(st.get("stage")))
        row["mb"] = max(st.get("bytes_in") or 0,
                        st.get("bytes_out") or 0) / 1e6
        rows.append(row)
    return rows


def model_view(run_name, graph, n_now=None):
    """The shared ``corpus -> fits -> confidence -> choices`` pipeline
    behind BOTH :func:`apply_model` (the decision) and ``explain()``'s
    cost lines (the preview), so the rendered trace and the applied
    decision cannot drift.  Returns a dict: ``records`` (rank-filtered
    corpus), ``model`` (CostModel or None), ``rows`` (shape-matched
    priced stages), ``ok``/``reason`` (confidence verdict),
    ``partition_choice`` (vs ``n_now``, default the static
    ``settings.partitions``), ``variance_choices``, ``tuned``,
    ``fingerprint``."""
    from ..obs import history
    from . import model as _model

    out = {"records": [], "model": None, "rows": [], "ok": False,
           "reason": None, "partition_choice": None,
           "variance_choices": [], "tuned": None, "fingerprint": None}
    try:
        records = [r for r in history.load(run_name)
                   if not r.get("rank")]
    except Exception:
        log.debug("model corpus unreadable for %r", run_name,
                  exc_info=True)
        records = []
    out["records"] = records
    if not records:
        out["reason"] = "no-history: empty corpus — static defaults " \
            "stand"
        return out
    fp = history.plan_fingerprint(ir.stage_shapes(graph))
    out["fingerprint"] = fp
    m = _model.build(records, fp)
    out["model"] = m
    hist, hist_reason = corpus_history(run_name, graph)
    rows = _hist_stage_rows(hist, graph) if hist else []
    out["rows"] = rows
    if not rows:
        out["reason"] = "{}: no shape-matched measurements to price " \
            "this plan with — median/static decisions stand".format(
                hist_reason or "shape-mismatch")
        return out
    ok, why = m.confident_for([r["op_class"] for r in rows])
    if not ok:
        out["reason"] = "{} — median-path decisions stand".format(why)
        return out
    out["ok"] = True
    out["partition_choice"] = _model.search_partitions(
        m, rows, n_now if n_now is not None else settings.partitions)
    current = {k: getattr(settings, k, None)
               for k in _model.VARIANCE_KNOBS}
    out["variance_choices"] = _model.search_variance_knobs(m, current)
    out["tuned"] = load_tuned(run_name)
    return out


def apply_model(runner, graph, report):
    """The learned-cost-model layer (:mod:`dampr_tpu.plan.model`): runs
    AFTER the median-path adaptation and may override its sizing when
    the per-operator fits are confident, recording every choice — and
    its predicted-vs-static delta — in ``report["cost"]``.

    Contract (pinned by tests): with ``DAMPR_TPU_COST_MODEL=0`` this
    function records the kill switch and touches NOTHING — the median
    path's decisions stand byte-identically.  An empty or thin corpus
    likewise degrades to the median/static decisions with the reason
    recorded."""
    from . import model as _model

    info = _model.empty_section(False)
    report["cost"] = info
    if not settings.cost_model_enabled():
        info["reason"] = "disabled (settings.cost_model={!r} / " \
            "DAMPR_TPU_COST_MODEL=0)".format(settings.cost_model)
        return
    if not settings.plan_adapt:
        info["reason"] = "plan_adapt off — no history-driven decisions"
        return
    if getattr(runner, "resume", False):
        info["reason"] = "resumable-run (re-sizing would orphan " \
            "checkpoints)"
        return
    run_name = getattr(runner, "name", None)
    if not run_name:
        info["reason"] = "unnamed run — no corpus to learn from"
        info["source"] = "static"
        return
    view = model_view(run_name, graph,
                      n_now=getattr(runner, "n_partitions", None))
    if view["model"] is not None:
        info["model"] = view["model"].to_dict()
    if not view["ok"]:
        info["reason"] = view["reason"]
        info["source"] = ("static" if not view["records"]
                          else "median-fallback")
        return
    m, rows = view["model"], view["rows"]
    info["enabled"] = True
    info["source"] = "model"
    choices = []

    # -- partition count: argmin of modeled fold/exchange seconds -----------
    tuned = view["tuned"]
    if tuned and tuned.get("fingerprint") not in (None,
                                                  view["fingerprint"]):
        # A winner measured on a DIFFERENT plan shape under this run
        # name: never apply it (fingerprint-less legacy files stay
        # accepted).
        info["tuned_stale"] = {"session": tuned.get("session"),
                               "fingerprint": tuned["fingerprint"]}
        tuned = None
    tuned_knobs = (tuned or {}).get("knobs") or {}
    if (not getattr(runner, "_explicit_partitions", True)
            and not getattr(runner, "resume", False)):
        n_now = runner.n_partitions
        tuned_p = tuned_knobs.get("n_partitions")
        if (isinstance(tuned_p, int)
                and _model.in_bounds("n_partitions", tuned_p)
                and tuned_p != n_now):
            choices.append({
                "knob": "n_partitions", "static": n_now,
                "chosen": tuned_p, "applied": True,
                "reason": "autotuned winner (tuned.json session {!r} "
                          "measured it fastest)".format(
                              (tuned or {}).get("session"))})
            runner.n_partitions = tuned_p
        else:
            ch = view["partition_choice"]
            if ch is not None:
                ch["applied"] = True
                runner.n_partitions = ch["chosen"]
                choices.append(ch)

    # -- run-level knobs: observed-variance choices (suggestions; the
    #    engine never mutates process-global settings mid-run — the
    #    autotune loop and the operator apply these via env) ---------------
    for ch in view["variance_choices"]:
        ch.setdefault("applied", False)
        choices.append(ch)
    info["choices"] = choices
    if tuned:
        info["tuned"] = {"session": tuned.get("session"),
                         "knobs": tuned_knobs,
                         "wall_seconds": tuned.get("wall_seconds")}

    # -- headline prediction: modeled wall at the chosen vs static sizing --
    basis_mb = max((r["mb"] for r in rows), default=0.0)
    chosen_s = _model.predict_plan(m, rows, runner.n_partitions)
    static_s = _model.predict_plan(m, rows, settings.partitions)
    if chosen_s and static_s:
        info["predicted"] = {
            "wall_seconds": round(chosen_s, 4),
            "static_wall_seconds": round(static_s, 4),
            "mbps": (round(basis_mb / chosen_s, 3)
                     if chosen_s > 0 else None),
            "static_mbps": (round(basis_mb / static_s, 3)
                            if static_s > 0 else None),
        }
    applied = [c for c in choices if c.get("applied")]
    if applied:
        log.info("plan: cost model applied %d knob choice(s): %s",
                 len(applied),
                 ", ".join("{}={}".format(c["knob"], c["chosen"])
                           for c in applied))
