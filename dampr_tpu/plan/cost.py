"""Stats-driven adaptive execution.

Every finalized run under a name appends one record to the run-history
corpus (:mod:`dampr_tpu.obs.history`) carrying per-stage records/bytes
in and out plus the plan's stage shapes; traced runs additionally leave
a ``stats.json`` (the pre-corpus source, still honored as a fallback).
When the CURRENT optimized plan has the same shape sequence, those
measurements — the newest record when the corpus holds fewer than three
matching runs, per-stage medians over the recent window otherwise — size
this run:

- **partition count**: the run's ``n_partitions`` is re-derived from the
  largest observed reduce input (``plan_partition_bytes`` per partition,
  clamped) — tiny workloads stop paying 64 partitions' worth of fixed
  per-partition numpy cost, huge ones fan out wider.  Skipped when the
  caller pinned ``n_partitions`` explicitly or the run is resumable
  (changing the partition count would invalidate every checkpoint).
- **block batch size**: map stages whose observed bytes/record is large
  get a per-stage ``batch_size`` option so blocks target
  ``plan_block_bytes`` instead of ``settings.batch_size`` records of
  unknown width (bounds per-block memory on fat records).
- **reducer job width**: reduce stages whose observed input was tiny run
  their partition jobs on one worker (``n_reducers=1``) — pool fan-out
  costs more than it buys under ``small_stage_bytes``.

No history, a shape mismatch, or ``settings.plan_adapt`` off -> static
defaults, untouched.  Every decision lands in the plan report's
``adaptive`` section (visible via ``explain()`` and ``em.stats()``).
"""

import logging

from .. import settings
from ..graph import GMap, GReduce
from . import ir

log = logging.getLogger("dampr_tpu.plan.cost")


def load_history(run_name):
    """The prior run's stats.json summary for this run name, or None.
    Never raises: adaptation is best-effort by design."""
    if not run_name:
        return None
    try:
        from ..obs import export

        summary, _path = export.load_stats(run_name)
        return summary
    except Exception:
        log.debug("stats history unreadable for %r", run_name, exc_info=True)
        return None


def corpus_history(run_name, graph):
    """(history, reason) for this run name from the run-history corpus
    (:mod:`dampr_tpu.obs.history`).

    The corpus accumulates one record per finalized run; only records
    whose stage-shape sequence matches ``graph`` count (per-sid
    measurements are meaningless across shapes).  One or two matching
    records behave exactly like the old single-stats.json path (the
    newest record verbatim — equivalence-pinned); three or more feed
    per-stage MEDIANS over the ``settings.history_window`` most recent,
    so one outlier run stops steering the sizing.  Runs that predate the
    corpus fall back to their stats.json.  Returns ``(None, reason)``
    when nothing usable exists; never raises."""
    if not run_name:
        return None, "no-history"
    shapes_now = ir.stage_shapes(graph)
    try:
        from ..obs import history

        records = history.load(run_name)
        if records:
            matched = history.matching(records, shapes_now)
            if not matched:
                return None, "shape-mismatch"
            window = max(1, settings.history_window)
            return history.synthesize(matched[-window:]), None
    except Exception:
        log.debug("history corpus unreadable for %r", run_name,
                  exc_info=True)
    hist = load_history(run_name)
    if hist is None:
        return None, "no-history"
    shapes_prev = (hist.get("plan") or {}).get("stage_shapes") or []
    if ([s.get("shape") for s in shapes_prev]
            != [s["shape"] for s in shapes_now]):
        return None, "shape-mismatch"
    return hist, None


def matched_history(run_name, graph):
    """The shape-matched history for ``run_name`` (corpus-backed), or
    None.  Used by the lowering pass's stats-driven placement and by
    explain()."""
    hist, _reason = corpus_history(run_name, graph)
    return hist


def shuffle_choice(hist_stage, n_dev, n_partitions, mode=None):
    """(target, reason) — route one redistribution stage's shuffle over
    the ``host`` threadpool path or the ``mesh`` collective byte exchange
    (:mod:`dampr_tpu.parallel.exchange`).

    Explicit ``settings.mesh_exchange`` modes always win; ``auto`` decides
    from the run-history corpus — a stage whose recorded shuffle input is
    under ``settings.exchange_min_bytes`` keeps the host path (the D*D
    window pack/unpack fixed cost dominates tiny exchanges), anything
    larger (or unmeasured) rides the budgeted collective schedule.  The
    reason string carries the evidence (bytes, record sizes, partition
    counts) into ``explain()`` and the plan report.
    """
    if mode is None:
        mode = settings.mesh_exchange
    m = str(mode).lower()
    if m in ("off", "0", "false") or not settings.use_device:
        return "host", "settings.mesh_exchange={!r} pins the host " \
            "shuffle".format(mode)
    if m in ("on", "1", "true"):
        return "mesh", "settings.mesh_exchange={!r} forces the " \
            "collective exchange".format(mode)
    if n_dev < 2:
        return "host", "single visible device — nothing to exchange over"
    st = hist_stage or {}
    bytes_in = st.get("bytes_in")
    if not bytes_in:
        return "mesh", "{} devices visible, no shuffle history — the " \
            "budgeted collective engages by availability".format(n_dev)
    if bytes_in < settings.exchange_min_bytes:
        return "host", (
            "history: {} B shuffle input < exchange_min_bytes={} — the "
            "D*D collective window pack/unpack overhead dominates; host "
            "shuffle is cheaper".format(
                bytes_in, settings.exchange_min_bytes))
    recs = st.get("records_in") or st.get("records_out") or 0
    rec_bytes = (bytes_in / float(recs)) if recs else None
    detail = "~{:.0f} B/record, ".format(rec_bytes) if rec_bytes else ""
    return "mesh", (
        "history: {} B shuffle input across {} partitions on {} devices "
        "({}windowed under exchange_hbm_budget={}){}".format(
            bytes_in, n_partitions, n_dev, detail,
            settings.exchange_hbm_budget,
            "; coded aggregation armed (exchange_coding={}) for "
            "sum-combinable folds".format(settings.exchange_coding)
            if settings.exchange_coding_enabled() else ""))


def _clamped_partitions(reduce_bytes):
    want = max(1, -(-int(reduce_bytes) // settings.plan_partition_bytes))
    floor = max(4, min(settings.max_processes, settings.partitions))
    ceil_ = max(settings.partitions, 4 * settings.partitions)
    return max(floor, min(want, ceil_))


def _batch_for(rec_bytes):
    """Records per block so a block targets plan_block_bytes: the largest
    power of two at or under the target, floored at 16 so degenerate
    histories (multi-MB records) still batch a handful at a time instead
    of overshooting the byte bound by orders of magnitude."""
    if rec_bytes <= 0:
        return None
    want = max(16, int(settings.plan_block_bytes // rec_bytes))
    b = 16
    while b * 2 <= want:
        b *= 2
    return b


def adapt(runner, graph, report):
    """Apply history-driven sizing to ``runner`` (n_partitions) and
    ``runner.graph`` (per-stage options).  Mutates nothing shared: stages
    that gain options are fresh clones."""
    info = {"applied": False, "reason": None, "history": None, "changes": []}
    report["adaptive"] = info
    if not settings.plan_adapt:
        info["reason"] = "disabled"
        return
    if getattr(runner, "resume", False):
        # Checkpoint fingerprints are salted with the partition count and
        # hash per-stage options: re-sizing would orphan every checkpoint.
        info["reason"] = "resumable-run"
        return
    hist, reason = corpus_history(getattr(runner, "name", None), graph)
    if hist is None:
        info["reason"] = reason
        return
    info["history"] = hist.get("stats_file") or hist.get("run")
    info["history_entries"] = hist.get("history_entries", 1)
    by_sid = {s.get("stage"): s for s in hist.get("stages", [])}

    # -- run-level partition count ------------------------------------------
    reduce_bytes = 0
    for i, stage in enumerate(graph.stages):
        if isinstance(stage, GReduce):
            st = by_sid.get(i) or {}
            reduce_bytes = max(reduce_bytes, st.get("bytes_in") or 0)
    if (reduce_bytes > 0
            and not getattr(runner, "_explicit_partitions", True)):
        want = _clamped_partitions(reduce_bytes)
        if want != runner.n_partitions:
            info["changes"].append({
                "what": "n_partitions", "from": runner.n_partitions,
                "to": want, "reduce_bytes_in": reduce_bytes})
            runner.n_partitions = want

    # -- per-stage options ---------------------------------------------------
    new_stages = None
    for i, stage in enumerate(graph.stages):
        st = by_sid.get(i) or {}
        opts = None
        if (isinstance(stage, GMap)
                and "batch_size" not in (stage.options or {})):
            recs, nbytes = st.get("records_out") or 0, st.get("bytes_out") or 0
            if recs and nbytes:
                batch = _batch_for(nbytes / float(recs))
                if batch and batch < settings.batch_size:
                    opts = dict(stage.options or {})
                    opts["batch_size"] = batch
                    info["changes"].append({
                        "what": "batch_size", "stage": i, "to": batch,
                        "record_bytes": round(nbytes / float(recs), 1)})
        elif (isinstance(stage, GReduce)
                and "n_reducers" not in (stage.options or {})):
            nbytes = st.get("bytes_in") or 0
            if 0 < nbytes <= settings.small_stage_bytes:
                opts = dict(stage.options or {})
                opts["n_reducers"] = 1
                info["changes"].append({
                    "what": "n_reducers", "stage": i, "to": 1,
                    "bytes_in": nbytes})
        if opts is not None:
            if new_stages is None:
                new_stages = list(graph.stages)
            new_stages[i] = ir.clone_with_options(stage, opts)
    if new_stages is not None:
        runner.graph = ir.rebuilt(new_stages)
    if info["changes"]:
        info["applied"] = True
        report["rules"]["adaptive"] = len(info["changes"])
        log.info("plan: adaptive sizing applied %d change(s) from %s",
                 len(info["changes"]), info["history"])
    else:
        info["reason"] = "within-defaults"
