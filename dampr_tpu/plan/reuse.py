"""Cross-run materialization cache: shared-prefix reuse + incremental
recompute over appended corpora.

:mod:`dampr_tpu.resume` restores checkpoints *within* one named run.
This module generalizes that to a **shared, content-addressed cache
across runs**: every non-volatile stage output can publish into a
scratch-root-level store keyed by the chained fingerprint of its whole
producing prefix — stage structure chained through the DAG exactly like
resume fingerprints, but with input *content signatures* (chunked
sha1 over file bytes) in place of resume's (path, size, mtime) stat
identity, so the same corpus reached through a different copy, run
name, or process still hits.  This is the shared ephemeral-vs-cached
materialization argument of the tf.data service paper (arXiv
2210.14826): identical pipeline prefixes across submissions dedupe
into one cached materialization.

Two reuse modes, decided per stage before the run executes:

- **full hit** — the stage's content key has a published entry: its
  partition frames are hardlinked into the run's own scratch (so a
  concurrent eviction can never yank files mid-read) and mounted in
  place of executing the stage *and its entire upstream prefix*.
- **incremental** — no full hit, but an entry exists for the same
  *structural* key (same pipeline, different input content) whose
  recorded input signature is an append-only prefix of the current one
  (every cached file still present byte-identical; only whole new
  files added).  The stage re-runs over just the new files and the
  fresh partials union with the cached partials — allowed only when
  the merge is provably exact (see :func:`incremental_eligible`).

Exactness contract (the reuse-off CI leg and the chaos leg pin it):

- cached, incremental, and cold runs produce byte-identical result
  *content*;
- volatile-fingerprint stages (DTA402) never cache — volatility
  propagates through ``resume._h`` exactly as for checkpoints;
- a corrupted or truncated entry (the ``cache_read`` fault site)
  degrades to recompute, recorded in ``stats()["reuse"]``
  ``recompute_fallbacks`` — never to wrong results;
- runs executing under an injected fault plan, or that quarantined
  records, consume but never publish (a chaos run must not seed the
  shared cache with lossy results).

Concurrency: publishes build under ``entries/.tmp-*`` and land with one
atomic directory rename — concurrent publishers of the same key race,
one wins, the loser discards its temp tree; eviction runs under an
exclusive flock on ``<cache>/.lock`` (degrading to lock-free on
filesystems without flock, like resume's RunGuard) and removes whole
least-recently-consumed entries until the store fits
``settings.reuse_budget_bytes``.

See ``docs/reuse.md`` for the key derivation and eligibility tables.
"""

import contextlib
import collections
import errno
import hashlib
import json
import logging
import os
import shutil
import time
import uuid

from .. import faults as _faults
from .. import inputs as _inputs
from .. import resume as _resume
from .. import settings
from ..dataset import Chunker
from ..obs import trace as _trace

log = logging.getLogger("dampr_tpu.plan.reuse")

#: Manifest schema tag; bumped on any incompatible layout change so a
#: newer engine never misreads an older shared cache (unknown schemas
#: read as a miss, not an error).
SCHEMA = "dampr-tpu-reuse/1"


class CacheEntryError(Exception):
    """A cache entry that exists but cannot be trusted (corrupt
    manifest, missing/truncated block, injected ``cache_read`` fault).
    Callers degrade to recompute and count the fallback."""


# ---------------------------------------------------------------------------
# Content signatures
# ---------------------------------------------------------------------------

def _file_chunk_hashes(path, window):
    """sha1 per ``window``-byte span of the file, in order.  The chunk
    list is what makes append-only *within* the signature recognizable
    later without re-reading old bytes' context: a changed early chunk
    changes its hash in place."""
    hashes = []
    with open(path, "rb") as f:
        while True:
            buf = f.read(window)
            if not buf:
                break
            hashes.append(hashlib.sha1(buf).hexdigest())
    if not hashes:  # empty file still needs a stable identity
        hashes.append(hashlib.sha1(b"").hexdigest())
    return hashes


def content_signature(tap):
    """Content signature dict for an input tap, or None when the tap is
    not signable (downstream keys then go volatile: never cached).

    Path taps hash every file's bytes in ``settings.reuse_chunk_bytes``
    windows — unlike resume's stat identity this is stable across
    copies and mtime churn.  Memory taps reuse the structural
    fingerprint of their items (content-addressed already)."""
    path = getattr(tap, "path", None)
    if isinstance(path, str):
        window = max(1 << 16, int(settings.reuse_chunk_bytes))
        files = []
        for p, size in sorted(_inputs.iter_files(path)):
            files.append([p, int(size), _file_chunk_hashes(p, window)])
        return {"kind": "path",
                "chunk_size": int(getattr(tap, "chunk_size", 0) or 0),
                "chunk_bytes": window,
                "files": files}
    items = getattr(tap, "items", None)
    if items is not None:
        return {"kind": "mem", "fp": _resume._fp(items),
                "partitions": int(getattr(tap, "partitions", 0) or 0)}
    return None


def signature_digest(sig):
    """One chained-fingerprint part summarizing a signature.  Paths are
    deliberately EXCLUDED for path taps: file order and bytes decide
    record content (keys are file-relative offsets), so the same corpus
    under a renamed directory still hits.  ``chunk_size`` stays in —
    it shapes combiner chunking, hence partial-fold block content."""
    if sig is None:
        return _resume._volatile()
    if sig.get("kind") == "path":
        return _resume._h(
            "sig-path", sig["chunk_size"],
            tuple((int(size), tuple(hashes))
                  for _p, size, hashes in sig["files"]))
    if sig.get("kind") == "mem":
        return _resume._h("sig-mem", sig["fp"], sig["partitions"])
    return _resume._volatile()


def signature_delta(cached, current):
    """Whole-new-files delta between two path signatures.

    Returns ``[(path, size), ...]`` — the files in ``current`` with no
    byte-identical counterpart in ``cached`` — ONLY when every cached
    file survives unchanged (matched as a multiset of (size, chunk
    hashes), so renames still count as unchanged).  Returns None when
    the growth is not append-only: a cached file that grew, shrank,
    changed, or vanished forces full recompute — a grown text file is
    never safe to re-chunk incrementally, because the old final chunk's
    line-boundary contract would make it read INTO the appended bytes.
    """
    if not cached or not current:
        return None
    if cached.get("kind") != "path" or current.get("kind") != "path":
        return None
    if cached.get("chunk_size") != current.get("chunk_size"):
        return None
    if cached.get("chunk_bytes") != current.get("chunk_bytes"):
        return None
    pool = collections.Counter(
        (int(size), tuple(hashes)) for _p, size, hashes in cached["files"])
    new = []
    for p, size, hashes in current["files"]:
        ident = (int(size), tuple(hashes))
        if pool.get(ident):
            pool[ident] -= 1
        else:
            new.append((p, int(size)))
    if any(v > 0 for v in pool.values()):
        return None  # a cached file changed or vanished: not append-only
    return new


class DeltaTap(Chunker):
    """The append-only remainder of a path tap: chunk plans for just the
    new files, with the original tap's chunk size — per-file planning
    means these chunks are bit-for-bit the chunks a cold run over the
    grown corpus would plan for the same files."""

    def __init__(self, files, chunk_size):
        self.files = list(files)
        self.chunk_size = int(chunk_size) or 64 * 1024 ** 2

    def chunks(self):
        for path, size in self.files:
            for spec in _inputs.plan_file(path, size, self.chunk_size):
                yield _inputs._spec_dataset(spec)

    def __repr__(self):
        return "DeltaTap[{} file(s)]".format(len(self.files))


# ---------------------------------------------------------------------------
# Keys
# ---------------------------------------------------------------------------

def reuse_keys(graph, salt=""):
    """``(keys, structs, sigs)`` for a graph.

    - ``keys[sid]`` — content-addressed chained key: stage body + options
      + input *content* keys, volatility propagating exactly like resume
      fingerprints.  Equal keys mean byte-equal computation.
    - ``structs[sid]`` — the same chain minus input content (tap type +
      chunk config only): equal structs with different keys mean "same
      pipeline, different data" — the incremental-candidate relation.
    - ``sigs[source]`` — the content signature per input tap source
      (None when unsignable), kept for delta detection and manifests.

    ``salt`` carries engine config shaping output layout (the partition
    count), like resume's — a cached partition set must co-partition
    with whatever consumes it."""
    from ..graph import GInput, GMap, GReduce, GSink

    keys, structs, sigs = {}, {}, {}
    src_key, src_struct = {}, {}
    _resume._tls.cache = {}  # one content hash per captured array per pass
    try:
        for sid, stage in enumerate(graph.stages):
            if isinstance(stage, GInput):
                sig = None
                try:
                    sig = content_signature(stage.tap)
                except Exception:
                    log.warning(
                        "reuse: tap %r not signable; downstream stages "
                        "are volatile for the cache",
                        type(stage.tap).__qualname__, exc_info=True)
                sigs[stage.output] = sig
                src_key[stage.output] = _resume._h(
                    "rtap", salt, signature_digest(sig))
                src_struct[stage.output] = (
                    _resume._volatile() if sig is None else _resume._h(
                        "rtap-struct", salt, type(stage.tap).__qualname__,
                        sig.get("chunk_size", sig.get("partitions", 0))))
                continue
            inputs_k = tuple(src_key.get(s, "missing") for s in stage.inputs)
            inputs_s = tuple(
                src_struct.get(s, "missing") for s in stage.inputs)
            if isinstance(stage, GMap):
                body = ("map", _resume._fp(stage.mapper),
                        _resume._fp(stage.combiner),
                        _resume._fp(stage.shuffler))
            elif isinstance(stage, GReduce):
                body = ("reduce", _resume._fp(stage.reducer))
            elif isinstance(stage, GSink):
                body = ("sink", _resume._fp(stage.sinker), stage.path)
            else:
                body = ("other", _resume._fp(stage))
            opts = _resume._fp(getattr(stage, "options", None) or {})
            # No sid in the chain (unlike resume): the chain is already
            # injective through its inputs, and position-independence is
            # what lets a shared prefix hit from a DIFFERENT pipeline.
            k = _resume._h("rstage", body, opts, inputs_k)
            s = _resume._h("rstruct", body, opts, inputs_s)
            src_key[stage.output] = k
            src_struct[stage.output] = s
            keys[sid] = k
            structs[sid] = s
    finally:
        _resume._tls.cache = None
    return keys, structs, sigs


# ---------------------------------------------------------------------------
# The shared store
# ---------------------------------------------------------------------------

def _checked_read(fn):
    """Run one cache read under the ``cache_read`` fault site.  IO
    errors and injected transient/deterministic faults surface as
    :class:`CacheEntryError` (degrade to recompute); fatal injections
    propagate — no retry layer may absorb them."""
    try:
        _faults.check("cache_read")
        return fn()
    except _faults.FatalInjectedFault:
        raise
    except (OSError, ValueError, KeyError, IndexError, TypeError,
            _faults.InjectedFault) as e:
        raise CacheEntryError("{}: {}".format(type(e).__name__, e))


def _dir_bytes(path):
    total = 0
    for d, _dirs, fs in os.walk(path):
        for f in fs:
            try:
                total += os.path.getsize(os.path.join(d, f))
            except OSError:
                pass
    return total


class CacheStore(object):
    """The on-disk shared cache: ``<root>/entries/<key>/`` holds one
    manifest.json plus that entry's block files (spill wire format —
    readers sniff, so hardlinked spill files and freshly written frames
    coexist)."""

    def __init__(self, root=None, budget=None):
        if root is None:
            root = settings.reuse_dir or os.path.join(
                settings.scratch_root, "reuse-cache")
        self.root = root
        self.budget = (settings.reuse_budget_bytes
                       if budget is None else budget)
        self.evictions = 0

    def _entries_dir(self):
        return os.path.join(self.root, "entries")

    def _entry_dir(self, key):
        return os.path.join(self._entries_dir(), key)

    def _manifest_path(self, key):
        return os.path.join(self._entry_dir(key), "manifest.json")

    @contextlib.contextmanager
    def _locked(self):
        """Exclusive flock over the whole store (publish landing +
        eviction).  Filesystems without flock degrade to lock-free —
        same rationale as resume.RunGuard: locking guards an
        optimization (space accounting), never correctness, because
        consumers hardlink before reading."""
        import fcntl

        os.makedirs(self.root, exist_ok=True)
        fd = os.open(os.path.join(self.root, ".lock"),
                     os.O_CREAT | os.O_RDWR, 0o644)
        locked = False
        try:
            try:
                fcntl.flock(fd, fcntl.LOCK_EX)
                locked = True
            except OSError:
                pass
            yield
        finally:
            try:
                if locked:
                    fcntl.flock(fd, fcntl.LOCK_UN)
            finally:
                os.close(fd)

    def lookup(self, key):
        """Validated manifest for ``key``: None = clean miss;
        :class:`CacheEntryError` = entry present but untrustworthy
        (caller records a recompute fallback).  Every block must exist
        at exactly its recorded file size — the truncation check that
        turns a half-evicted or corrupted entry into a fallback instead
        of a bad read.  A successful lookup touches the manifest mtime:
        the store's LRU clock."""
        if _resume.is_volatile(key):
            return None
        mpath = self._manifest_path(key)
        if not os.path.exists(mpath):
            return None

        def read_manifest():
            with open(mpath) as f:
                return json.load(f)

        m = _checked_read(read_manifest)
        if (not isinstance(m, dict) or m.get("schema") != SCHEMA
                or m.get("kind") != "pset" or m.get("key") != key):
            raise CacheEntryError("bad manifest for {}".format(key))
        edir = self._entry_dir(key)
        for b in m.get("blocks", ()):
            bpath = os.path.join(edir, b[1])
            try:
                fsize = os.path.getsize(bpath)
            except OSError:
                raise CacheEntryError("missing block {}".format(b[1]))
            if len(b) > 6 and b[6] and fsize != int(b[6]):
                raise CacheEntryError(
                    "truncated block {} ({} != {} bytes)".format(
                        b[1], fsize, b[6]))
        try:
            os.utime(mpath)
        except OSError:
            pass
        return m

    def lookup_struct(self, struct):
        """Newest entry sharing a *structural* key (same pipeline over
        different data) with a path-kind signature — the incremental
        candidate.  Best-effort scan; unreadable entries are skipped."""
        if _resume.is_volatile(struct):
            return None
        try:
            names = os.listdir(self._entries_dir())
        except OSError:
            return None
        best = None
        for name in names:
            if name.startswith(".tmp-"):
                continue
            try:
                with open(os.path.join(
                        self._entries_dir(), name, "manifest.json")) as f:
                    m = json.load(f)
            except (OSError, ValueError):
                continue
            if (not isinstance(m, dict) or m.get("schema") != SCHEMA
                    or m.get("struct") != struct):
                continue
            if (m.get("sig") or {}).get("kind") != "path":
                continue
            if best is None or m.get("created", 0) > best.get("created", 0):
                best = m
        return best

    def mount(self, manifest, run_store):
        """``(PartitionSet, nrec, bytes)`` backed by hardlinks into the
        RUN's scratch root — eviction (rmtree of the entry) can then
        never yank a file mid-read; the links die with the run's normal
        cleanup.  The ``.rblk`` suffix keeps resume's start-of-run
        ``gc_unreferenced`` sweep (which collects ``.blk`` orphans) off
        them."""
        from ..storage import BlockRef, PartitionSet

        edir = self._entry_dir(manifest["key"])
        mnt = os.path.join(run_store.root, "reuse", uuid.uuid4().hex)
        os.makedirs(mnt, exist_ok=True)
        flags = manifest.get("flags") or [False, False, False]
        pset = PartitionSet(manifest["n_partitions"], hash_routed=flags[0],
                            hash_sorted=flags[1], key_sorted_runs=flags[2])
        total = 0
        try:
            for i, b in enumerate(manifest["blocks"]):
                pid, fname, nrecords, nbytes, kdt, vdt = b[:6]
                src = os.path.join(edir, fname)
                dst = os.path.join(mnt, "{}.rblk".format(i))

                def link(src=src, dst=dst):
                    try:
                        os.link(src, dst)
                    except OSError as e:
                        if e.errno != errno.EXDEV:
                            raise
                        shutil.copyfile(src, dst)  # cache on another fs

                _checked_read(link)
                pset.add(pid, BlockRef.from_disk(
                    dst, nrecords, nbytes, kdt, vdt))
                total += int(b[6]) if len(b) > 6 and b[6] else int(nbytes)
        except BaseException:
            pset.delete()
            shutil.rmtree(mnt, ignore_errors=True)
            raise
        return pset, manifest["nrec"], total

    def publish(self, key, struct, result, nrec, sig, run_store):
        """Publish one stage output under ``key``; returns bytes landed
        (0 = declined, already present, or lost the race).  Blocks
        already on disk hardlink in for free; pinned refs write their
        packed stream; RAM-only blocks encode through the spill codec.
        The entry builds in a ``.tmp-`` sibling and lands with ONE
        atomic rename, so a reader can never observe a half-entry and
        concurrent publishers of the same key resolve to exactly one
        winner."""
        from ..storage import PartitionSet, save_block

        if _resume.is_volatile(key) or not isinstance(result, PartitionSet):
            return 0
        if os.path.exists(self._manifest_path(key)):
            return 0  # already published (this run or a sibling)
        tmp = os.path.join(self._entries_dir(), ".tmp-" + uuid.uuid4().hex)
        os.makedirs(tmp)
        t0 = _trace.now()
        try:
            blocks = []
            total = 0
            i = 0
            for pid in sorted(result.parts):
                for ref in result.parts[pid]:
                    fname = "b{}.frames".format(i)
                    i += 1
                    path = os.path.join(tmp, fname)
                    if ref.pin:
                        with open(path, "wb") as f:
                            f.write(ref._packed)
                    elif ref.path is not None:
                        try:
                            os.link(ref.path, path)
                        except OSError:
                            shutil.copyfile(ref.path, path)
                    else:
                        # get() covers every residency (RAM as-is, HBM
                        # via one counted fetch); ref.path stays unset —
                        # the cache copy must never be charged to (or
                        # deleted by) the run's own store.
                        save_block(ref.get(), path)
                    fsize = os.path.getsize(path)
                    total += fsize
                    blocks.append([pid, fname, ref.nrecords,
                                   int(ref.nbytes), str(ref.key_dtype),
                                   str(ref.value_dtype), int(fsize)])
            if self.budget and total > self.budget:
                shutil.rmtree(tmp, ignore_errors=True)
                return 0  # one entry over the whole budget: never fits
            manifest = {"schema": SCHEMA, "key": key, "struct": struct,
                        "kind": "pset",
                        "n_partitions": result.n_partitions,
                        "blocks": blocks, "nrec": int(nrec),
                        "flags": [bool(result.hash_routed),
                                  bool(result.hash_sorted),
                                  bool(result.key_sorted_runs)],
                        "bytes": int(total), "sig": sig,
                        "created": time.time()}
            with open(os.path.join(tmp, "manifest.json"), "w") as f:
                json.dump(manifest, f)
            with self._locked():
                try:
                    os.rename(tmp, self._entry_dir(key))
                except OSError:
                    # Concurrent publisher won the rename: their entry
                    # is byte-equivalent by construction (same key).
                    shutil.rmtree(tmp, ignore_errors=True)
                    return 0
                self.evict_to_budget(locked=True)
            _trace.complete("reuse", "publish", t0, bytes=total,
                            blocks=len(blocks))
            return total
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise

    def evict_to_budget(self, locked=False):
        """Remove least-recently-consumed whole entries until the store
        fits the byte budget; ``(entries_evicted, bytes_freed)``.  The
        LRU clock is the manifest mtime (touched by every successful
        lookup).  Unreadable/half-built entries sort oldest — they are
        garbage either way."""
        if not locked:
            with self._locked():
                return self.evict_to_budget(locked=True)
        ed = self._entries_dir()
        try:
            names = os.listdir(ed)
        except OSError:
            return 0, 0
        entries = []
        total = 0
        for name in names:
            if name.startswith(".tmp-"):
                continue
            mpath = os.path.join(ed, name, "manifest.json")
            try:
                mtime = os.stat(mpath).st_mtime
                with open(mpath) as f:
                    nbytes = int(json.load(f).get("bytes") or 0)
            except (OSError, ValueError):
                mtime, nbytes = 0.0, _dir_bytes(os.path.join(ed, name))
            entries.append((mtime, name, nbytes))
            total += nbytes
        n = freed = 0
        if self.budget:
            entries.sort()
            for _mtime, name, nbytes in entries:
                if total - freed <= self.budget:
                    break
                shutil.rmtree(os.path.join(ed, name), ignore_errors=True)
                freed += nbytes
                n += 1
        if n:
            self.evictions += n
            _trace.instant("reuse", "evict", entries=n, bytes=freed)
            log.info("reuse cache evicted %d entr%s (%d bytes) to fit "
                     "budget %d", n, "y" if n == 1 else "ies", freed,
                     self.budget)
        return n, freed

    def total_bytes(self):
        try:
            names = os.listdir(self._entries_dir())
        except OSError:
            return 0
        total = 0
        for name in names:
            mpath = os.path.join(self._entries_dir(), name, "manifest.json")
            try:
                with open(mpath) as f:
                    total += int(json.load(f).get("bytes") or 0)
            except (OSError, ValueError):
                pass
        return total


def union_psets(a, b):
    """One PartitionSet holding both sides' refs per partition.
    Provenance flags AND together — a downstream fast path may assume
    an invariant only when BOTH sides carry it.  Partition counts must
    match (the structural key salts the partition count, so an
    incremental pair always does)."""
    from ..storage import PartitionSet

    if a.n_partitions != b.n_partitions:
        raise ValueError("partition count mismatch: {} != {}".format(
            a.n_partitions, b.n_partitions))
    out = PartitionSet(
        a.n_partitions,
        hash_routed=bool(a.hash_routed and b.hash_routed),
        hash_sorted=bool(a.hash_sorted and b.hash_sorted),
        key_sorted_runs=bool(a.key_sorted_runs and b.key_sorted_runs))
    for src in (a, b):
        for pid in src.parts:
            for ref in src.parts[pid]:
                out.add(pid, ref)
    return out


# ---------------------------------------------------------------------------
# Incremental-merge eligibility
# ---------------------------------------------------------------------------

def incremental_eligible(graph, sid, manifest, outputs):
    """``(ok, reason)`` — may stage ``sid``'s cached output union with a
    delta re-run over just the new files?

    A map with NO combiner is exact unconditionally: per-file chunk
    planning makes the delta's chunks identical to the cold run's, so
    cached + fresh is the same record multiset, block layout aside.

    A map WITH a combiner produced partition-local *partials* whose
    grouping depends on chunk-to-job assignment; cached + fresh partials
    only converge with the cold run after the downstream fold.  That is
    exact when every consumer is a fold whose binop
    :mod:`~dampr_tpu.analyze.assoc` certifies associative ("yes" tier
    only — the kernel-contract kinds), excluding order-sensitive
    ``first`` and float sums/pair-sums (reordered float addition is not
    byte-identical); and the partials themselves must not be a
    requested output."""
    from ..graph import GInput, GMap, GReduce

    stage = graph.stages[sid]
    if not isinstance(stage, GMap):
        return False, "not-a-map"
    if len(stage.inputs) != 1:
        return False, "multi-input"
    producers = {s.output: s for s in graph.stages}
    if not isinstance(producers.get(stage.inputs[0]), GInput):
        return False, "input-not-a-tap"
    combined = (stage.combiner is not None
                or "binop" in (stage.options or {}))
    if not combined:
        return True, None
    if stage.output in outputs:
        return False, "partials-requested-as-output"
    binops = []
    if "binop" in (stage.options or {}):
        binops.append(stage.options["binop"])
    for consumer in graph.stages:
        if stage.output not in getattr(consumer, "inputs", ()):
            continue
        if not isinstance(consumer, GReduce):
            return False, "partials-consumed-by-non-fold"
        b = (consumer.options or {}).get("binop")
        if b is None:
            return False, "consumer-fold-unrecognized"
        binops.append(b)
    from ..analyze import assoc as _assoc

    vdts = [str(b[5]) for b in manifest.get("blocks", ())]
    for b in binops:
        try:
            v = _assoc.classify_binop(b)
        except Exception:
            return False, "fold-classification-failed"
        if v.get("assoc") != "yes":
            return False, "fold-not-certified-associative"
        if v.get("kind") == "first":
            return False, "first-fold-order-sensitive"
        if (v.get("kind") in ("sum", "pair_sum")
                and any(s.startswith("float") for s in vdts)):
            return False, "float-sum-reorder"
    return True, None


# ---------------------------------------------------------------------------
# The per-run coordinator
# ---------------------------------------------------------------------------

class RunReuse(object):
    """One run's reuse decisions, made eagerly BEFORE the stage walk.

    Mounting happens at plan time: a hit only skips its upstream prefix
    if the mount already succeeded, so a corrupted entry degrades to a
    normal recompute while every input is still scheduled — there is no
    dead-end where the prefix was skipped and the mount then fails.
    ``summary`` is the live dict the runner attaches as
    ``stats()["reuse"]``."""

    def __init__(self, runner, outputs):
        self.runner = runner
        self.cache = CacheStore()
        self.mounted = {}      # sid -> (pset, nrec, manifest)
        self.incremental = {}  # sid -> (pset, nrec, manifest, delta, sig)
        self.published = set()
        self.decisions = {}
        self.summary = {
            "enabled": True,
            "cache_dir": self.cache.root,
            "hits": 0, "misses": 0, "stages_skipped": 0,
            "bytes_mounted": 0, "bytes_published": 0,
            "incremental_merges": 0, "recompute_fallbacks": 0,
            "evictions": 0, "decisions": [],
        }
        salt = "p{}".format(runner.n_partitions)
        self.keys, self.structs, self.sigs = reuse_keys(runner.graph, salt)

    # -- planning ------------------------------------------------------------

    def plan(self, outputs, satisfied=()):
        """Decide hit / incremental / miss per needed stage, deepest
        first — a hit prices and mounts immediately; its whole prefix
        then drops out of the need-set.  ``satisfied`` carries resume's
        restorable checkpoint sids (a same-run checkpoint restore beats
        a cache mount: it is local and already validated)."""
        from ..graph import GInput, GSink

        graph = self.runner.graph
        t0 = _trace.now()
        hist = self._history_seconds()
        needed = set(outputs)
        for sid in range(len(graph.stages) - 1, -1, -1):
            stage = graph.stages[sid]
            if isinstance(stage, GInput):
                continue
            if stage.output not in needed and not isinstance(stage, GSink):
                continue
            if sid in satisfied:
                self.decisions[sid] = "resume-restored"
                continue
            if isinstance(stage, GSink):
                # Sink outputs are durable user files, not partition
                # frames: never cached, inputs always needed.
                needed.update(stage.inputs)
                continue
            key = self.keys.get(sid)
            if key is None or _resume.is_volatile(key):
                self.decisions[sid] = "volatile"
                needed.update(stage.inputs)
                continue
            if self._try_hit(sid, stage, key, hist):
                continue  # mounted: prefix not needed
            if self._try_incremental(sid, stage, outputs):
                continue  # delta re-run reads only its tap
            self.decisions.setdefault(sid, "miss")
            needed.update(stage.inputs)
        self.summary["decisions"] = self._decisions_list()
        rep = self.runner.plan_report
        if isinstance(rep, dict):
            # The plan report's reuse section: what explain() renders.
            rep["reuse"] = {"cache_dir": self.cache.root,
                            "decisions": self._decisions_list()}
        _trace.complete(
            "reuse", "plan", t0, hits=self.summary["hits"],
            incremental=len(self.incremental),
            fallbacks=self.summary["recompute_fallbacks"])

    def _try_hit(self, sid, stage, key, hist):
        try:
            m = self.cache.lookup(key)
        except CacheEntryError as e:
            self.summary["recompute_fallbacks"] += 1
            self.decisions[sid] = "fallback:" + str(e)[:120]
            return False
        if m is None:
            self.summary["misses"] += 1
            return False
        if not self._worth_mounting(sid, m, hist):
            self.decisions[sid] = "recompute-cheaper"
            return False
        try:
            pset, nrec, nbytes = self.cache.mount(m, self.runner.store)
        except CacheEntryError as e:
            self.summary["recompute_fallbacks"] += 1
            self.decisions[sid] = "fallback:" + str(e)[:120]
            return False
        self.mounted[sid] = (pset, nrec, m)
        self.summary["hits"] += 1
        self.summary["bytes_mounted"] += nbytes
        self.decisions[sid] = "hit"
        return True

    def _try_incremental(self, sid, stage, outputs):
        if len(stage.inputs) != 1:
            return False
        cur_sig = self.sigs.get(stage.inputs[0])
        if cur_sig is None or cur_sig.get("kind") != "path":
            return False
        struct = self.structs.get(sid)
        m = self.cache.lookup_struct(struct)
        if m is None or m.get("key") == self.keys.get(sid):
            return False
        delta = signature_delta(m.get("sig"), cur_sig)
        if not delta:
            self.decisions[sid] = "incremental-ineligible:not-append-only"
            return False
        ok, reason = incremental_eligible(
            self.runner.graph, sid, m, outputs)
        if not ok:
            self.decisions[sid] = "incremental-ineligible:" + reason
            return False
        try:
            valid = self.cache.lookup(m["key"])
            if valid is None:
                return False
            pset, nrec, nbytes = self.cache.mount(valid, self.runner.store)
        except CacheEntryError as e:
            self.summary["recompute_fallbacks"] += 1
            self.decisions[sid] = "fallback:" + str(e)[:120]
            return False
        self.incremental[sid] = (pset, nrec, m, delta, cur_sig)
        self.summary["bytes_mounted"] += nbytes
        self.decisions[sid] = "incremental:{}-new-file(s)".format(len(delta))
        return True

    # -- pricing -------------------------------------------------------------

    def _history_seconds(self):
        """{sid: measured seconds} from the shape-matched run-history
        corpus; empty when no usable evidence exists (mounting is then
        the default — hardlinks are near-free)."""
        try:
            from . import cost as _cost

            hist = _cost.matched_history(self.runner.name,
                                         self.runner.graph)
            if not hist:
                return {}
            return {int(st["stage"]): float(st.get("seconds") or 0.0)
                    for st in hist.get("stages") or ()
                    if st.get("stage") is not None}
        except Exception:
            return {}

    def _worth_mounting(self, sid, manifest, hist):
        """Mount unless the corpus proves recomputing the whole prefix
        is cheaper than reading the cached bytes back (tiny stages over
        fast recompute paths).  Mount cost model: per-block open/link
        overhead + bytes at disk stream rate."""
        if not hist:
            return True
        mount_cost = (0.002 * len(manifest.get("blocks") or ())
                      + (manifest.get("bytes") or 0) / 2e9)
        graph = self.runner.graph
        producers = {s.output: i for i, s in enumerate(graph.stages)}
        seen, stack, prefix_cost = set(), [sid], 0.0
        while stack:
            s = stack.pop()
            if s in seen:
                continue
            seen.add(s)
            prefix_cost += hist.get(s, 0.0)
            for inp in graph.stages[s].inputs:
                p = producers.get(inp)
                if p is not None:
                    stack.append(p)
        return mount_cost < prefix_cost + 0.05

    # -- the stage walk's hooks ----------------------------------------------

    def handles(self, sid):
        return sid in self.mounted or sid in self.incremental

    def apply(self, sid, stage, env):
        """Produce the stage result without full execution: install the
        mounted frames, or run the delta and union.  Returns ``(result,
        nrec, kind)`` with kind "reused" | "incremental"."""
        t0 = _trace.now()
        if sid in self.mounted:
            pset, nrec, m = self.mounted.pop(sid)
            self.summary["stages_skipped"] += 1
            _trace.complete("reuse", "mount:s{}".format(sid), t0,
                            blocks=len(m.get("blocks", ())), records=nrec)
            return pset, nrec, "reused"
        pset, nrec, m, delta, cur_sig = self.incremental.pop(sid)
        denv = {stage.inputs[0]: DeltaTap(
            delta, cur_sig.get("chunk_size") or 0)}
        try:
            fresh, fresh_nrec, _njobs = self.runner.run_map(
                sid, stage, denv)
        except BaseException:
            # The mounted half must not leak its scratch hardlinks when
            # the delta re-run fails and the stage recomputes in full.
            try:
                pset.delete(self.runner.store)
            except Exception:
                log.warning("reuse: mounted pset cleanup failed",
                            exc_info=True)
            raise
        merged = union_psets(pset, fresh)
        total = int(nrec) + int(fresh_nrec)
        self.summary["incremental_merges"] += 1
        _trace.complete("reuse", "incremental:s{}".format(sid), t0,
                        new_files=len(delta), cached_records=nrec,
                        fresh_records=fresh_nrec)
        # The merged output IS this run's full-key materialization:
        # publish it so the next identical run takes the full-hit path.
        self.maybe_publish(sid, stage, merged, total)
        return merged, total, "incremental"

    def note_fallback(self, sid):
        for table in (self.mounted, self.incremental):
            entry = table.pop(sid, None)
            if entry is not None:
                try:
                    entry[0].delete(self.runner.store)
                except Exception:
                    log.warning("reuse: mounted pset cleanup failed",
                                exc_info=True)
        self.summary["recompute_fallbacks"] += 1
        self.decisions[sid] = "fallback:apply-failed"
        self.summary["decisions"] = self._decisions_list()

    def maybe_publish(self, sid, stage, result, nrec):
        """Publish an executed stage's output, unless this run must not
        seed the shared cache: an active injected-fault plan (chaos
        results are for chaos runs) or quarantined records (lossy
        results) both gate publishing off — lookups stay allowed."""
        from ..graph import GSink
        from ..storage import PartitionSet

        if isinstance(stage, GSink) or not isinstance(result, PartitionSet):
            return
        key = self.keys.get(sid)
        if key is None or _resume.is_volatile(key) or key in self.published:
            return
        if _faults.active() is not None:
            return
        q = self.runner._quarantine
        if q is not None and q.count:
            return
        sig = (self.sigs.get(stage.inputs[0])
               if len(stage.inputs) == 1 else None)
        try:
            n = self.cache.publish(key, self.structs.get(sid), result,
                                   nrec, sig, self.runner.store)
        except Exception:
            log.warning("reuse: publish failed for stage %s (run "
                        "unaffected)", sid + 1, exc_info=True)
            return
        self.published.add(key)
        if n:
            self.summary["bytes_published"] += n
        self.summary["evictions"] = self.cache.evictions

    def _decisions_list(self):
        return [{"stage": sid, "decision": d}
                for sid, d in sorted(self.decisions.items())]
