"""Logical plan optimizer: the layer between graph construction and the
runner.

The DSL (:mod:`dampr_tpu.dampr`) compiles every chained call into its own
:class:`~dampr_tpu.graph.StageNode` — ``construction order is the
schedule`` — so an unoptimized ``memory(xs).map(f).map_values(g).filter(h)
.fold_by(k, op)`` would pay a full materialize boundary per call.  This
package rewrites the stage list before execution:

- :mod:`.ir` — plan-level views over the :class:`~dampr_tpu.graph.Graph`:
  consumer maps, mapper-chain flattening/composition, barrier detection,
  structural signatures (the idempotence witness).
- :mod:`.passes` — the pass pipeline: **map fusion** (chains of pure
  per-record ``GMap`` stages whose intermediate Source has a single
  consumer collapse into one composed mapper, preserving the tail's
  combiner/shuffler), **combiner hoisting** (an identity stage that only
  carries a map-side combiner folds into its producer), **sink fusion**
  (pure record chains compose into the sinker), and **dead-stage
  elimination** (stages unreachable from any requested output or sink are
  dropped).
- :mod:`.cost` — stats-driven adaptation: prior-run ``stats.json``
  summaries (per-stage records/bytes) size the run's partition count and
  per-stage block batch sizes, with safe static defaults when no history
  exists or the plan shape changed.
- :mod:`.explain` — the ``PBase.explain()`` surface: renders the
  before/after plan with fusion decisions and cost annotations.

Every rewrite is value-semantic: shared ``StageNode`` objects are never
mutated (handles stay freely shareable); changed stages are fresh nodes.

Wiring: ``dampr.py`` ``run()`` and ``MTRunner.run()`` both call
:func:`apply_to_runner` (idempotent — first caller wins), gated by
``settings.optimize`` (env ``DAMPR_TPU_OPTIMIZE``) with per-rule kill
switches (``settings.plan_fuse`` / ``plan_fuse_sinks`` / ``plan_dead`` /
``plan_adapt``).  The runner emits a ``plan`` trace span and a ``plan``
section in ``em.stats()`` describing stages before/after and the rules
that fired.  See ``docs/plan.md``.
"""

import time

from .. import settings
from . import cost, explain, ir, lower, passes, pipeline
from .explain import explain_text
from .ir import graph_signature
from .passes import optimize

__all__ = ["optimize", "apply_to_runner", "explain_text", "graph_signature",
           "ir", "passes", "cost", "explain", "lower", "pipeline"]


def empty_report(graph, enabled):
    n = ir.executed_stage_count(graph)
    return {
        "enabled": enabled,
        "stages_before": n,
        "stages_after": n,
        "rules": {"fuse_maps": 0, "hoist_combiners": 0, "fuse_sinks": 0,
                  "dead_stages": 0},
        "fused": [],
        "dead": [],
        "adaptive": {"applied": False, "reason": "disabled"},
        "cost": cost.empty_cost_section("optimizer off"),
        "lowering": lower.empty_section(False),
        "shuffle": lower.empty_shuffle_section(False),
        "analysis": {"enabled": False, "stages": [], "diagnostics": [],
                     "counts": {"error": 0, "warn": 0, "info": 0}},
        "device_stages": 0,
        "seconds": 0.0,
    }


def apply_to_runner(runner, outputs):
    """Optimize ``runner.graph`` in place for the requested ``outputs`` and
    attach the plan report as ``runner.plan_report``.

    Idempotent: a runner that already carries a report is left alone, so
    the DSL entry points and ``MTRunner.run`` can both invoke it without
    double-rewriting.  Duck-typed (needs ``.graph``; everything else is
    ``getattr`` with defaults) so custom runner classes keep working.
    Returns the report (or None when the runner has no graph).
    """
    if getattr(runner, "plan_report", None) is not None:
        return runner.plan_report
    graph = getattr(runner, "graph", None)
    if graph is None or not hasattr(graph, "stages"):
        return None
    t0 = time.perf_counter()
    if not settings.optimize:
        report = empty_report(graph, enabled=False)
    else:
        graph, report = optimize(graph, outputs)
        runner.graph = graph
        cost.adapt(runner, graph, report)
        # Learned-cost-model layer (plan/model.py): prices this plan
        # with per-operator fits over the corpus and may override the
        # median sizing; every choice + predicted-vs-static delta lands
        # in report["cost"].  DAMPR_TPU_COST_MODEL=0 records the kill
        # switch and leaves the median decisions untouched.
        cost.apply_model(runner, graph, report)
    # Device lowering runs on BOTH legs (a placement decision over
    # whatever stage list executes, not a graph-shape rewrite): assign
    # each stage its execution target, stats history pinning tiny stages
    # to host.
    lower.apply(runner, outputs, report)
    # Host-vs-mesh shuffle routing for the redistribution stages the
    # lowering pass left on host: a plan-level choice (explicit settings
    # win, auto decides from the history corpus) the runner's dispatch
    # consults when it exchanges partitions.
    lower.apply_shuffle(runner, report)
    # Streamed-edge analysis (plan/pipeline.py): which stage barriers the
    # pipelined executor may dissolve, decided over the stage list that
    # will EXECUTE (after fusion/lowering/shuffle routing, on both
    # optimizer legs).  Decisions land in report["pipeline"] always;
    # runner dispatch hints only when settings.pipeline is on.
    pipeline.apply(runner, outputs, report)
    # Static analysis (dampr_tpu.analyze, settings.analyze): per-stage
    # purity/determinism verdicts + coded diagnostics over the stage
    # list that will EXECUTE, recorded in the report's "analysis"
    # section (rendered by explain(), shipped in stats()["plan"]).
    # Fast bytecode-only classification here — the pickle probe and the
    # randomized associativity probe run from validate()/lint (and the
    # multi-process pre-flight check), not on every run.
    if settings.analyze:
        from ..analyze import validate as _av

        try:
            report["analysis"] = _av.report_section(
                getattr(runner, "graph", graph),
                probe_traceable=settings.lower_enabled())
        except Exception:  # noqa: BLE001 - analysis never fails a run
            report["analysis"] = _av.empty_section()
    else:
        report["analysis"] = {
            "enabled": False, "stages": [], "diagnostics": [],
            "counts": {"error": 0, "warn": 0, "info": 0}}
    # Shape records ride into stats.json so the NEXT run's cost layer can
    # match its plan against this run's measurements.
    report["stage_shapes"] = ir.stage_shapes(getattr(runner, "graph", graph))
    report["seconds"] = round(time.perf_counter() - t0, 6)
    try:
        runner.plan_report = report
    except AttributeError:
        pass
    return report
