"""Render a pipeline's logical plan: before/after stage lists, the fusion
decisions, and the cost layer's adaptive inputs.

Surfaced as ``PBase.explain()`` and the ``--explain`` flag on the
``dampr-tpu-wc`` / ``dampr-tpu-tfidf`` CLIs.  Pure rendering — running
``explain()`` never executes the pipeline and never mutates the handle's
graph (``optimize`` is value-semantic).
"""

from .. import settings
from ..graph import GInput
from . import cost, ir, lower, passes, pipeline as _pipeline


def _stage_lines(graph, indent="  "):
    pos = {s.output: i for i, s in enumerate(graph.stages)}
    lines = []
    for i, stage in enumerate(graph.stages):
        srcs = ", ".join("s{}".format(pos.get(s, "?")) for s in stage.inputs)
        arrow = " <- {}".format(srcs) if srcs else ""
        tag = "" if not isinstance(stage, GInput) else "  (free)"
        lines.append("{}s{}: {}{}{}".format(
            indent, i, ir.describe_stage(stage), arrow, tag))
    return lines


def explain_text(graph, outputs, name=None):
    """The plan report as display text.  ``name`` (a run name) pulls that
    run's stats history so the adaptive annotations show what the cost
    layer WOULD use."""
    lines = []
    n_before = ir.executed_stage_count(graph)
    lines.append("== logical plan ({} stages, {} executed) =="
                 .format(len(graph.stages), n_before))
    lines.extend(_stage_lines(graph))
    if not settings.optimize:
        lines.append("optimizer OFF (settings.optimize / "
                     "DAMPR_TPU_OPTIMIZE=0): the plan above executes as-is")
        lines.extend(_target_lines(graph, name, outputs))
        lines.extend(_shuffle_lines(graph, name, outputs))
        lines.extend(_pipeline_lines(graph, outputs))
        lines.extend(_analysis_lines(graph))
        lines.extend(_reuse_lines(graph))
        return "\n".join(lines)
    optimized, report = passes.optimize(graph, outputs)
    lines.append("== optimized plan ({} executed) =="
                 .format(report["stages_after"]))
    lines.extend(_stage_lines(optimized))
    fired = {k: v for k, v in sorted(report["rules"].items()) if v}
    lines.append("rules fired: {}".format(
        ", ".join("{}={}".format(k, v) for k, v in fired.items())
        if fired else "none (plan already minimal)"))
    for f in report["fused"]:
        lines.append("  {}: {}  =>  {}".format(
            f["rule"], "  +  ".join(f["members"]), f["into"]))
    for d in report["dead"]:
        lines.append("  dead: {}".format(d))
    # Adaptive annotations (best-effort; needs prior finalized runs —
    # the history corpus, or a traced run's stats.json as fallback).
    if not settings.plan_adapt:
        lines.append("adaptive: off (settings.plan_adapt)")
    else:
        hist, reason = (cost.corpus_history(name, optimized)
                        if name else (None, "no-history"))
        if hist is None:
            what = ("history shape mismatch"
                    if reason == "shape-mismatch" else "no history{}".format(
                        " for run {!r}".format(name) if name else ""))
            lines.append("adaptive: {} — static defaults "
                         "(partitions={}, batch_size={})".format(
                             what, settings.partitions,
                             settings.batch_size))
        else:
            n = hist.get("history_entries", 1)
            lines.append("adaptive: history {} ({} stages measured{})"
                         .format(hist.get("stats_file") or name,
                                 len(hist.get("stages", [])),
                                 ", median over {} runs".format(n)
                                 if n >= 3 else ""))
            for st in hist.get("stages", []):
                lines.append(
                    "    s{}: {}  {} rec / {} B out".format(
                        st.get("stage"), st.get("kind"),
                        st.get("records_out"), st.get("bytes_out")))
    lines.extend(_cost_lines(optimized, name))
    lines.extend(_target_lines(optimized, name, outputs))
    lines.extend(_shuffle_lines(optimized, name, outputs))
    lines.extend(_pipeline_lines(optimized, outputs))
    lines.extend(_analysis_lines(optimized))
    lines.extend(_reuse_lines(optimized))
    return "\n".join(lines)


def _pipeline_lines(graph, outputs=()):
    """The streamed-edge decision table (plan/pipeline.py): which stage
    barriers the pipelined executor dissolves and why the rest stay."""
    decisions = _pipeline.analyze(graph, outputs)
    if not decisions:
        return []
    n_str = sum(1 for d in decisions if d["decision"] == "streamed")
    state = ("on" if settings.pipeline_enabled()
             else "OFF (settings.pipeline / DAMPR_TPU_PIPELINE=0 — "
                  "staged execution)")
    lines = ["pipeline: {} of {} stage edge(s) streamed — {}".format(
        n_str, len(decisions), state)]
    for d in decisions:
        dst = "s{}".format(d["dst"]) if d["dst"] is not None else "read"
        what = ("streamed[{}]".format(d["mode"])
                if d["decision"] == "streamed" else "barrier")
        lines.append("  s{} -> {}: {}  ({})".format(
            d["src"], dst, what, d["reason"]))
    return lines


def _analysis_lines(graph):
    """The static analyzer's verdict summary (dampr_tpu.analyze): one
    property line per executed stage plus every coded diagnostic —
    the same records the run ships in ``stats()["plan"]["analysis"]``."""
    if not settings.analyze:
        return ["analysis: off (settings.analyze / DAMPR_TPU_ANALYZE=0)"]
    from ..analyze import validate as _av

    sec = _av.report_section(graph,
                             probe_traceable=settings.lower_enabled())
    c = sec["counts"]
    lines = ["analysis: {} stage(s) classified — {} error(s), {} "
             "warning(s), {} info".format(
                 len(sec["stages"]), c["error"], c["warn"], c["info"])]
    for st in sec["stages"]:
        marks = []
        if not st["pure"]:
            marks.append("impure")
        if not st["deterministic"]:
            marks.append("nondet")
        fold = st.get("fold_assoc")
        if fold is not None:
            marks.append("fold-assoc:" + fold["assoc"])
        if st.get("traceable"):
            marks.append("jax-traceable (certified)")
        lines.append("  s{}: {}  [{}]".format(
            st["sid"], st["stage"],
            ", ".join(marks) if marks else "pure, deterministic"))
    for d in sec["diagnostics"]:
        lines.append("  {}: {} s{} {}".format(
            d["severity"], d["code"], d["sid"], d["message"]))
        for e in d["evidence"][:3]:
            lines.append("      - {}".format(e))
    return lines


def _reuse_lines(graph):
    """Cross-run materialization cache preview (docs/reuse.md): a
    READ-ONLY consult of the shared cache with the same key derivation
    the runner plans with — which stages would mount, which would miss.
    Best-effort: the preview keys with the static ``settings.partitions``
    salt (a run that overrides ``n_partitions`` keys differently), and
    any cache error degrades to a one-line note, never an exception."""
    if not settings.reuse_enabled():
        return ["reuse: off (settings.reuse / DAMPR_TPU_REUSE) — every "
                "run recomputes from its inputs"]
    from ..graph import GSink
    from . import reuse as _reuse

    try:
        keys, _structs, _sigs = _reuse.reuse_keys(
            graph, "p{}".format(settings.partitions))
        cache = _reuse.CacheStore()
        lines = ["reuse: cache {} ({:.1f} MB used, budget {:.1f} MB)"
                 .format(cache.root, cache.total_bytes() / 1e6,
                         cache.budget / 1e6)]
        for sid, stage in enumerate(graph.stages):
            if isinstance(stage, (GInput, GSink)):
                continue
            if _reuse._resume.is_volatile(keys[sid]):
                lines.append("  s{}: volatile (never cached)".format(sid))
                continue
            try:
                hit = cache.lookup(keys[sid]) is not None
            except _reuse.CacheEntryError:
                lines.append("  s{}: corrupt entry (would recompute)"
                             .format(sid))
                continue
            lines.append("  s{}: {}".format(
                sid, "cached (would mount)" if hit else "miss"))
        return lines
    except Exception as exc:  # pure preview: never break explain()
        return ["reuse: preview unavailable ({})".format(exc)]


def _cost_lines(graph, name):
    """The learned cost model's decision trace (docs/tuning.md),
    rendered from the SAME ``cost.model_view`` pipeline apply_model
    decides with — the preview and the decision cannot drift."""
    if not settings.cost_model_enabled():
        return ["cost model: off (settings.cost_model / "
                "DAMPR_TPU_COST_MODEL=0) — median-path adaptation only"]
    if not settings.plan_adapt:
        return ["cost model: plan_adapt off — no history-driven "
                "decisions"]
    if not name:
        return ["cost model: no run name — nothing learned yet"]
    view = cost.model_view(name, graph)
    m = view["model"]
    if m is None:
        return ["cost model: empty corpus for run {!r} — static "
                "defaults stand".format(name)]
    lines = ["cost model: {} corpus record(s), {} operator class(es) "
             "fit".format(m.n_records, len(m.fits))]
    for cls, f in sorted(m.fits.items()):
        d = f.to_dict()
        lines.append("  {:<9} {:>8} s/MB  {:>8} s/job  ({} pts, "
                     "r2 {})".format(cls, d["secs_per_mb"],
                                     d["secs_per_job"], d["points"],
                                     d["r2"]))
    if not view["ok"]:
        lines.append("  abstaining: {}".format(view["reason"]))
        return lines
    ch = view["partition_choice"]
    if ch is not None:
        lines.append("  n_partitions: {} -> {}  (predicted {}s vs "
                     "static {}s)".format(
                         ch["static"], ch["chosen"],
                         ch["predicted_seconds"], ch["static_seconds"]))
    for c in view["variance_choices"]:
        if c.get("chosen") != c.get("static"):
            lines.append("  {}: {!r} -> {!r}  ({})".format(
                c["knob"], c["static"], c["chosen"], c["reason"]))
    tuned = view["tuned"]
    if tuned:
        stale = tuned.get("fingerprint") not in (None,
                                                 view["fingerprint"])
        lines.append("  autotuned winner on file: {} (session {!r}{})"
                     .format(tuned.get("knobs"), tuned.get("session"),
                             " — STALE: different plan shape, not "
                             "applied" if stale else ""))
    return lines


def _shuffle_lines(graph, name, outputs=()):
    """Host-vs-mesh routing for the plan's redistribution stages (the
    cost layer's shuffle choice): which exchanges ride the HBM-budgeted
    collective and why the rest keep the host shuffle.  Mirrors
    ``lower.apply_shuffle`` exactly — device-lowered reduces are
    reported as target=device, not as routed exchanges."""
    mode = str(settings.mesh_exchange).lower()
    if mode in ("off", "0", "false") or not settings.use_device:
        return ["shuffle: mesh exchange off (settings.mesh_exchange={!r}; "
                "every redistribution on the host shuffle)".format(
                    settings.mesh_exchange)]
    n_dev = (settings.device_count_for_auto()
             if mode not in ("on", "1", "true") else 2)
    device_sids = set()
    if settings.lower_enabled():
        hist_l = (cost.matched_history(name, graph)
                  if name and not settings.lower_forced() else None)
        device_sids = {
            d["sid"] for d in lower.analyze(graph, hist_l, outputs)
            if d["target"] == "device" and d["kind"] == "reduce"}
    decisions = lower.shuffle_analyze(
        graph, cost.matched_history(name, graph) if name else None,
        n_dev, settings.partitions, device_sids,
        model=cost.current_model(name, graph) if name else None)
    if not decisions:
        return []
    n_mesh = sum(1 for d in decisions if d["target"] == "mesh")
    lines = ["shuffle: {} of {} redistribution stage(s) routed over the "
             "mesh exchange (hbm budget {})".format(
                 n_mesh, len(decisions), settings.exchange_hbm_budget)]
    for d in decisions:
        lines.append("  s{}: {} shuffle -> {}  ({})".format(
            d["sid"], d["kind"], d["target"], d["reason"]))
    return lines


def _target_lines(graph, name, outputs=()):
    """Per-stage execution targets (the device-lowering pass): which
    stages compile to jitted device programs and why the rest stay host."""
    lines = []
    if not settings.lower_enabled():
        lines.append("targets: device lowering off (settings.lower={!r}; "
                     "every stage executes on host)".format(settings.lower))
        return lines
    decisions = lower.analyze(
        graph,
        (cost.matched_history(name, graph)
         if name and not settings.lower_forced() else None),
        outputs)
    n_dev = sum(1 for d in decisions if d["target"] == "device")
    lines.append("targets: {} of {} executed stages lowered to device "
                 "programs".format(n_dev, len(decisions)))
    for d in decisions:
        lines.append("  s{}: {} -> {}  ({})".format(
            d["sid"], d["kind"], d["target"], d["reason"]))
    # Cross-stage fusion: which device->device edges keep their lowered
    # dataflow HBM-resident (plan.lower.handoff_analyze — the runner
    # threads those producers' program outputs straight into the
    # consuming collective fold).
    edges = lower.handoff_analyze(graph, decisions, run_name=name)
    if edges:
        n_hand = sum(1 for e in edges if e["handoff"] == "device")
        lines.append("handoff: {} of {} device edge(s) stay "
                     "HBM-resident across the stage boundary".format(
                         n_hand, len(edges)))
        for e in edges:
            lines.append("  s{} -> s{}: {}  ({})".format(
                e["src"], e["dst"], e["handoff"], e["reason"]))
    return lines
