"""Streamed-edge analysis: which producer->consumer stage edges may run
barrier-free (docs/pipeline.md).

The staged executor materializes every stage's output fully before its
consumer starts a single job.  This pass walks the stage list that will
EXECUTE (it runs after optimize/lower/shuffle on both optimizer legs) and
marks each producer->consumer edge either ``streamed`` — the runner may
dissolve the barrier — or ``barrier``, with the reason recorded either
way.  Three streamed shapes exist, each chosen only where the pipelined
result is provably byte-identical to staged execution:

- ``early_fold`` (map -> keyed fold): completed map partitions publish
  into a bounded queue and a folder thread pre-folds them under the
  consuming reduce's associative op while the map stage is still
  running.  Safe because both reduce paths emit in ascending real-key
  order after an exact hash-grouped fold, so for commutative ops
  (integer/bool sums; min/max over numeric lanes — the runtime gates
  per-block dtypes, the same exactness rule the coded exchange uses)
  regrouping partials cannot change a single output byte.
- ``chain`` (map -> map the optimizer didn't fuse): consumer jobs run
  per completed producer partition block, collected back in the staged
  job order so every downstream flat record stream sees the identical
  sequence.  Requires hash fan-out on both sides (sorted-run
  materialization stays a barrier) and a downstream free of
  boundary-sensitive consumers (reduces, sinks).
- ``merge_stream`` (spill-merge generations -> final read): the
  sorted-run final read already streams a k-way merge straight from the
  run files; the pass records the edge so the decision table is total.

Everything else keeps the barrier: explicit checkpoints and ``cached()``
pins, sort materialization, multi-consumer outputs, sinks, resume
checkpointing, device lowering/handoff, and mesh-routed exchanges.
Decisions are computed even when ``settings.pipeline`` is off (the
report's ``active`` flag records the kill switch) so ``explain()``
always shows the table.

The runner consumes the decisions as ``runner._pipeline_edges`` — a
runtime dispatch hint keyed by producer sid, deliberately NOT stage
options, so resume fingerprints stay history-independent (the
``_handoff_sids`` precedent).
"""

from .. import base, settings
from ..graph import GInput, GMap, GReduce, GSink

#: Associative-op kinds whose cross-partial regrouping is byte-exact
#: (commutative under the runtime dtype gate — see runner._StreamFolder).
SAFE_FOLD_KINDS = ("sum", "min", "max")


def _consumers(graph):
    by_output = {}
    for sid, stage in enumerate(graph.stages):
        for src in stage.inputs:
            by_output.setdefault(src, []).append(sid)
    return by_output


def _is_barrier_stage(stage):
    """Explicit checkpoint / cached() pin: the user asked for a durable
    materialization boundary here."""
    return bool(stage.options.get("barrier") or stage.options.get("memory"))


def _feeds_boundary_sensitive(graph, output, consumers, requested, seen=None):
    """True when ``output`` transitively reaches a reduce or sink through
    map stages.  Chain streaming changes block boundaries (never record
    sequences); reduces' streamed merges and sinks' part files observe
    boundaries, so any such reachable consumer keeps the barrier."""
    if seen is None:
        seen = set()
    if output in seen:
        return False
    seen.add(output)
    for sid in consumers.get(output, ()):
        stage = graph.stages[sid]
        if isinstance(stage, (GReduce, GSink)):
            return True
        if isinstance(stage, GMap) and _feeds_boundary_sensitive(
                graph, stage.output, consumers, requested, seen):
            return True
    return False


def _mesh_possible():
    """Could a mesh fold/exchange engage this run?  Conservative: any
    multi-device auto resolution (or forced-on mesh knob) bars streaming
    — the mesh paths have their own windowed overlap and their exactness
    story must not depend on pre-folded inputs."""
    if not settings.use_device:
        return False
    fold = str(settings.mesh_fold).lower()
    exch = str(settings.mesh_exchange).lower()
    if fold in ("on", "1", "true") or exch in ("on", "1", "true"):
        return True
    if fold in ("off", "0", "false") and exch in ("off", "0", "false"):
        return False
    try:
        return settings.device_count_for_auto() > 1
    except Exception:  # noqa: BLE001 - device probe never fails planning
        return True


def analyze(graph, outputs, runner=None):
    """One decision record per producer->consumer edge (plus the
    sorted-run final-read edges): ``{src, dst, output, decision, mode,
    reason}``.  ``dst`` is None for final-read edges.  Pure analysis —
    never mutates the graph or the runner."""
    consumers = _consumers(graph)
    requested = set(outputs or ())
    decisions = []
    resume_active = runner is not None and bool(getattr(runner, "resume",
                                                        False))
    handoff_sids = (getattr(runner, "_handoff_sids", None) or set()
                    if runner is not None else set())
    # Only mesh-routed redistribution bars streaming: _shuffle_targets
    # records a {sid: "mesh"|"host"} decision for EVERY redistribution
    # stage, and host routing is the ordinary staged read path.
    shuffle_sids = set(
        sid for sid, tgt in
        (getattr(runner, "_shuffle_targets", None) or {}).items()
        if tgt == "mesh") if runner is not None else set()
    mesh = _mesh_possible()

    for sid, stage in enumerate(graph.stages):
        if isinstance(stage, (GInput, GSink)):
            continue
        out = stage.output
        pin = bool(stage.options.get("memory"))
        has_combiner = (stage.combiner is not None
                        or "binop" in stage.options) \
            if isinstance(stage, GMap) else False
        sinks = consumers.get(out, [])
        sorted_run = (isinstance(stage, GMap)
                      and settings.sort_runs_enabled()
                      and not has_combiner and not pin
                      and not any(isinstance(graph.stages[c], GReduce)
                                  for c in sinks))

        def edge(dst, decision, mode, reason):
            decisions.append({
                "src": sid, "dst": dst, "output": getattr(out, "sid", out),
                "decision": decision, "mode": mode, "reason": reason})

        if not sinks:
            # Final-read edge: a requested output with no stage consumer.
            if out in requested and sorted_run:
                edge(None, "streamed", "merge_stream",
                     "spill-merge generations stream into the final "
                     "k-way merge read")
            elif out in requested:
                edge(None, "barrier", None,
                     "requested output materializes")
            continue

        for dst in sinks:
            cons = graph.stages[dst]
            if not isinstance(stage, GMap):
                edge(dst, "barrier", None, "non-map producer")
                continue
            if _is_barrier_stage(stage) or _is_barrier_stage(cons):
                edge(dst, "barrier", None,
                     "explicit checkpoint/cached materialization")
                continue
            if len(sinks) > 1:
                edge(dst, "barrier", None, "multi-consumer output")
                continue
            if out in requested:
                edge(dst, "barrier", None,
                     "requested output materializes")
                continue
            if resume_active:
                edge(dst, "barrier", None,
                     "resume checkpointing persists stage boundaries")
                continue
            if settings.reuse_enabled():
                edge(dst, "barrier", None,
                     "reuse cache may publish this edge")
                continue
            if (stage.options.get("exec_target") == "device"
                    or cons.options.get("exec_target") == "device"):
                edge(dst, "barrier", None, "device-lowered stage")
                continue
            if sid in handoff_sids or dst in handoff_sids:
                edge(dst, "barrier", None, "device handoff edge")
                continue
            if sid in shuffle_sids or dst in shuffle_sids:
                edge(dst, "barrier", None, "mesh-routed exchange")
                continue
            if mesh:
                edge(dst, "barrier", None,
                     "mesh fold/exchange may engage")
                continue

            if isinstance(cons, GReduce):
                if len(cons.inputs) != 1:
                    edge(dst, "barrier", None, "multi-input reduce (join)")
                elif pin:
                    edge(dst, "barrier", None, "memory-pinned producer")
                elif (isinstance(cons.reducer, base.AssocFoldReducer)
                      and getattr(cons.reducer.op, "kind", None)
                      in SAFE_FOLD_KINDS):
                    edge(dst, "streamed", "early_fold",
                         "associative {} fold: partials pre-fold during "
                         "the map stage (runtime gates per-block dtypes)"
                         .format(cons.reducer.op.kind))
                else:
                    edge(dst, "barrier", None,
                         "order-sensitive reduce (no commutative "
                         "associative op)")
                continue

            if isinstance(cons, GSink):
                edge(dst, "barrier", None, "sink part files materialize")
                continue

            # map -> map chain.
            if sorted_run:
                edge(dst, "barrier", None,
                     "sorted-run materialization (spill-lean external "
                     "sort)")
                continue
            if has_combiner:
                edge(dst, "barrier", None,
                     "producer compaction may re-fold partials")
                continue
            if len(cons.inputs) != 1:
                edge(dst, "barrier", None, "multi-input consumer (join)")
                continue
            if (cons.combiner is not None or "binop" in cons.options):
                edge(dst, "barrier", None, "consumer carries a combiner")
                continue
            if not base.is_pure_record_stream(cons.mapper):
                edge(dst, "barrier", None,
                     "consumer is not a pure record stream")
                continue
            if (settings.sort_runs_enabled()
                    and not bool(cons.options.get("memory"))):
                edge(dst, "barrier", None,
                     "sorted-run materialization (consumer side)")
                continue
            if _feeds_boundary_sensitive(graph, cons.output, consumers,
                                         requested):
                edge(dst, "barrier", None,
                     "downstream reduce/sink observes block boundaries")
                continue
            edge(dst, "streamed", "chain",
                 "pure record chain: consumer jobs run per completed "
                 "producer block, collected in staged order")
    return decisions


def empty_section(active):
    return {"active": bool(active), "edges": [], "streamed": 0,
            "barriers": 0}


def apply(runner, outputs, report):
    """Attach the edge decisions to the report and the runner.  The
    runner hint maps producer sid -> its streamed edge (one at most —
    multi-consumer outputs stay barriers)."""
    graph = getattr(runner, "graph", None)
    active = settings.pipeline_enabled()
    if graph is None or not hasattr(graph, "stages"):
        report["pipeline"] = empty_section(active)
        return
    try:
        decisions = analyze(graph, outputs, runner=runner)
    except Exception:  # noqa: BLE001 - planning analysis never fails a run
        report["pipeline"] = empty_section(active)
        return
    streamed = [d for d in decisions if d["decision"] == "streamed"]
    report["pipeline"] = {
        "active": active,
        "edges": decisions,
        "streamed": len(streamed),
        "barriers": len(decisions) - len(streamed),
    }
    hints = {}
    if active:
        for d in streamed:
            if d["mode"] in ("early_fold", "chain") and d["dst"] is not None:
                hints[d["src"]] = {"mode": d["mode"], "dst": d["dst"]}
    try:
        runner._pipeline_edges = hints
    except AttributeError:
        pass
