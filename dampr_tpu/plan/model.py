"""Learned per-operator throughput model over the run-history corpus.

``plan/cost.py`` adapts a run from shape-matched history — the newest
record verbatim, or per-stage medians.  That replays what happened; it
cannot answer *what would happen under different knobs*.  This module is
ROADMAP item 3's model half (the tf.data-service argument, arXiv
2210.14826: input-pipeline configuration should be learned from observed
throughput; DrJAX, arXiv 2403.07128: MapReduce primitives are fast
exactly when their tiling/sharding parameters match the workload):

- **features** (:func:`stage_features`): every corpus record yields one
  feature row per executed stage — operator class (scanner / map / fold /
  merge / exchange / device / sink, derived from the stage-shape
  provenance and recorded execution targets), bytes in/out, record
  width, job count, spill volume, measured seconds — plus the run-level
  knob snapshot the corpus already carries.
- **fit** (:func:`fit`): per operator class, a closed-form least-squares
  regressor ``seconds = secs_per_mb * MB + secs_per_job * jobs`` with a
  single robustness pass (refit once with large-residual outliers
  dropped).  No dependencies beyond the stdlib; a class participates
  only past ``settings.cost_model_min_points`` measurements.
- **search** (:func:`search`): enumerate bounded candidate values for
  each tunable knob (:data:`KNOB_BOUNDS` is the documented legal range —
  the search NEVER proposes outside it, pinned by property tests), score
  each candidate with the fitted model, and keep a change only when it
  predicts at least ``settings.cost_model_margin`` improvement.  Knobs
  the per-stage regressors cannot see (codec choice, writer threads,
  overlap depth, exchange budget) are chosen from *observed variance*:
  when the corpus holds runs of this same plan fingerprint under
  different values of a knob, the best-measured value wins; with no
  variance the static default stands and the reason says so — which is
  exactly the gap the autotune loop (:mod:`dampr_tpu.obs.autotune`)
  closes by measuring new values and writing them back into the corpus.

Everything lands in the plan report's ``cost`` section (rendered by
``explain()`` and shipped in ``stats()["plan"]``): the per-class fits,
every choice with its predicted-vs-static delta, and the fallback reason
when the model abstained.  Kill switch ``DAMPR_TPU_COST_MODEL=0``
(``settings.cost_model``) reproduces the pre-model median-path decisions
byte-identically.  See ``docs/tuning.md``.
"""

import json
import logging
import math
import os
import statistics

from .. import settings

log = logging.getLogger("dampr_tpu.plan.model")

#: Operator classes the model fits separately.  ``scanner`` = native
#: byte-scanning maps (ops.text vocabulary), ``map`` = other host maps,
#: ``fold`` = host reduces, ``merge`` = sort/merge re-key maps,
#: ``exchange`` = mesh-routed redistributions, ``device`` = lowered
#: stages, ``sink`` = sinks.
OP_CLASSES = ("scanner", "map", "fold", "merge", "exchange", "device",
              "sink")

#: Native scanner op names (provenance via the stage shape string).
_SCANNER_OPS = ("TokenCounts", "DocFreq", "ParseNumbers")

#: Documented legal range per searchable knob — the single source of
#: truth the knob search clamps against (property-pinned: no proposal
#: ever leaves these bounds).  Discrete knobs list their legal values.
KNOB_BOUNDS = {
    "n_partitions": (1, 4096),
    "batch_size": (16, 1 << 20),
    "merge_fanin": (4, 4096),
    "overlap_windows": (0, 8),
    "spill_write_threads": (0, 8),
    "spill_read_prefetch": (0, 8),
    "exchange_hbm_budget": (1 << 20, 1 << 30),
    "exchange_chunk_bytes": (0, 1 << 30),
    "spill_codec": ("auto", "raw", "zlib", "gzip", "lz4", "zstd"),
    "shuffle_target": ("host", "mesh"),
}

#: Env var per knob (the vector the autotune loop exports to trial
#: subprocesses; knobs without an env var are engine-applied only).
ENV_OF = {
    "n_partitions": None,
    "batch_size": None,
    "merge_fanin": "DAMPR_TPU_MERGE_FANIN",
    "overlap_windows": "DAMPR_TPU_OVERLAP_WINDOWS",
    "spill_write_threads": "DAMPR_TPU_SPILL_WRITERS",
    "spill_read_prefetch": "DAMPR_TPU_SPILL_PREFETCH",
    "exchange_hbm_budget": "DAMPR_TPU_EXCHANGE_HBM",
    "exchange_chunk_bytes": "DAMPR_TPU_EXCHANGE_CHUNK",
    "spill_codec": "DAMPR_TPU_SPILL_CODEC",
}

#: Run-level knobs whose effect the per-stage regressors cannot model:
#: chosen from observed corpus variance (same plan fingerprint, different
#: knob value -> measured throughput decides).
VARIANCE_KNOBS = ("overlap_windows", "spill_write_threads",
                  "spill_read_prefetch", "merge_fanin", "spill_codec",
                  "exchange_hbm_budget")


def in_bounds(knob, value):
    """Is ``value`` legal for ``knob`` per :data:`KNOB_BOUNDS`?"""
    bounds = KNOB_BOUNDS.get(knob)
    if bounds is None:
        return False
    if isinstance(bounds[0], str):
        return value in bounds
    lo, hi = bounds
    return (isinstance(value, (int, float))
            and not isinstance(value, bool) and lo <= value <= hi)


def clamp(knob, value):
    """``value`` forced inside ``knob``'s documented bounds."""
    bounds = KNOB_BOUNDS.get(knob)
    if bounds is None or isinstance(bounds[0], str):
        return value
    lo, hi = bounds
    return max(lo, min(hi, value))


def op_class(stage_rec, shape):
    """Operator class for one recorded stage (its provenance is the
    shape string the corpus match key already carries)."""
    target = stage_rec.get("target")
    if target == "device":
        return "device"
    kind = stage_rec.get("kind") or (shape.split(":", 1)[0]
                                     if shape else None)
    if kind == "reduce":
        if stage_rec.get("shuffle_target") == "mesh":
            return "exchange"
        return "fold"
    if kind == "sink":
        return "sink"
    if kind == "map":
        if any(op in (shape or "") for op in _SCANNER_OPS):
            return "scanner"
        if "Rekey" in (shape or "") and not (shape or "").endswith("+c"):
            # A combiner-less re-key chain is a sort_by materialization
            # (read back through the k-way merge); a combinered one is a
            # keyed map feeding a fold — a plain map for cost purposes.
            return "merge"
        if stage_rec.get("shuffle_target") == "mesh":
            return "exchange"
        return "map"
    return "map"


def stage_features(record):
    """Feature rows for one corpus record: one dict per recorded stage
    with measured IO, derived widths, the op class, and the run-level
    knob snapshot.  Tolerant by construction — missing fields become
    None/0, never a raise (legacy and corrupt-adjacent records degrade
    to thinner features; see tests)."""
    if not isinstance(record, dict):
        return []
    shapes = {s.get("sid"): s.get("shape")
              for s in record.get("stage_shapes") or ()
              if isinstance(s, dict)}
    knobs = record.get("settings") or {}
    rows = []
    for st in record.get("stages") or ():
        if not isinstance(st, dict):
            continue
        sid = st.get("stage")
        shape = shapes.get(sid)
        bytes_in = st.get("bytes_in") or 0
        bytes_out = st.get("bytes_out") or 0
        recs_out = st.get("records_out") or 0
        seconds = st.get("seconds")
        if not isinstance(seconds, (int, float)) or seconds < 0:
            continue
        rows.append({
            "run": record.get("run"),
            "sid": sid,
            "shape": shape,
            "op_class": op_class(st, shape),
            "bytes_in": bytes_in,
            "bytes_out": bytes_out,
            "mb": max(bytes_in, bytes_out) / 1e6,
            "record_bytes": (bytes_out / float(recs_out)
                             if recs_out else None),
            "records_in": st.get("records_in") or 0,
            "records_out": recs_out,
            "jobs": st.get("jobs") or 1,
            "spill_bytes": st.get("spill_bytes") or 0,
            "seconds": float(seconds),
            "n_partitions": record.get("n_partitions"),
            "knobs": knobs,
        })
    return rows


def features(records):
    """Flat feature rows over a record list.  Rank-tagged records
    (non-zero ranks of a fleet run) are excluded — their rank-local
    timings would weight one run once per rank."""
    rows = []
    for rec in records or ():
        if isinstance(rec, dict) and rec.get("rank"):
            continue
        rows.extend(stage_features(rec))
    return rows


class ClassFit(object):
    """One operator class's regressor: seconds = secs_per_mb * MB +
    secs_per_job * jobs (both clamped non-negative)."""

    def __init__(self, op_cls, secs_per_mb, secs_per_job, points, r2):
        self.op_class = op_cls
        self.secs_per_mb = secs_per_mb
        self.secs_per_job = secs_per_job
        self.points = points
        self.r2 = r2

    def predict(self, mb, jobs=1):
        return max(0.0, self.secs_per_mb * max(0.0, mb)
                   + self.secs_per_job * max(0, jobs))

    def mbps(self):
        """Modeled marginal throughput (MB/s), None for fixed-cost-only
        fits."""
        if self.secs_per_mb <= 0:
            return None
        return 1.0 / self.secs_per_mb

    def to_dict(self):
        return {
            "op_class": self.op_class,
            "secs_per_mb": round(self.secs_per_mb, 6),
            "secs_per_job": round(self.secs_per_job, 6),
            "mbps": (round(self.mbps(), 3)
                     if self.mbps() is not None else None),
            "points": self.points,
            "r2": round(self.r2, 4),
        }


def _lstsq2(points):
    """Least squares for seconds = b*mb + g*jobs over (mb, jobs, secs)
    triples (closed-form 2x2 normal equations, no intercept — a stage
    over zero bytes with zero jobs takes zero time).  Falls back to the
    single-feature slope when the system is singular or a coefficient
    goes negative.  Returns (b, g)."""
    sxx = sxy = syy = sxs = sys_ = 0.0
    for mb, jobs, secs in points:
        sxx += mb * mb
        sxy += mb * jobs
        syy += jobs * jobs
        sxs += mb * secs
        sys_ += jobs * secs
    det = sxx * syy - sxy * sxy
    if abs(det) > 1e-12:
        b = (sxs * syy - sys_ * sxy) / det
        g = (sys_ * sxx - sxs * sxy) / det
        if b >= 0 and g >= 0:
            return b, g
    # Degenerate or sign-flipped: one-feature fits, best SSE wins.
    b1 = (sxs / sxx) if sxx > 0 else 0.0
    g1 = (sys_ / syy) if syy > 0 else 0.0
    sse_b = sum((secs - b1 * mb) ** 2 for mb, _j, secs in points)
    sse_g = sum((secs - g1 * jobs) ** 2 for _m, jobs, secs in points)
    if b1 > 0 and (g1 <= 0 or sse_b <= sse_g):
        return max(0.0, b1), 0.0
    return 0.0, max(0.0, g1)


def _fit_class(op_cls, rows):
    points = [(r["mb"], r["jobs"], r["seconds"]) for r in rows]
    if len(points) < max(2, settings.cost_model_min_points):
        return None
    b, g = _lstsq2(points)
    # One robustness pass: drop large-residual outliers, refit (a cold
    # first run or a noisy-neighbor spike must not own the slope).
    resid = [abs(secs - (b * mb + g * jobs)) for mb, jobs, secs in points]
    med = statistics.median(resid)
    if med > 0:
        kept = [p for p, r in zip(points, resid) if r <= 3.0 * med]
        if len(kept) >= max(2, settings.cost_model_min_points):
            points = kept
            b, g = _lstsq2(points)
    mean_s = sum(p[2] for p in points) / len(points)
    sst = sum((p[2] - mean_s) ** 2 for p in points)
    sse = sum((secs - (b * mb + g * jobs)) ** 2
              for mb, jobs, secs in points)
    r2 = 1.0 - (sse / sst) if sst > 0 else (1.0 if sse < 1e-9 else 0.0)
    return ClassFit(op_cls, b, g, len(points), r2)


class CostModel(object):
    """Per-operator-class fits + per-knob observed-variance tables."""

    def __init__(self, fits, knob_obs, n_records):
        self.fits = fits            # {op_class: ClassFit}
        self.knob_obs = knob_obs    # {knob: {value_repr: [mbps,...]}}
        self.n_records = n_records

    def fit_for(self, op_cls):
        return self.fits.get(op_cls)

    def predict_stage(self, op_cls, mb, jobs=1):
        f = self.fits.get(op_cls)
        return f.predict(mb, jobs) if f is not None else None

    def confident_for(self, op_classes):
        """(ok, reason): can the model price a plan whose stages span
        ``op_classes``?  Every class present must be fit."""
        missing = sorted(c for c in set(op_classes) if c not in self.fits)
        if not self.fits:
            return False, "thin-corpus ({} record(s) yield no fit; " \
                "floor is {} per class)".format(
                    self.n_records, settings.cost_model_min_points)
        if missing:
            return False, "unfit operator class(es): {} (< {} " \
                "measurements)".format(
                    ", ".join(missing), settings.cost_model_min_points)
        return True, None

    def shuffle_prediction(self, mb):
        """See module-level :func:`shuffle_prediction`."""
        return shuffle_prediction(self, mb)

    def to_dict(self):
        return {
            "records": self.n_records,
            "classes": {c: f.to_dict()
                        for c, f in sorted(self.fits.items())},
        }


def _knob_value_key(v):
    return json.dumps(v, sort_keys=True, default=str)


def _knob_observations(records, fingerprint):
    """{knob: {value_key: {"value": v, "mbps": [..]}}} over records of
    one plan fingerprint — run-level measured throughput grouped by the
    knob value the run executed under."""
    out = {k: {} for k in VARIANCE_KNOBS}
    for rec in records or ():
        if not isinstance(rec, dict) or rec.get("rank"):
            continue
        if fingerprint and rec.get("fingerprint") != fingerprint:
            continue
        mbps = ((rec.get("throughput") or {}).get("mbps"))
        if not isinstance(mbps, (int, float)) or mbps <= 0:
            continue
        knobs = rec.get("settings") or {}
        for knob in VARIANCE_KNOBS:
            if knob not in knobs:
                continue
            cell = out[knob].setdefault(
                _knob_value_key(knobs[knob]),
                {"value": knobs[knob], "mbps": []})
            cell["mbps"].append(float(mbps))
    return out


def build(records, fingerprint=None):
    """Fit a :class:`CostModel` from corpus records (rank-tagged records
    excluded).  ``fingerprint`` scopes the knob-variance tables to one
    plan shape — cross-shape throughput is not comparable."""
    rows = features(records)
    by_class = {}
    for r in rows:
        by_class.setdefault(r["op_class"], []).append(r)
    fits = {}
    for op_cls, cls_rows in by_class.items():
        f = _fit_class(op_cls, cls_rows)
        if f is not None:
            fits[op_cls] = f
    n = sum(1 for r in records or ()
            if isinstance(r, dict) and not r.get("rank"))
    return CostModel(fits, _knob_observations(records, fingerprint), n)


def _pow2_candidates(lo, hi):
    out = []
    v = 1
    while v <= hi:
        if v >= lo:
            out.append(v)
        v *= 2
    return out


def search_partitions(model, hist_stages, n_now):
    """Model-searched partition count: predicted run seconds over the
    plan's fold/exchange stages as a function of P (their job count
    tracks P; byte volume does not), minimized over bounded power-of-two
    candidates.  Returns (choice dict or None)."""
    targets = [st for st in hist_stages
               if st.get("op_class") in ("fold", "exchange")
               and st.get("mb") is not None]
    if not targets:
        return None
    lo, hi = KNOB_BOUNDS["n_partitions"]
    cands = _pow2_candidates(max(lo, 4),
                             min(hi, max(4 * settings.partitions, n_now)))
    if n_now not in cands:
        cands.append(n_now)

    def predicted(P):
        total = 0.0
        for st in targets:
            sec = model.predict_stage(st["op_class"], st["mb"], P)
            if sec is None:
                return None
            total += sec
        return total

    static_s = predicted(n_now)
    if static_s is None:
        return None
    best, best_s = n_now, static_s
    for P in cands:
        s = predicted(P)
        if s is not None and s < best_s:
            best, best_s = P, s
    if best == n_now or static_s <= 0:
        return None
    if (static_s - best_s) / static_s < settings.cost_model_margin:
        return None
    return {
        "knob": "n_partitions",
        "static": n_now,
        "chosen": int(clamp("n_partitions", best)),
        "predicted_seconds": round(best_s, 4),
        "static_seconds": round(static_s, 4),
        "reason": "argmin of modeled fold/exchange seconds over {} "
                  "candidate partition counts (secs_per_job prices the "
                  "per-partition fixed cost)".format(len(cands)),
    }


def search_variance_knobs(model, current):
    """Observed-variance choices for the run-level knobs the per-stage
    regressors cannot see.  ``current`` maps knob -> this run's value.
    Returns a list of choice dicts; knobs without variance (or without
    enough measured gain) contribute a no-change entry with the reason
    recorded — the honest 'measure me' signal the autotune loop acts
    on."""
    choices = []
    for knob in VARIANCE_KNOBS:
        obs = model.knob_obs.get(knob) or {}
        cur = current.get(knob)
        if len(obs) < 2:
            choices.append({
                "knob": knob, "static": cur, "chosen": cur,
                "reason": ("no-variance: corpus holds {} distinct "
                           "value(s) — autotune a trial to measure "
                           "another".format(len(obs)))})
            continue
        scored = sorted(
            ((statistics.median(cell["mbps"]), cell["value"])
             for cell in obs.values()),
            key=lambda t: -t[0])
        best_mbps, best_val = scored[0]
        cur_cell = obs.get(_knob_value_key(cur))
        cur_mbps = (statistics.median(cur_cell["mbps"])
                    if cur_cell else None)
        if best_val == cur or not in_bounds(knob, best_val):
            choices.append({
                "knob": knob, "static": cur, "chosen": cur,
                "reason": "current value measured best over {} "
                          "observed value(s)".format(len(obs))})
            continue
        if (cur_mbps is not None and cur_mbps > 0
                and (best_mbps - cur_mbps) / cur_mbps
                < settings.cost_model_margin):
            choices.append({
                "knob": knob, "static": cur, "chosen": cur,
                "reason": "observed gain under the {:.0%} margin".format(
                    settings.cost_model_margin)})
            continue
        choice = {
            "knob": knob, "static": cur, "chosen": best_val,
            "measured_mbps": round(best_mbps, 3),
            "reason": "measured {} MB/s at {!r} vs {} at the current "
                      "{!r} over {} corpus value(s)".format(
                          round(best_mbps, 2), best_val,
                          round(cur_mbps, 2) if cur_mbps else "?",
                          cur, len(obs)),
        }
        if ENV_OF.get(knob):
            choice["env"] = ENV_OF[knob]
        choices.append(choice)
    return choices


def predict_plan(model, hist_stages, n_partitions):
    """Modeled wall for a plan whose per-stage history rows are
    ``hist_stages``: sum of per-stage predictions (fold/exchange job
    counts track the partition count).  None when any class is unfit."""
    total = 0.0
    for st in hist_stages:
        jobs = (n_partitions if st.get("op_class") in ("fold", "exchange")
                else st.get("jobs") or 1)
        sec = model.predict_stage(st["op_class"], st.get("mb") or 0.0,
                                  jobs)
        if sec is None:
            return None
        total += sec
    return total


def _run_secs_per_mb(rec):
    """Wall seconds per input megabyte for one corpus record, or None.
    Input volume is the largest stage ``bytes_in`` (the corpus scan) —
    normalizing lets differently-sized runs of the same plan shape
    price one edge."""
    wall = rec.get("wall_seconds")
    if not wall or wall <= 0:
        return None
    mb = max((float(st.get("bytes_in") or 0)
              for st in rec.get("stages") or ()), default=0.0) / 1e6
    if mb <= 0:
        return None
    return float(wall) / mb


def price_handoff(records, fingerprint):
    """Observed handoff-vs-spill pricing for one plan fingerprint: BEST
    wall seconds PER INPUT MB of corpus runs whose plan carried
    device-handoff edges vs LOWERED runs that spilled the same edges.
    Only lowered runs qualify on either side — a host-codec run never
    had the edge to decide, so its wall says nothing about handoff-vs-
    spill — and only the most recent ``settings.history_window`` records
    per side vote, so stale configurations age out.  The comparison is
    each side's MINIMUM: recorded walls include one-time jit compiles
    (every cold process re-pays them) and box-load noise, both of which
    only ever inflate a wall, so the best observed run is the honest
    steady-state estimate — a median would let one side's cold-compile
    records outvote the other side's warm ones.  Returns (decision,
    reason) — decision ``"device"``/``"spill"``, or None when the corpus
    lacks variance (auto then defaults to the handoff; the reason is the
    honest 'measure me' signal the autotune loop acts on)."""
    on, off = [], []
    for rec in records:
        if not isinstance(rec, dict) or rec.get("rank"):
            continue
        if rec.get("fingerprint") != fingerprint:
            continue
        if not rec.get("device_fraction"):
            continue  # host-path run: the edge never existed
        spm = _run_secs_per_mb(rec)
        if spm is None:
            continue
        h = rec.get("handoff") or {}
        if h.get("edges") and not h.get("degrades"):
            on.append(spm)
        elif not h.get("edges"):
            off.append(spm)
        # degraded handoff runs vote on neither side: their wall mixes
        # both paths
    win = max(1, int(getattr(settings, "history_window", 8)))
    on, off = on[-win:], off[-win:]
    if not on or not off:
        return None, ("no handoff-vs-spill variance among lowered runs "
                      "({} with the edge resident, {} without)"
                      .format(len(on), len(off)))
    mon = min(on)
    moff = min(off)
    if mon > moff * (1.0 + max(0.0, settings.cost_model_margin)):
        return "spill", ("corpus prices the spill path faster "
                         "({:.3f} vs {:.3f} s/MB best-of over {}+{} "
                         "lowered runs) — edge declined".format(
                             moff, mon, len(off), len(on)))
    return "device", ("corpus prices the resident edge faster "
                      "({:.3f} vs {:.3f} s/MB best-of over {}+{} lowered "
                      "runs)".format(mon, moff, len(on), len(off)))


def shuffle_prediction(model, mb):
    """(target, reason) from modeled exchange-vs-fold throughput for one
    redistribution of ``mb`` megabytes, or None when either class is
    unfit — the caller then falls back to the byte-floor heuristic."""
    ex = model.fit_for("exchange")
    fold = model.fit_for("fold")
    if ex is None or fold is None:
        return None
    ex_s = ex.predict(mb, 1)
    host_s = fold.predict(mb, 1)
    if ex_s <= 0 or host_s <= 0:
        return None
    if ex_s * (1.0 + settings.cost_model_margin) < host_s:
        return "mesh", ("model: exchange predicts {:.3f}s vs {:.3f}s on "
                        "the host fold path for {:.1f} MB".format(
                            ex_s, host_s, mb))
    return "host", ("model: host fold predicts {:.3f}s vs {:.3f}s over "
                    "the mesh exchange for {:.1f} MB".format(
                        host_s, ex_s, mb))


# ---------------------------------------------------------------------------
# Checked-in trajectory feedstock (BENCH/SHUFFLE/MULTICHIP/SKEW/TUNE JSONs)
# ---------------------------------------------------------------------------

def load_trajectory(paths):
    """Coarse run-level records from the checked-in bench trajectory
    files (BENCH_r*.json / SHUFFLE_r*.json / SKEW_r*.json / TUNE_r*.json;
    driver ``parsed`` wrappers unwrapped).  Each yields
    ``{"metric", "mbps", "knobs": {...}}`` — feedstock for the autotune
    loop's knob priors, NOT per-stage fits (the trajectory has no
    per-stage telemetry).  Unreadable files are skipped, never fatal."""
    out = []
    for path in paths or ():
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(doc, dict) and isinstance(doc.get("parsed"), dict):
            doc = doc["parsed"]
        if not isinstance(doc, dict):
            continue
        if isinstance(doc.get("autotune"), dict):
            win = doc["autotune"].get("winner") or {}
            out.append({
                "metric": doc.get("metric") or "autotune",
                "mbps": win.get("mbps"),
                "knobs": win.get("knobs") or {},
                "source": os.path.basename(path),
            })
            continue
        value = doc.get("value")
        if not isinstance(value, (int, float)):
            continue
        knobs = {k: doc[k] for k in ("overlap_windows",)
                 if k in doc}
        out.append({"metric": doc.get("metric"), "mbps": float(value),
                    "knobs": knobs, "source": os.path.basename(path)})
    return out


def empty_section(enabled, reason=None, source="median"):
    sec = {"enabled": enabled, "source": source, "choices": [],
           "model": None}
    if reason:
        sec["reason"] = reason
    return sec
