"""Native host codec: builds and binds the C++ tokenizer via ctypes.

The shared object compiles on first use (g++ -O3, ~1s) and is cached next to
the source; set DAMPR_TPU_NATIVE=0 to force the pure-numpy fallback.  The
binding is ctypes on purpose — no pybind11 in the image, and the interface is
four flat arrays, exactly what ctypes does well.
"""

import ctypes
import logging
import os
import subprocess
import threading

import numpy as np

log = logging.getLogger("dampr_tpu.native")

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "tokenizer.cpp")
_SO = os.path.join(_HERE, "_native.so")

_lock = threading.Lock()
_lib = None
_tried = False


def _build():
    cmd = ["g++", "-O3", "-shared", "-fPIC", _SRC, "-o", _SO]
    try:
        subprocess.run(cmd + ["-march=native"], check=True,
                       capture_output=True)
    except (subprocess.CalledProcessError, OSError):
        subprocess.run(cmd, check=True, capture_output=True)


def _codec_timed(fn):
    """Charge this native-codec entry point's wall time to the 'codec'
    devtime bucket (the bench's host/device split)."""
    import functools

    @functools.wraps(fn)
    def wrapped(*a, **kw):
        from ..ops import devtime

        with devtime.track("codec"):
            return fn(*a, **kw)

    return wrapped


def get_lib():
    """The loaded native library, or None when unavailable/disabled."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("DAMPR_TPU_NATIVE", "1") in ("0", "false"):
            return None
        try:
            if (not os.path.exists(_SO)
                    or os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
                _build()
            lib = ctypes.CDLL(_SO)
            fn = lib.dampr_tokenize_hash
            fn.restype = ctypes.c_long
            fn.argtypes = [
                ctypes.c_void_p, ctypes.c_long, ctypes.c_int, ctypes.c_int,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p,
            ]
            fc = lib.dampr_token_counts
            fc.restype = ctypes.c_long
            fc.argtypes = [
                ctypes.c_void_p, ctypes.c_long, ctypes.c_int, ctypes.c_int,
                ctypes.c_int, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ]
            try:
                fp = lib.dampr_parse_i64
                fp.restype = ctypes.c_long
                fp.argtypes = [
                    ctypes.c_void_p, ctypes.c_long, ctypes.c_void_p,
                    ctypes.c_void_p,
                ]
            except AttributeError:
                log.warning("cached native library predates "
                            "dampr_parse_i64; rebuild to enable it")
            # Newer symbol: bind guarded so a stale cached .so (mtime-
            # preserving deploys can skip the rebuild) degrades only this
            # entry point, never the tokenizer fast paths it still exports.
            try:
                fb = lib.dampr_hash_bytes_batch
                fb.restype = None
                fb.argtypes = [
                    ctypes.c_void_p, ctypes.c_void_p, ctypes.c_long,
                    ctypes.c_void_p, ctypes.c_void_p,
                ]
            except AttributeError:
                log.warning("cached native library predates "
                            "dampr_hash_bytes_batch; rebuild to enable it")
            _lib = lib
        except Exception as exc:  # noqa: BLE001 - any failure -> numpy path
            log.warning("native tokenizer unavailable (%s); using numpy", exc)
            _lib = None
    return _lib


@_codec_timed
def tokenize_hash(buf, mode, lower, want_line_ids=False):
    """One native pass: (starts, lens, h1, h2[, line_ids]) for a uint8 buffer.
    Returns None when the native library is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    n = len(buf)
    cap = n // 2 + 1
    starts = np.empty(cap, dtype=np.int64)
    lens = np.empty(cap, dtype=np.int32)
    h1 = np.empty(cap, dtype=np.uint32)
    h2 = np.empty(cap, dtype=np.uint32)
    line_ids = np.empty(cap, dtype=np.int64) if want_line_ids else None
    buf = np.ascontiguousarray(buf)
    count = lib.dampr_tokenize_hash(
        buf.ctypes.data, n, int(mode), int(lower),
        starts.ctypes.data, lens.ctypes.data,
        h1.ctypes.data, h2.ctypes.data,
        line_ids.ctypes.data if want_line_ids else None)
    out = (starts[:count], lens[:count], h1[:count], h2[:count])
    if want_line_ids:
        out = out + (line_ids[:count],)
    return out


@_codec_timed
def parse_i64(buf):
    """Whitespace-separated int64 parse of a uint8 buffer in one C pass.
    Returns an int64 array, None when the native library is unavailable,
    or raises ValueError on the first unparsable/out-of-range token
    (numpy-parse error semantics)."""
    lib = get_lib()
    if lib is None or not hasattr(lib, "dampr_parse_i64"):
        return None
    buf = np.ascontiguousarray(buf)
    n = len(buf)
    out = np.empty(n // 2 + 1, dtype=np.int64)
    bad = ctypes.c_long(-1)
    count = lib.dampr_parse_i64(buf.ctypes.data, n, out.ctypes.data,
                                ctypes.byref(bad))
    if bad.value >= 0:
        raise ValueError(
            "unparsable numeric token at index {}".format(bad.value))
    return out[:count].copy()


@_codec_timed
def hash_bytes_batch(bs):
    """Dual-lane FNV over a list of bytes keys in one C pass.  Returns
    (h1, h2) uint32 arrays, or None when the native library is
    unavailable."""
    lib = get_lib()
    if lib is None or not hasattr(lib, "dampr_hash_bytes_batch"):
        return None
    n = len(bs)
    offs = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(np.fromiter((len(b) for b in bs), dtype=np.int64, count=n),
              out=offs[1:])
    buf = np.frombuffer(b"".join(bs), dtype=np.uint8)
    h1 = np.empty(n, dtype=np.uint32)
    h2 = np.empty(n, dtype=np.uint32)
    lib.dampr_hash_bytes_batch(
        np.ascontiguousarray(buf).ctypes.data, offs.ctypes.data, n,
        h1.ctypes.data, h2.ctypes.data)
    return h1, h2


@_codec_timed
def token_counts(buf, mode, lower, dedup_per_line):
    """Fused native tokenize+hash+count: one pass, no sort.  Returns
    (h1, h2, counts, rep_starts, rep_lens) over distinct tokens, or None when
    the native library is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    n = len(buf)
    cap = n // 2 + 1
    h1 = np.empty(cap, dtype=np.uint32)
    h2 = np.empty(cap, dtype=np.uint32)
    counts = np.empty(cap, dtype=np.int64)
    starts = np.empty(cap, dtype=np.int64)
    lens = np.empty(cap, dtype=np.int32)
    buf = np.ascontiguousarray(buf)
    k = lib.dampr_token_counts(
        buf.ctypes.data, n, int(mode), int(lower), int(dedup_per_line),
        h1.ctypes.data, h2.ctypes.data, counts.ctypes.data,
        starts.ctypes.data, lens.ctypes.data)
    if k < 0:
        return None
    return h1[:k], h2[:k], counts[:k], starts[:k], lens[:k]
