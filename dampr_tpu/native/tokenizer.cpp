// Host-side record codec: single-pass tokenizer + dual-lane FNV-1a hasher.
//
// The TPU compute path (XLA/segment kernels) starts from token hash lanes;
// producing those lanes from raw text is host work that pure numpy does in
// several passes (class lookup, boundary scan, padded gather, column-wise
// FNV).  This C++ pass fuses all of it: one walk over the chunk buffer emits
// token offsets, lengths, and both hash lanes.  This is the framework's
// native "host I/O layer" component (SURVEY §7.2): the reference is pure
// Python end-to-end, so there is no reference counterpart to mirror — the
// design target is simply to outrun the TPU feed.
//
// Hash compatibility: lanes MUST match ops/hashing.py exactly
// (_FNV_OFFSET1/2, _FNV_PRIME1/2 over utf-8 bytes) so tokens group with
// equal Python-string keys everywhere in the engine.
//
// Build: g++ -O3 -march=native -shared -fPIC tokenizer.cpp -o _native.so

#include <cstdint>
#include <cstdlib>
#include <cstring>

#if defined(__x86_64__)
#include <immintrin.h>  // _mm_prefetch everywhere; AVX-512 used when built
#endif

extern "C" {

// Token classification modes (keep in sync with dampr_tpu/ops/text.py):
//   mode 0: whitespace-delimited (str.split semantics, ASCII whitespace)
//   mode 1: word characters [0-9A-Za-z_] + bytes >= 128 (re [^\w]+ on ASCII)
// Table-driven: one L1-resident lookup per byte beats the range-compare
// chain in the hot scan.
struct ClassTables {
    bool tok[2][256];
    uint8_t fold[2][256];  // [lower?][byte] -> case-folded byte
    ClassTables() {
        for (int b = 0; b < 256; ++b) {
            tok[0][b] = !(b == ' ' || b == '\t' || b == '\n' || b == '\r' ||
                          b == '\v' || b == '\f');
            tok[1][b] = (b >= '0' && b <= '9') || (b >= 'A' && b <= 'Z') ||
                        (b >= 'a' && b <= 'z') || b == '_' || b >= 128;
            fold[0][b] = (uint8_t)b;
            fold[1][b] = (b >= 'A' && b <= 'Z') ? (uint8_t)(b + 32)
                                                : (uint8_t)b;
        }
    }
};
static const ClassTables kTables;

// Single pass: tokenize + hash + (optional) lowercase folding into the hash.
// Returns the number of tokens found.  Output arrays must hold at least
// n/2 + 1 entries (the worst case: alternating token/separator bytes).
// line_ids receives the 0-based line index of each token (newlines counted
// in the raw buffer) — pass nullptr to skip.
long dampr_tokenize_hash(const uint8_t* buf, long n, int mode, int lower,
                         int64_t* starts, int32_t* lens,
                         uint32_t* h1_out, uint32_t* h2_out,
                         int64_t* line_ids) {
    const uint32_t OFF1 = 2166136261u, OFF2 = 0x9747B28Cu;
    const uint32_t P1 = 16777619u, P2 = 0x85EBCA6Bu;

    const uint8_t* fold = kTables.fold[lower ? 1 : 0];
    const bool* tokt = kTables.tok[mode ? 1 : 0];
    long count = 0;
    long i = 0;
    int64_t line = 0;
    while (i < n) {
        uint8_t b = buf[i];
        if (b == '\n') { ++line; ++i; continue; }
        if (!tokt[b]) { ++i; continue; }
        // token run
        long s = i;
        uint32_t h1 = OFF1, h2 = OFF2;
        int64_t tok_line = line;
        do {
            uint8_t c = fold[buf[i]];
            h1 = (h1 ^ c) * P1;
            h2 = (h2 ^ c) * P2;
            ++i;
        } while (i < n && tokt[buf[i]]);
        starts[count] = s;
        lens[count] = (int32_t)(i - s);
        h1_out[count] = h1;
        h2_out[count] = h2;
        if (line_ids) line_ids[count] = tok_line;
        ++count;
    }
    return count;
}

// Fused tokenize + hash + count: one pass over the buffer feeding an
// open-addressing table keyed on the 64-bit hash pair *verified by byte
// comparison* — a probe hit requires equal hashes AND equal token bytes
// (case-folded when lower is set), so distinct tokens colliding in all 64
// hash bits occupy separate slots and are never silently merged.  (They then
// emit separate entries sharing (h1, h2); the engine's sort-based grouping
// repairs exactly that shape downstream by comparing real keys.)
//
// Emits one entry per distinct token: (h1, h2, count, representative
// offset/len).  With dedup_per_line != 0 a token increments at most once per
// newline-delimited line (document frequency — the reference TF-IDF
// benchmark's map+count, tf-idf-dampr.py:13-15).
//
// Returns the number of distinct tokens (<= out array capacity n/2+1), or -1
// on allocation failure.

// Byte equality of the tails past the inline 8-byte prefix (folded when
// lower is set).  Only runs for tokens longer than 8 bytes whose hashes,
// length, and prefix all matched — rare, so the random buffer access it
// costs is off the hot path.
static inline bool tail_eq(const uint8_t* buf, int64_t a, int64_t b,
                           int32_t len, int lower) {
    if (!lower) return memcmp(buf + a + 8, buf + b + 8, (size_t)(len - 8)) == 0;
    for (int32_t i = 8; i < len; ++i) {
        uint8_t x = buf[a + i], y = buf[b + i];
        if (x >= 'A' && x <= 'Z') x += 32;
        if (y >= 'A' && y <= 'Z') y += 32;
        if (x != y) return false;
    }
    return true;
}
// Probe-hash mix of the per-token summary words.  This is NOT the FNV
// lanes the engine sees — equality at the table is byte-verified, so the
// probe hash only has to spread slots, and one 64-bit multiply per token
// replaces the old two-multiplies-per-byte FNV in the scan loop.  The
// exact FNV lanes are recomputed at emit time for the (few) distinct
// tokens only.
static inline uint64_t probe_mix(uint64_t prefix, uint64_t tailw,
                                 int32_t len) {
    uint64_t ph = prefix ^ (tailw * 0xC2B2AE3D27D4EB4FULL);
    ph ^= (uint64_t)(uint32_t)len * 0x9E3779B97F4A7C15ULL;
    ph *= 0xFF51AFD7ED558CCDULL;
    ph ^= ph >> 33;
    return ph;
}

// Table state for the counting pass, split out so the scalar and SIMD scan
// drivers share one probe/insert/grow path.
struct CountTable {
    struct Entry {
        uint64_t prefix;    // first <=8 folded bytes, zero-padded
        uint64_t tailw;     // last 8 folded bytes when len > 8, else 0
        int64_t count;
        int64_t start;      // representative occurrence (first seen)
        int64_t last_line;  // for per-line dedup; -1 = never seen
        int32_t len;
        uint32_t tag;       // high probe-hash bits | 1; 0 = empty slot
    };
    Entry* tbl;
    long cap;
    long used;
    bool oom;
};

// SWAR case-fold of 8 packed bytes: ASCII A-Z += 0x20, all other bytes
// (including >= 0x80) unchanged — bitwise identical to kTables.fold[1].
static inline uint64_t fold8(uint64_t w) {
    const uint64_t kOnes = 0x0101010101010101ULL;
    const uint64_t kHigh = 0x8080808080808080ULL;
    uint64_t hi = w & kHigh;
    uint64_t w7 = w & ~kHigh;
    uint64_t ge_a = (w7 + (0x80 - 'A') * kOnes) & kHigh;  // byte >= 'A'
    uint64_t gt_z = (w7 + (0x7F - 'Z') * kOnes) & kHigh;  // byte >  'Z'
    uint64_t is_upper = (ge_a & ~gt_z) & ~hi;
    return w + (is_upper >> 2);  // 0x80 >> 2 == 0x20
}

static inline uint64_t load8(const uint8_t* p) {
    uint64_t w;
    memcpy(&w, p, 8);
    return w;
}

// Folded (prefix, tailw) summary words of token [s, s+len).
static inline void summarize_token(const uint8_t* buf, long n, int lower,
                                   const uint8_t* fold, long s, int32_t len,
                                   uint64_t* out_prefix, uint64_t* out_tailw) {
    uint64_t prefix;
    if (len >= 8) {
        prefix = load8(buf + s);
        prefix = lower ? fold8(prefix) : prefix;
    } else if (s + 8 <= n) {
        prefix = load8(buf + s) & ((1ULL << (len * 8)) - 1);
        prefix = lower ? fold8(prefix) : prefix;
    } else {
        prefix = 0;  // token at the very end of the buffer: bytewise
        for (int j = 0; j < len; ++j)
            prefix |= ((uint64_t)fold[buf[s + j]]) << (j * 8);
    }
    uint64_t tailw = 0;
    if (len > 8) {
        tailw = load8(buf + s + len - 8);
        tailw = lower ? fold8(tailw) : tailw;
    }
    *out_prefix = prefix;
    *out_tailw = tailw;
}

// Double the table when load passes 70% (callers ensure headroom for the
// occurrences they are about to insert).
static inline void maybe_grow(CountTable* T, long incoming) {
    if (T->oom) return;  // don't retry a failed multi-MB calloc per token
    if ((T->used + incoming) * 10 < T->cap * 7) return;
    long ncap = T->cap * 2;
    CountTable::Entry* nt =
        (CountTable::Entry*)calloc(ncap, sizeof(CountTable::Entry));
    if (!nt) { T->oom = true; return; }
    for (long j = 0; j < T->cap; ++j) {
        if (!T->tbl[j].tag) continue;
        uint64_t h = probe_mix(T->tbl[j].prefix, T->tbl[j].tailw,
                               T->tbl[j].len);
        long k = (long)(h & (uint64_t)(ncap - 1));
        while (nt[k].tag) k = (k + 1) & (ncap - 1);
        nt[k] = T->tbl[j];
    }
    free(T->tbl);
    T->tbl = nt;
    T->cap = ncap;
}

// Probe/insert/count one summarized occurrence.  The caller has already
// handled growth (so batched callers can prefetch slots safely).
static inline void probe_token(CountTable* T, const uint8_t* buf,
                               int lower, int dedup_per_line,
                               long s, int32_t len, int64_t line,
                               uint64_t prefix, uint64_t tailw, uint64_t ph) {
    CountTable::Entry* tbl = T->tbl;
    long cap_tbl = T->cap;
    uint32_t tag = (uint32_t)(ph >> 32) | 1u;
    long k = (long)(ph & (uint64_t)(cap_tbl - 1));
    while (tbl[k].tag &&
           !(tbl[k].tag == tag && tbl[k].len == len &&
             tbl[k].prefix == prefix && tbl[k].tailw == tailw &&
             (len <= 16 || tail_eq(buf, tbl[k].start, s, len, lower))))
        k = (k + 1) & (cap_tbl - 1);
    if (!tbl[k].tag) {
        tbl[k].tag = tag;
        tbl[k].prefix = prefix;
        tbl[k].tailw = tailw;
        tbl[k].count = 0;
        tbl[k].start = s;
        tbl[k].len = len;
        tbl[k].last_line = -1;
        ++T->used;
    }
    if (dedup_per_line) {
        if (tbl[k].last_line != line) {
            tbl[k].last_line = line;
            tbl[k].count += 1;
        }
    } else {
        tbl[k].count += 1;
    }
}

// One token occurrence [s, s+len) on line `line`: summarize, grow, probe.
static inline void count_token(CountTable* T, const uint8_t* buf, long n,
                               int lower, int dedup_per_line,
                               long s, int32_t len, int64_t line) {
    const uint8_t* fold = kTables.fold[lower ? 1 : 0];
    uint64_t prefix, tailw;
    summarize_token(buf, n, lower, fold, s, len, &prefix, &tailw);
    maybe_grow(T, 1);
    if (T->oom) return;
    probe_token(T, buf, lower, dedup_per_line, s, len, line,
                prefix, tailw, probe_mix(prefix, tailw, len));
}

#if defined(__AVX512BW__)
// 64-byte classification: token-char and newline bitmasks (bit j = byte j).
// Bits at or past `nb` (short final block) read as separators.
static inline void classify64(const uint8_t* p, int nb, int mode,
                              uint64_t* tokm, uint64_t* nlm) {
    __mmask64 lm = nb >= 64 ? ~(__mmask64)0 : (((__mmask64)1 << nb) - 1);
    __m512i v = _mm512_maskz_loadu_epi8(lm, p);
    __mmask64 nl = _mm512_cmpeq_epi8_mask(v, _mm512_set1_epi8('\n')) & lm;
    __mmask64 tok;
    if (mode) {
        // word chars: [0-9A-Za-z_] plus any byte >= 0x80
        __m512i low = _mm512_or_si512(v, _mm512_set1_epi8(0x20));
        __mmask64 alpha = _mm512_cmp_epu8_mask(
            _mm512_sub_epi8(low, _mm512_set1_epi8('a')),
            _mm512_set1_epi8(25), _MM_CMPINT_LE);
        __mmask64 digit = _mm512_cmp_epu8_mask(
            _mm512_sub_epi8(v, _mm512_set1_epi8('0')),
            _mm512_set1_epi8(9), _MM_CMPINT_LE);
        __mmask64 us = _mm512_cmpeq_epi8_mask(v, _mm512_set1_epi8('_'));
        __mmask64 hib = _mm512_movepi8_mask(v);  // sign bit = byte >= 0x80
        tok = alpha | digit | us | hib;
    } else {
        // whitespace-delimited: token = not in " \t\n\r\v\f"
        __mmask64 ws =
            _mm512_cmpeq_epi8_mask(v, _mm512_set1_epi8(' ')) |
            _mm512_cmpeq_epi8_mask(v, _mm512_set1_epi8('\t')) | nl |
            _mm512_cmpeq_epi8_mask(v, _mm512_set1_epi8('\r')) |
            _mm512_cmpeq_epi8_mask(v, _mm512_set1_epi8('\v')) |
            _mm512_cmpeq_epi8_mask(v, _mm512_set1_epi8('\f'));
        tok = ~ws;
    }
    *tokm = tok & lm;
    *nlm = nl;
}

// One-time cross-check of the intrinsic classifier against kTables (the
// single source of truth shared with the scalar paths and ops/text.py):
// every byte value, both modes.  On divergence the SIMD path refuses
// (callers fall back to numpy — slower, never wrong).
static bool classify64_selfcheck() {
    uint8_t all[256];
    for (int b = 0; b < 256; ++b) all[b] = (uint8_t)b;
    for (int mode = 0; mode < 2; ++mode) {
        for (int base = 0; base < 256; base += 64) {
            uint64_t tokm, nlm;
            classify64(all + base, 64, mode, &tokm, &nlm);
            for (int j = 0; j < 64; ++j) {
                int b = base + j;
                bool want_tok = kTables.tok[mode][b];
                bool want_nl = (b == '\n');
                if (((tokm >> j) & 1) != (want_tok ? 1u : 0u)) return false;
                if (((nlm >> j) & 1) != (want_nl ? 1u : 0u)) return false;
            }
        }
    }
    return true;
}
#endif  // __AVX512BW__

long dampr_token_counts(const uint8_t* buf, long n, int mode, int lower,
                        int dedup_per_line,
                        uint32_t* out_h1, uint32_t* out_h2,
                        int64_t* out_count,
                        int64_t* out_start, int32_t* out_len) {
    const uint32_t OFF1 = 2166136261u, OFF2 = 0x9747B28Cu;
    const uint32_t P1 = 16777619u, P2 = 0x85EBCA6Bu;

    CountTable T;
    T.cap = 1 << 16;
    T.tbl = (CountTable::Entry*)calloc(T.cap, sizeof(CountTable::Entry));
    T.used = 0;
    T.oom = false;
    if (!T.tbl) return -1;

    const uint8_t* fold = kTables.fold[lower ? 1 : 0];

#if defined(__AVX512BW__)
    static const bool kSimdOk = classify64_selfcheck();
    if (!kSimdOk) { free(T.tbl); return -1; }  // numpy fallback, never wrong
    // Block scan: classify 64 bytes into bitmasks, then walk token runs
    // with tzcnt — no per-byte branches, so short tokens stop costing a
    // mispredict each (measured 2x on the 4-byte-average Zipf corpus).
    int in_token = 0;
    long tok_start = 0;
    int64_t tok_line = 0;
    int64_t line = 0;
    for (long base = 0; base < n && !T.oom; base += 64) {
        int nb = (n - base) >= 64 ? 64 : (int)(n - base);
        uint64_t t, nlm;
        classify64(buf + base, nb, mode, &t, &nlm);
        if (in_token) {
            if (t == ~0ULL) continue;  // token spans the whole block
            int e = __builtin_ctzll(~t);
            count_token(&T, buf, n, lower, dedup_per_line, tok_start,
                        (int32_t)(base + e - tok_start), tok_line);
            in_token = 0;
            if (e > 0) t &= ~(((uint64_t)1 << e) - 1);
        }
        while (t) {
            int s = __builtin_ctzll(t);
            uint64_t run = ~(t >> s);  // first zero past s = run end
            // run == 0 (ones all the way to bit 63) must not reach
            // ctzll(0), which is undefined: treat as run-to-edge.
            int rl = run ? __builtin_ctzll(run) : (64 - s);
            int64_t at_line =
                line + __builtin_popcountll(
                           s ? (nlm & (((uint64_t)1 << s) - 1)) : 0);
            if (s + rl >= 64) {
                // run touches the block edge: may continue next block
                in_token = 1;
                tok_start = base + s;
                tok_line = at_line;
                break;
            }
            count_token(&T, buf, n, lower, dedup_per_line, base + s,
                        (int32_t)rl, at_line);
            t &= ~(((uint64_t)1 << (s + rl)) - 1);
        }
        line += __builtin_popcountll(nlm);
    }
    if (in_token)
        count_token(&T, buf, n, lower, dedup_per_line, tok_start,
                    (int32_t)(n - tok_start), tok_line);
#else
    // Scalar fallback (build without AVX-512): per-byte boundary scan.
    const bool* tokt = kTables.tok[mode ? 1 : 0];
    long i = 0;
    int64_t line = 0;
    while (i < n && !T.oom) {
        uint8_t b = buf[i];
        if (b == '\n') { ++line; ++i; continue; }
        if (!tokt[b]) { ++i; continue; }
        long s = i;
        do { ++i; } while (i < n && tokt[buf[i]]);
        count_token(&T, buf, n, lower, dedup_per_line, s,
                    (int32_t)(i - s), line);
    }
#endif
    if (T.oom) { free(T.tbl); return -1; }

    // Emit: the exact engine FNV lanes, computed once per DISTINCT token
    // from its representative bytes (folded identically to the scan).
    long out = 0;
    for (long j = 0; j < T.cap; ++j) {
        if (!T.tbl[j].tag) continue;
        uint32_t h1 = OFF1, h2 = OFF2;
        const int64_t s = T.tbl[j].start;
        for (int32_t p = 0; p < T.tbl[j].len; ++p) {
            uint8_t c = fold[buf[s + p]];
            h1 = (h1 ^ c) * P1;
            h2 = (h2 ^ c) * P2;
        }
        out_h1[out] = h1;
        out_h2[out] = h2;
        out_count[out] = T.tbl[j].count;
        out_start[out] = s;
        out_len[out] = T.tbl[j].len;
        ++out;
    }
    free(T.tbl);
    return out;
}

// Whitespace-separated signed int64 parse (the external-sort ingest hot
// path): one pass emits values; any token that is not a fully-valid
// in-range integer sets *bad to its index and stops, so the Python caller
// can re-raise with numpy's exact error semantics.  Matches
// np.array(data.split(), dtype=int64) for valid input.
long dampr_parse_i64(const uint8_t* buf, long n, int64_t* out, long* bad) {
    long count = 0;
    long i = 0;
    *bad = -1;
    const uint64_t kCut = (uint64_t)1 << 63;  // |INT64_MIN|
    while (i < n) {
        uint8_t b = buf[i];
        if (b == ' ' || b == '\t' || b == '\n' || b == '\r' || b == '\v' ||
            b == '\f') {
            ++i;
            continue;
        }
        bool neg = false;
        if (b == '-' || b == '+') {
            neg = (b == '-');
            ++i;
        }
        uint64_t v = 0;
        long digits = 0;
        while (i < n) {
            uint8_t c = buf[i];
            if (c >= '0' && c <= '9') {
                uint64_t nv = v * 10u + (uint64_t)(c - '0');
                if (v > (kCut / 10u) || nv < v) { *bad = count; return count; }
                v = nv;
                ++digits;
                ++i;
            } else if (c == ' ' || c == '\t' || c == '\n' || c == '\r' ||
                       c == '\v' || c == '\f') {
                break;
            } else {
                *bad = count;  // junk inside the token
                return count;
            }
        }
        if (digits == 0 || v > (neg ? kCut : kCut - 1)) {
            *bad = count;
            return count;
        }
        out[count++] = neg ? (int64_t)(~v + 1u) : (int64_t)v;
    }
    return count;
}

// Batch dual-lane FNV over concatenated key bytes: key i is
// buf[offs[i], offs[i+1]).  The host-side hash for string keys that did
// not come from the tokenizer (re-keyed records, group keys, canonical
// object encodings): one C pass replaces numpy's column-by-column matrix
// scan.  Lanes match ops/hashing.py exactly.
void dampr_hash_bytes_batch(const uint8_t* buf, const int64_t* offs,
                            long n_keys, uint32_t* h1_out,
                            uint32_t* h2_out) {
    const uint32_t OFF1 = 2166136261u, OFF2 = 0x9747B28Cu;
    const uint32_t P1 = 16777619u, P2 = 0x85EBCA6Bu;
    for (long i = 0; i < n_keys; ++i) {
        uint32_t h1 = OFF1, h2 = OFF2;
        for (int64_t j = offs[i]; j < offs[i + 1]; ++j) {
            uint8_t c = buf[j];
            h1 = (h1 ^ c) * P1;
            h2 = (h2 ^ c) * P2;
        }
        h1_out[i] = h1;
        h2_out[i] = h2;
    }
}

}  // extern "C"
