// Host-side record codec: single-pass tokenizer + dual-lane FNV-1a hasher.
//
// The TPU compute path (XLA/segment kernels) starts from token hash lanes;
// producing those lanes from raw text is host work that pure numpy does in
// several passes (class lookup, boundary scan, padded gather, column-wise
// FNV).  This C++ pass fuses all of it: one walk over the chunk buffer emits
// token offsets, lengths, and both hash lanes.  This is the framework's
// native "host I/O layer" component (SURVEY §7.2): the reference is pure
// Python end-to-end, so there is no reference counterpart to mirror — the
// design target is simply to outrun the TPU feed.
//
// Hash compatibility: lanes MUST match ops/hashing.py exactly
// (_FNV_OFFSET1/2, _FNV_PRIME1/2 over utf-8 bytes) so tokens group with
// equal Python-string keys everywhere in the engine.
//
// Build: g++ -O3 -march=native -shared -fPIC tokenizer.cpp -o _native.so

#include <cstdint>
#include <cstdlib>
#include <cstring>

extern "C" {

// Token classification modes (keep in sync with dampr_tpu/ops/text.py):
//   mode 0: whitespace-delimited (str.split semantics, ASCII whitespace)
//   mode 1: word characters [0-9A-Za-z_] + bytes >= 128 (re [^\w]+ on ASCII)
// Table-driven: one L1-resident lookup per byte beats the range-compare
// chain in the hot scan.
struct ClassTables {
    bool tok[2][256];
    uint8_t fold[2][256];  // [lower?][byte] -> case-folded byte
    ClassTables() {
        for (int b = 0; b < 256; ++b) {
            tok[0][b] = !(b == ' ' || b == '\t' || b == '\n' || b == '\r' ||
                          b == '\v' || b == '\f');
            tok[1][b] = (b >= '0' && b <= '9') || (b >= 'A' && b <= 'Z') ||
                        (b >= 'a' && b <= 'z') || b == '_' || b >= 128;
            fold[0][b] = (uint8_t)b;
            fold[1][b] = (b >= 'A' && b <= 'Z') ? (uint8_t)(b + 32)
                                                : (uint8_t)b;
        }
    }
};
static const ClassTables kTables;

// Single pass: tokenize + hash + (optional) lowercase folding into the hash.
// Returns the number of tokens found.  Output arrays must hold at least
// n/2 + 1 entries (the worst case: alternating token/separator bytes).
// line_ids receives the 0-based line index of each token (newlines counted
// in the raw buffer) — pass nullptr to skip.
long dampr_tokenize_hash(const uint8_t* buf, long n, int mode, int lower,
                         int64_t* starts, int32_t* lens,
                         uint32_t* h1_out, uint32_t* h2_out,
                         int64_t* line_ids) {
    const uint32_t OFF1 = 2166136261u, OFF2 = 0x9747B28Cu;
    const uint32_t P1 = 16777619u, P2 = 0x85EBCA6Bu;

    const uint8_t* fold = kTables.fold[lower ? 1 : 0];
    const bool* tokt = kTables.tok[mode ? 1 : 0];
    long count = 0;
    long i = 0;
    int64_t line = 0;
    while (i < n) {
        uint8_t b = buf[i];
        if (b == '\n') { ++line; ++i; continue; }
        if (!tokt[b]) { ++i; continue; }
        // token run
        long s = i;
        uint32_t h1 = OFF1, h2 = OFF2;
        int64_t tok_line = line;
        do {
            uint8_t c = fold[buf[i]];
            h1 = (h1 ^ c) * P1;
            h2 = (h2 ^ c) * P2;
            ++i;
        } while (i < n && tokt[buf[i]]);
        starts[count] = s;
        lens[count] = (int32_t)(i - s);
        h1_out[count] = h1;
        h2_out[count] = h2;
        if (line_ids) line_ids[count] = tok_line;
        ++count;
    }
    return count;
}

// Fused tokenize + hash + count: one pass over the buffer feeding an
// open-addressing table keyed on the 64-bit hash pair *verified by byte
// comparison* — a probe hit requires equal hashes AND equal token bytes
// (case-folded when lower is set), so distinct tokens colliding in all 64
// hash bits occupy separate slots and are never silently merged.  (They then
// emit separate entries sharing (h1, h2); the engine's sort-based grouping
// repairs exactly that shape downstream by comparing real keys.)
//
// Emits one entry per distinct token: (h1, h2, count, representative
// offset/len).  With dedup_per_line != 0 a token increments at most once per
// newline-delimited line (document frequency — the reference TF-IDF
// benchmark's map+count, tf-idf-dampr.py:13-15).
//
// Returns the number of distinct tokens (<= out array capacity n/2+1), or -1
// on allocation failure.

// Byte equality of the tails past the inline 8-byte prefix (folded when
// lower is set).  Only runs for tokens longer than 8 bytes whose hashes,
// length, and prefix all matched — rare, so the random buffer access it
// costs is off the hot path.
static inline bool tail_eq(const uint8_t* buf, int64_t a, int64_t b,
                           int32_t len, int lower) {
    if (!lower) return memcmp(buf + a + 8, buf + b + 8, (size_t)(len - 8)) == 0;
    for (int32_t i = 8; i < len; ++i) {
        uint8_t x = buf[a + i], y = buf[b + i];
        if (x >= 'A' && x <= 'Z') x += 32;
        if (y >= 'A' && y <= 'Z') y += 32;
        if (x != y) return false;
    }
    return true;
}
long dampr_token_counts(const uint8_t* buf, long n, int mode, int lower,
                        int dedup_per_line,
                        uint32_t* out_h1, uint32_t* out_h2,
                        int64_t* out_count,
                        int64_t* out_start, int32_t* out_len) {
    const uint32_t OFF1 = 2166136261u, OFF2 = 0x9747B28Cu;
    const uint32_t P1 = 16777619u, P2 = 0x85EBCA6Bu;

    struct Entry {
        uint32_t h1, h2;
        uint64_t prefix;    // first <=8 folded bytes, zero-padded: the
                            // cache-local equality word for short tokens
        int64_t count;
        int64_t start;
        int32_t len;
        int64_t last_line;  // for per-line dedup; -1 = never seen
        bool used;
    };

    long cap_tbl = 1 << 16;
    Entry* tbl = (Entry*)calloc(cap_tbl, sizeof(Entry));
    if (!tbl) return -1;
    long used = 0;

    const uint8_t* fold = kTables.fold[lower ? 1 : 0];
    const bool* tokt = kTables.tok[mode ? 1 : 0];
    long i = 0;
    int64_t line = 0;
    while (i < n) {
        uint8_t b = buf[i];
        if (b == '\n') { ++line; ++i; continue; }
        if (!tokt[b]) { ++i; continue; }
        long s = i;
        uint32_t h1 = OFF1, h2 = OFF2;
        uint64_t prefix = 0;
        do {
            uint8_t c = fold[buf[i]];
            h1 = (h1 ^ c) * P1;
            h2 = (h2 ^ c) * P2;
            long off = i - s;
            if (off < 8) prefix |= ((uint64_t)c) << (off * 8);
            ++i;
        } while (i < n && tokt[buf[i]]);
        int32_t len = (int32_t)(i - s);

        // grow at 70% load
        if (used * 10 >= cap_tbl * 7) {
            long ncap = cap_tbl * 2;
            Entry* nt = (Entry*)calloc(ncap, sizeof(Entry));
            if (!nt) { free(tbl); return -1; }
            for (long j = 0; j < cap_tbl; ++j) {
                if (!tbl[j].used) continue;
                uint64_t h = ((uint64_t)tbl[j].h1 << 32) | tbl[j].h2;
                long k = (long)(h & (uint64_t)(ncap - 1));
                while (nt[k].used) k = (k + 1) & (ncap - 1);
                nt[k] = tbl[j];
            }
            free(tbl);
            tbl = nt;
            cap_tbl = ncap;
        }

        uint64_t h = ((uint64_t)h1 << 32) | h2;
        long k = (long)(h & (uint64_t)(cap_tbl - 1));
        while (tbl[k].used &&
               !(tbl[k].h1 == h1 && tbl[k].h2 == h2 && tbl[k].len == len &&
                 tbl[k].prefix == prefix &&
                 (len <= 8 || tail_eq(buf, tbl[k].start, s, len, lower))))
            k = (k + 1) & (cap_tbl - 1);
        if (!tbl[k].used) {
            tbl[k].used = true;
            tbl[k].h1 = h1;
            tbl[k].h2 = h2;
            tbl[k].prefix = prefix;
            tbl[k].count = 0;
            tbl[k].start = s;
            tbl[k].len = len;
            tbl[k].last_line = -1;
            ++used;
        }
        if (dedup_per_line) {
            if (tbl[k].last_line != line) {
                tbl[k].last_line = line;
                tbl[k].count += 1;
            }
        } else {
            tbl[k].count += 1;
        }
    }

    long out = 0;
    for (long j = 0; j < cap_tbl; ++j) {
        if (!tbl[j].used) continue;
        out_h1[out] = tbl[j].h1;
        out_h2[out] = tbl[j].h2;
        out_count[out] = tbl[j].count;
        out_start[out] = tbl[j].start;
        out_len[out] = tbl[j].len;
        ++out;
    }
    free(tbl);
    return out;
}

// Whitespace-separated signed int64 parse (the external-sort ingest hot
// path): one pass emits values; any token that is not a fully-valid
// in-range integer sets *bad to its index and stops, so the Python caller
// can re-raise with numpy's exact error semantics.  Matches
// np.array(data.split(), dtype=int64) for valid input.
long dampr_parse_i64(const uint8_t* buf, long n, int64_t* out, long* bad) {
    long count = 0;
    long i = 0;
    *bad = -1;
    const uint64_t kCut = (uint64_t)1 << 63;  // |INT64_MIN|
    while (i < n) {
        uint8_t b = buf[i];
        if (b == ' ' || b == '\t' || b == '\n' || b == '\r' || b == '\v' ||
            b == '\f') {
            ++i;
            continue;
        }
        bool neg = false;
        if (b == '-' || b == '+') {
            neg = (b == '-');
            ++i;
        }
        uint64_t v = 0;
        long digits = 0;
        while (i < n) {
            uint8_t c = buf[i];
            if (c >= '0' && c <= '9') {
                uint64_t nv = v * 10u + (uint64_t)(c - '0');
                if (v > (kCut / 10u) || nv < v) { *bad = count; return count; }
                v = nv;
                ++digits;
                ++i;
            } else if (c == ' ' || c == '\t' || c == '\n' || c == '\r' ||
                       c == '\v' || c == '\f') {
                break;
            } else {
                *bad = count;  // junk inside the token
                return count;
            }
        }
        if (digits == 0 || v > (neg ? kCut : kCut - 1)) {
            *bad = count;
            return count;
        }
        out[count++] = neg ? (int64_t)(~v + 1u) : (int64_t)v;
    }
    return count;
}

// Batch dual-lane FNV over concatenated key bytes: key i is
// buf[offs[i], offs[i+1]).  The host-side hash for string keys that did
// not come from the tokenizer (re-keyed records, group keys, canonical
// object encodings): one C pass replaces numpy's column-by-column matrix
// scan.  Lanes match ops/hashing.py exactly.
void dampr_hash_bytes_batch(const uint8_t* buf, const int64_t* offs,
                            long n_keys, uint32_t* h1_out,
                            uint32_t* h2_out) {
    const uint32_t OFF1 = 2166136261u, OFF2 = 0x9747B28Cu;
    const uint32_t P1 = 16777619u, P2 = 0x85EBCA6Bu;
    for (long i = 0; i < n_keys; ++i) {
        uint32_t h1 = OFF1, h2 = OFF2;
        for (int64_t j = offs[i]; j < offs[i + 1]; ++j) {
            uint8_t c = buf[j];
            h1 = (h1 ^ c) * P1;
            h2 = (h2 ^ c) * P2;
        }
        h1_out[i] = h1;
        h2_out[i] = h2;
    }
}

}  // extern "C"
