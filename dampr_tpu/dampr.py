"""The fluent DSL: lazy, value-semantic pipeline construction.

Parity surface: reference dampr/dampr.py (977 LoC) — ``Dampr`` entrypoints
(memory/text/json/read_input/from_dataset, 845-912), ``PMap`` chainable
collection ops (85-652), ``ARReduce`` associative reduces (654-709),
``PReduce`` general reduces (711-766), ``PJoin`` (768-829), ``ValueEmitter``
(19-51), multi-output ``Dampr.run`` (914-945).

Semantics preserved exactly: handles are immutable (every op returns a new
handle over a copied graph), ``a_group_by`` installs a map-side combiner,
``join`` unions graphs deduping shared prefixes, results stream back
key-sorted.

Stage granularity: every chained call compiles to its own
:class:`~dampr_tpu.graph.StageNode` — the graph IS the user's logical
plan, one node per op.  Fusing consecutive per-record ops into one
executed map stage is the job of the logical plan optimizer
(:mod:`dampr_tpu.plan`, ``settings.optimize``, on by default), which
``run()`` invokes before handing the graph to the runner; ``explain()``
renders the before/after plan.  ``checkpoint()`` is the explicit
materialization barrier the optimizer never fuses across.

TPU-native difference: ``a_group_by``/``fold_by``/``count``/``sum``/``mean``
carry :class:`~dampr_tpu.ops.segment.AssocOp` descriptors, so recognized
associative folds execute as device segment kernels end-to-end instead of
per-record Python.
"""

import itertools
import json
import logging
import random
import sys
import threading
import time
import weakref

from . import faults as _faults
from . import settings
from .base import (AssocFoldReducer, ComposedMapper, Filter, FlatMap, Inspect,
                   KeyedInnerJoin, KeyedLeftJoin, KeyedOuterJoin, KeyedReduce,
                   Map, MapAllJoin, MapCrossJoin, MapKeys, MapValues, Mapper,
                   PartialReduceCombiner, Prefix, Reducer, Rekey, Sample,
                   StreamMapper, StreamReducer, Streamable, Suffix, ValueMap,
                   _identity, _shared_instance_deepcopy)
from .dataset import CatDataset, Chunker
from .graph import GMap, Graph, Source
from .inputs import MemoryInput, PathInput, UrlsInput
from .ops import segment
from .runner import MTRunner


class RunStats(list):
    """Per-run metrics handle: a list of per-stage dicts (the historical
    ``ValueEmitter.stats`` shape, kept for compatibility) that is also
    *callable* — ``emitter.stats()`` returns the full run summary dict
    (the ``stats.json`` payload: stages, devtime, spill/merge/mesh totals,
    overlap stall fraction, retry counts, trace file location).  See
    :mod:`dampr_tpu.obs`."""

    def __init__(self, stages=(), summary=None):
        super(RunStats, self).__init__(stages)
        self.summary = summary if summary is not None else {}

    def __call__(self):
        return self.summary

    @property
    def trace_file(self):
        """Path of the run's Chrome trace-event JSON (None untraced)."""
        return self.summary.get("trace_file")

    @property
    def stats_file(self):
        """Path of the persisted stats.json (None untraced)."""
        return self.summary.get("stats_file")


class ValueEmitter(object):
    """Reads values from a completed run — the shell-friendly result handle
    (reference dampr.py:19-51).  ``stats`` holds the run's per-stage metrics
    (jobs, records, seconds) and, called as ``stats()``, the full run
    summary — observability the reference lacks."""

    def __init__(self, dataset):
        self.dataset = dataset
        self.stats = RunStats()

    def stream(self):
        for _k, v in self.dataset.read():
            yield v

    def read(self, k=None):
        if k is None:
            return list(self.stream())
        return list(itertools.islice(self.stream(), k))

    def __iter__(self):
        return self.stream()

    def delete(self):
        self.dataset.delete()




log = logging.getLogger("dampr_tpu.dampr")


def _drive_runner(make_runner, sources, resume):
    """Execute a run, with crash auto-resume when ``resume="auto"``.

    Auto mode behaves like ``resume=True`` (durable per-stage
    checkpoints) plus a whole-run retry loop: a failed run rebuilds a
    FRESH runner (the old one's store/obs state died with it) and
    re-executes — :mod:`dampr_tpu.resume` restores every stage whose
    manifest survived, so only work past the last durable checkpoint
    repeats, and results are byte-identical to a cold run (the resume
    exactness contract).  Fatal failures (kills, MemoryError,
    quarantine overflow) never auto-resume; transient-classified
    failures back off with jitter between attempts.  Returns
    ``(runner, datasets)``."""
    from . import plan as _plan

    auto = isinstance(resume, str) and resume.lower() == "auto"
    attempts = (max(0, settings.run_retries) + 1) if auto else 1
    prev_quarantine = None
    for attempt in range(attempts):
        runner = make_runner()
        if prev_quarantine is not None and getattr(
                runner, "_quarantine", None) is not None:
            # The retry adopts the failed attempt's quarantine: its
            # committed records (whose stages may now restore from
            # checkpoints without re-running) keep their budget charge
            # and audit lines — the fresh runner's constructor had
            # truncated the sink, so re-materialize it.
            runner._quarantine = prev_quarantine
            prev_quarantine.rewrite_sink()
        _plan.apply_to_runner(runner, sources)
        try:
            return runner, runner.run(sources)
        except BaseException as e:
            prev_quarantine = getattr(runner, "_quarantine", None)
            kind = _faults.classify(e)
            if kind == "fatal" or attempt + 1 >= attempts:
                raise
            delay = _faults.backoff(attempt) if kind == "transient" else 0.0
            log.warning(
                "run failed (%s: %s — classified %s); auto-resume "
                "attempt %d/%d re-executes from the last durable "
                "checkpoint%s", type(e).__name__, str(e)[:300], kind,
                attempt + 2, attempts,
                " in %.0f ms" % (delay * 1000) if delay else "")
            if delay:
                time.sleep(delay)


#: Every live pipeline handle (weakly held).  ``dampr-tpu-lint`` uses
#: this to discover the pipelines a linted module constructed at import
#: time without running anything; the DSL itself never reads it.
_live_handles = weakref.WeakSet()


class PBase(object):
    def __init__(self, source, pmer):
        assert isinstance(source, Source)
        self.source = source
        self.pmer = pmer
        _live_handles.add(self)

    def run(self, name=None, **kwargs):
        """Evaluate the composed graph; returns a ValueEmitter (its ``stats``
        attribute carries per-stage timing/record counters).

        ``resume=True`` makes the run durable: each completed stage
        checkpoints its output under the run's scratch root, and a rerun
        with the SAME ``name`` skips every stage whose checkpoint is still
        valid (see :mod:`dampr_tpu.resume`).  Requires an explicit name —
        an auto-generated one can never match a previous run.

        ``resume="auto"`` adds crash recovery on top: a run that fails
        with a non-fatal error re-executes in place (up to
        ``settings.run_retries`` times, transient failures backing off
        with jitter) from its last durable checkpoint manifest, and the
        result is byte-identical to a cold run.  Fatal failures
        (``MemoryError``, kills, quarantine-budget overflow) never
        auto-resume.  See ``docs/robustness.md``.

        Input-file identity is (path, size, mtime_ns) plus a content hash
        of the first and last 64KB.  An edit that preserves size AND
        resets mtime AND touches only the interior of a file >128KB is
        therefore undetectable without a full read; pass a fresh ``name``
        (or delete the scratch root) after such an edit.

        Starting any run under a name garbage-collects scratch blocks no
        checkpoint references (skipped while another live process is
        mid-run under the same name), so finish reading (or materialize)
        any OutputDataset from a previous run of the same name before
        rerunning it.
        """
        if kwargs.get("resume") and name is None:
            raise ValueError(
                "resume=True requires a stable run name: run(name=..., "
                "resume=True)")
        if name is None:
            name = "dampr/{}".format(random.random())
        if settings.seed is not None:
            _reset_sample_rngs()
        # The logical plan optimizer (dampr_tpu.plan) rewrites the stage
        # list before execution (applied inside _drive_runner, which
        # also implements resume="auto" crash recovery: a failed run
        # re-executes from its last durable checkpoint manifest).
        runner, ds = _drive_runner(
            lambda: self.pmer.runner(name, self.pmer.graph, **kwargs),
            [self.source], kwargs.get("resume"))
        em = ValueEmitter(ds[0])
        em.stats = RunStats(
            [s.as_dict() for s in getattr(runner, "stats", [])],
            getattr(runner, "run_summary", None))
        return em

    def explain(self, name=None):
        """Render this pipeline's logical plan — the constructed stage
        list, the optimizer's rewrite (fusion decisions, eliminated
        stages), and the cost layer's adaptive annotations — WITHOUT
        executing anything.  ``name`` points at a run name whose persisted
        stats history the adaptive layer would consume (see docs/plan.md).
        """
        from . import plan as _plan

        return _plan.explain_text(self.pmer.graph, [self.source], name=name)

    def validate(self, resume=False, num_processes=1, probe=True):
        """Pre-flight diagnostics for this pipeline — the
        ``dampr-tpu-lint`` surface as an API (docs/analysis.md), WITHOUT
        executing anything.  Returns the ordered diagnostic list
        (:class:`dampr_tpu.analyze.Diagnostic`, errors first; empty =
        clean).  Runs the full probe set — serialization, randomized
        associativity, jax traceability — regardless of
        ``settings.analyze``: an explicit call is its own opt-in.
        ``num_processes > 1`` promotes unpicklable captures to errors
        (rank dispatch WILL fail on them); ``resume=True`` adds the
        checkpoint fingerprint-stability checks; ``probe=False`` keeps
        it to the fast bytecode-only classification."""
        from .analyze import validate as _av

        return _av.validate_graph(
            self.pmer.graph, resume=resume,
            num_processes=num_processes, probe_traceable=probe,
            probe_assoc=probe, probe_pickle=probe)

    def read(self, k=None, **kwargs):
        """Shorthand for run() + read()."""
        return self.run(**kwargs).read(k)

    def submit(self, url, tenant="default", **kwargs):
        """Ship this composed pipeline to a ``dampr-tpu-serve`` daemon
        instead of running it in-process; returns a
        :class:`dampr_tpu.serve.RemoteJob` (``.wait()`` / ``.result()``
        / ``.read()`` / ``.cancel()``).  The plan travels validated and
        fingerprinted — an unpicklable capture fails fast client-side
        with the coded ``DTA401`` diagnostic, and identical in-flight
        submissions coalesce onto one run daemon-side.  See
        docs/serve.md."""
        from .serve.client import ServeClient

        return ServeClient(url).submit(self, tenant=tenant, **kwargs)


class _TopKBlocks(Mapper):
    """Per-chunk top-k candidate selection at block granularity: numeric
    1D value lanes select with one np.argpartition per block, then the
    tiny per-block winners merge through nlargest.  Non-block chunks and
    object/composite lanes stream through the same decorated-pair
    nlargest as the DSL's generic path — emitted candidate records are
    identical either way: ``(1, (x, x))``."""

    __deepcopy__ = _shared_instance_deepcopy

    def __init__(self, k):
        self.k = k

    def map(self, *datasets):
        import heapq

        import numpy as np

        from .blocks import pylist

        assert len(datasets) == 1
        ds = datasets[0]
        k = self.k
        if k <= 0:
            return
        if hasattr(ds, "iter_blocks"):
            blocks = [b for b in ds.iter_blocks() if len(b)]
            if all(b.values.dtype != object and b.values.ndim == 1
                   for b in blocks):
                cands = []
                for b in blocks:
                    v = b.values
                    if len(v) > k:
                        v = v[np.argpartition(v, len(v) - k)[len(v) - k:]]
                    cands.extend((x, x) for x in pylist(v))
                for p in heapq.nlargest(k, cands):
                    yield 1, p
                return
        it = (v for _k, v in ds.read())
        for p in heapq.nlargest(k, ((x, x) for x in it)):
            yield 1, p


class PMap(PBase):
    """A lazy collection.  Every chained op lands in the graph as its own
    stage node immediately; the plan optimizer (:mod:`dampr_tpu.plan`)
    re-fuses pure per-record chains into single executed map stages at
    ``run()`` time."""

    def __init__(self, source, pmer, agg=None):
        super(PMap, self).__init__(source, pmer)
        # Vestigial (pre-plan-optimizer API): per-record ops used to queue
        # here until the next checkpoint; they now land in the graph
        # immediately, so there are never pending ops.  The attribute is
        # kept because callers probe `.agg` truthiness to decide whether a
        # checkpoint is needed before handing the graph to a runner —
        # but PASSING pending mappers would silently drop them, so fail
        # loudly instead.
        assert not agg, (
            "PMap no longer queues pending mappers; chain ops through the "
            "DSL (each lands in the graph immediately) instead of passing "
            "agg")
        self.agg = []

    # -- stage plumbing ----------------------------------------------------
    def _add_mapper(self, mapper, options=None):
        assert isinstance(mapper, Streamable)
        source, pmer = self.pmer._add_mapper([self.source], mapper,
                                             options=options)
        return PMap(source, pmer)

    def _add_map(self, f):
        return self._add_mapper(Map(f))

    def _materialized_for_reduce(self):
        """A handle whose source a GReduce may consume directly.  Map-stage
        outputs carry the hash-routing/sorted-run invariants a reduce
        depends on by construction (the runner's ``feeds_reduce`` view);
        taps, sinks, and reduce outputs get an identity copy stage — the
        re-routing pass the alias provenance gate would force anyway
        (reduce outputs are registered under the reduce job's pid with
        whatever keys the reducer emitted)."""
        for stage in self.pmer.graph.stages:
            if stage.output == self.source:
                if isinstance(stage, GMap):
                    return self
                break
        source, pmer = self.pmer._add_mapper([self.source], Map(_identity))
        return PMap(source, pmer)

    def checkpoint(self, force=False, combiner=None, options=None):
        """Install an EXPLICIT materialization barrier: the stage's output
        is computed and pinned at this boundary, and the plan optimizer
        never fuses across it (``options["barrier"]``).  Use it to share a
        sub-graph between branches (dedup happens in Graph.union) or to
        force a spill/merge boundary; a redundant barrier over an
        already-materialized input aliases at run time instead of copying.
        ``force`` is accepted for API compatibility (every checkpoint now
        materializes)."""
        opts = dict(options) if options else {}
        opts.setdefault("barrier", True)
        source, pmer = self.pmer._add_mapper(
            [self.source], Map(_identity), combiner=combiner, options=opts)
        return PMap(source, pmer)

    # -- per-record ops ----------------------------------------------------
    # Each queues a typed RecordOp (base.py): the engine executes chains of
    # these over whole batches — one tight loop per op per batch — instead
    # of per-record generator frames, and falls back to their stream()
    # lowering wherever a generator is needed.
    def map(self, f):
        """Map each value through ``f``."""
        return self._add_mapper(ValueMap(f))

    def map_values(self, f):
        """Map the second element of two-tuple values."""
        return self._add_mapper(MapValues(f))

    def map_keys(self, f):
        """Map the first element of two-tuple values."""
        return self._add_mapper(MapKeys(f))

    def prefix(self, f):
        """value -> (f(value), value)."""
        return self._add_mapper(Prefix(f))

    def suffix(self, f):
        """value -> (value, f(value))."""
        return self._add_mapper(Suffix(f))

    def filter(self, f):
        """Keep values where predicate holds."""
        return self._add_mapper(Filter(f))

    def flat_map(self, f):
        """Map values to iterables and flatten."""
        return self._add_mapper(FlatMap(f))

    def sample(self, prob):
        """Uniformly keep ``prob`` of records."""
        assert 0 <= prob <= 1.0
        return self._add_mapper(Sample(prob, _get_rand))

    def inspect(self, prefix="", exit=False):
        """Print records as they stream through (debug passthrough)."""
        ins = self._add_mapper(Inspect(prefix))
        if exit:
            ins.run()
            sys.exit(0)
        return ins

    # -- grouping ----------------------------------------------------------
    def group_by(self, key, vf=None):
        """General (non-associative) grouping; returns PReduce.  ``vf``
        defaults to the identity (records keep their value)."""
        pm = self._add_mapper(Rekey(key, vf))
        return PReduce(pm.source, pm.pmer)

    def a_group_by(self, key, vf=None):
        """Associative grouping: enables map-side combining before the
        shuffle (the combiner stage lands when the binop is known).
        ``vf`` defaults to the identity."""
        pm = self._add_mapper(Rekey(key, vf))
        return ARReduce(pm)

    def fold_by(self, key, binop, value=lambda x: x, **options):
        """Shortcut for ``a_group_by(key, value).reduce(binop)``."""
        return self.a_group_by(key, value).reduce(binop, **options)

    def fold_values(self, binop, **options):
        """Fold values by each record's EXISTING key — no re-key map pass.
        Blocks flow into the combine with their cached hash lanes and
        (numeric) value lanes intact, so the whole aggregation stays on the
        vectorized path with zero per-record Python.  Use after block
        mappers that already emit records keyed by the group key
        (ops.text.TokenCounts/DocFreq with ``pair_values=False``).  Beyond
        the reference surface: its fold_by always re-keys per record
        (reference dampr.py:406-410)."""
        return ARReduce(self).reduce(binop, **options)

    def sort_by(self, key, **options):
        """Globally sort values by a key function (results merge key-sorted).
        The re-key stage is a plain map node — a sort_by feeding further
        per-record ops fuses with them (mid-pipeline record order is not
        part of the contract; only the FINAL read merges key-sorted)."""
        return self._add_mapper(Rekey(key), options=options or None)

    def count(self, key=lambda x: x, **options):
        """Count values per key — compiles to a device segment-sum."""
        return self.a_group_by(key, lambda v: 1).reduce(segment.SUM, **options)

    def mean(self, key=lambda x: 1, value=lambda x: x, **options):
        """Per-key mean: the (sum, count) pair IS the value column — int
        and float values build a 2D composite lane the segment sum kernels
        fold in one vectorized pass (blocks._tuple_column); anything else
        falls back to an exact pairwise object-lane fold.  Same observable
        behavior as the reference's per-record tuple binop (ref
        dampr.py:445-458), different execution: the pair never exists as
        a per-record Python object on the numeric path."""
        def _pair(v):
            x = value(v)
            # count carries the value's own lane dtype so the pair stays
            # type-uniform (a mixed (float, int) tuple would force the
            # object lane); ints keep exact int64 sums.
            return (x, 1.0) if type(x) is float else (x, 1)

        def _avg(x):
            return (x[0], x[1][0] / float(x[1][1]))

        return (self.a_group_by(key, _pair)
                .reduce(segment.PAIR_SUM, **options)
                .map(_avg))

    def len(self):
        """Count all items in the collection.  The map side never touches
        records: text chunks count owned newlines, block-backed chunks sum
        block lengths (CountRecords).  Valid at ANY point in a chain —
        the handle's source always refers to the realized record stream
        (ops are stage nodes, never pending)."""
        def _sum_counts(groups):
            totals = [c for _k, cs in groups for c in cs]
            return ((1, sum(totals)),) if totals else ()

        from .ops.text import CountRecords
        return (self.custom_mapper(CountRecords())
                .partition_reduce(_sum_counts)
                .map(lambda x: x[1]))

    def topk(self, k, value=None):
        """Top-k values by a comparable sort key.  Identity-keyed
        block-backed partitions select candidates with one np.argpartition
        per block — no per-record Python; everything else decorates once
        and takes ``heapq.nlargest`` per partition.  Candidates from all
        partitions merge through one global nlargest.  Ordering criterion
        is the (sort_key, value) pair, so tie behavior matches the
        reference's heap of pairs (ref dampr.py:621-652)."""
        import heapq

        vf = value

        def _cands(values):
            pairs = (((x, x) for x in values) if vf is None
                     else ((vf(x), x) for x in values))
            return ((1, p) for p in heapq.nlargest(k, pairs))

        def _select(groups):
            cands = (p for _one, ps in groups for p in ps)
            return ((p[1], 1) for p in heapq.nlargest(k, cands))

        if vf is None:
            head = self.custom_mapper(_TopKBlocks(k))
        else:
            head = self.partition_map(_cands)
        return head.partition_reduce(_select).map(lambda x: x[0])

    # -- custom operators --------------------------------------------------
    def custom_mapper(self, mapper, name=None, **options):
        """Install a user Mapper instance as its own stage (low-level).
        A bare Streamable (no ``map``) is wrapped so the stage can drive
        it over its input dataset."""
        if isinstance(mapper, Streamable) and not isinstance(mapper, Mapper):
            mapper = ComposedMapper(Map(_identity), mapper)
        assert isinstance(mapper, Mapper)
        source, pmer = self.pmer._add_mapper([self.source], mapper,
                                             options=options or None)
        return PMap(source, pmer)

    def custom_reducer(self, reducer, name=None, **options):
        """Install a user Reducer instance (low-level)."""
        assert isinstance(reducer, Reducer)
        me = self._materialized_for_reduce()
        source, pmer = me.pmer._add_reducer([me.source], reducer,
                                            options=options or None)
        return PMap(source, pmer)

    def partition_map(self, f, **options):
        """Map a whole partition's value iterator (runs on empty partitions)."""
        return self.custom_mapper(StreamMapper(f), **options)

    def partition_reduce(self, f):
        """Reduce a whole partition's group iterator (runs on empty
        partitions)."""
        return self.custom_reducer(StreamReducer(f))

    # -- two-source ops ----------------------------------------------------
    def join(self, other):
        """Co-partitioned join with another collection; returns PJoin."""
        assert isinstance(other, PBase)
        me = self._materialized_for_reduce()
        if isinstance(other, PMap):
            other = other._materialized_for_reduce()
        pmer = Dampr(me.pmer.graph.union(other.pmer.graph))
        return PJoin(me.source, pmer, other.source)

    def cross_right(self, other, cross, memory=False):
        """Map-side cross product, loop order right-major."""
        assert isinstance(other, PMap)
        return other.cross_left(self, lambda xi, yi: cross(yi, xi), memory)

    def cross_left(self, other, cross, memory=False, **options):
        """Map-side cross product (broadcast join).  ``memory=True`` pins the
        replicated side in RAM."""
        def _cross(k1, v1, k2, v2):
            yield k1, cross(v2, v1)

        pmer = Dampr(self.pmer.graph.union(other.pmer.graph))
        source, pmer = pmer._add_mapper(
            [other.source, self.source], MapCrossJoin(_cross, cache=memory),
            combiner=None, options=options)
        return PMap(source, pmer)

    def cross_set(self, other, cross, agg=None, **options):
        """Load the whole other side through ``agg`` and pass it to every
        record."""
        def _cross(k1, v1, right):
            yield k1, cross(v1, right)

        if agg is None:
            agg = list

        def _aggregate(d):
            return agg(v for _k, v in d)

        pmer = Dampr(self.pmer.graph.union(other.pmer.graph))
        source, pmer = pmer._add_mapper(
            [other.source, self.source], MapAllJoin(_cross, _aggregate),
            combiner=None, options=options)
        return PMap(source, pmer)

    # -- persistence -------------------------------------------------------
    def cached(self, **options):
        """Materialize and pin this stage's output in RAM (never spills)."""
        options["memory"] = True
        return self.checkpoint(force=True, options=options)

    def sink(self, path):
        """Write each value as a text line into part-files under ``path``
        (durable — exempt from cleanup).  The sink node starts as an
        identity sinker; the plan optimizer composes any pure record
        chain feeding it into the sinker, so transformed records stream
        straight to disk without an intermediate materialization."""
        source, pmer = self.pmer._add_sink([self.source], Map(_identity),
                                           path=path, options=None)
        return PMap(source, pmer)

    def sink_tsv(self, path):
        """Tab-join tuple values, then sink."""
        return self.map(lambda x: u"\t".join(str(p) for p in x)).sink(path)

    def sink_json(self, path):
        """JSON-serialize values line-delimited, then sink."""
        return self.map(json.dumps).sink(path)


class ARReduce(object):
    """Associative reduce handle: folds map-side, shuffles compacted partials,
    folds again reduce-side (reference dampr.py:654-709; the decomposition is
    the reference's PartialReduceCombiner pipeline restated as segment
    kernels — see SURVEY §3.3)."""

    def __init__(self, pmap):
        self.pmap = pmap

    def reduce(self, binop, reduce_buffer=1000, **options):
        """Reduce groups with an associative binop.  ``reduce_buffer`` is
        accepted for API parity; block-size accounting replaces it.

        Plants an identity stage carrying the map-side combiner (the
        local-combine half of the shuffle) ahead of the final-fold
        reduce; the plan optimizer hoists that combiner into the
        producing map stage, so optimized runs fold map-side inside the
        producer's own jobs."""
        op = segment.as_assoc_op(binop)
        options.update({"binop": op, "reduce_buffer": reduce_buffer})
        source, pmer = self.pmap.pmer._add_mapper(
            [self.pmap.source], Map(_identity),
            combiner=PartialReduceCombiner(op), options=options)
        new_source, pmer = pmer._add_reducer(
            [source], AssocFoldReducer(op), options=options)
        return PMap(new_source, pmer)

    def first(self, **options):
        """First value seen per key."""
        return self.reduce(segment.FIRST, **options)

    def sum(self, **options):
        """Sum values per key — device segment-sum end-to-end for numeric
        values."""
        return self.reduce(segment.SUM, **options)


class PReduce(PBase):
    """General grouped collection (post group_by)."""

    def reduce(self, f):
        """``f(key, value_iter) -> value`` per group."""
        new_source, pmer = self.pmer._add_reducer([self.source], KeyedReduce(f))
        return PMap(new_source, pmer)

    def unique(self, key=lambda x: x):
        """Distinct values per group (first occurrence wins)."""
        def _uniq(k, it):
            seen = set()
            agg = []
            for v in it:
                fv = key(v)
                if fv not in seen:
                    seen.add(fv)
                    agg.append(v)
            return agg

        return self.reduce(_uniq)

    def join(self, other):
        """Join grouped data with another collection."""
        assert isinstance(other, PBase)
        if isinstance(other, PMap):
            other = other._materialized_for_reduce()
        pmer = Dampr(self.pmer.graph.union(other.pmer.graph))
        return PJoin(self.source, pmer, other.source)

    def partition_reduce(self, f):
        """Whole-partition reduce over the grouped stream."""
        new_source, pmer = self.pmer._add_reducer([self.source],
                                                  StreamReducer(f))
        return PMap(new_source, pmer)


class PJoin(PBase):
    """Join handle over two co-partitioned grouped sources."""

    def __init__(self, source, pmer, right):
        super(PJoin, self).__init__(source, pmer)
        self.right = right

    def run(self, name=None, **kwargs):
        return self.reduce(lambda l, r: (list(l), list(r))).run(name, **kwargs)

    def explain(self, name=None):
        # A bare PJoin runs through the default pairing reduce; explain
        # the plan that run() would actually execute.
        return self.reduce(
            lambda l, r: (list(l), list(r))).explain(name=name)

    def reduce(self, aggregate, many=False):
        """Inner join: ``aggregate(left_iter, right_iter)`` per matched key;
        ``many=True`` flattens the result into separate records."""
        def _reduce(k, left, right):
            return aggregate(left, right)

        source, pmer = self.pmer._add_reducer(
            [self.source, self.right], KeyedInnerJoin(_reduce, many))
        return PMap(source, pmer)

    def left_reduce(self, aggregate):
        """Left join: missing right keys see an empty iterator."""
        def _reduce(k, left, right):
            return aggregate(left, right)

        source, pmer = self.pmer._add_reducer(
            [self.source, self.right], KeyedLeftJoin(_reduce))
        return PMap(source, pmer)

    def outer_reduce(self, aggregate):
        """Full outer join: whichever side is missing a key sees an empty
        iterator.  (New capability — the reference defines but never exposes
        an outer join, and its implementation is broken: base.py:355, 366.)"""
        def _reduce(k, left, right):
            return aggregate(left, right)

        source, pmer = self.pmer._add_reducer(
            [self.source, self.right], KeyedOuterJoin(_reduce))
        return PMap(source, pmer)


class Dampr(object):
    """Entrypoint: constructors for sources + the multi-output run."""

    def __init__(self, graph=None, runner=None):
        self.graph = Graph() if graph is None else graph
        self.runner = MTRunner if runner is None else runner

    @classmethod
    def memory(cls, items, partitions=50):
        """In-memory collection (keys = positions)."""
        mi = MemoryInput(list(enumerate(items)), partitions)
        source, ng = Graph().add_input(mi)
        return PMap(source, cls(ng))

    @classmethod
    def read_input(cls, *datasets):
        """Read from datasets / chunkers directly."""
        if len(datasets) == 1:
            ds = datasets[0]
        else:
            ds = CatDataset(list(datasets))
        source, ng = Graph().add_input(ds)
        return PMap(source, cls(ng))

    @classmethod
    def text(cls, fname, chunk_size=16 * 1024 ** 2, followlinks=False):
        """Newline-delimited text from a file/dir/glob, split into byte-range
        chunks."""
        return cls.read_input(PathInput(fname, chunk_size, followlinks))

    @classmethod
    def json(cls, *args, **kwargs):
        """Line-delimited JSON records."""
        return cls.text(*args, **kwargs).map(json.loads)

    @classmethod
    def urls(cls, urls, skip_on_error=True):
        """Fetch newline-delimited text over HTTP, one chunk per URL."""
        return cls.read_input(UrlsInput(urls, skip_on_error))

    @classmethod
    def from_dataset(cls, dataset):
        """Wrap raw stage outputs / custom Dataset subclasses as an input."""
        assert isinstance(dataset, Chunker)
        source, ng = Graph().add_input(dataset)
        return PMap(source, cls(ng))

    @classmethod
    def run(cls, *pmers, **kwargs):
        """Run several graphs in one pass; shared prefixes compute once.
        Returns one ValueEmitter per argument."""
        assert len(pmers) > 0, "Need at least one graph to run!"
        sources = []
        graph = None
        pmer = None
        for i, pmer in enumerate(pmers):
            if isinstance(pmer, PJoin):
                pmer = pmer.reduce(lambda l, r: (list(l), list(r)))
            graph = pmer.pmer.graph if i == 0 else pmer.pmer.graph.union(graph)
            sources.append(pmer.source)

        if kwargs.get("resume") and kwargs.get("name") is None:
            raise ValueError(
                "resume=True requires a stable run name: Dampr.run(..., "
                "name=..., resume=True)")
        name = kwargs.pop("name", "dampr/{}".format(random.random()))
        if settings.seed is not None:
            _reset_sample_rngs()
        runner, ds = _drive_runner(
            lambda: pmer.pmer.runner(name, graph, **kwargs),
            sources, kwargs.get("resume"))
        stats = RunStats([s.as_dict() for s in getattr(runner, "stats", [])],
                         getattr(runner, "run_summary", None))
        emitters = []
        for d in ds:
            em = ValueEmitter(d)
            em.stats = stats
            emitters.append(em)
        return emitters

    # -- graph builders (value semantics) ----------------------------------
    def _add_mapper(self, *args, **kwargs):
        output, ng = self.graph.add_mapper(*args, **kwargs)
        return output, Dampr(ng)

    def _add_reducer(self, *args, **kwargs):
        output, ng = self.graph.add_reducer(*args, **kwargs)
        return output, Dampr(ng)

    def _add_sink(self, *args, **kwargs):
        output, ng = self.graph.add_sink(*args, **kwargs)
        return output, Dampr(ng)


# Per-thread RNG for sample(): jobs run on threads, and a shared Random would
# serialize them on its lock and interleave streams nondeterministically.
#
# Seeding (settings.seed, satellite of the plan-optimizer work): with a
# seed set, each thread's RNG derives deterministically from
# (seed, per-run thread index) — re-derived at every run start via
# _reset_sample_rngs() — so sampled pipelines reproduce exactly whenever
# job->thread assignment is deterministic: serial runs (max_processes=1,
# or single-job stages, where jobs execute on the stage-walk thread)
# always are.  Parallel runs get deterministic per-thread STREAMS but a
# nondeterministic job->thread mapping, so only the distribution is
# pinned — the documented limit (docs/plan.md).  Default (seed=None)
# keeps the historical time-seeded behavior.
_RAND_LOCAL = threading.local()
_RAND_LOCK = threading.Lock()
_RAND_STATE = {"epoch": 0, "next_index": None}


def _reset_sample_rngs():
    """Start a fresh deterministic RNG generation (called at run start
    when settings.seed is set): every thread re-seeds from
    (seed, index-within-run) at its next draw."""
    with _RAND_LOCK:
        _RAND_STATE["epoch"] += 1
        _RAND_STATE["next_index"] = itertools.count()


def _get_rand():
    seed = settings.seed
    st = _RAND_LOCAL
    if seed is None:
        r = getattr(st, "rand", None)
        if r is None or getattr(st, "seeded", False):
            # Random() seeds from os.urandom: always distinct per thread.
            # (The old time.time()+thread_ident seed was quantized to
            # ~16 ms steps by float64 at pthread-address magnitudes, so a
            # recycled ident within that window REPLAYED the stream.)
            r = random.Random()
            st.rand, st.seeded = r, False
        return r
    epoch = _RAND_STATE["epoch"]
    if (getattr(st, "epoch", None) != epoch
            or not getattr(st, "seeded", False)):
        with _RAND_LOCK:
            counter = _RAND_STATE["next_index"]
            if counter is None:  # seeded draw before any run: index 0 et seq
                counter = _RAND_STATE["next_index"] = itertools.count()
            idx = next(counter)
        st.rand = random.Random(seed * 1000003 + idx * 7919)
        st.epoch, st.seeded = epoch, True
    return st.rand


def setup_logging(debug=False):
    level = logging.DEBUG if debug else logging.INFO
    logging.basicConfig(
        level=level,
        format="%(asctime)s [%(levelname)s] %(name)s: %(message)s")
