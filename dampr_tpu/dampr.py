"""The fluent DSL: lazy, value-semantic pipeline construction.

Parity surface: reference dampr/dampr.py (977 LoC) — ``Dampr`` entrypoints
(memory/text/json/read_input/from_dataset, 845-912), ``PMap`` chainable
collection ops (85-652), ``ARReduce`` associative reduces (654-709),
``PReduce`` general reduces (711-766), ``PJoin`` (768-829), ``ValueEmitter``
(19-51), map fusion (959-967), multi-output ``Dampr.run`` (914-945).

Semantics preserved exactly: handles are immutable (every op returns a new
handle over a copied graph), consecutive per-record ops fuse into one map
stage, ``a_group_by`` installs a map-side combiner, ``join`` unions graphs
deduping shared prefixes, results stream back key-sorted.

TPU-native difference: ``a_group_by``/``fold_by``/``count``/``sum``/``mean``
carry :class:`~dampr_tpu.ops.segment.AssocOp` descriptors, so recognized
associative folds execute as device segment kernels end-to-end instead of
per-record Python.
"""

import itertools
import json
import logging
import random
import sys
import threading
import time

from .base import (AssocFoldReducer, Filter, FlatMap, Inspect, KeyedInnerJoin,
                   KeyedLeftJoin, KeyedOuterJoin, KeyedReduce, Map, MapAllJoin,
                   MapCrossJoin, MapKeys, MapValues, Mapper,
                   PartialReduceCombiner, Prefix, Reducer, Rekey, Sample,
                   StreamMapper, StreamReducer, Streamable, Suffix, ValueMap,
                   _identity, _shared_instance_deepcopy, fuse)
from .dataset import CatDataset, Chunker
from .graph import Graph, Source
from .inputs import MemoryInput, PathInput, UrlsInput
from .ops import segment
from .runner import MTRunner


class RunStats(list):
    """Per-run metrics handle: a list of per-stage dicts (the historical
    ``ValueEmitter.stats`` shape, kept for compatibility) that is also
    *callable* — ``emitter.stats()`` returns the full run summary dict
    (the ``stats.json`` payload: stages, devtime, spill/merge/mesh totals,
    overlap stall fraction, retry counts, trace file location).  See
    :mod:`dampr_tpu.obs`."""

    def __init__(self, stages=(), summary=None):
        super(RunStats, self).__init__(stages)
        self.summary = summary if summary is not None else {}

    def __call__(self):
        return self.summary

    @property
    def trace_file(self):
        """Path of the run's Chrome trace-event JSON (None untraced)."""
        return self.summary.get("trace_file")

    @property
    def stats_file(self):
        """Path of the persisted stats.json (None untraced)."""
        return self.summary.get("stats_file")


class ValueEmitter(object):
    """Reads values from a completed run — the shell-friendly result handle
    (reference dampr.py:19-51).  ``stats`` holds the run's per-stage metrics
    (jobs, records, seconds) and, called as ``stats()``, the full run
    summary — observability the reference lacks."""

    def __init__(self, dataset):
        self.dataset = dataset
        self.stats = RunStats()

    def stream(self):
        for _k, v in self.dataset.read():
            yield v

    def read(self, k=None):
        if k is None:
            return list(self.stream())
        return list(itertools.islice(self.stream(), k))

    def __iter__(self):
        return self.stream()

    def delete(self):
        self.dataset.delete()




class PBase(object):
    def __init__(self, source, pmer):
        assert isinstance(source, Source)
        self.source = source
        self.pmer = pmer

    def run(self, name=None, **kwargs):
        """Evaluate the composed graph; returns a ValueEmitter (its ``stats``
        attribute carries per-stage timing/record counters).

        ``resume=True`` makes the run durable: each completed stage
        checkpoints its output under the run's scratch root, and a rerun
        with the SAME ``name`` skips every stage whose checkpoint is still
        valid (see :mod:`dampr_tpu.resume`).  Requires an explicit name —
        an auto-generated one can never match a previous run.

        Input-file identity is (path, size, mtime_ns) plus a content hash
        of the first and last 64KB.  An edit that preserves size AND
        resets mtime AND touches only the interior of a file >128KB is
        therefore undetectable without a full read; pass a fresh ``name``
        (or delete the scratch root) after such an edit.

        Starting any run under a name garbage-collects scratch blocks no
        checkpoint references (skipped while another live process is
        mid-run under the same name), so finish reading (or materialize)
        any OutputDataset from a previous run of the same name before
        rerunning it.
        """
        if kwargs.get("resume") and name is None:
            raise ValueError(
                "resume=True requires a stable run name: run(name=..., "
                "resume=True)")
        if name is None:
            name = "dampr/{}".format(random.random())
        runner = self.pmer.runner(name, self.pmer.graph, **kwargs)
        ds = runner.run([self.source])
        em = ValueEmitter(ds[0])
        em.stats = RunStats(
            [s.as_dict() for s in getattr(runner, "stats", [])],
            getattr(runner, "run_summary", None))
        return em

    def read(self, k=None, **kwargs):
        """Shorthand for run() + read()."""
        return self.run(**kwargs).read(k)


class _TopKBlocks(Mapper):
    """Per-chunk top-k candidate selection at block granularity: numeric
    1D value lanes select with one np.argpartition per block, then the
    tiny per-block winners merge through nlargest.  Non-block chunks and
    object/composite lanes stream through the same decorated-pair
    nlargest as the DSL's generic path — emitted candidate records are
    identical either way: ``(1, (x, x))``."""

    __deepcopy__ = _shared_instance_deepcopy

    def __init__(self, k):
        self.k = k

    def map(self, *datasets):
        import heapq

        import numpy as np

        from .blocks import pylist

        assert len(datasets) == 1
        ds = datasets[0]
        k = self.k
        if k <= 0:
            return
        if hasattr(ds, "iter_blocks"):
            blocks = [b for b in ds.iter_blocks() if len(b)]
            if all(b.values.dtype != object and b.values.ndim == 1
                   for b in blocks):
                cands = []
                for b in blocks:
                    v = b.values
                    if len(v) > k:
                        v = v[np.argpartition(v, len(v) - k)[len(v) - k:]]
                    cands.extend((x, x) for x in pylist(v))
                for p in heapq.nlargest(k, cands):
                    yield 1, p
                return
        it = (v for _k, v in ds.read())
        for p in heapq.nlargest(k, ((x, x) for x in it)):
            yield 1, p


class PMap(PBase):
    """A lazy collection; consecutive per-record ops are queued in ``agg`` and
    fused into a single map stage at the next checkpoint."""

    def __init__(self, source, pmer, agg=None):
        super(PMap, self).__init__(source, pmer)
        self.agg = [] if agg is None else agg

    def run(self, name=None, **kwargs):
        if len(self.agg) > 0:
            return self.checkpoint().run(name, **kwargs)
        return super(PMap, self).run(name, **kwargs)

    # -- fusion plumbing ---------------------------------------------------
    def _add_mapper(self, mapper):
        assert isinstance(mapper, Streamable)
        return PMap(self.source, self.pmer, self.agg + [mapper])

    def _add_map(self, f):
        return self._add_mapper(Map(f))

    def checkpoint(self, force=False, combiner=None, options=None):
        """Fuse queued maps into a materialized stage boundary; shared
        sub-graphs are then computed once (dedup happens in Graph.union)."""
        if len(self.agg) > 0 or force:
            aggs = [Map(_identity)] if len(self.agg) == 0 else self.agg[:]
            source, pmer = self.pmer._add_mapper(
                [self.source], fuse(aggs), combiner=combiner, options=options)
            return PMap(source, pmer)
        return self

    # -- per-record ops ----------------------------------------------------
    # Each queues a typed RecordOp (base.py): the engine executes chains of
    # these over whole batches — one tight loop per op per batch — instead
    # of per-record generator frames, and falls back to their stream()
    # lowering wherever a generator is needed.
    def map(self, f):
        """Map each value through ``f``."""
        return self._add_mapper(ValueMap(f))

    def map_values(self, f):
        """Map the second element of two-tuple values."""
        return self._add_mapper(MapValues(f))

    def map_keys(self, f):
        """Map the first element of two-tuple values."""
        return self._add_mapper(MapKeys(f))

    def prefix(self, f):
        """value -> (f(value), value)."""
        return self._add_mapper(Prefix(f))

    def suffix(self, f):
        """value -> (value, f(value))."""
        return self._add_mapper(Suffix(f))

    def filter(self, f):
        """Keep values where predicate holds."""
        return self._add_mapper(Filter(f))

    def flat_map(self, f):
        """Map values to iterables and flatten."""
        return self._add_mapper(FlatMap(f))

    def sample(self, prob):
        """Uniformly keep ``prob`` of records."""
        assert 0 <= prob <= 1.0
        return self._add_mapper(Sample(prob, _get_rand))

    def inspect(self, prefix="", exit=False):
        """Print records as they stream through (debug passthrough)."""
        ins = self._add_mapper(Inspect(prefix))
        if exit:
            ins.run()
            sys.exit(0)
        return ins

    # -- grouping ----------------------------------------------------------
    def group_by(self, key, vf=None):
        """General (non-associative) grouping; returns PReduce.  ``vf``
        defaults to the identity (records keep their value)."""
        pm = self._add_mapper(Rekey(key, vf)).checkpoint()
        return PReduce(pm.source, pm.pmer)

    def a_group_by(self, key, vf=None):
        """Associative grouping: enables map-side combining before the
        shuffle (no checkpoint until the binop is known).  ``vf`` defaults
        to the identity."""
        pm = self._add_mapper(Rekey(key, vf))
        return ARReduce(pm)

    def fold_by(self, key, binop, value=lambda x: x, **options):
        """Shortcut for ``a_group_by(key, value).reduce(binop)``."""
        return self.a_group_by(key, value).reduce(binop, **options)

    def fold_values(self, binop, **options):
        """Fold values by each record's EXISTING key — no re-key map pass.
        Blocks flow into the combine with their cached hash lanes and
        (numeric) value lanes intact, so the whole aggregation stays on the
        vectorized path with zero per-record Python.  Use after block
        mappers that already emit records keyed by the group key
        (ops.text.TokenCounts/DocFreq with ``pair_values=False``).  Beyond
        the reference surface: its fold_by always re-keys per record
        (reference dampr.py:406-410)."""
        return ARReduce(self).reduce(binop, **options)

    def sort_by(self, key, **options):
        """Globally sort values by a key function (results merge key-sorted)."""
        return self._add_mapper(Rekey(key)).checkpoint(options=options)

    def count(self, key=lambda x: x, **options):
        """Count values per key — compiles to a device segment-sum."""
        return self.a_group_by(key, lambda v: 1).reduce(segment.SUM, **options)

    def mean(self, key=lambda x: 1, value=lambda x: x, **options):
        """Per-key mean: the (sum, count) pair IS the value column — int
        and float values build a 2D composite lane the segment sum kernels
        fold in one vectorized pass (blocks._tuple_column); anything else
        falls back to an exact pairwise object-lane fold.  Same observable
        behavior as the reference's per-record tuple binop (ref
        dampr.py:445-458), different execution: the pair never exists as
        a per-record Python object on the numeric path."""
        def _pair(v):
            x = value(v)
            # count carries the value's own lane dtype so the pair stays
            # type-uniform (a mixed (float, int) tuple would force the
            # object lane); ints keep exact int64 sums.
            return (x, 1.0) if type(x) is float else (x, 1)

        def _avg(x):
            return (x[0], x[1][0] / float(x[1][1]))

        return (self.a_group_by(key, _pair)
                .reduce(segment.PAIR_SUM, **options)
                .map(_avg))

    def len(self):
        """Count all items in the collection.  With no pending per-record
        ops the map side never touches records: text chunks count owned
        newlines, block-backed chunks sum block lengths (CountRecords).
        Pending ops force one streamed pass — the count is of TRANSFORMED
        records (a flat_map changes it), so there is nothing to vectorize."""
        def _count_stream(values):
            return ((1, sum(1 for _ in values)),)

        def _sum_counts(groups):
            totals = [c for _k, cs in groups for c in cs]
            return ((1, sum(totals)),) if totals else ()

        if not self.agg:
            from .ops.text import CountRecords
            head = self.custom_mapper(CountRecords())
        else:
            head = self.partition_map(_count_stream)
        return (head
                .partition_reduce(_sum_counts)
                .map(lambda x: x[1]))

    def topk(self, k, value=None):
        """Top-k values by a comparable sort key.  Identity-keyed
        block-backed partitions select candidates with one np.argpartition
        per block — no per-record Python; everything else decorates once
        and takes ``heapq.nlargest`` per partition.  Candidates from all
        partitions merge through one global nlargest.  Ordering criterion
        is the (sort_key, value) pair, so tie behavior matches the
        reference's heap of pairs (ref dampr.py:621-652)."""
        import heapq

        vf = value

        def _cands(values):
            pairs = (((x, x) for x in values) if vf is None
                     else ((vf(x), x) for x in values))
            return ((1, p) for p in heapq.nlargest(k, pairs))

        def _select(groups):
            cands = (p for _one, ps in groups for p in ps)
            return ((p[1], 1) for p in heapq.nlargest(k, cands))

        if vf is None and not self.agg:
            head = self.custom_mapper(_TopKBlocks(k))
        else:
            head = self.partition_map(_cands)
        return head.partition_reduce(_select).map(lambda x: x[0])

    # -- custom operators --------------------------------------------------
    def custom_mapper(self, mapper, name=None, **options):
        """Install a user Mapper instance (low-level; does not fuse)."""
        if isinstance(mapper, Streamable):
            return self._add_mapper(mapper)
        assert isinstance(mapper, Mapper)
        me = self.checkpoint()
        source, pmer = me.pmer._add_mapper([me.source], mapper, options=options)
        return PMap(source, pmer)

    def custom_reducer(self, reducer, name=None, **options):
        """Install a user Reducer instance (low-level)."""
        assert isinstance(reducer, Reducer)
        me = self.checkpoint(force=True)
        source, pmer = me.pmer._add_reducer([me.source], reducer,
                                            options=options)
        return PMap(source, pmer)

    def partition_map(self, f, **options):
        """Map a whole partition's value iterator (runs on empty partitions)."""
        return self.custom_mapper(StreamMapper(f), **options)

    def partition_reduce(self, f):
        """Reduce a whole partition's group iterator (runs on empty
        partitions)."""
        return self.custom_reducer(StreamReducer(f))

    # -- two-source ops ----------------------------------------------------
    def join(self, other):
        """Co-partitioned join with another collection; returns PJoin."""
        assert isinstance(other, PBase)
        me = self.checkpoint(True)
        if isinstance(other, PMap):
            other = other.checkpoint(True)
        pmer = Dampr(me.pmer.graph.union(other.pmer.graph))
        return PJoin(me.source, pmer, other.source)

    def cross_right(self, other, cross, memory=False):
        """Map-side cross product, loop order right-major."""
        assert isinstance(other, PMap)
        return other.cross_left(self, lambda xi, yi: cross(yi, xi), memory)

    def cross_left(self, other, cross, memory=False, **options):
        """Map-side cross product (broadcast join).  ``memory=True`` pins the
        replicated side in RAM."""
        def _cross(k1, v1, k2, v2):
            yield k1, cross(v2, v1)

        me = self.checkpoint()
        other = other.checkpoint()
        pmer = Dampr(me.pmer.graph.union(other.pmer.graph))
        source, pmer = pmer._add_mapper(
            [other.source, me.source], MapCrossJoin(_cross, cache=memory),
            combiner=None, options=options)
        return PMap(source, pmer)

    def cross_set(self, other, cross, agg=None, **options):
        """Load the whole other side through ``agg`` and pass it to every
        record."""
        def _cross(k1, v1, right):
            yield k1, cross(v1, right)

        if agg is None:
            agg = list

        def _aggregate(d):
            return agg(v for _k, v in d)

        me = self.checkpoint()
        other = other.checkpoint()
        pmer = Dampr(me.pmer.graph.union(other.pmer.graph))
        source, pmer = pmer._add_mapper(
            [other.source, me.source], MapAllJoin(_cross, _aggregate),
            combiner=None, options=options)
        return PMap(source, pmer)

    # -- persistence -------------------------------------------------------
    def cached(self, **options):
        """Materialize and pin this stage's output in RAM (never spills)."""
        options["memory"] = True
        return self.checkpoint(force=True, options=options)

    def sink(self, path):
        """Write each value as a text line into part-files under ``path``
        (durable — exempt from cleanup)."""
        aggs = [Map(_identity)] if len(self.agg) == 0 else self.agg[:]
        source, pmer = self.pmer._add_sink([self.source], fuse(aggs),
                                           path=path, options=None)
        return PMap(source, pmer)

    def sink_tsv(self, path):
        """Tab-join tuple values, then sink."""
        return self.map(lambda x: u"\t".join(str(p) for p in x)).sink(path)

    def sink_json(self, path):
        """JSON-serialize values line-delimited, then sink."""
        return self.map(json.dumps).sink(path)


class ARReduce(object):
    """Associative reduce handle: folds map-side, shuffles compacted partials,
    folds again reduce-side (reference dampr.py:654-709; the decomposition is
    the reference's PartialReduceCombiner pipeline restated as segment
    kernels — see SURVEY §3.3)."""

    def __init__(self, pmap):
        self.pmap = pmap

    def reduce(self, binop, reduce_buffer=1000, **options):
        """Reduce groups with an associative binop.  ``reduce_buffer`` is
        accepted for API parity; block-size accounting replaces it."""
        op = segment.as_assoc_op(binop)
        options.update({"binop": op, "reduce_buffer": reduce_buffer})
        pm = self.pmap.checkpoint(
            True, combiner=PartialReduceCombiner(op), options=options)
        new_source, pmer = pm.pmer._add_reducer(
            [pm.source], AssocFoldReducer(op), options=options)
        return PMap(new_source, pmer)

    def first(self, **options):
        """First value seen per key."""
        return self.reduce(segment.FIRST, **options)

    def sum(self, **options):
        """Sum values per key — device segment-sum end-to-end for numeric
        values."""
        return self.reduce(segment.SUM, **options)


class PReduce(PBase):
    """General grouped collection (post group_by)."""

    def reduce(self, f):
        """``f(key, value_iter) -> value`` per group."""
        new_source, pmer = self.pmer._add_reducer([self.source], KeyedReduce(f))
        return PMap(new_source, pmer)

    def unique(self, key=lambda x: x):
        """Distinct values per group (first occurrence wins)."""
        def _uniq(k, it):
            seen = set()
            agg = []
            for v in it:
                fv = key(v)
                if fv not in seen:
                    seen.add(fv)
                    agg.append(v)
            return agg

        return self.reduce(_uniq)

    def join(self, other):
        """Join grouped data with another collection."""
        assert isinstance(other, PBase)
        if isinstance(other, PMap):
            other = other.checkpoint(True)
        pmer = Dampr(self.pmer.graph.union(other.pmer.graph))
        return PJoin(self.source, pmer, other.source)

    def partition_reduce(self, f):
        """Whole-partition reduce over the grouped stream."""
        new_source, pmer = self.pmer._add_reducer([self.source],
                                                  StreamReducer(f))
        return PMap(new_source, pmer)


class PJoin(PBase):
    """Join handle over two co-partitioned grouped sources."""

    def __init__(self, source, pmer, right):
        super(PJoin, self).__init__(source, pmer)
        self.right = right

    def run(self, name=None, **kwargs):
        return self.reduce(lambda l, r: (list(l), list(r))).run(name, **kwargs)

    def reduce(self, aggregate, many=False):
        """Inner join: ``aggregate(left_iter, right_iter)`` per matched key;
        ``many=True`` flattens the result into separate records."""
        def _reduce(k, left, right):
            return aggregate(left, right)

        source, pmer = self.pmer._add_reducer(
            [self.source, self.right], KeyedInnerJoin(_reduce, many))
        return PMap(source, pmer)

    def left_reduce(self, aggregate):
        """Left join: missing right keys see an empty iterator."""
        def _reduce(k, left, right):
            return aggregate(left, right)

        source, pmer = self.pmer._add_reducer(
            [self.source, self.right], KeyedLeftJoin(_reduce))
        return PMap(source, pmer)

    def outer_reduce(self, aggregate):
        """Full outer join: whichever side is missing a key sees an empty
        iterator.  (New capability — the reference defines but never exposes
        an outer join, and its implementation is broken: base.py:355, 366.)"""
        def _reduce(k, left, right):
            return aggregate(left, right)

        source, pmer = self.pmer._add_reducer(
            [self.source, self.right], KeyedOuterJoin(_reduce))
        return PMap(source, pmer)


class Dampr(object):
    """Entrypoint: constructors for sources + the multi-output run."""

    def __init__(self, graph=None, runner=None):
        self.graph = Graph() if graph is None else graph
        self.runner = MTRunner if runner is None else runner

    @classmethod
    def memory(cls, items, partitions=50):
        """In-memory collection (keys = positions)."""
        mi = MemoryInput(list(enumerate(items)), partitions)
        source, ng = Graph().add_input(mi)
        return PMap(source, cls(ng))

    @classmethod
    def read_input(cls, *datasets):
        """Read from datasets / chunkers directly."""
        if len(datasets) == 1:
            ds = datasets[0]
        else:
            ds = CatDataset(list(datasets))
        source, ng = Graph().add_input(ds)
        return PMap(source, cls(ng))

    @classmethod
    def text(cls, fname, chunk_size=16 * 1024 ** 2, followlinks=False):
        """Newline-delimited text from a file/dir/glob, split into byte-range
        chunks."""
        return cls.read_input(PathInput(fname, chunk_size, followlinks))

    @classmethod
    def json(cls, *args, **kwargs):
        """Line-delimited JSON records."""
        return cls.text(*args, **kwargs).map(json.loads)

    @classmethod
    def urls(cls, urls, skip_on_error=True):
        """Fetch newline-delimited text over HTTP, one chunk per URL."""
        return cls.read_input(UrlsInput(urls, skip_on_error))

    @classmethod
    def from_dataset(cls, dataset):
        """Wrap raw stage outputs / custom Dataset subclasses as an input."""
        assert isinstance(dataset, Chunker)
        source, ng = Graph().add_input(dataset)
        return PMap(source, cls(ng))

    @classmethod
    def run(cls, *pmers, **kwargs):
        """Run several graphs in one pass; shared prefixes compute once.
        Returns one ValueEmitter per argument."""
        assert len(pmers) > 0, "Need at least one graph to run!"
        sources = []
        graph = None
        pmer = None
        for i, pmer in enumerate(pmers):
            if isinstance(pmer, PMap):
                pmer = pmer.checkpoint()
            elif isinstance(pmer, PJoin):
                pmer = pmer.reduce(lambda l, r: (list(l), list(r)))
            graph = pmer.pmer.graph if i == 0 else pmer.pmer.graph.union(graph)
            sources.append(pmer.source)

        if kwargs.get("resume") and kwargs.get("name") is None:
            raise ValueError(
                "resume=True requires a stable run name: Dampr.run(..., "
                "name=..., resume=True)")
        name = kwargs.pop("name", "dampr/{}".format(random.random()))
        runner = pmer.pmer.runner(name, graph, **kwargs)
        ds = runner.run(sources)
        stats = RunStats([s.as_dict() for s in getattr(runner, "stats", [])],
                         getattr(runner, "run_summary", None))
        emitters = []
        for d in ds:
            em = ValueEmitter(d)
            em.stats = stats
            emitters.append(em)
        return emitters

    # -- graph builders (value semantics) ----------------------------------
    def _add_mapper(self, *args, **kwargs):
        output, ng = self.graph.add_mapper(*args, **kwargs)
        return output, Dampr(ng)

    def _add_reducer(self, *args, **kwargs):
        output, ng = self.graph.add_reducer(*args, **kwargs)
        return output, Dampr(ng)

    def _add_sink(self, *args, **kwargs):
        output, ng = self.graph.add_sink(*args, **kwargs)
        return output, Dampr(ng)


# Per-thread RNG for sample(): jobs run on threads, and a shared Random would
# serialize them on its lock and interleave streams nondeterministically.
_RAND_LOCAL = threading.local()


def _get_rand():
    r = getattr(_RAND_LOCAL, "rand", None)
    if r is None:
        r = random.Random(time.time() + threading.get_ident())
        _RAND_LOCAL.rand = r
    return r


def setup_logging(debug=False):
    level = logging.DEBUG if debug else logging.INFO
    logging.basicConfig(
        level=level,
        format="%(asctime)s [%(levelname)s] %(name)s: %(message)s")
