"""Ingest: chunk planning, readahead, and splittable compressed taps.

Redesigned in round 3 as a *planner + prefetcher*, not per-file generator
nesting:

1. **Chunk planning** (:func:`plan_chunks`): one scandir-based walk produces
   every chunk spec up front.  File sizes come free from ``DirEntry`` (one
   ``getdents`` batch per directory instead of a stat round-trip per file),
   ordering is fully deterministic (names sorted at every level), and the
   container format is sniffed from magic bytes, not the extension — a
   mis-named uncompressed ``.gz`` splits like the text file it is.
2. **Splittable gzip** (BGZF): blocked-gzip files (bgzip/htslib framing —
   concatenated gzip members carrying their compressed size in a ``BC``
   extra subfield) are split at member boundaries into parallel chunks,
   each with the same line-boundary contract as byte-range text chunks.
   Plain gzip streams remain one unsplittable chunk.
3. **Readahead** (:class:`Readahead`): a bounded background prefetcher
   loads the next chunks' bytes (file read + gzip inflate, both of which
   release the GIL) while the current chunk computes.  It starts lazily on
   the first ``read_bytes`` call, so per-record consumers never pay for it.
4. **Byte-first taps**: every planned chunk exposes ``read_bytes()``, so
   the native tokenizer codec consumes raw buffers straight from the tap
   (bytes -> token blocks with no intermediate str lines).

Parity surface (public names unchanged): ``read_paths``, ``PathInput``,
``TextInput``, ``MemoryInput``, ``UrlsInput`` — the capability set of
reference dampr/inputs.py, re-architected.
"""

import collections
import glob
import os
import threading
import zlib
from contextlib import closing

from . import settings
from .dataset import (Dataset, Chunker, GzipLineDataset, MemoryDataset,
                      TextLineDataset)

# ---------------------------------------------------------------------------
# Planning
# ---------------------------------------------------------------------------

#: One planned unit of ingest work.  ``kind`` is "text" (byte range),
#: "gzip" (whole unsplittable stream), or "bgzf" (member-aligned compressed
#: range).  ``start``/``end`` are byte offsets into the file as stored.
ChunkSpec = collections.namedtuple("ChunkSpec", "path start end kind size")

_GZIP_MAGIC = b"\x1f\x8b"


def _scan_tree(root, follow_links):
    """Depth-first scandir walk: yields (path, size) for every visible file,
    names sorted at every level, sizes from the DirEntry stat cache."""
    try:
        entries = sorted(os.scandir(root), key=lambda e: e.name)
    except NotADirectoryError:
        yield root, os.stat(root).st_size
        return
    except OSError:
        return  # broken symlink / vanished path: yield nothing (old
        #         os.walk behavior), never kill the whole ingest
    dirs = []
    for e in entries:
        if e.name.startswith("."):
            continue
        try:
            if e.is_file(follow_symlinks=True):
                yield e.path, e.stat(follow_symlinks=True).st_size
            elif e.is_dir(follow_symlinks=follow_links):
                dirs.append(e.path)
        except OSError:
            continue  # vanished between scandir and stat
    for d in dirs:
        for item in _scan_tree(d, follow_links):
            yield item


def iter_files(paths, follow_links=True):
    """Expand globs / walk directories; hide dotfiles; yield (path, size)."""
    if not isinstance(paths, list):
        paths = [paths]
    for path_glob in paths:
        for path in sorted(glob.glob(path_glob)):
            if os.path.isfile(path):
                yield path, os.stat(path).st_size
            else:
                for item in _scan_tree(path, follow_links):
                    yield item


def read_paths(paths, follow_links=True):
    """Parity helper: just the paths from :func:`iter_files`."""
    return (p for p, _size in iter_files(paths, follow_links))


def _sniff(path):
    """Classify a file by magic bytes: 'text', 'gzip', or 'bgzf'."""
    with open(path, "rb") as f:
        hdr = f.read(18)
    if len(hdr) < 18 or hdr[:2] != _GZIP_MAGIC:
        return "text"
    flg = hdr[3]
    if flg & 4:  # FEXTRA
        xlen = int.from_bytes(hdr[10:12], "little")
        # BGZF fixes exactly one subfield: SI 'BC', SLEN 2, at the front.
        if (xlen >= 6 and hdr[12:14] == b"BC"
                and int.from_bytes(hdr[14:16], "little") == 2):
            return "bgzf"
    return "gzip"


def _bgzf_member_size(f, off):
    """Size of the BGZF member at ``off`` (or None at EOF / bad framing)."""
    f.seek(off)
    hdr = f.read(18)
    if len(hdr) < 18 or hdr[:2] != _GZIP_MAGIC or hdr[12:14] != b"BC":
        return None
    return int.from_bytes(hdr[16:18], "little") + 1


def _load_gzi(path):
    """Block offsets from a bgzip ``.gzi`` index, if one ships alongside
    (uint64 count, then (compressed, uncompressed) offset pairs per block
    after the first).  Saves the member walk entirely on indexed corpora."""
    gzi = path + ".gzi"
    try:
        with open(gzi, "rb") as f:
            data = f.read()
    except OSError:
        return None
    if len(data) < 8:
        return None
    n = int.from_bytes(data[:8], "little")
    if len(data) < 8 + 16 * n:
        return None
    offs = [0]
    for k in range(n):
        offs.append(int.from_bytes(data[8 + 16 * k: 16 + 16 * k], "little"))
    return offs


def _bgzf_boundaries(path, size, chunk_size):
    """Member-aligned chunk boundaries: from the ``.gzi`` index when
    present, else one seek + 18-byte header read per member (16 bytes of
    plan IO per ~64KB of data; ship a .gzi for very large corpora).
    Returns None when the stream stops parsing as BGZF before ``size`` —
    e.g. a trailing plain-gzip member in a concatenated file — so the
    caller falls back to one whole-stream chunk and loses nothing."""
    offs = _load_gzi(path)
    if offs is not None:
        bounds = [0]
        acc = 0
        for a, b in zip(offs, offs[1:] + [size]):
            acc += b - a
            if acc >= chunk_size and b < size:
                bounds.append(b)
                acc = 0
        bounds.append(size)
        return bounds
    bounds = [0]
    with open(path, "rb") as f:
        off = 0
        acc = 0
        while off < size:
            msize = _bgzf_member_size(f, off)
            if msize is None:
                return None  # not BGZF all the way: caller must not split
            off += msize
            acc += msize
            if acc >= chunk_size and off < size:
                bounds.append(off)
                acc = 0
    bounds.append(size)
    return bounds


def plan_file(path, size, chunk_size):
    """Chunk specs for one file, splitting where the format allows."""
    kind = _sniff(path) if size else "text"
    if kind == "bgzf":
        bounds = _bgzf_boundaries(path, size, chunk_size)
        if bounds is None or len(bounds) < 2:
            kind = "gzip"
        else:
            return [ChunkSpec(path, a, b, "bgzf", size)
                    for a, b in zip(bounds, bounds[1:]) if b > a]
    if kind == "gzip":
        return [ChunkSpec(path, 0, size, "gzip", size)]
    return [ChunkSpec(path, at, min(at + chunk_size, size), "text", size)
            for at in range(0, max(size, 1), chunk_size)]


def plan_chunks(paths, chunk_size, follow_links=True):
    """The full ingest plan: every chunk of every matched file."""
    specs = []
    for path, size in iter_files(paths, follow_links):
        specs.extend(plan_file(path, size, chunk_size))
    return specs


def _spec_dataset(spec):
    if spec.kind == "gzip":
        return GzipLineDataset(spec.path)
    if spec.kind == "bgzf":
        return BgzfChunkDataset(spec.path, spec.start, spec.end, spec.size)
    return TextLineDataset(spec.path, spec.start,
                           None if spec.end >= spec.size else spec.end)


# ---------------------------------------------------------------------------
# Readahead
# ---------------------------------------------------------------------------


class Readahead(object):
    """Bounded background prefetcher over an ordered list of byte loaders.

    One daemon thread walks the loaders in plan order, holding at most
    ``depth`` unconsumed buffers (a semaphore slot per buffer).  Consumers
    call :meth:`take`; an index the thread hasn't reached (or is mid-load
    on) is claimed and loaded directly by the consumer, so out-of-order
    consumption can never deadlock — at worst one chunk is read twice.
    The thread starts lazily on the first ``take``, so pipelines that never
    touch ``read_bytes`` (pure per-record paths) pay nothing.
    """

    def __init__(self, loaders, depth=2):
        self._loaders = loaders
        self._sem = threading.Semaphore(max(1, depth))
        self._lock = threading.Lock()
        self._results = {}
        self._claimed = set()
        self._events = [threading.Event() for _ in loaders]
        self._inflight = None
        self._started = False

    def _run(self):
        for i, load in enumerate(self._loaders):
            self._sem.acquire()
            with self._lock:
                if i in self._claimed:
                    self._sem.release()
                    continue
                self._inflight = i
            try:
                data = load()
            except BaseException as e:  # delivered to the consumer
                data = e
            with self._lock:
                self._inflight = None
                self._results[i] = data
            self._events[i].set()

    def _pop(self, i):
        with self._lock:
            data = self._results.pop(i)
            self._sem.release()
        if isinstance(data, BaseException):
            raise data
        return data

    def take(self, i):
        wait = False
        with self._lock:
            if not self._started:
                self._started = True
                threading.Thread(target=self._run, daemon=True,
                                 name="dampr-tpu-readahead").start()
            if i in self._results:
                wait = True  # ready now; pop below, outside this block
            elif self._inflight == i:
                wait = True  # mid-load: wait for it, never load twice
            else:
                self._claimed.add(i)
        if wait:
            self._events[i].wait()
            return self._pop(i)
        return self._loaders[i]()


class PrefetchedChunk(object):
    """A planned chunk whose ``read_bytes`` is served by the shared
    :class:`Readahead`; everything else delegates to the inner dataset."""

    def __init__(self, inner, readahead, index):
        self._inner = inner
        self._readahead = readahead
        self._index = index

    def read_bytes(self):
        return self._readahead.take(self._index)

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def __repr__(self):
        return "Prefetched[{!r}]".format(self._inner)


# ---------------------------------------------------------------------------
# Taps (parity surface)
# ---------------------------------------------------------------------------


class PathInput(Chunker):
    """File / directory / glob of newline-delimited text, planned up front
    and served through the readahead window."""

    def __init__(self, path, chunk_size=64 * 1024 ** 2, follow_links=True):
        self.path = path
        self.chunk_size = chunk_size
        self.follow_links = follow_links

    def chunks(self):
        specs = plan_chunks(self.path, self.chunk_size, self.follow_links)
        datasets = [_spec_dataset(s) for s in specs]
        depth = settings.readahead_chunks
        if depth and len(datasets) > 1:
            ra = Readahead([ds.read_bytes for ds in datasets], depth)
            datasets = [PrefetchedChunk(ds, ra, i)
                        for i, ds in enumerate(datasets)]
        for ds in datasets:
            yield ds


class TextInput(Chunker):
    """One file's chunks (format sniffed from magic bytes, no readahead)."""

    def __init__(self, path, chunk_size=64 * 1024 ** 2):
        self.path = path
        self.chunk_size = chunk_size

    def chunks(self):
        size = os.stat(self.path).st_size
        for spec in plan_file(self.path, size, self.chunk_size):
            yield _spec_dataset(spec)


class BgzfChunkDataset(Dataset):
    """A member-aligned compressed range ``[start, end)`` of a BGZF file.

    Line-boundary contract — the decompressed-stream mirror of
    :class:`~dampr_tpu.dataset.TextLineDataset`'s byte-range rules: a chunk
    with ``start > 0`` drops everything up to and including the first
    newline of its own decompressed range; every chunk that doesn't end the
    file keeps decompressing subsequent members through the line that
    crosses its boundary.  Adjacent chunks therefore read every line
    exactly once, and a chunk whose entire range is one partial line owns
    nothing (that line belongs to its left neighbor).
    """

    def __init__(self, path, start, end, file_size):
        self.path = path
        self.start = start
        self.end = end
        self.file_size = file_size

    @staticmethod
    def _inflate(raw):
        """Decompress concatenated gzip members.  BGZF members are hopped by
        their BSIZE field so each inflate sees exactly one member — the
        naive unused_data chain would copy the whole remaining buffer per
        member, O(members * bytes).  Non-BGZF members (possible only via a
        corrupt index) fall back to the generic chain for the tail."""
        out = []
        mv = memoryview(raw)
        off, n = 0, len(raw)
        while off < n:
            if (n - off >= 18 and bytes(mv[off:off + 2]) == _GZIP_MAGIC
                    and bytes(mv[off + 12:off + 14]) == b"BC"):
                msize = int.from_bytes(mv[off + 16:off + 18], "little") + 1
                dec = zlib.decompressobj(wbits=31)
                out.append(dec.decompress(mv[off:off + msize]))
                off += msize
            else:
                data = bytes(mv[off:])
                while data:
                    dec = zlib.decompressobj(wbits=31)
                    out.append(dec.decompress(data))
                    data = dec.unused_data
                break
        return b"".join(out)

    def read_bytes(self):
        with open(self.path, "rb") as f:
            f.seek(self.start)
            own = self._inflate(f.read(self.end - self.start))
            if self.start > 0:
                nl = own.find(b"\n")
                if nl < 0:
                    # our whole range is a partial line owned by the left
                    # neighbor (mirrors TextLineDataset's crossed-end skip)
                    return b""
                own = own[nl + 1:]
            if self.end < self.file_size:
                ext = []
                off = self.end
                while off < self.file_size:
                    msize = _bgzf_member_size(f, off)
                    if msize is None:
                        break
                    f.seek(off)
                    piece = self._inflate(f.read(msize))
                    off += msize
                    nl = piece.find(b"\n")
                    if nl >= 0:
                        ext.append(piece[: nl + 1])
                        break
                    ext.append(piece)
                own += b"".join(ext)
        return own

    def read(self):
        # Keys are int offsets (compressed chunk start + local decompressed
        # position): unique-ish identifiers in the same int64 fast lane as
        # the text/gzip taps' byte offsets — never semantic offsets.
        data = self.read_bytes()
        pos = 0
        n = len(data)
        while pos < n:
            nl = data.find(b"\n", pos)
            end = n if nl < 0 else nl
            yield self.start + pos, data[pos:end].decode("utf-8")
            pos = end + 1

    def __repr__(self):
        return "Bgzf[path={},start={},end={}]".format(
            self.path, self.start, self.end)


class MemoryInput(Chunker):
    """In-memory (k, v) list split into ~`partitions` chunks."""

    def __init__(self, items, partitions=50):
        self.items = items
        self.partitions = min(len(items), partitions)

    def chunks(self):
        if self.partitions == 0:
            yield MemoryDataset(self.items)
        else:
            chunk_size = max(1, int(len(self.items) // float(self.partitions)))
            for start in range(0, len(self.items), chunk_size):
                yield MemoryDataset(self.items[start:start + chunk_size])


class UrlsInput(Chunker):
    """One chunk per URL; HTTP errors optionally skipped."""

    def __init__(self, urls, skip_on_error=True):
        self.urls = urls
        self.skip_on_error = skip_on_error

    def chunks(self):
        for url in self.urls:
            yield UrlDataset(url, self.skip_on_error)


class UrlDataset(Dataset):
    def __init__(self, url, skip_on_error=True):
        self.url = url
        self.skip_on_error = skip_on_error

    def read(self):
        from urllib.error import HTTPError, URLError
        from urllib.request import urlopen

        try:
            with closing(urlopen(self.url)) as h:
                for i, line in enumerate(h):
                    yield i, line.decode("utf-8")
        except (HTTPError, URLError):
            if not self.skip_on_error:
                raise

    def __repr__(self):
        return "Url[{}]".format(self.url)
