"""Logical lazy DAG.

Parity surface: reference dampr/runner.py:17-135 — copy-on-write ``Graph`` whose
``add_*`` methods return ``(Source, new_graph)``; ``Source`` identity comes from a
global counter; ``union`` merges two graphs deduping shared stages; stages are kept in
a linear list in construction order (order *is* the schedule — reference
runner.py:178).

These semantics are engine-independent and proven by the reference conformance tests,
so they transfer conceptually unchanged; the implementation below is written fresh.
The execution engine that consumes this graph is completely different (see
runner.py: stages lower to JAX programs instead of forked workers).

The constructed list is the LOGICAL plan — one node per chained DSL call.
Before execution the plan optimizer (:mod:`dampr_tpu.plan`) rewrites it
(map fusion, combiner hoisting, dead-stage elimination, adaptive sizing);
with ``settings.optimize`` off the runner executes this list literally.
"""

import itertools


class Source(object):
    """Handle naming the output of one stage (reference runner.py:17-33).

    Identity is a process-global monotonically increasing id so sources are
    hashable, ordered, and unique across graph copies.
    """

    _ids = itertools.count()

    __slots__ = ("sid",)

    def __init__(self):
        self.sid = next(Source._ids)

    def __hash__(self):
        return hash(self.sid)

    def __eq__(self, other):
        return isinstance(other, Source) and self.sid == other.sid

    def __lt__(self, other):
        return self.sid < other.sid

    def __repr__(self):
        return "Source[{}]".format(self.sid)


class StageNode(object):
    """Base for graph stage nodes; `options` carries per-op overrides
    (n_maps/n_reducers/memory/binop — reference runner.py:285/331).

    ``_provenance`` is observability metadata, not plan semantics: the
    fusion passes record the ORIGINAL user-stage descriptions a fused
    node absorbed (an attribute rather than an options entry so resume
    fingerprints — which hash options — are unaffected), and the
    per-operator profiler reports against it."""

    __slots__ = ("inputs", "output", "options", "_provenance")

    def __init__(self, inputs, output, options=None):
        self.inputs = list(inputs)
        self.output = output
        self.options = options or {}
        self._provenance = None


class GInput(StageNode):
    """Pseudo-node binding a Source to an input tap (reference keeps taps in
    Graph.inputs, runner.py:75-89; we make it explicit for uniform walking)."""

    __slots__ = ("tap",)

    def __init__(self, tap, output):
        super(GInput, self).__init__([], output)
        self.tap = tap

    def __repr__(self):
        return "GInput[{} <- {!r}]".format(self.output, self.tap)


class GMap(StageNode):
    """Map stage: fused mapper (+ optional combiner/shuffler) — reference
    runner.py:35-47."""

    __slots__ = ("mapper", "combiner", "shuffler")

    def __init__(self, inputs, output, mapper, combiner=None, shuffler=None,
                 options=None):
        super(GMap, self).__init__(inputs, output, options)
        self.mapper = mapper
        self.combiner = combiner
        self.shuffler = shuffler

    def __repr__(self):
        return "GMap[{} <- {}]".format(self.output, self.inputs)


class GReduce(StageNode):
    """Reduce stage over co-partitioned inputs — reference runner.py:49-59."""

    __slots__ = ("reducer",)

    def __init__(self, inputs, output, reducer, options=None):
        super(GReduce, self).__init__(inputs, output, options)
        self.reducer = reducer

    def __repr__(self):
        return "GReduce[{} <- {}]".format(self.output, self.inputs)


class GSink(StageNode):
    """Durable output stage — reference runner.py:61-71."""

    __slots__ = ("sinker", "path")

    def __init__(self, inputs, output, sinker, path, options=None):
        super(GSink, self).__init__(inputs, output, options)
        self.sinker = sinker
        self.path = path

    def __repr__(self):
        return "GSink[{} <- {} -> {}]".format(self.output, self.inputs, self.path)


class Graph(object):
    """Copy-on-write stage list (reference runner.py:74-135).

    ``stages`` is an ordered list of StageNodes; construction order is the
    schedule.  Every ``add_*`` returns ``(Source, Graph)`` with the receiver
    unmodified, so handles are freely shareable and branches can diverge.
    """

    def __init__(self, stages=None):
        self.stages = list(stages) if stages else []

    # -- builders ----------------------------------------------------------
    def _extend(self, node):
        g = Graph(self.stages)
        g.stages.append(node)
        return node.output, g

    def add_input(self, tap):
        return self._extend(GInput(tap, Source()))

    def add_mapper(self, inputs, mapper, combiner=None, shuffler=None,
                   name=None, options=None):
        return self._extend(
            GMap(inputs, Source(), mapper, combiner, shuffler, options))

    def add_reducer(self, inputs, reducer, name=None, options=None):
        return self._extend(GReduce(inputs, Source(), reducer, options))

    def add_sink(self, inputs, sinker, path, name=None, options=None):
        return self._extend(GSink(inputs, Source(), sinker, path, options))

    # -- merging -----------------------------------------------------------
    def union(self, other):
        """Merge two graphs, deduping shared stage nodes by identity of their
        output Source (reference runner.py:127-135).  Shared prefixes — the same
        node object reachable from both handles — appear once; relative order is
        preserved (stable by first appearance, self first)."""
        seen = set()
        stages = []
        for node in itertools.chain(self.stages, other.stages):
            if node.output not in seen:
                seen.add(node.output)
                stages.append(node)
        return Graph(stages)

    def __repr__(self):
        return "Graph[{} stages]".format(len(self.stages))
