"""Prometheus text exposition (version 0.0.4) for the metrics plane.

Dependency-free rendering of a run's metrics — either a live
:class:`~.metrics.Metrics` registry or the persisted ``metrics``
section of ``stats.json`` — as the text format every Prometheus scraper
and ``promtool`` ingests::

    # TYPE dampr_tpu_store_records counter
    dampr_tpu_store_records{run="bench-tfidf"} 1.2345e+06

Engine metric names are dotted (``writer.queue_depth``); exposition
names flatten to ``dampr_tpu_writer_queue_depth``.  Counters export as
``counter``, sampled gauges as ``gauge`` (last sample), histograms as a
``summary``-style ``_count``/``_sum`` pair plus ``_min``/``_max``
gauges.  The ``dampr-tpu-stats --prom`` CLI renders a completed run;
serving a live run is one ``render(metrics.active())`` behind any HTTP
handler (a scrape example lives in docs/observability.md).
"""

import re

_PREFIX = "dampr_tpu_"
_BAD = re.compile(r"[^a-zA-Z0-9_]")


def sanitize(name):
    """Dotted engine metric name -> a legal Prometheus metric name."""
    out = _PREFIX + _BAD.sub("_", str(name))
    if out[0].isdigit():
        out = "_" + out
    return out


def escape_label_value(value):
    """Escape a label VALUE per the Prometheus text-format spec
    (exposition formats, version 0.0.4): backslash first (so escapes
    don't double-escape), then double-quote, then line feed as the two
    characters ``\\n`` — a raw newline inside a label would truncate the
    sample line and corrupt the whole exposition."""
    return (str(value).replace("\\", "\\\\")
            .replace('"', '\\"')
            .replace("\n", "\\n"))


def _labels(run, rank=None):
    parts = []
    if run:
        parts.append('run="{}"'.format(escape_label_value(run)))
    if rank is not None:
        # Fleet-aware exposition: every sample names its rank, so one
        # Prometheus scraping N ranks' /metrics endpoints can group and
        # diff per-rank series (the tf.data-service per-worker telemetry
        # shape).
        parts.append('rank="{}"'.format(escape_label_value(rank)))
    if not parts:
        return ""
    return "{{{}}}".format(",".join(parts))


def _num(v):
    if isinstance(v, bool):
        return "1" if v else "0"
    return repr(float(v))


def _emit(lines, name, typ, value, run, rank=None):
    lines.append("# TYPE {} {}".format(name, typ))
    lines.append("{}{} {}".format(name, _labels(run, rank), _num(value)))


def render(metrics, rank=None):
    """A live registry -> exposition text (counters, current gauges,
    histogram summaries, sampler self-metrics).  ``rank`` adds the
    per-rank label the live ``/metrics`` endpoint (:mod:`.serve`)
    always sets."""
    summary = metrics.summary()
    # Live gauges beat the last sample: snapshot() pulls callbacks now.
    snap = metrics.snapshot()
    series = {name: {"last": v} for name, v in snap.items()}
    for k, meta in summary.get("series", {}).items():
        series.setdefault(k, {"last": meta.get("last")})
    summary = dict(summary, series={
        k: {"last": v["last"], "samples": 0, "peak": v["last"]}
        for k, v in series.items()})
    return render_summary({"metrics": summary, "run": metrics.run},
                          rank=rank)


def render_summary(stats_summary, rank=None):
    """A persisted stats.json dict (or a fragment with a ``metrics``
    key) -> exposition text.  A run with no metrics section (or an
    empty registry) renders as the EMPTY exposition — zero bytes is the
    valid text-format encoding of "no samples", and scrapers/promtool
    accept it; callers that want to tell the user about it check
    falsiness (the stats CLI does).  ``rank`` defaults from the
    summary's own ``process`` block for multi-process runs, so a
    persisted rank artifact exposes the same labels the live endpoint
    serves."""
    m = stats_summary.get("metrics") or {}
    run = stats_summary.get("run")
    if rank is None:
        proc = stats_summary.get("process") or {}
        if (proc.get("num_processes") or 1) > 1:
            rank = proc.get("process_id", 0)
    lines = []
    counters = m.get("counters") or {}
    series = m.get("series") or {}
    for name in sorted(counters):
        _emit(lines, sanitize(name) + "_total", "counter", counters[name],
              run, rank)
    for name in sorted(series):
        if name in counters:
            continue  # already exported as a counter
        meta = series[name]
        if not isinstance(meta, dict) or "last" not in meta:
            continue
        v = meta["last"]
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            continue
        _emit(lines, sanitize(name), "gauge", v, run, rank)
    for name in sorted(m.get("histograms") or {}):
        h = m["histograms"][name]
        base = sanitize(name)
        lines.append("# TYPE {} summary".format(base))
        lines.append("{}_count{} {}".format(base, _labels(run, rank),
                                            _num(h.get("count", 0))))
        lines.append("{}_sum{} {}".format(base, _labels(run, rank),
                                          _num(h.get("sum", 0.0))))
        for k in ("min", "max"):
            if k in h:
                _emit(lines, "{}_{}".format(base, k), "gauge", h[k],
                      run, rank)
    sampler = m.get("sampler") or {}
    for k in ("samples", "series_drops", "overhead"):
        if k in sampler:
            _emit(lines, sanitize("sampler." + k), "gauge", sampler[k],
                  run, rank)
    return "\n".join(lines) + ("\n" if lines else "")
