"""Regression sentry: MAD anomaly detection over the telemetry store.

A perf regression that lands quietly — a config drift, a slower codec, a
straggler that mitigation stopped absorbing — shows up as the newest
telemetry point drifting away from its plan-fingerprint series.  The
sentry formalizes "drifting away" with the robust z-score:

    med   = median(baseline)                  # trailing window
    mad   = median(|x - med|)                 # median absolute deviation
    scale = 1.4826 * mad                      # ~sigma for normal data
    z     = (value - med) / scale

MAD (not mean/stdev) because a baseline of a handful of wall-clock
samples routinely contains one noisy-neighbor outlier — the median pair
shrugs it off where a stdev would inflate and mask the regression.  A
flat baseline (mad == 0, common for counters that sit at 0) falls back
to ``scale = max(|med| * 0.05, 1e-9)`` so a genuinely new nonzero value
still trips and identical values never do.

Per-metric direction (``timeseries.METRICS``) keeps the test one-sided:
wall/spill/retries regress UP, throughput/reuse/residency regress DOWN
— a run that got *faster* never alarms.

Detection needs at least :data:`MIN_BASELINE` comparable points (same
plan fingerprint, metric present); thinner series stay silent.  The
runner's finalize hook runs the sentry warn-only (a run never fails on
its own telemetry); ``dampr-tpu-sentry --strict`` and the perf-gate CI
leg escalate findings to a nonzero exit.
"""

import json
import logging
import statistics

from .. import settings
from . import timeseries as _timeseries

log = logging.getLogger("dampr_tpu.obs.sentry")

#: Minimum comparable baseline points before the sentry will judge.
MIN_BASELINE = 3

#: metric -> (settings attr, env var, why) — the doctor-playbook-style
#: knob pointer a finding names so the reader knows which dial moves the
#: regressed metric.  Every attr must exist on ``dampr_tpu.settings``
#: (pinned by test_sentry).
METRIC_KNOBS = {
    "wall_seconds": (
        "max_memory_per_stage", "DAMPR_TPU_MEMORY_BUDGET",
        "wall time regressions usually track spill/eviction pressure; "
        "check the stage memory budget first"),
    "mbps": (
        "overlap_windows", "DAMPR_TPU_OVERLAP_WINDOWS",
        "throughput drops when the producer lookahead stops covering "
        "consumer stalls"),
    "spill_bytes": (
        "max_memory_per_stage", "DAMPR_TPU_MEMORY_BUDGET",
        "growing spill volume means the working set stopped fitting the "
        "stage budget"),
    "retries": (
        "io_retries", "DAMPR_TPU_IO_RETRIES",
        "rising retry absorption points at a degrading disk/codec; the "
        "retry budget is masking it"),
    "quarantined": (
        "max_quarantined", "DAMPR_TPU_MAX_QUARANTINED",
        "more quarantined partitions means more data silently excluded "
        "from results"),
    "late_ratio": (
        "mitigate", "DAMPR_TPU_MITIGATE",
        "worsening straggler skew; speculative mitigation can re-absorb "
        "it"),
    "reuse_hit_rate": (
        "reuse_budget_bytes", "DAMPR_TPU_REUSE_BUDGET",
        "falling cross-run cache yield — the reuse budget may be "
        "evicting still-hot prefixes"),
    "device_fraction": (
        "lower", "DAMPR_TPU_LOWER",
        "compute is sliding off the accelerator back onto host fallback "
        "paths"),
    "handoff_fraction": (
        "handoff", "DAMPR_TPU_HANDOFF",
        "stage boundaries stopped staying device-resident and are "
        "round-tripping through host spill"),
}


def effective_window():
    return max(0, settings.sentry_window)


def effective_threshold():
    return settings.sentry_mad_threshold


def detect(points, window=None, threshold=None):
    """Judge the NEWEST point of one fingerprint series against the
    trailing ``window`` points before it.  Returns a (possibly empty)
    list of finding dicts, one per regressed metric::

        {metric, value, median, mad, z, threshold, window, direction,
         run, ts, fingerprint, setting, env, why}

    ``points`` must already be one comparable series (same fingerprint,
    oldest -> newest); thinner-than-MIN_BASELINE metrics stay silent.
    """
    window = effective_window() if window is None else window
    threshold = effective_threshold() if threshold is None else threshold
    if len(points) < 2 or window <= 0 or threshold <= 0:
        return []
    newest = points[-1]
    trailing = points[:-1][-window:]
    findings = []
    for metric, direction in _timeseries.METRICS.items():
        value = newest.get(metric)
        if not isinstance(value, (int, float)) or isinstance(value, bool):
            continue
        baseline = [p[metric] for p in trailing
                    if isinstance(p.get(metric), (int, float))
                    and not isinstance(p.get(metric), bool)]
        if len(baseline) < MIN_BASELINE:
            continue
        med = statistics.median(baseline)
        mad = statistics.median(abs(x - med) for x in baseline)
        scale = 1.4826 * mad
        if scale <= 0:
            # Flat baseline: allow 5% drift of the median before a unit
            # of z; epsilon floor keeps an all-zero baseline judgeable.
            scale = max(abs(med) * 0.05, 1e-9)
        z = (value - med) / scale
        bad = z > threshold if direction == "high" else z < -threshold
        if not bad:
            continue
        knob = METRIC_KNOBS.get(metric)
        findings.append({
            "metric": metric,
            "value": value,
            "median": med,
            "mad": mad,
            "z": round(z, 2),
            "threshold": threshold,
            "window": len(baseline),
            "direction": direction,
            "run": newest.get("run"),
            "ts": newest.get("ts"),
            "fingerprint": newest.get("fingerprint"),
            "setting": knob[0] if knob else None,
            "env": knob[1] if knob else None,
            "why": knob[2] if knob else None,
        })
    findings.sort(key=lambda f: -abs(f["z"]))
    return findings


def check_run(run_name, summary=None, window=None, threshold=None):
    """Sentry verdict for a run name's NEWEST telemetry point (the one
    the runner just appended).  Rebuilds the store from the history
    corpus when it is missing but history exists (pre-telemetry
    corpora).  ``summary`` narrows judgement to that run's fingerprint
    when given.  Never raises; no data -> no findings."""
    try:
        points = _timeseries.load(run_name)
        if not points:
            from . import history as _hist

            if _hist.load(run_name):
                _timeseries.fold(run_name)
                points = _timeseries.load(run_name)
        if not points:
            return []
        fp = None
        if summary is not None:
            from . import history as _hist

            fp = _hist.plan_fingerprint(
                (summary.get("plan") or {}).get("stage_shapes") or [])
        if fp is None:
            fp = points[-1].get("fingerprint")
        series = _timeseries.series(points, fingerprint=fp)
        return detect(series, window=window, threshold=threshold)
    except Exception:
        log.debug("sentry check failed for %r", run_name, exc_info=True)
        return []


def format_findings(findings):
    """Human lines for a findings list (the CLI / doctor rendering)."""
    out = []
    for f in findings:
        arrow = "above" if f["direction"] == "high" else "below"
        line = ("REGRESSION {metric}: {value:g} is {z:+.1f} robust "
                "sigma {arrow} the baseline median {median:g} "
                "(window={window} run(s), run={run})".format(
                    arrow=arrow, **f))
        out.append(line)
        if f.get("setting"):
            out.append("  knob: settings.{setting} ({env}) — {why}".format(
                **f))
    return out


def main(argv=None):
    """``dampr-tpu-sentry``: judge a run's newest telemetry point.

    Warn-only by default (exit 0, findings printed); ``--strict`` exits
    2 when any metric regressed — the perf-gate CI contract.  Exit 1
    means no telemetry/history exists for the run at all.
    """
    import argparse

    p = argparse.ArgumentParser(
        prog="dampr-tpu-sentry",
        description="regression sentry over dampr_tpu run telemetry")
    p.add_argument("run", help="run name (scratch-root corpus key)")
    p.add_argument("--strict", action="store_true",
                   help="exit nonzero when a regression is detected")
    p.add_argument("--json", action="store_true", help="machine output")
    p.add_argument("--window", type=int, default=None,
                   help="baseline window (default: settings.sentry_window"
                        " = DAMPR_TPU_SENTRY_WINDOW)")
    p.add_argument("--threshold", type=float, default=None,
                   help="robust z threshold (default: settings."
                        "sentry_mad_threshold = DAMPR_TPU_SENTRY_MAD)")
    p.add_argument("--fingerprint", metavar="F", default=None,
                   help="judge this plan-shape series instead of the "
                        "newest point's")
    p.add_argument("--fold", action="store_true",
                   help="rebuild the telemetry store from the history "
                        "corpus first")
    args = p.parse_args(argv)

    if args.fold:
        n = _timeseries.fold(args.run)
        print("folded {} point(s) from the history corpus".format(n))
    points = _timeseries.load(args.run)
    if not points:
        from . import history as _hist

        if _hist.load(args.run):
            _timeseries.fold(args.run)
            points = _timeseries.load(args.run)
    if not points:
        print("no telemetry for run {!r} under {} (and no history "
              "corpus to fold)".format(args.run, settings.scratch_root))
        return 1

    fp = args.fingerprint or points[-1].get("fingerprint")
    series = _timeseries.series(points, fingerprint=fp)
    findings = detect(series, window=args.window, threshold=args.threshold)

    if args.json:
        print(json.dumps({
            "run": args.run,
            "fingerprint": fp,
            "points": len(series),
            "window": (args.window if args.window is not None
                       else effective_window()),
            "threshold": (args.threshold if args.threshold is not None
                          else effective_threshold()),
            "findings": findings,
        }, indent=2, sort_keys=True))
    else:
        print("sentry: run={} fingerprint={} series={} point(s)".format(
            args.run, fp, len(series)))
        if findings:
            for line in format_findings(findings):
                print(line)
        elif len(series) <= MIN_BASELINE:
            print("baseline too thin to judge "
                  "(need >{} comparable points)".format(MIN_BASELINE))
        else:
            print("no regression: newest point within {:g} robust sigma "
                  "of its baseline".format(
                      args.threshold if args.threshold is not None
                      else effective_threshold()))
    if findings and args.strict:
        return 2
    return 0


if __name__ == "__main__":
    import sys as _sys

    _sys.exit(main())
