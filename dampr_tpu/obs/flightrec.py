"""Flight recorder: a bounded tail of recent spans + metric samples,
flushed to ``crashdump.json`` when a run dies.

The trace/stats artifacts are post-hoc — ``_finalize_obs`` writes them
when the run returns, so a wedged or killed run used to leave *nothing*.
The flight recorder closes that gap: while a run is live, every span the
tracer records and every sample the metrics sampler takes also lands in
a fixed-capacity ring (``settings.flight_recorder_events``); the kill /
exception path (``MTRunner`` and ``RunStore.abort_writes``) flushes the
ring to ``<trace_dir>/<run>/trace/crashdump.json``.

The dump IS a Chrome trace-event document — the same schema as
``trace.json`` (``docs/trace_schema.json``; counter samples are
``"ph":"C"`` events), so it loads in Perfetto and validates with
``tools/validate_trace.py`` unchanged.  ``otherData.crash`` carries the
death context: reason, exception type/message, ring occupancy/drops.

The ring is append-only and lock-free on the record path (``deque``
appends are atomic under the GIL; the drop counter is a best-effort
approximation) — recording must never slow the run it exists to
autopsy.  Flushing is idempotent: each call rewrites the dump
atomically, so a later flush with richer context (the runner's
exception handler after ``abort_writes``) simply supersedes the
earlier one.
"""

import collections
import json
import logging
import os
import threading
import time

log = logging.getLogger("dampr_tpu.obs.flightrec")

CRASHDUMP_FILE = "crashdump.json"

_active = None
_lock = threading.Lock()


def crashdump_filename(rank=None):
    """Per-rank crashdump name: rank 0 keeps the legacy
    ``crashdump.json``; a killed non-zero rank writes
    ``crashdump.rank<k>.json`` so its death artifact never clobbers
    rank 0's (and the filename alone names the dead rank)."""
    if rank is None:
        from ..parallel.mesh import rank_info

        rank = rank_info()[0]
    if rank and rank > 0:
        return "crashdump.rank{}.json".format(int(rank))
    return CRASHDUMP_FILE


class FlightRecorder(object):
    """Bounded ring of recent observability events for one run.

    Entries are ``("span", cat, name, t_abs, dur, lane, lane_name,
    args)`` or ``("sample", t_abs, {series: value})``; ``t_abs`` is an
    absolute ``perf_counter`` timestamp (converted to the recorder's
    epoch at flush, so span and sample clocks always agree in the
    dump)."""

    def __init__(self, run_name, capacity):
        self.run = run_name
        self.capacity = max(1, int(capacity))
        self.epoch = time.perf_counter()
        self.wall_start = time.time()
        self._ring = collections.deque(maxlen=self.capacity)
        #: Bounded tail of structured WARN+ log records (obs.log mirrors
        #: them here), flushed as ``otherData.log`` — a crashdump names
        #: the operational events that preceded the death, not just the
        #: span/sample timeline.
        self._log = collections.deque(maxlen=self.capacity)
        self.drops = 0  # best-effort (unlocked): ring evictions
        self.flush_count = 0
        self.path = None

    # -- record path (hot: no locks) ----------------------------------------
    def record_span(self, cat, name, t_abs, dur, lane, lane_name, args):
        ring = self._ring
        if len(ring) >= self.capacity:
            self.drops += 1
        ring.append(("span", cat, name, t_abs, dur, lane, lane_name,
                     args))

    def record_sample(self, t_abs, vals):
        ring = self._ring
        if len(ring) >= self.capacity:
            self.drops += 1
        ring.append(("sample", t_abs, vals))

    def record_log(self, rec):
        """One structured log record (a dict per docs/trace_schema.json's
        ``otherData.log`` items) into the bounded log tail."""
        self._log.append(rec)

    def __len__(self):
        return len(self._ring)

    # -- flush --------------------------------------------------------------
    def _events(self, snapshot):
        """Ring entries -> Chrome trace events (schema-valid: lanes get
        thread_name metadata, spans are X/i, samples are C counter
        events)."""
        pid = 1
        out = [{"ph": "M", "pid": pid, "tid": 0, "name": "process_name",
                "args": {"name": "dampr_tpu:{} (crashdump)".format(
                    self.run)}}]
        tid_of = {}
        metas = []
        body = []
        for ev in snapshot:
            if ev[0] == "sample":
                _kind, t_abs, vals = ev
                ts = round(max(0.0, t_abs - self.epoch) * 1e6, 3)
                for series, v in sorted(vals.items()):
                    if not isinstance(v, (int, float)) or isinstance(
                            v, bool):
                        continue
                    body.append({"ph": "C", "name": series, "cat": "metric",
                                 "pid": pid, "tid": 0, "ts": ts,
                                 "args": {"value": v}})
                continue
            _kind, cat, name, t_abs, dur, lane, lane_name, args = ev
            tid = tid_of.get(lane)
            if tid is None:
                tid = tid_of[lane] = len(tid_of) + 1
                metas.append({"ph": "M", "pid": pid, "tid": tid,
                              "name": "thread_name",
                              "args": {"name": lane_name or str(lane)}})
            rec = {"name": name, "cat": cat, "pid": pid, "tid": tid,
                   "ts": round(max(0.0, t_abs - self.epoch) * 1e6, 3)}
            if dur is None:
                rec["ph"] = "i"
                rec["s"] = "t"
            else:
                rec["ph"] = "X"
                rec["dur"] = round(dur * 1e6, 3)
            if args:
                rec["args"] = args
            body.append(rec)
        if not metas:
            # The validator requires named lanes; a sample-only dump
            # (metrics without tracing) still declares its one lane.
            metas.append({"ph": "M", "pid": pid, "tid": 0,
                          "name": "thread_name", "args": {"name": "main"}})
        return out + metas + body

    def flush(self, reason, exc=None):
        """Write the ring as ``crashdump.json`` under the run's trace
        directory; returns the path (None on failure — flushing happens
        on paths that are already dying and must not mask the original
        error)."""
        from . import export as _export

        try:
            snapshot = list(self._ring)
            proc = _export.process_section()
            crash = {
                "reason": reason,
                "events": len(snapshot),
                "ring_capacity": self.capacity,
                "ring_drops": self.drops,
                "flushed_at": round(time.time(), 3),
            }
            if exc is not None:
                crash["exception"] = type(exc).__name__
                crash["message"] = str(exc)[:2000]
            doc = {
                "traceEvents": self._events(snapshot),
                "displayTimeUnit": "ms",
                "otherData": {
                    "run": self.run,
                    "wall_start": self.wall_start,
                    "producer": "dampr_tpu.obs.flightrec",
                    "process": proc,
                    "crash": crash,
                },
            }
            log_tail = list(self._log)
            if log_tail:
                doc["otherData"]["log"] = log_tail
            rank = proc.get("process_id", 0)
            tdir = _export.run_trace_dir(self.run, rank=rank)
            os.makedirs(tdir, exist_ok=True)
            path = os.path.join(tdir, crashdump_filename(rank))
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, path)
            self.path = path
            self.flush_count += 1
            log.warning("flight recorder: crash dump written to %s (%s)",
                        path, reason)
            return path
        except Exception:
            log.warning("flight recorder flush failed", exc_info=True)
            return None


# -- module-level lifecycle (mirrors trace/metrics) --------------------------

def start(recorder):
    global _active
    with _lock:
        _active = recorder


def stop(recorder):
    global _active
    with _lock:
        if _active is recorder:
            _active = None


def active():
    return _active


def flush_active(reason, exc=None):
    """Flush the live recorder, if any (the ``abort_writes`` hook: the
    kill path may reach the store before the runner's own handler)."""
    rec = _active
    if rec is not None:
        return rec.flush(reason, exc)
    return None


def clear_stale(run_name):
    """Remove a PREVIOUS run's crashdump for this run name (called at
    run start): the dump — and the non-zero ``dampr-tpu-stats`` exit it
    drives — must describe the latest run, not a long-fixed failure."""
    from . import export as _export

    try:
        os.unlink(os.path.join(_export.run_trace_dir(run_name),
                               crashdump_filename()))
    except OSError:
        pass


def locate_crashdump(run_or_dir):
    """Resolve a run name / run directory / file path to an existing
    crashdump, or None.  Mirrors ``export.locate_stats``; any rank's
    dump counts — a fleet with one dead rank IS a crashed run (the
    first match in rank order is returned)."""
    dumps = locate_all_crashdumps(run_or_dir)
    return dumps[0] if dumps else None


def _rank_dumps_under(trace_dir):
    """Every crashdump under one run's trace dir: the legacy rank-0
    ``crashdump.json`` plus every ``rank<k>/crashdump.rank<k>.json``
    (and tolerantly any ``crashdump*.json`` either place — artifacts
    from future layouts must not hide a death)."""
    import glob

    out = []
    for pat in ("crashdump.json", "crashdump.rank*.json",
                "rank*/crashdump*.json"):
        out.extend(glob.glob(os.path.join(trace_dir, pat)))
    return sorted(set(out))


def locate_all_crashdumps(run_or_dir):
    """EVERY rank's crashdump for a run name / run dir / file path,
    sorted (rank 0's legacy path first when present).  ``dampr-tpu-stats``
    exit-code-3 detection scans this list so a killed non-zero rank is
    never masked by a clean rank 0."""
    from . import export as _export

    dirs = []
    if os.path.isfile(run_or_dir):
        dirs.append(os.path.dirname(os.path.abspath(run_or_dir)))
    if os.path.isdir(run_or_dir):
        dirs.append(run_or_dir)
        dirs.append(os.path.join(run_or_dir, "trace"))
    dirs.append(_export.run_trace_dir(run_or_dir, rank=0))
    seen = []
    for d in dirs:
        if not os.path.isdir(d):
            continue
        for dump in _rank_dumps_under(d):
            if dump not in seen:
                seen.append(dump)
        if seen:
            break  # one resolved layout; don't mix candidate roots
    return seen
