"""Fleet observability: merge per-rank telemetry into one timeline.

PR 8 made execution genuinely multi-process; this module makes it
*observable*.  Every rank of a traced run writes its own artifacts
(rank 0 under the legacy ``<run>/trace/``, rank k under
``<run>/trace/rank<k>/`` — :func:`~.export.run_trace_dir`); this module
reads them all back and answers the first question any distributed run
raises — **which rank is the straggler and why**:

- :func:`merge_traces` folds the per-rank ``trace.json`` files into ONE
  Perfetto-loadable timeline: each rank becomes its own process lane
  (Chrome ``pid`` = rank + 1, ``process_name`` metadata names it), and
  per-rank counter series are prefixed ``rank<k>/`` so counter tracks
  stay distinct and per-series monotonic.
- **Clock alignment** never trusts wall clocks: at
  ``init_distributed()`` every rank runs a barrier collective and
  records its monotonic clock at the barrier's exit
  (:data:`dampr_tpu.parallel.mesh.clock_sync`).  All ranks leave a
  barrier within network latency of the same instant, so shifting each
  rank's events by ``epoch_perf - barrier_perf`` places them on a
  fleet-common axis regardless of per-host clock (or NTP) drift.  Runs
  whose handshake never happened degrade to wall-start alignment and
  say so (``alignment: "wall"``).
- :func:`fleet_section` builds ``stats()["fleet"]``: per-rank
  wall/records/bytes/spill totals, the rank x rank exchange send/recv
  matrices (folded from the per-device route accounting PR 8's
  ``mesh_blob_exchange`` keeps), and per-collective-step **skew** — for
  every chunked exchange step, the spread between the first and last
  rank's entry into the collective as a fraction of the step's wall.
  Per-step skew is what separates "the network is slow" (low skew, long
  steps) from "rank 2 is late" (high skew — the collective itself was
  fast once everyone arrived).

Rank 0 runs the merge at finalize (bounded wait for sibling artifacts —
``settings.fleet_wait_ms`` — so a killed sibling can't wedge the
survivor); ``dampr-tpu-stats --fleet`` re-runs it post-hoc on any run
directory.  The merged timeline lands at ``<run>/trace/fleet/trace.json``
and validates against ``docs/trace_schema.json`` unchanged.
"""

import json
import logging
import os
import re
import time

log = logging.getLogger("dampr_tpu.obs.fleet")

MERGED_TRACE_FILE = "trace.json"
FLEET_DIR = "fleet"

_RANK_DIR = re.compile(r"^rank(\d+)$")
_STEP_NAME = re.compile(r"^step:(\d+)$")


def resolve_base_dir(run_or_dir):
    """The run's rank-0 (legacy) trace directory for a run name, a run
    scratch directory, or a trace directory / artifact path."""
    from . import export as _export

    p = str(run_or_dir)
    if os.path.isfile(p):
        p = os.path.dirname(os.path.abspath(p))
    if os.path.isdir(p):
        if os.path.isdir(os.path.join(p, "trace")):
            return os.path.join(p, "trace")
        return p
    return _export.run_trace_dir(p, rank=0)


def rank_dirs(run_or_dir):
    """{rank: per-rank trace dir} discovered on disk.  Rank 0 is the
    base dir itself (legacy layout); non-zero ranks are ``rank<k>/``
    subdirectories."""
    base = resolve_base_dir(run_or_dir)
    out = {}
    if os.path.isdir(base):
        out[0] = base
        for entry in sorted(os.listdir(base)):
            m = _RANK_DIR.match(entry)
            if m and os.path.isdir(os.path.join(base, entry)):
                out[int(m.group(1))] = os.path.join(base, entry)
    return out


def _load_json(path):
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def load_ranks(run_or_dir):
    """{rank: {"dir", "trace" (doc or None), "stats" (dict or None)}}
    for every per-rank directory that holds at least one artifact."""
    from . import export as _export

    out = {}
    for rank, d in rank_dirs(run_or_dir).items():
        trace = _load_json(os.path.join(d, _export.TRACE_FILE))
        stats = _load_json(os.path.join(d, _export.STATS_FILE))
        if trace is None and stats is None:
            continue
        out[rank] = {"dir": d, "trace": trace, "stats": stats}
    return out


# -- clock alignment ---------------------------------------------------------

def _proc_block(rank_data):
    doc = rank_data.get("trace") or {}
    proc = (doc.get("otherData") or {}).get("process")
    if proc:
        return proc
    return (rank_data.get("stats") or {}).get("process") or {}


def clock_shifts(ranks):
    """Per-rank timeline shift (seconds added to a rank's relative event
    timestamps to land on the fleet-common axis) and the alignment mode.

    Clock mode (every rank carries the barrier handshake): common zero
    is the barrier instant — ``shift = epoch_perf - barrier_perf`` (the
    tracer epoch's signed distance past the barrier on that rank's own
    monotonic clock).  Wall mode (any rank missing the handshake):
    shifts derive from ``wall_start`` deltas against the earliest rank —
    honest but NTP-trusting, flagged so consumers can tell.  A final
    normalization makes the earliest shifted event sit at t=0 either
    way."""
    anchors = {}
    walls = {}
    clock_ok = True
    for rank, data in ranks.items():
        proc = _proc_block(data)
        clock = proc.get("clock") or {}
        epoch = proc.get("epoch_perf")
        if epoch is not None and clock.get("barrier_perf") is not None:
            anchors[rank] = float(epoch) - float(clock["barrier_perf"])
        else:
            clock_ok = False
        doc = data.get("trace") or {}
        ws = (doc.get("otherData") or {}).get("wall_start")
        if ws is None:
            ws = (data.get("stats") or {}).get("started_at")
        walls[rank] = float(ws) if ws is not None else 0.0
    if clock_ok and len(anchors) == len(ranks) and ranks:
        return dict(anchors), "clock"
    if len(ranks) <= 1:
        return {rank: 0.0 for rank in ranks}, "none"
    w0 = min(walls.values()) if walls else 0.0
    return {rank: walls.get(rank, 0.0) - w0 for rank in ranks}, "wall"


def _events_of(rank_data):
    doc = rank_data.get("trace") or {}
    return doc.get("traceEvents") or []


# -- merge -------------------------------------------------------------------

def merge_traces(ranks, shifts, run_name=None):
    """Fold per-rank Chrome trace docs into one multi-process document.

    Per rank: ``pid`` = rank + 1 with a ``process_name`` metadata lane
    (``rank<k>``), thread lanes carried through per-pid, X/i/C event
    timestamps shifted onto the common axis, and counter series renamed
    ``rank<k>/<series>`` (distinct Perfetto counter tracks; keeps the
    validator's per-series monotonic pin).  Timestamps are re-based so
    the earliest merged event sits at ts=0 (Perfetto-friendly, and the
    schema's counter clamp stays valid)."""
    # Pass 1: earliest shifted timestamp across the fleet.
    t_min = None
    for rank, data in ranks.items():
        us = shifts.get(rank, 0.0) * 1e6
        for ev in _events_of(data):
            ts = ev.get("ts")
            if isinstance(ts, (int, float)):
                t = ts + us
                t_min = t if t_min is None else min(t_min, t)
    t_min = t_min or 0.0

    events = []
    wall_start = None
    for rank in sorted(ranks):
        data = ranks[rank]
        pid = rank + 1
        us = shifts.get(rank, 0.0) * 1e6
        doc = data.get("trace") or {}
        ws = (doc.get("otherData") or {}).get("wall_start")
        if ws is not None:
            wall_start = ws if wall_start is None else min(wall_start, ws)
        n = _proc_block(data).get("num_processes")
        events.append({
            "ph": "M", "pid": pid, "tid": 0, "name": "process_name",
            "args": {"name": "rank{}{}".format(
                rank, "/{}".format(n) if n else "")}})
        for ev in _events_of(data):
            ph = ev.get("ph")
            if ph == "M":
                if ev.get("name") == "process_name":
                    continue  # replaced by the rank lane name above
                ev = dict(ev, pid=pid)
            elif ph in ("X", "i", "C"):
                ev = dict(ev, pid=pid)
                ts = ev.get("ts")
                if isinstance(ts, (int, float)):
                    ev["ts"] = round(ts + us - t_min, 3)
                if ph == "C":
                    ev["name"] = "rank{}/{}".format(rank, ev.get("name"))
            else:
                ev = dict(ev, pid=pid)
            events.append(ev)
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "run": run_name or next(
                ((d.get("trace") or {}).get("otherData", {}).get("run")
                 or (d.get("stats") or {}).get("run")
                 for d in ranks.values()), None) or "?",
            "wall_start": wall_start or 0.0,
            "producer": "dampr_tpu.obs.fleet",
        },
    }, t_min


# -- skew --------------------------------------------------------------------

def straggler_of(mean_late):
    """``(straggler_rank, late_ratio)`` from per-rank mean entry
    lateness ({rank: seconds late after the first arriver}).  The ratio
    is the straggler's lateness over the fleet mean (>= 1.0) — the
    number the doctor renders as "rank K enters steps N.Nx late" and the
    live mitigation controller (:mod:`dampr_tpu.parallel.mitigate`)
    thresholds against ``settings.speculate_threshold``.  One shared
    definition so the post-hoc and live signals can never disagree."""
    if not mean_late:
        return None, 1.0
    straggler = max(mean_late, key=mean_late.get)
    fleet_mean = sum(mean_late.values()) / len(mean_late)
    if fleet_mean <= 1e-12:
        return straggler, 1.0
    return straggler, mean_late[straggler] / fleet_mean


def step_skew(ranks, shifts):
    """Per-collective-step skew from the aligned ``exchange`` step
    spans: for each chunked all_to_all step seen by >= 2 ranks, the
    spread between the earliest and latest rank ENTRY as a fraction of
    the step's fleet wall (first entry -> last exit).  Fractions are in
    [0, 1] by construction; per-rank mean entry lateness (seconds after
    the first arriver, averaged over steps) names the straggler."""
    entries = {}  # step id -> {rank: (entry_s, exit_s)}
    for rank, data in ranks.items():
        shift = shifts.get(rank, 0.0)
        for ev in _events_of(data):
            if ev.get("ph") != "X" or ev.get("cat") != "exchange":
                continue
            m = _STEP_NAME.match(ev.get("name") or "")
            if not m:
                continue
            t0 = float(ev.get("ts", 0.0)) / 1e6 + shift
            t1 = t0 + float(ev.get("dur", 0.0)) / 1e6
            step = int(m.group(1))
            # A rank may run several exchanges (several windows reuse
            # step ids): key by occurrence index per (rank, step) so
            # the i-th occurrence on every rank lines up.
            occ = sum(1 for r in entries.get(step, {}) if r[0] == rank)
            entries.setdefault(step, {})[(rank, occ)] = (t0, t1)
    steps = []
    lateness = {}  # rank -> [seconds late per step]
    for step in sorted(entries):
        by_occ = {}
        for (rank, occ), tt in entries[step].items():
            by_occ.setdefault(occ, {})[rank] = tt
        for occ in sorted(by_occ):
            per_rank = by_occ[occ]
            if len(per_rank) < 2:
                continue
            first = min(t0 for t0, _t1 in per_rank.values())
            last_entry = max(t0 for t0, _t1 in per_rank.values())
            last_exit = max(t1 for _t0, t1 in per_rank.values())
            wall = last_exit - first
            spread = last_entry - first
            frac = 0.0
            if wall > 1e-12:
                frac = max(0.0, min(1.0, spread / wall))
            rank_entries = {}
            for rank, (t0, _t1) in sorted(per_rank.items()):
                late = t0 - first
                rank_entries[str(rank)] = round(late, 6)
                lateness.setdefault(rank, []).append(late)
            steps.append({
                "step": step,
                "spread_seconds": round(max(0.0, spread), 6),
                "wall_seconds": round(max(0.0, wall), 6),
                "fraction": round(frac, 4),
                "entry_lateness": rank_entries,
            })
    if not steps:
        return None
    mean_late = {rank: sum(ls) / len(ls) for rank, ls in lateness.items()}
    straggler, late_ratio = straggler_of(mean_late)
    fracs = [s["fraction"] for s in steps]
    return {
        "steps": steps,
        "skew_seconds": round(sum(s["spread_seconds"] for s in steps), 6),
        "max_fraction": round(max(fracs), 4),
        "mean_fraction": round(sum(fracs) / len(fracs), 4),
        "straggler_rank": straggler,
        "mean_entry_lateness": {str(r): round(v, 6)
                                for r, v in sorted(mean_late.items())},
        # How much later the straggler enters collectives than the fleet
        # average (>= 1; the doctor's "rank K enters steps N.Nx late").
        "late_ratio": round(late_ratio, 2),
    }


# -- fleet stats section -----------------------------------------------------

def _rank_of_device(dev, num_processes, n_devices):
    if n_devices <= 0 or num_processes <= 0:
        return 0
    per = max(1, n_devices // num_processes)
    return min(num_processes - 1, int(dev) // per)


def _device_count(ranks, num_processes):
    """Global device count for the device->rank mapping.  The
    authoritative source is the process block's ``global_devices``
    (stamped once the process group is up — jax enumerates devices
    contiguously per process, so rank of device d is d // per_proc).
    Fallback: the largest device index seen in any route (+1), which
    undercounts when high devices moved nothing — hence the preference
    order."""
    counts = []
    for data in ranks.values():
        doc = data.get("trace") or {}
        for proc in ((doc.get("otherData") or {}).get("process"),
                     (data.get("stats") or {}).get("process")):
            c = (proc or {}).get("global_devices")
            if isinstance(c, int) and c > 0:
                counts.append(c)
    if counts:
        return max(counts)
    hi = -1
    for data in ranks.values():
        ex = (((data.get("stats") or {}).get("mesh") or {})
              .get("exchange") or {})
        for s, d, _n in ex.get("routes") or ():
            hi = max(hi, int(s), int(d))
        for key in ("sent_per_device", "received_per_device"):
            for dev in (ex.get(key) or {}):
                try:
                    hi = max(hi, int(dev))
                except (TypeError, ValueError):
                    pass
    return hi + 1 if hi >= 0 else num_processes


def _exchange_matrices(ranks, num_processes, n_dev):
    """rank x rank sent-bytes matrix from the per-device route triples
    (``mesh.exchange.routes`` — identical on every rank, since each rank
    observes the global schedule; the first rank that recorded routes
    wins)."""
    for _rank, data in sorted(ranks.items()):
        ex = (((data.get("stats") or {}).get("mesh") or {})
              .get("exchange") or {})
        routes = ex.get("routes")
        if not routes:
            continue
        sent = [[0] * num_processes for _ in range(num_processes)]
        for s, d, n in routes:
            rs = _rank_of_device(s, num_processes, n_dev)
            rd = _rank_of_device(d, num_processes, n_dev)
            sent[rs][rd] += int(n)
        recv = [[sent[s][d] for s in range(num_processes)]
                for d in range(num_processes)]
        return {
            "devices": n_dev,
            "bytes": sum(int(n) for _s, _d, n in routes),
            "rank_sent_matrix": sent,
            "rank_received_matrix": recv,
        }
    return None


def fleet_section(ranks, shifts=None, alignment=None):
    """The ``stats()["fleet"]`` payload from loaded per-rank artifacts.
    Returns None for single-process runs (back-compat: the section is
    absent, never empty-but-present)."""
    if not ranks:
        return None
    num = max((_proc_block(d).get("num_processes") or 1)
              for d in ranks.values())
    num = max(num, max(ranks) + 1)
    if num <= 1:
        return None
    if shifts is None:
        shifts, alignment = clock_shifts(ranks)
    n_dev = _device_count(ranks, num)

    def _own_device_sum(per_device, rank):
        # The exchange accounting is GLOBAL on every rank (the host side
        # packs the full schedule), so per-rank traffic must be sliced
        # to the devices that rank actually owns — summing everything
        # would report the identical fleet total on every row.
        total = 0
        for dev, n in (per_device or {}).items():
            try:
                dev = int(dev)
            except (TypeError, ValueError):
                continue
            if _rank_of_device(dev, num, n_dev) == rank:
                total += n
        return total

    per_rank = []
    for rank in sorted(ranks):
        stats = ranks[rank].get("stats") or {}
        totals = stats.get("totals") or {}
        ex = ((stats.get("mesh") or {}).get("exchange") or {})
        entry = {
            "rank": rank,
            "wall_seconds": stats.get("wall_seconds"),
            "records_out": totals.get("records_out"),
            "bytes_out": totals.get("bytes_out"),
            "spill_bytes": totals.get("spill_bytes"),
            "io_wait_fraction": (stats.get("io") or {}).get(
                "io_wait_fraction"),
            "device_fraction": (stats.get("device") or {}).get(
                "device_fraction"),
            "verdict": ((stats.get("critpath") or {}).get("run")
                        or {}).get("verdict"),
            "exchange_sent_bytes": _own_device_sum(
                ex.get("sent_per_device"), rank),
            "exchange_received_bytes": _own_device_sum(
                ex.get("received_per_device"), rank),
        }
        per_rank.append(entry)
    section = {
        "num_processes": num,
        "ranks": sorted(ranks),
        "missing_ranks": [r for r in range(num) if r not in ranks],
        "alignment": alignment or "none",
        "per_rank": per_rank,
    }
    matrices = _exchange_matrices(ranks, num, n_dev)
    if matrices is not None:
        section["exchange"] = matrices
    # Mitigation visibility (dampr_tpu.parallel.mitigate): the shared
    # collective state (engagements, skipped windows, down-weights) is
    # identical on every rank by construction, but steals and
    # speculative wins are LOCAL per-rank counters — the fleet view
    # sums them so host-path mitigation on any rank is visible, next to
    # the skew that triggered it.
    mits = [(rank, (data.get("stats") or {}).get("mitigation"))
            for rank, data in sorted(ranks.items())
            if (data.get("stats") or {}).get("mitigation")]
    if mits:
        merged = dict(mits[0][1])
        for key in ("speculative_attempts", "speculative_wins",
                    "stolen_partitions"):
            merged[key] = sum(int(m.get(key) or 0) for _r, m in mits)
        section["mitigation"] = merged
    skew = step_skew(ranks, shifts)
    if skew is not None:
        section["skew"] = skew
        by_rank = {e["rank"]: e for e in per_rank}
        for rank_s, late in skew["mean_entry_lateness"].items():
            e = by_rank.get(int(rank_s))
            if e is not None:
                e["mean_entry_lateness_seconds"] = late
    return section


# -- orchestration -----------------------------------------------------------

def _expected_ranks(ranks, summary=None):
    num = 1
    if summary is not None:
        num = (summary.get("process") or {}).get("num_processes") or 1
    for data in ranks.values():
        num = max(num, _proc_block(data).get("num_processes") or 1)
    return num


def wait_for_ranks(run_or_dir, num_processes, wait_ms):
    """Poll (bounded) until every expected rank's stats.json landed.
    Returns the list of ranks still MISSING at the deadline (empty =
    everyone arrived) — a killed sibling stops arriving and the
    deadline moves the merge on with what exists."""
    from . import export as _export

    deadline = time.monotonic() + max(0, wait_ms) / 1000.0
    base = resolve_base_dir(run_or_dir)
    while True:
        missing = []
        for rank in range(num_processes):
            d = base if rank == 0 else os.path.join(
                base, "rank{}".format(rank))
            if not os.path.isfile(os.path.join(d, _export.STATS_FILE)):
                missing.append(rank)
        if not missing or time.monotonic() >= deadline:
            return missing
        time.sleep(0.05)


def merge_run(run_or_dir, wait_ms=0, summary=None, write=True):
    """Build the merged fleet timeline + ``fleet`` stats section for a
    run and (by default) persist both: the merged Perfetto trace at
    ``<base>/fleet/trace.json`` and the section injected into rank 0's
    ``stats.json``.  Returns the fleet section (None when the run was
    single-process or left no per-rank artifacts)."""
    from . import critpath as _critpath, export as _export

    ranks = load_ranks(run_or_dir)
    num = _expected_ranks(ranks, summary)
    if wait_ms and num > 1:
        missing = wait_for_ranks(run_or_dir, num, wait_ms)
        if missing:
            log.warning("fleet merge proceeding without rank(s) %s "
                        "(deadline %d ms)", missing, wait_ms)
        ranks = load_ranks(run_or_dir)
    if not ranks:
        return None
    shifts, alignment = clock_shifts(ranks)
    section = fleet_section(ranks, shifts, alignment)
    if section is None:
        return None
    merged, _t0 = merge_traces(ranks, shifts)
    base = resolve_base_dir(run_or_dir)
    if write:
        fdir = os.path.join(base, FLEET_DIR)
        os.makedirs(fdir, exist_ok=True)
        mpath = os.path.join(fdir, MERGED_TRACE_FILE)
        tmp = mpath + ".tmp"
        with open(tmp, "w") as f:
            json.dump(merged, f)
        os.replace(tmp, mpath)
        section["merged_trace_file"] = mpath
        # Rank 0's stats.json is the fleet's front door: re-persist it
        # with the fleet section (and a skew-aware critpath) attached.
        spath = os.path.join(base, _export.STATS_FILE)
        stats = _load_json(spath)
        if stats is not None:
            stats["fleet"] = section
            if stats.get("critpath"):
                _critpath.apply_skew(stats["critpath"], section,
                                     stats.get("wall_seconds") or 0.0)
            _export.write_stats(stats, spath)
            if summary is not None and summary.get("critpath"):
                _critpath.apply_skew(summary["critpath"], section,
                                     summary.get("wall_seconds") or 0.0)
    return section


def format_fleet(section):
    """Human rendering for ``dampr-tpu-stats --fleet``."""
    if not section:
        return "no fleet section: single-process run (nothing to merge)"
    lines = []
    add = lines.append
    add("fleet: {} process(es), ranks present {} · alignment: {}".format(
        section.get("num_processes"), section.get("ranks"),
        section.get("alignment")))
    if section.get("missing_ranks"):
        add("MISSING ranks: {} (killed or still running)".format(
            section["missing_ranks"]))
    add("{:>5} {:>9} {:>12} {:>10} {:>10} {:>11} {:>11}  {}".format(
        "rank", "wall", "records", "bytes", "spill", "ex_sent",
        "ex_recv", "verdict"))
    for e in section.get("per_rank") or ():
        add("{:>5} {:>9} {:>12} {:>10} {:>10} {:>11} {:>11}  {}".format(
            e.get("rank"),
            "{:.2f}s".format(e["wall_seconds"])
            if e.get("wall_seconds") is not None else "-",
            e.get("records_out") if e.get("records_out") is not None
            else "-",
            "{:.1f}MB".format((e.get("bytes_out") or 0) / 1e6),
            "{:.1f}MB".format((e.get("spill_bytes") or 0) / 1e6),
            "{:.1f}MB".format((e.get("exchange_sent_bytes") or 0) / 1e6),
            "{:.1f}MB".format(
                (e.get("exchange_received_bytes") or 0) / 1e6),
            e.get("verdict") or "?"))
    skew = section.get("skew")
    if skew:
        add("skew: {} step(s) · mean {:.0%} / max {:.0%} of step wall · "
            "fleet waited {:.3f}s on stragglers".format(
                len(skew.get("steps") or ()), skew.get("mean_fraction", 0),
                skew.get("max_fraction", 0), skew.get("skew_seconds", 0)))
        add("straggler: rank {} (enters collectives {:.2f}x later than "
            "the fleet average)".format(
                skew.get("straggler_rank"), skew.get("late_ratio", 1.0)))
    ex = section.get("exchange")
    if ex:
        add("exchange: {} over {} device(s); rank sent matrix "
            "(bytes): {}".format(
                "{:.1f}MB".format((ex.get("bytes") or 0) / 1e6),
                ex.get("devices"), ex.get("rank_sent_matrix")))
    mt = section.get("merged_trace_file")
    if mt:
        add("merged trace: {}  (load in https://ui.perfetto.dev)".format(
            mt))
    return "\n".join(lines)


def main(argv=None):
    """``python -m dampr_tpu.obs.fleet <run>`` — merge + print."""
    import argparse

    ap = argparse.ArgumentParser(
        description="merge a multi-process run's per-rank traces into "
                    "one Perfetto timeline + fleet stats section")
    ap.add_argument("run", help="run name, run scratch dir, or trace dir")
    ap.add_argument("--json", action="store_true",
                    help="emit the fleet section as JSON")
    ap.add_argument("--no-write", action="store_true",
                    help="compute only; do not persist the merged trace")
    args = ap.parse_args(argv)
    section = merge_run(args.run, write=not args.no_write)
    if args.json:
        print(json.dumps(section, indent=2, sort_keys=True))
    else:
        print(format_fleet(section))
    return 0 if section else 1


if __name__ == "__main__":
    import sys

    sys.exit(main())
