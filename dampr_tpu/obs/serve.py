"""Live metrics endpoint: a stdlib-only HTTP thread per rank.

The obs plane so far is post-hoc (artifacts) or console-bound (the
progress line).  This module is the first *service-shaped* surface — the
piece ROADMAP item 3's disaggregated pipeline service scrapes — exposing
the LIVE metrics registry while a run is still in flight:

- ``GET /metrics`` — Prometheus text exposition (version 0.0.4) of the
  active registry (:func:`dampr_tpu.obs.promtext.render`), every sample
  rank-labeled (``rank="<k>"``), so one scrape config covering a fleet's
  per-rank ports yields groupable per-worker series (the tf.data-service
  per-worker telemetry shape, arXiv 2210.14826).  A process with no
  metered run in flight serves the empty exposition (valid: zero
  samples), never an error — scrapers must survive run boundaries.
- ``GET /healthz`` — JSON liveness: run name, rank identity, whether a
  registry is live.  The fleet's "is rank k up" probe.

Enabled by ``settings.metrics_port`` (default 0 = off; the runner starts
one server per run on ``metrics_port + process_id`` so co-located ranks
never collide, and setting the port implies the 100 ms sampler so the
gauges actually move).  Dependency-free by design: ``http.server``
behind a daemon thread, request handling never touches the run's hot
path — the registry's own locks bound a scrape's cost to one snapshot.
"""

import json
import logging
import threading

log = logging.getLogger("dampr_tpu.obs.serve")

#: The exposition content type Prometheus scrapers negotiate.
METRICS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class MetricsServer(object):
    """One rank's live observability endpoint.

    Serves whatever registry is ACTIVE at request time
    (:func:`dampr_tpu.obs.metrics.active`) rather than binding one
    registry at construction — the server outlives nothing (the runner
    stops it at run teardown), but within a run this also makes it
    correct for nested runs (innermost registry wins, same contract as
    the tracer)."""

    def __init__(self, port, run_name=None, rank=None, num_processes=None):
        from ..parallel.mesh import rank_info

        pid, num = rank_info()
        self.rank = pid if rank is None else rank
        self.num_processes = num if num_processes is None else num_processes
        self.run_name = run_name
        #: Requested port BEFORE the per-rank offset; 0 = OS-assigned
        #: (tests).  ``self.port`` is the live bound port after start().
        self.base_port = int(port)
        self.port = None
        #: True when the per-rank port was taken and the endpoint bound
        #: a fallback port instead (concurrent runs on one box); the
        #: runner records the live port in ``stats()["endpoint"]`` so
        #: scrapers and ``dampr-tpu-top`` can still find the rank.
        self.fallback = False
        self._httpd = None
        self._thread = None

    # -- request handling ---------------------------------------------------
    def _metrics_text(self):
        from . import metrics as _metrics, promtext

        reg = _metrics.active()
        if reg is None:
            return ""
        return promtext.render(reg, rank=self.rank)

    def _health(self):
        from . import metrics as _metrics

        reg = _metrics.active()
        return {
            "status": "ok",
            "run": (reg.run if reg is not None else self.run_name),
            "process_id": self.rank,
            "num_processes": self.num_processes,
            "metrics_live": reg is not None,
        }

    # -- lifecycle ----------------------------------------------------------
    def start(self):
        """Bind and serve on a daemon thread.  Returns self, or None
        when the bind fails (port taken): a busy port degrades the
        endpoint, never the run."""
        import http.server

        server = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 - http.server API
                try:
                    if self.path.split("?")[0] == "/metrics":
                        body = server._metrics_text().encode("utf-8")
                        self.send_response(200)
                        self.send_header("Content-Type",
                                         METRICS_CONTENT_TYPE)
                        self.send_header("Content-Length", str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                    elif self.path.split("?")[0] == "/healthz":
                        body = json.dumps(server._health()).encode("utf-8")
                        self.send_response(200)
                        self.send_header("Content-Type",
                                         "application/json")
                        self.send_header("Content-Length", str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                    else:
                        self.send_error(404)
                except (BrokenPipeError, ConnectionResetError):
                    pass  # scraper went away mid-response

            def log_message(self, fmt, *args):
                log.debug("metrics endpoint: " + fmt, *args)

        from . import log as _obslog

        requested = self.base_port
        if requested > 0:
            # Per-rank offset: co-located ranks each get their own port
            # (rank 0 = the configured port, rank k = port + k).
            requested += self.rank
        # Port-collision fallback: when the per-rank port is taken
        # (back-to-back runs racing teardown, or two fleets sharing one
        # box and one base port), probe the next free ports ABOVE the
        # fleet's block (base + num_processes..) instead of giving up —
        # a second run's endpoint must not silently vanish, and it must
        # not steal a sibling rank's expected port either.
        candidates = [requested]
        if requested > 0:
            probe_base = self.base_port + max(1, int(self.num_processes
                                                     or 1))
            candidates += [p for p in range(probe_base, probe_base + 32)
                           if p != requested]
        err = None
        for port in candidates:
            try:
                self._httpd = http.server.ThreadingHTTPServer(
                    ("", port), Handler)
                break
            except OSError as e:
                err = e
        if self._httpd is None:
            _obslog.warn(
                "metrics-bind-failed",
                "metrics endpoint bind failed on port %d (and %d fallback "
                "probes): %s (endpoint disabled for this run)", requested,
                len(candidates) - 1, err, logger=log, rank=self.rank)
            return None
        self.port = self._httpd.server_address[1]
        self.fallback = requested > 0 and self.port != requested
        if self.fallback:
            _obslog.warn(
                "metrics-port-fallback",
                "metrics endpoint port %d was taken; rank %d bound the "
                "next free port %d instead (recorded in stats)",
                requested, self.rank, self.port, logger=log,
                requested=requested, bound=self.port)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="dampr-tpu-metrics-http")
        self._thread.start()
        log.info("metrics endpoint: rank %d serving /metrics + /healthz "
                 "on port %d", self.rank, self.port)
        return self

    def stop(self):
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            try:
                httpd.shutdown()
                httpd.server_close()
            except Exception:
                log.debug("metrics endpoint shutdown failed",
                          exc_info=True)
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2.0)
            if t.is_alive():
                log.warning(
                    "metrics endpoint thread %s did not stop within "
                    "2.0s at shutdown; abandoning it (daemon) — a "
                    "wedged in-flight request is still being served",
                    t.name)


def start_server(port, run_name=None):
    """Convenience for the runner: build + start, returning the live
    server or None (bind failure / port <= 0 with no override)."""
    if port is None:
        return None
    srv = MetricsServer(port, run_name=run_name)
    return srv.start()
