"""Critical-path analysis: which resource bounds each stage's wall time.

The span timeline (:mod:`.trace`) records *what happened when* on every
engine lane — codec windows, folds, spill writes and queue latency,
writer backpressure, merge generations, device program dispatches, HBM
transfers, consumer stalls.  This module walks that span DAG and answers
the diagnosis question the raw timeline leaves open: **per stage (and
for the whole run), what was the run actually waiting on?**

Method: every executed stage records one ``stage`` span on the
``stages`` lane, giving a wall window per sid.  Within each window the
resource spans are clipped and merged per resource with the same
wall-clock interval-union discipline as the live ``codec_wait`` bucket
(:func:`dampr_tpu.ops.devtime.union_seconds`): two codec producer
threads tokenizing concurrently cover the wall once, not twice — so
every resource fraction is comparable against elapsed wall.  The
dominant resource is the stage's *verdict*; wall not covered by any
resource span is ``host-compute`` (generic Python/UDF time — the
fallback verdict when nothing instrumented dominates).

The bottleneck taxonomy (every verdict maps to concrete settings in
``dampr-tpu-doctor``):

===============  ============================================================
verdict          meaning
===============  ============================================================
``codec``        native decode/tokenize/parse work bounds the stage
``fold``         map-side segment folds bound it
``spill-queue``  spill writes queued behind the writer pool (backpressure
                 included) — the stage outran ``spill_write_threads``
``spill-write``  spill disk writes themselves (bandwidth, not backlog)
``io-read``      frame reads outran the prefetcher
``merge``        k-way merge generations bound it
``device``       jitted device programs (compute) bound it
``transfer``     h2d/d2h movement (HBM tier puts/fetches, program drains)
``overlap-stall``  every live fold consumer blocked on its codec producer
``mesh``         collective folds/exchanges bound it
``skew``         collective-entry spread: the fleet waited on a straggler
                 rank (only measurable from the merged cross-rank
                 timeline — see :mod:`.fleet` and :func:`apply_skew`)
``host-compute`` uninstrumented host work (opaque UDFs, Python glue)
===============  ============================================================

Consumed as ``stats()["critpath"]`` (built by the runner for traced
runs), by the run-history corpus, and by ``dampr-tpu-doctor``.
"""

import json
import os
import re

from ..ops.devtime import union_seconds

#: Span category -> resource (see the taxonomy table above).
_RESOURCE_BY_CAT = {
    "codec": "codec",
    "fold": "fold",
    "spill": "spill-write",
    "spill_queue": "spill-queue",
    "merge": "merge",
    "collective": "mesh",
    "exchange": "mesh",
    "hbm": "transfer",
    # Handoff spans (table-probe dispatches, the finalize that registers
    # HBM-resident refs) are device program time: the tier exists to
    # REPLACE transfer work, so classifying it as transfer would report
    # the cure as the disease.
    "handoff": "device",
    "stall": "overlap-stall",
    "checkpoint": "checkpoint",
    # Reuse-cache spans (mount hardlinking, delta re-runs, publishes)
    # are checkpoint-shaped work: durable materialization IO, never
    # productive compute — same tie-break tier as checkpoints.
    "reuse": "checkpoint",
    # Streamed-edge folder/chain spans (runner._StreamFolder) are
    # productive fold work hidden under the producing stage; the
    # publish backpressure wait rides "stall" spans named stream-wait
    # and classifies as pipeline-stall via _resource_of below.
    "pipeline": "fold",
}

#: Verdicts that may be *covered* by other work happening concurrently:
#: a stall/queue span only matters where nothing productive overlapped
#: it, so productive resources win ties at equal fractions.
_PRIORITY = ("device", "codec", "fold", "merge", "mesh", "spill-write",
             "transfer", "spill-queue", "io-read", "overlap-stall",
             "pipeline-stall", "skew", "checkpoint", "host-compute")

_STAGE_NAME = re.compile(r"^s(\d+):")


def _resource_of(cat, name):
    if cat == "io_wait":
        return "spill-queue" if "writer" in (name or "") else "io-read"
    if cat == "stall":
        # Streamed-edge publish backpressure ("stream-wait") is its own
        # verdict — the doctor's fix (raise pipeline_queue_bytes) is
        # different from the overlap executor's stall knobs, whose
        # "pipe-wait" spans stay overlap-stall.
        return ("pipeline-stall" if "stream" in (name or "")
                else "overlap-stall")
    if cat == "device":
        # Both the dispatch ("map-fold") and the drain span are device
        # time: dispatch is async, so the program's COMPUTE surfaces
        # inside the drain's block — classifying drain as transfer
        # would diagnose compute-bound runs as transfer-bound.  The
        # h2d/d2h split comes from the profiler's sub-phases (and the
        # hbm spans), not from span names.
        return "device"
    return _RESOURCE_BY_CAT.get(cat)


def normalize_events(events):
    """Accept either a live Tracer's compact tuples ``(cat, name, t0,
    dur, lane, args)`` (seconds) or persisted Chrome trace events
    (dicts, microseconds); yield ``(cat, name, t0_s, dur_s)`` for
    complete spans only."""
    out = []
    for ev in events:
        if isinstance(ev, dict):
            if ev.get("ph") != "X":
                continue
            out.append((ev.get("cat"), ev.get("name"),
                        float(ev.get("ts", 0)) / 1e6,
                        float(ev.get("dur", 0)) / 1e6))
        else:
            cat, name, t0, dur = ev[0], ev[1], ev[2], ev[3]
            if dur is None:
                continue
            out.append((cat, name, float(t0), float(dur)))
    return out


def _stage_windows(spans):
    """{sid: (t0, t1, kind)} from the per-stage spans."""
    windows = {}
    for cat, name, t0, dur in spans:
        if cat != "stage":
            continue
        m = _STAGE_NAME.match(name or "")
        if not m:
            continue
        sid = int(m.group(1))
        kind = (name or "").split(":", 1)[-1]
        windows[sid] = (t0, t0 + dur, kind)
    return windows


def _clip(intervals, lo, hi):
    for t0, t1 in intervals:
        a, b = max(t0, lo), min(t1, hi)
        if b > a:
            yield (a, b)


def _verdict_for(window, by_resource):
    """(verdict, fractions, attributed) for one wall window."""
    lo, hi = window
    wall = hi - lo
    if wall <= 1e-9:
        return "idle", {}, 0.0
    fractions = {}
    all_intervals = []
    for resource, intervals in by_resource.items():
        clipped = list(_clip(intervals, lo, hi))
        if not clipped:
            continue
        sec = union_seconds(clipped)
        if sec > 0:
            fractions[resource] = round(min(1.0, sec / wall), 4)
            all_intervals.extend(clipped)
    attributed = round(min(1.0, union_seconds(all_intervals) / wall), 4)
    unattributed = round(max(0.0, 1.0 - attributed), 4)
    if unattributed > 0:
        fractions["host-compute"] = unattributed
    verdict = max(fractions,
                  key=lambda r: (fractions[r], -_PRIORITY.index(r)
                                 if r in _PRIORITY else 0))
    return verdict, fractions, attributed


def analyze(summary, events):
    """The ``critpath`` section: per-stage and whole-run dominant-
    bottleneck verdicts from a stats summary + its span events.

    ``events`` may be live tracer tuples or persisted trace-event dicts;
    with no usable spans the section degrades to the stats-only run
    verdict (:func:`from_summary_only`)."""
    spans = normalize_events(events or ())
    if not spans:
        return from_summary_only(summary)
    by_resource = {}
    t_lo, t_hi = None, None
    for cat, name, t0, dur in spans:
        t1 = t0 + dur
        t_lo = t0 if t_lo is None else min(t_lo, t0)
        t_hi = t1 if t_hi is None else max(t_hi, t1)
        resource = _resource_of(cat, name)
        if resource is not None:
            by_resource.setdefault(resource, []).append((t0, t1))

    stages = []
    for sid, (t0, t1, kind) in sorted(_stage_windows(spans).items()):
        verdict, fractions, attributed = _verdict_for((t0, t1), by_resource)
        stages.append({
            "stage": sid, "kind": kind,
            "seconds": round(t1 - t0, 4),
            "verdict": verdict,
            "fractions": fractions,
            "attributed_fraction": attributed,
        })

    wall = summary.get("wall_seconds") or (
        (t_hi - t_lo) if t_hi is not None else 0.0)
    run_window = (0.0, max(wall, t_hi or 0.0))
    run_verdict, run_fractions, run_attr = _verdict_for(run_window,
                                                        by_resource)
    return {
        "source": "spans",
        "stages": stages,
        "run": {
            "verdict": run_verdict,
            "fractions": run_fractions,
            "attributed_fraction": run_attr,
            "seconds": round(run_window[1] - run_window[0], 4),
        },
    }


def from_summary_only(summary):
    """Degraded analysis for an untraced run: run-level fractions
    derived from the summary's own accounting (devtime buckets, io wait
    fractions, device_fraction) — no per-stage windows, so ``stages``
    carries coarse share-of-wall entries only."""
    wall = summary.get("wall_seconds") or 0.0
    fractions = {}
    if wall > 0:
        dev = summary.get("devtime") or {}
        io = summary.get("io") or {}
        device = summary.get("device") or {}
        # codec_wait is already a wall-clock union (the live bucket);
        # device_fraction is thread-seconds over wall, so clamp.
        pairs = (
            ("overlap-stall", (dev.get("codec_wait") or 0.0) / wall),
            ("spill-queue", io.get("io_wait_write_fraction") or 0.0),
            ("io-read", max(0.0, (io.get("io_wait_fraction") or 0.0)
                            - (io.get("io_wait_write_fraction") or 0.0))),
            ("device", device.get("device_fraction") or 0.0),
        )
        for resource, frac in pairs:
            if frac > 0:
                fractions[resource] = round(min(1.0, frac), 4)
    attributed = round(min(1.0, sum(fractions.values())), 4)
    fractions["host-compute"] = round(max(0.0, 1.0 - attributed), 4)
    verdict = max(fractions, key=fractions.get) if fractions else "idle"
    stages = []
    for st in summary.get("stages") or ():
        stages.append({
            "stage": st.get("stage"), "kind": st.get("kind"),
            "seconds": st.get("seconds"),
            "verdict": ("device" if st.get("target") == "device"
                        else "host-compute"),
            "fractions": {},
            "attributed_fraction": 0.0,
        })
    return {
        "source": "summary",
        "stages": stages,
        "run": {"verdict": verdict, "fractions": fractions,
                "attributed_fraction": attributed,
                "seconds": round(wall, 4)},
    }


def apply_skew(section, fleet, wall):
    """Inject the fleet's ``skew`` resource into a run-level critpath
    section (in place) once the merged cross-rank timeline exists.

    Skew is invisible to a single rank's span union — a rank blocked in
    a collective waiting for a straggler shows up as ``mesh`` time.  The
    fleet merge (:func:`dampr_tpu.obs.fleet.step_skew`) measures the
    collective-entry spread directly, so here it becomes its own
    resource fraction (sum of per-step spreads over run wall, clamped)
    and may take the run verdict when it dominates.  Stage verdicts are
    untouched: skew is a fleet-level phenomenon."""
    skew = (fleet or {}).get("skew") or {}
    sec = (skew.get("skew_seconds") or 0.0)
    run = (section or {}).get("run")
    if not run or sec <= 0 or wall <= 0:
        return section
    fractions = run.setdefault("fractions", {})
    fractions["skew"] = round(min(1.0, sec / wall), 4)
    verdict = max(fractions,
                  key=lambda r: (fractions[r], -_PRIORITY.index(r)
                                 if r in _PRIORITY else 0))
    run["verdict"] = verdict
    run["skew_seconds"] = round(sec, 4)
    return section


def from_run(run):
    """Resolve a run name / run dir / stats path to its critpath
    section, recomputing from the persisted trace.json when the summary
    predates the analyzer.  Returns (section, summary) — (None, None)
    when no stats exist."""
    from . import export

    summary, path = export.load_stats(run)
    if summary is None:
        return None, None
    section = summary.get("critpath")
    if section:
        return section, summary
    events = ()
    tf = summary.get("trace_file")
    if not tf or not os.path.isfile(tf):
        cand = os.path.join(os.path.dirname(path), "trace.json")
        tf = cand if os.path.isfile(cand) else None
    if tf:
        try:
            with open(tf) as f:
                events = json.load(f).get("traceEvents") or ()
        except (OSError, ValueError):
            events = ()
    return analyze(summary, events), summary
