"""Live fleet dashboard: ``dampr-tpu-top`` over the per-rank /metrics.

The metrics endpoints (:mod:`.serve`) already expose every rank's live
registry; this module is the consumer — a stdlib-only terminal view that
polls each rank's ``/metrics`` + ``/healthz`` and renders one row per
rank: run/stage progress, writer queue depth and in-flight bytes, store
residency and spill volume, overlap occupancy, skew mitigation, and a
derived MB/s from successive scrapes.

Liveness discipline (the whole point of a fleet view):

- every HTTP request carries a hard timeout (bounded by the refresh
  interval) — a wedged rank can never hang the dashboard;
- a rank that stops answering renders as a ``DEAD`` marker row within
  one refresh, it does not vanish (operators must SEE the hole);
- ``--once`` (optionally ``--json``) takes a single snapshot and exits —
  the CI/scripting mode, no terminal control codes.

Port resolution mirrors the server side: rank k serves on
``base_port + k`` (``--port``, default ``settings.metrics_port``), with
``--ports`` accepting an explicit comma list for fleets whose ranks
landed on fallback ports (stats()["endpoint"] records those).
"""

import json
import sys
import time

from .. import settings

#: Flattened exposition names -> row fields (see .promtext.sanitize).
_GAUGES = {
    "dampr_tpu_run_stage": "stage",
    "dampr_tpu_run_active_jobs": "active_jobs",
    "dampr_tpu_run_jobs_done": "jobs_done",
    "dampr_tpu_run_jobs_started": "jobs_started",
    "dampr_tpu_writer_queue_depth": "queue_depth",
    "dampr_tpu_writer_inflight_bytes": "inflight_bytes",
    "dampr_tpu_store_resident_bytes": "resident_bytes",
    "dampr_tpu_store_spilled_bytes": "spilled_bytes",
    "dampr_tpu_store_bytes": "store_bytes",
    "dampr_tpu_overlap_live_slots": "overlap_live",
    "dampr_tpu_overlap_stalled_slots": "overlap_stalled",
}
_COUNTERS = {
    "dampr_tpu_mitigation_engagements_total": "mitigation_engagements",
    "dampr_tpu_mitigation_speculative_wins_total": "speculative_wins",
}


def parse_exposition(text):
    """Prometheus text format -> ``{metric_name: value}`` (labels
    dropped — one scrape is one rank, so samples are unambiguous).
    Tolerant: malformed lines are skipped, never fatal."""
    out = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        # name{labels} value   |   name value
        head, _, tail = line.rpartition(" ")
        if not head:
            continue
        name = head.split("{", 1)[0].strip()
        try:
            out[name] = float(tail)
        except ValueError:
            continue
    return out


def _fetch(url, timeout):
    import urllib.request

    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read().decode("utf-8", "replace")


def scrape(port, timeout=1.0, host="127.0.0.1"):
    """One rank's snapshot: ``{port, alive, health, metrics}``.  A rank
    that refuses/timeouts/errors is ``alive=False`` — never a raise,
    never a hang past ``timeout`` per request."""
    base = "http://{}:{}".format(host, port)
    try:
        health = json.loads(_fetch(base + "/healthz", timeout))
        metrics = parse_exposition(_fetch(base + "/metrics", timeout))
    except Exception:
        return {"port": port, "alive": False, "health": None,
                "metrics": {}}
    return {"port": port, "alive": True, "health": health,
            "metrics": metrics}


def _row_from_scrape(rank, snap, prev=None, dt=None):
    """One dashboard row.  ``prev``/``dt`` (the last row + seconds since)
    derive the MB/s rate from the store-bytes counter movement."""
    row = {"rank": rank, "port": snap["port"], "alive": snap["alive"]}
    if not snap["alive"]:
        return row
    health = snap.get("health") or {}
    row["run"] = health.get("run")
    row["metrics_live"] = health.get("metrics_live")
    m = snap["metrics"]
    for name, field in _GAUGES.items():
        if name in m:
            row[field] = m[name]
    for name, field in _COUNTERS.items():
        if name in m:
            row[field] = m[name]
    if (prev is not None and dt and dt > 0
            and isinstance(prev.get("store_bytes"), float)
            and isinstance(row.get("store_bytes"), float)):
        delta = row["store_bytes"] - prev["store_bytes"]
        if delta >= 0:
            row["mbps"] = round(delta / 1e6 / dt, 2)
    return row


def resolve_ports(base_port=None, ranks=None, ports=None, timeout=1.0):
    """The port list to poll.  Explicit ``ports`` wins; otherwise rank k
    maps to ``base_port + k``, with the rank count taken from ``ranks``
    or asked of rank 0's /healthz (falling back to 1 when it's down —
    the dashboard still renders the hole)."""
    if ports:
        return list(ports)
    base = settings.metrics_port if base_port is None else base_port
    if base <= 0:
        return []
    n = ranks
    if not n:
        snap = scrape(base, timeout=timeout)
        n = ((snap.get("health") or {}).get("num_processes")
             if snap["alive"] else None) or 1
    return [base + k for k in range(int(n))]


def snapshot(ports, prev_rows=None, dt=None, timeout=1.0):
    """Scrape every port -> ordered row list (rank = list index)."""
    rows = []
    for rank, port in enumerate(ports):
        prev = None
        if prev_rows and rank < len(prev_rows):
            prev = prev_rows[rank]
        rows.append(_row_from_scrape(rank, scrape(port, timeout=timeout),
                                     prev=prev, dt=dt))
    return rows


# -- rendering --------------------------------------------------------------

_COLS = (
    ("rank", "RANK", 4), ("state", "STATE", 5), ("run", "RUN", 16),
    ("stage", "STG", 3), ("jobs", "JOBS", 9), ("queue_depth", "WQ", 4),
    ("inflight", "INFL", 7), ("resident", "RES", 7),
    ("spilled", "SPILL", 7), ("overlap", "OVLP", 5),
    ("mitigation", "MIT", 4), ("mbps", "MB/S", 8),
)


def _mb(v):
    if not isinstance(v, (int, float)):
        return "-"
    return "{:.0f}M".format(v / 1e6) if v >= 1e6 else "{:.0f}K".format(
        v / 1e3) if v >= 1e3 else "{:.0f}".format(v)


def _cell(row, key):
    if key == "rank":
        return str(row.get("rank", "?"))
    if key == "state":
        return "UP" if row.get("alive") else "DEAD"
    if not row.get("alive"):
        return "-"
    if key == "run":
        return str(row.get("run") or "-")[:16]
    if key == "stage":
        v = row.get("stage")
        return "{:.0f}".format(v) if isinstance(v, float) else "-"
    if key == "jobs":
        done, started = row.get("jobs_done"), row.get("jobs_started")
        if isinstance(done, float) and isinstance(started, float):
            return "{:.0f}/{:.0f}".format(done, started)
        return "-"
    if key == "queue_depth":
        v = row.get("queue_depth")
        return "{:.0f}".format(v) if isinstance(v, float) else "-"
    if key == "inflight":
        return _mb(row.get("inflight_bytes"))
    if key == "resident":
        return _mb(row.get("resident_bytes"))
    if key == "spilled":
        return _mb(row.get("spilled_bytes"))
    if key == "overlap":
        live, stalled = row.get("overlap_live"), row.get("overlap_stalled")
        if isinstance(live, float):
            return "{:.0f}/{:.0f}".format(
                live, stalled if isinstance(stalled, float) else 0)
        return "-"
    if key == "mitigation":
        v = row.get("mitigation_engagements")
        return "{:.0f}".format(v) if isinstance(v, float) else "-"
    if key == "mbps":
        v = row.get("mbps")
        return "{:.2f}".format(v) if isinstance(v, (int, float)) else "-"
    return "-"


def render(rows, color=False):
    """Row dicts -> fixed-width table text (one header + one line per
    rank).  ``color`` adds ANSI: green UP, bold red DEAD."""
    lines = []
    header = "  ".join("{:<{w}}".format(title, w=w)
                       for _, title, w in _COLS)
    lines.append(header)
    for row in rows:
        cells = []
        for key, _, w in _COLS:
            text = "{:<{w}}".format(_cell(row, key), w=w)
            if color and key == "state":
                text = ("\x1b[32m" + text + "\x1b[0m" if row.get("alive")
                        else "\x1b[1;31m" + text + "\x1b[0m")
            cells.append(text)
        lines.append("  ".join(cells))
    return "\n".join(lines)


# -- serve daemon jobs view -------------------------------------------------

_JOB_COLS = (
    ("job", "JOB", 6), ("tenant", "TENANT", 10), ("state", "STATE", 9),
    ("queue_wait_s", "WAIT", 7), ("wall_s", "WALL", 7),
    ("reuse_hits", "REUSE", 5), ("records", "RECS", 7),
    ("coalesced", "COAL", 4), ("error", "ERROR", 24),
)


def scrape_jobs(url, timeout=1.0):
    """One serve daemon's /jobs document, or None when it's down (same
    liveness discipline as rank scrapes: bounded, never a raise)."""
    try:
        return json.loads(_fetch(url.rstrip("/") + "/jobs", timeout))
    except Exception:
        return None


def _job_cell(row, key):
    v = row.get(key)
    if key in ("queue_wait_s", "wall_s"):
        return "{:.2f}s".format(v) if isinstance(v, (int, float)) else "-"
    if key == "error":
        return str(v)[:24] if v else "-"
    if v is None:
        return "-"
    return str(v)


def render_jobs(doc):
    """A /jobs document -> the daemon job table (tenant, state, queue
    wait, reuse hits — the serve-side rows next to the per-rank ones)."""
    if doc is None:
        return "serve daemon: DEAD (no /jobs answer)"
    lines = ["serve daemon {} — {} job(s){}".format(
        doc.get("daemon", "?"), len(doc.get("jobs") or ()),
        " — DRAINING" if doc.get("draining") else "")]
    lines.append("  ".join("{:<{w}}".format(title, w=w)
                           for _, title, w in _JOB_COLS))
    for row in doc.get("jobs") or ():
        lines.append("  ".join(
            "{:<{w}}".format(_job_cell(row, key), w=w)
            for key, _, w in _JOB_COLS))
    tenants = doc.get("tenants") or {}
    if tenants:
        parts = []
        for name, st in sorted(tenants.items()):
            parts.append("{}: {} queued, {}/{} reserved".format(
                name, st.get("queued", 0),
                _mb(st.get("reserved_bytes", 0)),
                _mb(st.get("budget_bytes", 0))))
        lines.append("tenants: " + "; ".join(parts))
    return "\n".join(lines)


def _live_loop(ports, refresh_ms, timeout, jobs_url=None):
    interval = max(0.05, refresh_ms / 1000.0)
    prev_rows, prev_t = None, None
    try:
        while True:
            t0 = time.monotonic()
            dt = (t0 - prev_t) if prev_t is not None else None
            rows = snapshot(ports, prev_rows=prev_rows, dt=dt,
                            timeout=timeout)
            alive = sum(1 for r in rows if r["alive"])
            # Home + clear-to-end each frame (no full clear: less flicker).
            sys.stdout.write("\x1b[H\x1b[J")
            sys.stdout.write(
                "dampr-tpu-top — {}/{} rank(s) up — ports {} — "
                "refresh {:.1f}s\n\n".format(
                    alive, len(rows),
                    ",".join(str(p) for p in ports), interval))
            sys.stdout.write(render(rows, color=True) + "\n")
            if jobs_url:
                sys.stdout.write("\n" + render_jobs(
                    scrape_jobs(jobs_url, timeout=timeout)) + "\n")
            sys.stdout.flush()
            prev_rows, prev_t = rows, t0
            time.sleep(max(0.0, interval - (time.monotonic() - t0)))
    except KeyboardInterrupt:
        sys.stdout.write("\n")
        return 0


def main(argv=None):
    """``dampr-tpu-top``: live terminal dashboard over a fleet's
    per-rank metrics endpoints.  Exit 0; ``--once`` exits 1 when NO
    rank answered (something to alert on in scripts)."""
    import argparse

    p = argparse.ArgumentParser(
        prog="dampr-tpu-top",
        description="live per-rank dashboard over dampr_tpu /metrics "
                    "endpoints")
    p.add_argument("--port", type=int, default=None,
                   help="base metrics port (rank k = port + k; default: "
                        "settings.metrics_port = DAMPR_TPU_METRICS_PORT)")
    p.add_argument("--ranks", type=int, default=None,
                   help="rank count (default: ask rank 0's /healthz)")
    p.add_argument("--ports", default=None,
                   help="explicit comma-separated port list (overrides "
                        "--port/--ranks; for fallback-shifted ranks)")
    p.add_argument("--refresh", type=int, default=None, metavar="MS",
                   help="refresh interval (default: settings."
                        "top_refresh_ms = DAMPR_TPU_TOP_REFRESH_MS)")
    p.add_argument("--timeout", type=float, default=None,
                   help="per-request timeout seconds (default: bounded "
                        "by the refresh interval, max 1s)")
    p.add_argument("--jobs", default=None, metavar="URL",
                   help="also poll a dampr-tpu-serve daemon (base URL, "
                        "e.g. http://127.0.0.1:9400) and render its job "
                        "table (tenant, state, queue wait, reuse hits) "
                        "below the rank rows")
    p.add_argument("--once", action="store_true",
                   help="one snapshot, no terminal control codes")
    p.add_argument("--json", action="store_true",
                   help="with --once: machine-readable rows")
    args = p.parse_args(argv)

    refresh_ms = (settings.top_refresh_ms if args.refresh is None
                  else args.refresh)
    timeout = args.timeout
    if timeout is None:
        timeout = min(1.0, max(0.1, refresh_ms / 1000.0))
    ports = None
    if args.ports:
        try:
            ports = [int(s) for s in args.ports.split(",") if s.strip()]
        except ValueError:
            p.error("--ports wants a comma-separated integer list")
    ports = resolve_ports(base_port=args.port, ranks=args.ranks,
                          ports=ports, timeout=timeout)
    if not ports and not args.jobs:
        print("no metrics ports to poll: pass --port/--ports, set "
              "DAMPR_TPU_METRICS_PORT, or pass --jobs URL",
              file=sys.stderr)
        return 1

    if args.once:
        rows = snapshot(ports, timeout=timeout) if ports else []
        jobs_doc = scrape_jobs(args.jobs, timeout=timeout) \
            if args.jobs else None
        if args.json:
            doc = {"ports": ports, "ranks": rows}
            if args.jobs:
                doc["jobs"] = jobs_doc
            print(json.dumps(doc, indent=2, sort_keys=True))
        else:
            if ports:
                print(render(rows))
            if args.jobs:
                if ports:
                    print()
                print(render_jobs(jobs_doc))
        alive = any(r["alive"] for r in rows) or jobs_doc is not None
        return 0 if alive else 1

    return _live_loop(ports, refresh_ms, timeout, jobs_url=args.jobs)


if __name__ == "__main__":
    import sys as _sys

    _sys.exit(main())
