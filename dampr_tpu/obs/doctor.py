"""``dampr-tpu-doctor``: turn a run's telemetry into a ranked diagnosis.

The obs plane records what happened (spans, counters), the critical-path
analyzer says what bound each stage (:mod:`.critpath`), the profiler
says which user op the time went to (:mod:`.profile`), and the history
corpus says how that compares to previous runs (:mod:`.history`).  This
module is the layer that reads all of it back and answers the operator's
actual question: *why was this run slow, and which knob do I turn?*

Every finding ties a bottleneck verdict to CONCRETE settings that exist
in :mod:`dampr_tpu.settings` (the suggestion table is asserted against
the module at import time in tests), ranked by estimated wall-time
impact::

    $ dampr-tpu-doctor /tmp/dampr_tpu/bench-tfidf
    run bench-tfidf: 12.41s wall · bottleneck: codec
    1. [high] stage 1 (map, 8.2s): codec-bound (0.61 of stage wall)
       -> try DAMPR_TPU_LOWER=1 (settings.lower): this scanner stage is
          device-eligible; the jitted program moves tokenize+fold off host
    ...

``--diff A B`` compares two runs (wall, per-stage seconds, verdicts,
settings snapshots from the history corpus).  ``--json`` emits the
machine-readable report (schema ``dampr-tpu-doctor/1``, checked in as
``docs/doctor_schema.json`` and validated in CI by the dependency-free
``tools/validate_doctor.py``).
"""

import json

from .. import settings

SCHEMA = "dampr-tpu-doctor/1"

#: Bottleneck taxonomy -> settings suggestions.  Every entry names a knob
#: that EXISTS in dampr_tpu.settings (pinned by tests) plus its env var
#: and a why; ``suggest`` computes a proposed value from the current one.
_PLAYBOOK = {
    "spill-queue": [
        ("spill_write_threads", "DAMPR_TPU_SPILL_WRITERS",
         lambda cur: max(4, int(cur or 0) * 2),
         "spill writes queue behind the writer pool — more writer "
         "threads drain the backlog before folds block on it"),
        ("spill_inflight_bytes", "DAMPR_TPU_SPILL_INFLIGHT",
         lambda cur: None,
         "raise the queued-spill byte cap (default budget/2) so "
         "admission stops throttling the fold side"),
    ],
    "io-read": [
        ("spill_read_prefetch", "DAMPR_TPU_SPILL_PREFETCH",
         lambda cur: max(4, int(cur or 0) * 2),
         "merge/final reads outran the frame prefetcher — deeper "
         "readahead overlaps decode with consumption"),
        ("spill_read_threads", "DAMPR_TPU_SPILL_READ_THREADS",
         lambda cur: max(4, int(cur or 0) * 2),
         "more frame-read threads decode sibling runs in parallel"),
    ],
    "overlap-stall": [
        ("overlap_windows", "DAMPR_TPU_OVERLAP_WINDOWS",
         lambda cur: max(4, int(cur or 0) * 2),
         "every live fold consumer was blocked on its codec producer — "
         "deeper overlap windows keep folds fed"),
    ],
    "pipeline-stall": [
        ("pipeline_queue_bytes", "DAMPR_TPU_PIPELINE_QUEUE",
         lambda cur: None,
         "streamed-edge publishes blocked on the folder's backpressure "
         "bound (default budget/4) — a larger queue lets map jobs run "
         "further ahead of the early-fold consumer"),
        ("pipeline", "DAMPR_TPU_PIPELINE",
         lambda cur: "0",
         "if the stalls outweigh the overlap the edge buys, the kill "
         "switch restores fully staged execution byte-identically"),
    ],
    "codec": [
        ("lower", "DAMPR_TPU_LOWER",
         lambda cur: "1",
         "host decode/tokenize bounds the stage and it is "
         "device-eligible — the jitted program moves tokenize+hash+fold "
         "off the host codec"),
        ("scan_window_bytes", "",
         lambda cur: None,
         "larger line-aligned scan windows amortize per-window codec "
         "fixed costs (at the cost of window-sized RSS)"),
    ],
    "fold": [
        ("mesh_fold", "DAMPR_TPU_MESH_FOLD",
         lambda cur: "on",
         "map-side folds bound the stage — the mesh collective fold "
         "path spreads keyed folds across devices"),
    ],
    "merge": [
        ("merge_fanin", "DAMPR_TPU_MERGE_FANIN",
         lambda cur: max(64, int(cur or 0) * 2),
         "merge generations bound the run — higher fan-in merges more "
         "runs per pass (memory budget permitting)"),
        ("spill_read_prefetch", "DAMPR_TPU_SPILL_PREFETCH",
         lambda cur: max(4, int(cur or 0) * 2),
         "deeper frame readahead keeps the k-way merge fed"),
    ],
    "spill-write": [
        ("max_memory_per_stage", "",
         lambda cur: int(cur or 0) * 2,
         "spill disk bandwidth bounds the run — a larger stage budget "
         "spills fewer bytes in the first place"),
        ("spill_codec", "DAMPR_TPU_SPILL_CODEC",
         lambda cur: "zstd" if str(cur) != "zstd" else "lz4",
         "a faster/denser frame codec moves fewer bytes through the "
         "same disk"),
    ],
    "transfer": [
        ("lower_batch", "DAMPR_TPU_LOWER_BATCH",
         lambda cur: int(cur or 0) * 2,
         "h2d/d2h movement bounds device stages — larger program "
         "batches amortize per-dispatch transfer"),
        ("hbm_budget", "DAMPR_TPU_HBM_BUDGET",
         lambda cur: None,
         "a larger HBM residency budget keeps reduce-feeding lanes on "
         "device instead of round-tripping"),
    ],
    "device": [
        ("lower_batch", "DAMPR_TPU_LOWER_BATCH",
         lambda cur: int(cur or 0) * 2,
         "device programs bound the run — larger batches amortize "
         "dispatch overhead per token"),
    ],
    # Cross-stage device handoff declined (or degraded mid-run) while
    # transfer/device work bounds the run: the knobs that fund / admit
    # the HBM-resident edge (docs/plan.md "Cross-stage device fusion").
    "handoff": [
        ("handoff", "DAMPR_TPU_HANDOFF",
         lambda cur: None,
         "the tier's own switch: auto declines when the run's config "
         "disables lowering or zeroes the HBM budget — on forces the "
         "edge resident"),
        ("hbm_budget", "DAMPR_TPU_HBM_BUDGET",
         lambda cur: None,
         "a funded HBM residency budget lets lowered producer outputs "
         "stay device-resident into the consuming fold instead of "
         "round-tripping through host spill"),
        ("lower_min_records", "DAMPR_TPU_LOWER_MIN_RECORDS",
         lambda cur: max(1, int(cur or 0) // 4),
         "a lower placement floor lets more adjacent stages lower, "
         "turning spill edges into device-handoff edges"),
    ],
    "host-compute": [
        ("max_processes", "",
         lambda cur: None,
         "uninstrumented host work (opaque UDFs / Python glue) bounds "
         "the stage — profile it (DAMPR_TPU_PROFILE=1) to see which op, "
         "and check worker-thread width"),
    ],
    "skew": [
        ("mitigate", "DAMPR_TPU_MITIGATE",
         lambda cur: "on",
         "act on the skew instead of diagnosing it: the mitigation "
         "controller steals work from backlogged queues, speculatively "
         "re-executes straggler jobs (first-result-wins, exactly-once "
         "under attempt-scoped commits), degrades collective exchanges "
         "in place while a rank is late, and down-weights a "
         "persistently pathological rank's partition share for the "
         "rest of the run"),
        ("speculate_threshold", "DAMPR_TPU_SPECULATE_THRESHOLD",
         lambda cur: None,
         "how many times slower than its peers (vs the other ranks' "
         "mean entry lateness + the 20 ms jitter floor; for host jobs, "
         "vs the median job duration) a worker must run before "
         "mitigation engages — lower it to act on milder skew, raise "
         "it if mitigation flaps on jitter"),
        ("speculate_after_steps", "DAMPR_TPU_SPECULATE_AFTER",
         lambda cur: None,
         "consecutive pathological windows before engaging (and "
         "healthy probes before disengaging) — the debounce between "
         "acting fast and acting on noise"),
        ("exchange_coding", "DAMPR_TPU_EXCHANGE_CODING",
         lambda cur: "camr",
         "coded aggregation for sum-combinable keyed folds: pre-fold "
         "each exchange window per destination partition so fewer "
         "bytes serialize on the slow rank's steps (replicated "
         "map-side fold work traded for shuffle bytes)"),
        ("spill_read_prefetch", "DAMPR_TPU_SPILL_PREFETCH",
         lambda cur: max(4, int(cur or 0) * 2),
         "the straggler rank arrives late at collective steps — deeper "
         "frame readahead on that rank overlaps its decode with the "
         "fleet's compute so it reaches the barrier with everyone else"),
        ("partitions", "",
         lambda cur: None,
         "rebalance partitions: persistent per-rank lateness with a "
         "lopsided exchange send/recv matrix means some ranks carry "
         "more bytes per step than others"),
        ("exchange_hbm_budget", "DAMPR_TPU_EXCHANGE_HBM",
         lambda cur: max(64 * 1024 ** 2, int(cur or 0) * 2),
         "fewer, larger collective steps amortize the per-step entry "
         "spread when skew is jitter rather than a persistent straggler"),
    ],
    "fault-retry": [
        ("job_retries", "DAMPR_TPU_JOB_RETRIES",
         lambda cur: max(3, int(cur or 0)),
         "jobs failed and re-executed — a deeper retry budget absorbs "
         "longer flaky-IO bursts (transient failures back off with "
         "jitter between attempts)"),
        ("retry_backoff_ms", "DAMPR_TPU_RETRY_BACKOFF_MS",
         lambda cur: max(100, int(cur or 0) * 2),
         "a retry STORM (many retries, little progress) wants a longer "
         "backoff base so attempts decorrelate from the failing "
         "resource's recovery window"),
        ("io_retries", "DAMPR_TPU_IO_RETRIES",
         lambda cur: max(4, int(cur or 0) * 2),
         "transient spill-IO failures are absorbed inside the IO layer "
         "— a deeper in-place budget keeps them from surfacing as job "
         "failures at all"),
    ],
    "quarantine": [
        ("max_quarantined", "DAMPR_TPU_MAX_QUARANTINED",
         lambda cur: None,
         "poison records were skipped into the quarantine sink — raise "
         "the budget if more are expected, or set 0 to fail fast and "
         "fix the data; the sink file lists every skipped record"),
        ("job_retries", "DAMPR_TPU_JOB_RETRIES",
         lambda cur: None,
         "deterministic record failures are NOT healed by retries — "
         "inspect quarantine.jsonl and fix the records or the UDF"),
    ],
    "exchange-timeout": [
        ("exchange_timeout_ms", "DAMPR_TPU_EXCHANGE_TIMEOUT_MS",
         lambda cur: max(60000, int(cur or 0) * 2),
         "a collective exchange step hit its deadline and the run "
         "aborted — raise the deadline if the fleet was merely slow; "
         "the shuffle stays degraded to host until faults.jsonl is "
         "cleared"),
        ("exchange_hbm_budget", "DAMPR_TPU_EXCHANGE_HBM",
         lambda cur: max(64 * 1024 ** 2, int(cur or 0) * 2),
         "fewer, larger collective steps shrink the window in which a "
         "rank death can strand a step (and amortize per-step cost)"),
        ("mesh_exchange", "DAMPR_TPU_MESH_EXCHANGE",
         lambda cur: "off",
         "or pin every redistribution to the host shuffle while the "
         "fleet is unstable"),
    ],
    "mesh": [
        ("exchange_hbm_budget", "DAMPR_TPU_EXCHANGE_HBM",
         lambda cur: max(64 * 1024 ** 2, int(cur or 0) * 2),
         "collective exchange steps bound the run — a larger in-flight "
         "budget lets the replan schedule move the same bytes in fewer, "
         "bigger chunked collectives (device memory permitting)"),
        ("exchange_chunk_bytes", "DAMPR_TPU_EXCHANGE_CHUNK",
         lambda cur: None,
         "or pin the per-piece chunk size explicitly when the device is "
         "memory-pressured beyond what the in-flight model captures "
         "(smaller chunks = more steps, lower peak)"),
        ("exchange_min_bytes", "DAMPR_TPU_EXCHANGE_MIN_BYTES",
         lambda cur: max(4 * 1024 ** 2, int(cur or 0) * 2),
         "tiny shuffles pay D*D pack/unpack fixed costs — a higher "
         "floor keeps them on the host path (auto mode; explicit "
         "DAMPR_TPU_MESH_EXCHANGE=off pins every stage host)"),
        ("shuffle_capacity_factor", "",
         lambda cur: None,
         "for the associative collective fold, tune exchange capacity "
         "or keep the shuffle on host (DAMPR_TPU_MESH_EXCHANGE=off)"),
    ],
    "reuse-thrash": [
        ("reuse_budget_bytes", "DAMPR_TPU_REUSE_BUDGET",
         lambda cur: max(2 * 1024 ** 3, int(cur or 0) * 2),
         "the shared materialization cache evicted entries as fast as "
         "it published them — a larger byte budget lets warm prefixes "
         "survive to the next run instead of churning"),
        ("reuse_dir", "DAMPR_TPU_REUSE_DIR",
         lambda cur: None,
         "or point the cache at a volume with room: eviction pressure "
         "often means the scratch filesystem is shared with spill "
         "traffic"),
    ],
    "reuse-off": [
        ("reuse", "DAMPR_TPU_REUSE",
         lambda cur: "on",
         "the run corpus shows this exact plan shape executed before — "
         "with the cross-run cache enabled, unchanged stage prefixes "
         "mount from disk instead of recomputing"),
    ],
}

#: Verdicts that never produce a finding on their own.
_BENIGN = ("idle", "checkpoint")


class DoctorError(Exception):
    pass


def _severity(impact_seconds, wall):
    if wall <= 0:
        return "low"
    frac = impact_seconds / wall
    if frac >= 0.25:
        return "high"
    if frac >= 0.10:
        return "medium"
    return "low"


def _run_settings(summary, hist_records):
    """The DIAGNOSED RUN's settings values, not the doctor process's:
    the history corpus snapshots the performance knobs per run, and the
    summary itself records the authoritative ones (io.writer_threads,
    overlap.windows, the sampler cadence).  A doctor invoked in a
    different environment must not compute 'current -> suggested' from
    its own defaults."""
    out = dict((hist_records[-1].get("settings") or {})
               if hist_records else {})
    io = summary.get("io") or {}
    if io.get("writer_threads") is not None:
        out["spill_write_threads"] = io["writer_threads"]
    if io.get("read_prefetch") is not None:
        out["spill_read_prefetch"] = io["read_prefetch"]
    ov = summary.get("overlap") or {}
    if ov.get("windows") is not None:
        out["overlap_windows"] = ov["windows"]
    sm = (summary.get("metrics") or {}).get("sampler") or {}
    if sm.get("interval_ms"):
        out["metrics_interval_ms"] = sm["interval_ms"]
    dev = summary.get("device") or {}
    if dev.get("lowered") is not None:
        out.setdefault("lower", "1" if dev["lowered"] else str(
            settings.lower))
    return out


def _suggestions_for(verdict, summary, stage_entry=None,
                     run_settings=None):
    out = []
    run_settings = run_settings or {}
    for knob, env, propose, why in _PLAYBOOK.get(verdict, ()):
        if not hasattr(settings, knob):
            continue  # playbook drift: never suggest a knob that's gone
        if verdict == "codec" and knob == "lower":
            # Only suggest lowering when an eligible host stage exists:
            # the plan report records per-stage decisions with reasons.
            if not _has_lowerable_host_stage(summary, stage_entry):
                continue
        cur = (run_settings[knob] if knob in run_settings
               else getattr(settings, knob))
        try:
            proposed = propose(cur)
        except (TypeError, ValueError):
            proposed = None
        sug = {
            "setting": knob,
            "current": cur if _jsonable(cur) else str(cur),
            "suggested": proposed if _jsonable(proposed) else str(proposed),
            "why": why,
        }
        if env:  # omitted (not null) when the knob has no env var —
            sug["env"] = env  # the schema types env as a string
        out.append(sug)
    return out


def _jsonable(v):
    return v is None or isinstance(v, (bool, int, float, str))


def _has_lowerable_host_stage(summary, stage_entry=None):
    """Is there a host-executed map stage the lowering pass would (or
    could) place on device?  True when lowering was off entirely, or a
    host decision's reason shows eligibility was only blocked by
    history/settings."""
    lowering = ((summary.get("plan") or {}).get("lowering")) or {}
    if not lowering.get("enabled"):
        # Lowering never ran (off / auto-off on CPU): a codec-bound
        # scanner stage MAY be eligible — worth the suggestion.
        return True
    sids = None
    if stage_entry is not None and stage_entry.get("stage") is not None:
        sids = {stage_entry["stage"]}
    for d in lowering.get("targets") or ():
        if sids is not None and d.get("sid") not in sids:
            continue
        if d.get("target") == "host" and "history:" in (d.get("reason")
                                                        or ""):
            return True
    return False


def _stage_kind(summary, sid):
    for st in summary.get("stages") or ():
        if st.get("stage") == sid:
            return st
    return {}


def diagnose(run):
    """Build the full report dict for one run (name / run dir / stats
    path).  Raises DoctorError when no stats exist."""
    from . import critpath, flightrec, history

    section, summary = critpath.from_run(run)
    if summary is None:
        raise DoctorError(
            "no stats.json found for {!r}: doctor reads a finalized "
            "run's artifacts (traced runs persist them — "
            "DAMPR_TPU_TRACE=1)".format(run))
    wall = summary.get("wall_seconds") or 0.0
    # Rank-tagged corpus records (non-zero ranks of a fleet run) carry
    # rank-local settings/timings — the diagnosis baseline is the
    # run-level (rank-0) trail.
    hist = [r for r in history.load(summary.get("run"))
            if not r.get("rank")]
    run_settings = _run_settings(summary, hist)
    findings = []

    # -- per-stage verdicts --------------------------------------------------
    stage_entries = []
    for s in (section or {}).get("stages") or ():
        sid = s.get("stage")
        st = _stage_kind(summary, sid)
        entry = {
            "stage": sid,
            "kind": s.get("kind") or st.get("kind"),
            "target": st.get("target", "host"),
            "seconds": s.get("seconds"),
            "verdict": s.get("verdict"),
            "fractions": s.get("fractions") or {},
        }
        stage_entries.append(entry)
        verdict = s.get("verdict")
        if verdict in _BENIGN or verdict is None:
            continue
        frac = (s.get("fractions") or {}).get(verdict, 0.0)
        sec = (s.get("seconds") or 0.0) * frac
        if sec <= 0:
            continue
        sugg = _suggestions_for(verdict, summary, entry, run_settings)
        findings.append({
            "stage": sid,
            "bottleneck": verdict,
            "impact_seconds": round(sec, 4),
            "severity": _severity(sec, wall),
            "evidence": "stage {} ({}, {:.2f}s): {} covers {:.0%} of "
                        "stage wall".format(
                            sid, entry["kind"], s.get("seconds") or 0.0,
                            verdict, frac),
            "suggestions": sugg,
        })

    # -- run-level signals the per-stage windows can miss --------------------
    # Only where no per-stage finding already names the same verdict: a
    # stage-level spill-queue finding and its run-level mirror are ONE
    # root cause — double-reporting would rank the same seconds twice
    # and demote genuinely distinct second-place bottlenecks.
    staged_verdicts = {f["bottleneck"] for f in findings}
    io = summary.get("io") or {}
    if ("spill-queue" not in staged_verdicts
            and (io.get("io_wait_write_fraction") or 0.0) > 0.05):
        # io_wait_write_seconds is THREAD-seconds (concurrently blocked
        # folds each add their own wait); impact must be on the same
        # wall-clock axis the stage findings rank on, so clamp the
        # fraction at 1 and charge wall time.
        frac = min(1.0, io["io_wait_write_fraction"])
        sec = frac * wall
        findings.append({
            "stage": None,
            "bottleneck": "spill-queue",
            "impact_seconds": round(sec, 4),
            "severity": _severity(sec, wall),
            "evidence": "folds spent {:.2f} thread-seconds blocked on "
                        "writer-pool backpressure ({:.0%} of wall, "
                        "clamped)".format(
                            io.get("io_wait_write_seconds") or sec, frac),
            "suggestions": _suggestions_for("spill-queue", summary,
                                            run_settings=run_settings),
        })
    ov = summary.get("overlap") or {}
    if ("overlap-stall" not in staged_verdicts
            and (ov.get("stall_fraction") or 0.0) > 0.15):
        sec = min(1.0, ov["stall_fraction"]) * wall
        findings.append({
            "stage": None,
            "bottleneck": "overlap-stall",
            "impact_seconds": round(sec, 4),
            "severity": _severity(sec, wall),
            "evidence": "codec_wait union covered {:.0%} of wall — every "
                        "live fold consumer was starved by its codec "
                        "producer".format(ov["stall_fraction"]),
            "suggestions": _suggestions_for("overlap-stall", summary,
                                            run_settings=run_settings),
        })
    met = (summary.get("metrics") or {}).get("sampler") or {}
    if (met.get("overhead") or 0.0) > 0.03:
        sec = min(1.0, met["overhead"]) * wall
        interval = (run_settings.get("metrics_interval_ms")
                    or met.get("interval_ms") or 100)
        findings.append({
            "stage": None,
            "bottleneck": "host-compute",
            "impact_seconds": round(sec, 4),
            "severity": "low",
            "evidence": "metrics sampler overhead {:.2%} exceeds the 3% "
                        "budget".format(met["overhead"]),
            "suggestions": [{
                "setting": "metrics_interval_ms",
                "env": "DAMPR_TPU_METRICS_MS",
                "current": interval,
                "suggested": max(200, interval * 4),
                "why": "a longer sampling cadence bounds sampler cost",
            }],
        })

    # -- declined device handoff while transfer/device bounds the run --------
    # The plan saw a device->device edge but spilled it, or the runtime
    # degraded the edge mid-stage; if transfer or device work then
    # dominated, the handoff/HBM-budget knobs are the lever (ROADMAP 5b,
    # docs/plan.md "Cross-stage device fusion").  Only ACTIONABLE
    # declines count, by the edge's typed `kind`: "settings" (the
    # handoff/budget knobs directly) and "no-device-consumer" (a lower
    # placement floor can lower the consumer).  "object-lane" has no
    # device tier to buy, and a "priced" decline is the cost model
    # already choosing the faster path — suggesting knobs against its
    # evidence would be noise.
    verdicts = {f["bottleneck"] for f in findings}
    verdicts.add(((section or {}).get("run") or {}).get("verdict"))
    for s in (section or {}).get("stages") or ():
        verdicts.add(s.get("verdict"))
    dev = summary.get("device") or {}
    declined = [
        e for e in (((summary.get("plan") or {}).get("lowering") or {})
                    .get("handoff") or ())
        if e.get("handoff") == "spill"
        and e.get("kind") in ("settings", "no-device-consumer")]
    degrades = dev.get("handoff_degrades") or 0
    if (verdicts & {"transfer", "device"}) and (declined or degrades):
        rf = ((section or {}).get("run") or {}).get("fractions") or {}
        frac = min(1.0, (rf.get("transfer") or 0.0)
                   + (rf.get("device") or 0.0))
        sec = (frac or 0.05) * wall
        if declined:
            ev = ("transfer/device work bounds the run and {} device "
                  "handoff edge(s) were declined ({})".format(
                      len(declined), declined[0].get("reason")))
        else:
            ev = ("transfer/device work bounds the run and the device "
                  "handoff degraded to the spill path {} time(s) "
                  "mid-run".format(degrades))
        findings.append({
            "stage": None,
            "bottleneck": "handoff",
            "impact_seconds": round(sec, 4),
            "severity": _severity(sec, wall),
            "evidence": ev,
            "suggestions": _suggestions_for("handoff", summary,
                                            run_settings=run_settings),
        })

    # -- fleet verdicts (multi-process runs with a merged timeline) ----------
    fleet = summary.get("fleet") or {}
    fleet_report = None
    if (fleet.get("num_processes") or 1) > 1:
        skew = fleet.get("skew") or {}
        straggler = skew.get("straggler_rank")
        fleet_report = {
            "num_processes": fleet.get("num_processes"),
            "ranks": fleet.get("ranks"),
            "missing_ranks": fleet.get("missing_ranks") or [],
            "alignment": fleet.get("alignment"),
            "straggler_rank": straggler,
            "late_ratio": skew.get("late_ratio"),
            "mean_step_skew_fraction": skew.get("mean_fraction"),
            "max_step_skew_fraction": skew.get("max_fraction"),
            "skew_seconds": skew.get("skew_seconds"),
            "per_rank": [
                {k: v for k, v in e.items() if v is not None}
                for e in fleet.get("per_rank") or ()],
        }
        # Schema discipline: typed optional keys are omitted, not null.
        fleet_report = {k: v for k, v in fleet_report.items()
                        if v is not None}
        mitigation = fleet.get("mitigation") or summary.get("mitigation")
        if mitigation:
            fleet_report["mitigation"] = mitigation
        sec = skew.get("skew_seconds") or 0.0
        # A skew finding is worth ranking when the fleet measurably
        # waited: spreads covering >=5% of wall, or any step where the
        # entry spread dominated the step (a hard straggler signature).
        if (wall > 0 and straggler is not None
                and (sec / wall > 0.05
                     or (skew.get("max_fraction") or 0.0) >= 0.5)):
            straggler_verdict = None
            for e in fleet.get("per_rank") or ():
                if e.get("rank") == straggler:
                    straggler_verdict = e.get("verdict")
            evidence = ("rank {} enters collective steps {:.1f}x later "
                        "than the fleet average (entry spread covered "
                        "{:.0%} of step wall over {} step(s); the fleet "
                        "waited {:.2f}s on it)".format(
                            straggler, skew.get("late_ratio") or 1.0,
                            skew.get("mean_fraction") or 0.0,
                            len(skew.get("steps") or ()), sec))
            if straggler_verdict and straggler_verdict not in (
                    "idle", "host-compute"):
                evidence += ("; that rank's own bottleneck is {} — fix "
                             "it there first".format(straggler_verdict))
            mit = fleet.get("mitigation") or summary.get("mitigation")
            if mit and mit.get("engagements"):
                evidence += (
                    "; mitigation ACTED on it ({} engagement(s), {} "
                    "collective window(s) degraded in place, {} "
                    "speculative win(s), {} stolen partition(s){})"
                    .format(
                        mit.get("engagements"),
                        mit.get("windows_skipped") or 0,
                        mit.get("speculative_wins") or 0,
                        mit.get("stolen_partitions") or 0,
                        ", down-weighted rank(s) {}".format(
                            sorted(mit["downweighted_ranks"],
                                   key=lambda r: int(r)))
                        if mit.get("downweighted_ranks") else ""))
            elif mit:
                evidence += ("; mitigation was armed but never engaged "
                             "(late_ratio stayed under "
                             "speculate_threshold for "
                             "speculate_after_steps windows)")
            findings.append({
                "stage": None,
                "bottleneck": "skew",
                "impact_seconds": round(min(sec, wall), 4),
                "severity": _severity(min(sec, wall), wall),
                "evidence": evidence,
                "suggestions": _suggestions_for(
                    "skew", summary, run_settings=run_settings),
            })
        for missing in fleet_report["missing_ranks"]:
            findings.append({
                "stage": None,
                "bottleneck": "skew",
                "impact_seconds": 0.0,
                "severity": "high",
                "evidence": "rank {} left no artifacts — it was killed "
                            "or never finished (check its crashdump: "
                            "crashdump.rank{}.json)".format(
                                missing, missing),
                "suggestions": [],
            })

    # -- failure-recovery signals (dampr_tpu.faults) -------------------------
    from .. import faults as _faults_mod

    fa = summary.get("faults") or {}
    events = _faults_mod.load_events(summary.get("run"))
    timeouts = [ev for ev in events if ev.get("kind") == "exchange_timeout"]
    retries = fa.get("retries") or 0
    quarantined = fa.get("quarantined") or 0
    backoff_s = fa.get("backoff_seconds") or 0.0
    if retries:
        sec = min(backoff_s, wall) if wall > 0 else backoff_s
        io_r = fa.get("io_retries") or {}
        findings.append({
            "stage": None,
            "bottleneck": "fault-retry",
            "impact_seconds": round(sec, 4),
            "severity": _severity(sec, wall) if sec else "low",
            "evidence": "{} classified retries absorbed ({} job "
                        "re-execution(s), {} in-place IO retr{}), "
                        "{:.2f}s spent backing off".format(
                            retries, fa.get("job_retries") or 0,
                            sum(io_r.values()),
                            "y" if sum(io_r.values()) == 1 else "ies",
                            backoff_s),
            "suggestions": _suggestions_for("fault-retry", summary,
                                            run_settings=run_settings),
        })
    if quarantined:
        findings.append({
            "stage": None,
            "bottleneck": "quarantine",
            "impact_seconds": 0.0,
            "severity": "medium",
            "evidence": "{} poison record(s) quarantined (budget "
                        "max_quarantined={}) — the stage completed "
                        "without them; inspect {}".format(
                            quarantined, fa.get("max_quarantined"),
                            fa.get("quarantine_file")
                            or "the quarantine sink"),
            "suggestions": _suggestions_for("quarantine", summary,
                                            run_settings=run_settings),
        })
    if timeouts:
        stages_to = sorted({ev.get("stage") for ev in timeouts
                            if ev.get("stage") is not None})
        findings.append({
            "stage": stages_to[0] if len(stages_to) == 1 else None,
            "bottleneck": "exchange-timeout",
            "impact_seconds": 0.0,
            "severity": "high",
            "evidence": "{} recorded collective exchange timeout(s)"
                        "{} — surviving ranks aborted with crashdumps; "
                        "affected stages stay degraded to the host "
                        "shuffle until faults.jsonl is cleared".format(
                            len(timeouts),
                            " at stage(s) {}".format(stages_to)
                            if stages_to else ""),
            "suggestions": _suggestions_for("exchange-timeout", summary,
                                            run_settings=run_settings),
        })

    # -- cross-run materialization cache signals (plan/reuse.py) -------------
    reuse = summary.get("reuse") or {}
    if reuse.get("enabled"):
        hits = reuse.get("hits") or 0
        evictions = reuse.get("evictions") or 0
        # Thrash: the run published into the cache but eviction churned
        # at least as much as lookups hit — the budget is too small for
        # the working set, so the NEXT run's prefixes won't be there.
        if evictions and evictions >= max(1, hits):
            findings.append({
                "stage": None,
                "bottleneck": "reuse-thrash",
                "impact_seconds": 0.0,
                "severity": "medium",
                "evidence": "reuse cache evicted {} entr{} against {} "
                            "hit(s) this run ({:.1f} MB published) — "
                            "the byte budget is churning the working "
                            "set".format(
                                evictions,
                                "y" if evictions == 1 else "ies", hits,
                                (reuse.get("bytes_published") or 0) / 1e6),
                "suggestions": _suggestions_for("reuse-thrash", summary,
                                                run_settings=run_settings),
            })
    elif hist:
        # Missed reuse: the corpus has PRIOR records of this exact plan
        # fingerprint, but the cache was off — an identical re-run would
        # have mounted its unchanged prefix instead of recomputing.
        fp = history.plan_fingerprint(
            (summary.get("plan") or {}).get("stage_shapes"))
        prior = [r for r in hist[:-1] if r.get("fingerprint") == fp]
        if fp and prior:
            findings.append({
                "stage": None,
                "bottleneck": "reuse-off",
                "impact_seconds": 0.0,
                "severity": "low",
                "evidence": "this plan shape has {} prior corpus "
                            "record(s) with an identical fingerprint "
                            "but the cross-run cache was disabled — "
                            "repeated runs recompute unchanged "
                            "prefixes".format(len(prior)),
                "suggestions": _suggestions_for("reuse-off", summary,
                                                run_settings=run_settings),
            })

    # -- long-horizon regressions (obs/sentry over the telemetry store) ------
    sentry_section = None
    if settings.sentry_window > 0:
        from . import sentry as _sentry

        sfindings = summary.get("sentry")
        if sfindings is None:
            sfindings = _sentry.check_run(summary.get("run"),
                                          summary=summary)
        if sfindings:
            sentry_section = {"findings": sfindings,
                              "window": _sentry.effective_window(),
                              "threshold": _sentry.effective_threshold()}
        for sf in sfindings or ():
            sugs = []
            if sf.get("setting"):
                cur = (run_settings[sf["setting"]]
                       if sf["setting"] in run_settings
                       else getattr(settings, sf["setting"], None))
                sug = {"setting": sf["setting"],
                       "current": cur if _jsonable(cur) else str(cur),
                       "suggested": None,
                       "why": sf.get("why") or ""}
                if sf.get("env"):
                    sug["env"] = sf["env"]
                sugs.append(sug)
            findings.append({
                "stage": None,
                "bottleneck": "regression",
                "impact_seconds": 0.0,
                "severity": ("high" if abs(sf.get("z") or 0)
                             >= 2 * (sf.get("threshold") or 1)
                             else "medium"),
                "evidence": "{} regressed against its {}-run baseline: "
                            "{:g} vs median {:g} ({:+.1f} robust sigma, "
                            "plan {})".format(
                                sf.get("metric"), sf.get("window"),
                                sf.get("value"), sf.get("median"),
                                sf.get("z") or 0.0,
                                sf.get("fingerprint")),
                "suggestions": sugs,
            })

    findings.sort(key=lambda f: -(f.get("impact_seconds") or 0.0))
    for rank, f in enumerate(findings, 1):
        f["rank"] = rank

    fault_section = None
    if fa or events:
        fault_section = {
            "enabled": bool(fa.get("enabled")),
            "retries": retries,
            "job_retries": fa.get("job_retries") or 0,
            "io_retries": fa.get("io_retries") or {},
            "backoff_seconds": backoff_s,
            "quarantined": quarantined,
            "exchange_timeouts": len(timeouts),
        }
        if fa.get("max_quarantined") is not None:
            fault_section["max_quarantined"] = fa["max_quarantined"]
        if fa.get("quarantine_file"):
            fault_section["quarantine_file"] = fa["quarantine_file"]
        if fa.get("plan"):
            fault_section["plan"] = fa["plan"]
            fault_section["injected"] = fa.get("injected") or {}
        if events:
            fault_section["events"] = events[-10:]

    report = {
        "schema": SCHEMA,
        "run": summary.get("run"),
        "wall_seconds": wall,
        "bottleneck": ((section or {}).get("run") or {}).get("verdict"),
        "critpath_source": (section or {}).get("source"),
        "stages": stage_entries,
        "findings": findings,
        "history_entries": len(hist),
        "crashed": flightrec.locate_crashdump(run) is not None,
    }
    if fleet_report is not None:
        report["fleet"] = fleet_report
    if sentry_section is not None:
        report["sentry"] = sentry_section
    if fault_section is not None:
        report["faults"] = fault_section
    if summary.get("mitigation"):
        report["mitigation"] = summary["mitigation"]
    if summary.get("reuse"):
        report["reuse"] = summary["reuse"]
    return report


def _by_sid(summary):
    return {st.get("stage"): st for st in summary.get("stages") or ()}


def diff(run_a, run_b):
    """Comparison report for two runs: wall and per-stage deltas,
    verdict changes, and settings-snapshot differences (from each run's
    newest history-corpus record when available)."""
    from . import critpath, history

    sec_a, sum_a = critpath.from_run(run_a)
    sec_b, sum_b = critpath.from_run(run_b)
    if sum_a is None or sum_b is None:
        missing = run_a if sum_a is None else run_b
        raise DoctorError("no stats.json found for {!r}".format(missing))
    wall_a = sum_a.get("wall_seconds") or 0.0
    wall_b = sum_b.get("wall_seconds") or 0.0
    verd_a = {s.get("stage"): s.get("verdict")
              for s in (sec_a or {}).get("stages") or ()}
    verd_b = {s.get("stage"): s.get("verdict")
              for s in (sec_b or {}).get("stages") or ()}
    stages = []
    a_stages, b_stages = _by_sid(sum_a), _by_sid(sum_b)
    for sid in sorted(set(a_stages) | set(b_stages)):
        sa, sb = a_stages.get(sid) or {}, b_stages.get(sid) or {}
        stages.append({
            "stage": sid,
            "kind": sb.get("kind") or sa.get("kind"),
            "seconds_a": sa.get("seconds"),
            "seconds_b": sb.get("seconds"),
            "delta_seconds": (round(sb["seconds"] - sa["seconds"], 4)
                              if isinstance(sa.get("seconds"), (int, float))
                              and isinstance(sb.get("seconds"),
                                             (int, float)) else None),
            "verdict_a": verd_a.get(sid),
            "verdict_b": verd_b.get(sid),
        })

    def newest_settings(run_name):
        recs = history.load(run_name)
        return (recs[-1].get("settings") or {}) if recs else {}

    set_a = newest_settings(sum_a.get("run"))
    set_b = newest_settings(sum_b.get("run"))
    settings_delta = {
        k: {"a": set_a.get(k), "b": set_b.get(k)}
        for k in sorted(set(set_a) | set(set_b))
        if set_a.get(k) != set_b.get(k)
    }

    def mit_counts(s):
        m = s.get("mitigation") or {}
        if not m:
            return None
        out = {k: m.get(k) or 0 for k in (
            "engagements", "windows_skipped", "speculative_wins",
            "stolen_partitions")}
        out["downweighted_ranks"] = m.get("downweighted_ranks") or {}
        return out

    mit_a, mit_b = mit_counts(sum_a), mit_counts(sum_b)
    report = {
        "schema": SCHEMA,
        "run": "{} vs {}".format(sum_a.get("run"), sum_b.get("run")),
        "wall_seconds": wall_b,
        "bottleneck": ((sec_b or {}).get("run") or {}).get("verdict"),
        "critpath_source": (sec_b or {}).get("source"),
        "stages": [],
        "findings": [],
        "history_entries": 0,
        "crashed": False,
        "diff": {
            "run_a": sum_a.get("run"), "run_b": sum_b.get("run"),
            "wall_a": wall_a, "wall_b": wall_b,
            "wall_delta": round(wall_b - wall_a, 4),
            "wall_ratio": (round(wall_b / wall_a, 4) if wall_a > 0
                           else None),
            "stages": stages,
            "settings_delta": settings_delta,
        },
    }
    if mit_a or mit_b:
        # Mitigation deltas: what each run DID about its skew — next to
        # the knob deltas that changed the behavior.
        report["diff"]["mitigation"] = {"a": mit_a, "b": mit_b}
    return report


def format_report(report, show_faults=False):
    """Human-readable rendering.  ``show_faults`` (the ``--faults``
    flag) adds the failure-recovery section: classified retry counts,
    quarantine state, injection plan, and recorded exchange timeouts."""
    lines = []
    add = lines.append
    d = report.get("diff")
    if d:
        add("doctor diff: {} -> {}".format(d["run_a"], d["run_b"]))
        ratio = d.get("wall_ratio")
        add("wall: {:.2f}s -> {:.2f}s ({})".format(
            d["wall_a"], d["wall_b"],
            "{:+.1%}".format(ratio - 1) if ratio else "n/a"))
        for st in d["stages"]:
            line = "  stage {:>2} ({:<10}) {:>8} -> {:>8}".format(
                st["stage"], st.get("kind") or "?",
                "{:.2f}s".format(st["seconds_a"])
                if st.get("seconds_a") is not None else "-",
                "{:.2f}s".format(st["seconds_b"])
                if st.get("seconds_b") is not None else "-")
            if st.get("verdict_a") or st.get("verdict_b"):
                line += "   {} -> {}".format(st.get("verdict_a") or "?",
                                             st.get("verdict_b") or "?")
            add(line)
        if d["settings_delta"]:
            add("settings changed:")
            for k, v in sorted(d["settings_delta"].items()):
                add("  {}: {!r} -> {!r}".format(k, v["a"], v["b"]))
        else:
            add("settings: no recorded differences")
        md = d.get("mitigation")
        if md:
            def _fmt_mit(m):
                if not m:
                    return "off"
                return ("{} engagement(s), {} window(s) degraded, {} "
                        "speculative win(s), {} stolen".format(
                            m.get("engagements") or 0,
                            m.get("windows_skipped") or 0,
                            m.get("speculative_wins") or 0,
                            m.get("stolen_partitions") or 0))
            add("mitigation: {} -> {}".format(_fmt_mit(md.get("a")),
                                              _fmt_mit(md.get("b"))))
        return "\n".join(lines)

    add("run {}: {:.2f}s wall · bottleneck: {}".format(
        report.get("run"), report.get("wall_seconds") or 0.0,
        report.get("bottleneck") or "?"))
    if report.get("crashed"):
        add("NOTE: this run left a crashdump (it did not finish cleanly)")
    if report.get("critpath_source") == "summary":
        add("note: no span timeline — verdicts are stats-derived "
            "(trace the run for per-stage windows: DAMPR_TPU_TRACE=1)")
    for st in report.get("stages") or ():
        fr = st.get("fractions") or {}
        top = ", ".join("{} {:.0%}".format(k, v) for k, v in sorted(
            fr.items(), key=lambda kv: -kv[1])[:3])
        add("  stage {:>2} ({:<10} {:>6}) {:>8}  {}  [{}]".format(
            st.get("stage"), st.get("kind") or "?",
            st.get("target") or "host",
            "{:.2f}s".format(st["seconds"])
            if st.get("seconds") is not None else "-",
            st.get("verdict") or "?", top))
    fl = report.get("fleet")
    if fl:
        line = "fleet: {} process(es)".format(fl.get("num_processes"))
        if fl.get("missing_ranks"):
            line += " · MISSING ranks {}".format(fl["missing_ranks"])
        if fl.get("straggler_rank") is not None:
            line += (" · straggler: rank {} ({:.1f}x late, mean step "
                     "skew {:.0%})".format(
                         fl["straggler_rank"], fl.get("late_ratio") or 1.0,
                         fl.get("mean_step_skew_fraction") or 0.0))
        add(line)
        for e in fl.get("per_rank") or ():
            add("  rank {:>2}: {:>8} wall · {} spill · verdict {}".format(
                e.get("rank"),
                "{:.2f}s".format(e["wall_seconds"])
                if e.get("wall_seconds") is not None else "-",
                "{:.1f}MB".format((e.get("spill_bytes") or 0) / 1e6),
                e.get("verdict") or "?"))
        mit = fl.get("mitigation")
        if mit:
            add("  mitigation: {} · {} engagement(s) · {} window(s) "
                "degraded in place · {} speculative win(s) · {} stolen "
                "partition(s){}".format(
                    "ENGAGED" if mit.get("engaged") else "disengaged",
                    mit.get("engagements") or 0,
                    mit.get("windows_skipped") or 0,
                    mit.get("speculative_wins") or 0,
                    mit.get("stolen_partitions") or 0,
                    " · down-weighted: {}".format({
                        r: mit["downweighted_ranks"][r]
                        for r in sorted(mit["downweighted_ranks"],
                                        key=lambda r: int(r))})
                    if mit.get("downweighted_ranks") else ""))
    if show_faults:
        fa = report.get("faults")
        if not fa:
            add("faults: nothing recorded (run predates the fault "
                "section, or stats.json is missing it)")
        else:
            io_r = fa.get("io_retries") or {}
            add("faults: {} retr{} ({} job re-execution(s), {} IO) · "
                "backoff {:.2f}s · quarantined {}{}".format(
                    fa.get("retries") or 0,
                    "y" if (fa.get("retries") or 0) == 1 else "ies",
                    fa.get("job_retries") or 0, sum(io_r.values()),
                    fa.get("backoff_seconds") or 0.0,
                    fa.get("quarantined") or 0,
                    "/{}".format(fa["max_quarantined"])
                    if fa.get("max_quarantined") is not None else ""))
            if fa.get("plan"):
                add("  injection plan: {!r} · injected: {}".format(
                    fa["plan"], fa.get("injected") or {}))
            if fa.get("quarantine_file"):
                add("  quarantine sink: {}".format(fa["quarantine_file"]))
            if fa.get("exchange_timeouts"):
                add("  exchange timeouts recorded: {} (stages degraded "
                    "to the host shuffle until faults.jsonl is "
                    "cleared)".format(fa["exchange_timeouts"]))
            for ev in fa.get("events") or ():
                add("  event: {}".format(json.dumps(ev, sort_keys=True)))
    if not report.get("findings"):
        add("no findings: nothing instrumented dominates — this run "
            "looks healthy at the recorded granularity")
    for f in report.get("findings") or ():
        add("{}. [{}] {}".format(f["rank"], f["severity"], f["evidence"]))
        for s in f.get("suggestions") or ():
            env = " ({})".format(s["env"]) if s.get("env") else ""
            tail = ("{!r} -> {!r}".format(s["current"], s["suggested"])
                    if s.get("suggested") is not None
                    else "current {!r}".format(s["current"]))
            add("   -> settings.{}{}: {}".format(s["setting"], env, tail))
            add("      {}".format(s["why"]))
    if report.get("history_entries"):
        add("history: {} recorded run(s) under this name "
            "(dampr-tpu-doctor --diff compares two)".format(
                report["history_entries"]))
    return "\n".join(lines)


def main(argv=None):
    """Console entry (``dampr-tpu-doctor``)."""
    import argparse
    import sys

    ap = argparse.ArgumentParser(
        description="diagnose a dampr_tpu run: ranked bottlenecks with "
                    "concrete settings suggestions")
    ap.add_argument("run", help="run name, run scratch/trace directory, "
                                "or stats.json path")
    ap.add_argument("runs", nargs="*",
                    help="(with --diff) the second run; (with "
                         "--autotune) the pipeline command, after --")
    ap.add_argument("--diff", action="store_true",
                    help="compare two runs: doctor --diff RUN_A RUN_B")
    ap.add_argument("--json", action="store_true",
                    help="emit the machine-readable report "
                         "(docs/doctor_schema.json)")
    ap.add_argument("--faults", action="store_true",
                    help="render the failure-recovery section: "
                         "classified retries, quarantine state, "
                         "injection plan, recorded exchange timeouts")
    ap.add_argument("--autotune", action="store_true",
                    help="closed-loop tuning: re-run the given pipeline "
                         "command under model-suggested knob vectors, "
                         "keep the fastest byte-identical winner, and "
                         "persist it (docs/tuning.md): dampr-tpu-doctor "
                         "RUN --autotune [--trials N] [--assert-dir D] "
                         "[--report TUNE.json] -- CMD ...")
    ap.add_argument("--trials", type=int, default=None,
                    help="(--autotune) measured trial budget, baseline "
                         "included (default settings.autotune_trials)")
    ap.add_argument("--assert-dir", default=None,
                    help="(--autotune) output directory whose content "
                         "digest must match trial 0 for a trial to "
                         "qualify (the byte-exactness witness)")
    ap.add_argument("--report", default=None,
                    help="(--autotune) write the schema-valid tuning "
                         "report here")
    # Everything after a literal ``--`` is the --autotune pipeline
    # command, verbatim (argparse's own ``--`` handling cannot keep an
    # option-looking command intact after optionals).
    if argv is None:
        argv = sys.argv[1:]
    command = None
    if "--" in argv:
        split = list(argv).index("--")
        command = list(argv[split + 1:])
        argv = list(argv[:split])
    args = ap.parse_args(argv)

    if args.autotune:
        from . import autotune as _autotune

        if command:
            args.runs = command
        return _autotune.main_autotune(args)
    if command:
        args.runs = (args.runs or []) + command
    try:
        if args.diff:
            if len(args.runs) != 1:
                ap.error("--diff takes exactly two runs")
            report = diff(args.run, args.runs[0])
        else:
            if args.runs:
                ap.error("one run expected (use --diff to compare two)")
            report = diagnose(args.run)
    except DoctorError as e:
        print("doctor: {}".format(e), file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        print(format_report(report, show_faults=args.faults))
    # A crashed run is a diagnosis, not a doctor failure — but scripts
    # should see it (same convention as dampr-tpu-stats).
    return 3 if report.get("crashed") else 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
