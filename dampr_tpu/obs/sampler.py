"""Background gauge sampler: the metrics plane's clock.

One daemon thread per metered run snapshots every gauge (pull callbacks
+ pushed values + counters) on the ``settings.metrics_interval_ms``
cadence and appends the result to the registry's in-memory time series
(:meth:`~.metrics.Metrics.record_sample`).  Each sample also lands in
the flight recorder ring (when one is attached), so a crash dump's tail
always carries the most recent gauge state — e.g. the writer-pool queue
depth at the moment of death.

The sampler measures its own cost: each pass's wall time accrues into
the registry's ``sample_seconds``, surfaced as the ``overhead``
self-metric (sampler wall / run wall) in ``stats()``.

Timestamps are ``perf_counter`` seconds relative to the registry epoch —
monotonic non-decreasing by construction, which the export relies on
(Chrome counter events must not go backwards) and tests pin.
"""

import threading
import time

import logging

log = logging.getLogger("dampr_tpu.obs.sampler")


class Sampler(object):
    """Snapshot thread for one :class:`~.metrics.Metrics` registry.

    ``recorder`` (optional) is a :class:`~.flightrec.FlightRecorder`;
    every sample is pushed into its ring alongside recent spans.
    """

    def __init__(self, metrics, interval_ms, recorder=None):
        self.metrics = metrics
        self.interval = max(1, int(interval_ms)) / 1000.0
        self.recorder = recorder
        self._stop = threading.Event()
        self._thread = None

    # -- lifecycle ----------------------------------------------------------
    def start(self):
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="dampr-tpu-sampler")
        self._thread.start()

    def stop(self, final_sample=True):
        """Stop the thread (joined briefly — it is a daemon, a wedged
        gauge callback cannot hang run teardown) and take one last
        snapshot so the series always reflects end-of-run state."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
            self._thread = None
            if t.is_alive():
                log.warning(
                    "metrics sampler thread %s did not stop within "
                    "2.0s at shutdown; abandoning it (daemon) — a "
                    "wedged gauge callback is still sampling", t.name)
        if final_sample:
            try:
                self._sample_once()
            except Exception:
                log.debug("final metrics sample failed", exc_info=True)

    @property
    def alive(self):
        t = self._thread
        return t is not None and t.is_alive()

    # -- sampling -----------------------------------------------------------
    def _sample_once(self):
        m = self.metrics
        t0 = time.perf_counter()
        vals = m.snapshot()
        cost = time.perf_counter() - t0
        # The registry's series store epoch-RELATIVE timestamps (what the
        # trace export emits); the flight recorder stores ABSOLUTE
        # perf_counter values and converts against its own epoch at flush
        # so span and sample clocks agree in the dump.
        m.record_sample(t0 - m.epoch, vals, cost)
        rec = self.recorder
        if rec is not None:
            rec.record_sample(t0, vals)

    def _loop(self):
        # Fixed-cadence loop: sleep to the next multiple of the interval
        # rather than interval-after-work, so a slow gauge pass doesn't
        # silently stretch the cadence (it shows up in ``overhead``
        # instead).
        next_at = time.perf_counter()
        while not self._stop.is_set():
            try:
                from .. import faults as _faults

                _faults.check("sampler_tick")  # slow-stop shutdown tests
                self._sample_once()
            except Exception:
                # A broken gauge must degrade observability, not the run.
                log.warning("metrics sample failed", exc_info=True)
            next_at += self.interval
            delay = next_at - time.perf_counter()
            if delay <= 0:
                # Fell behind (pass cost > interval): resync instead of
                # spinning to catch up.
                next_at = time.perf_counter()
                continue
            self._stop.wait(delay)
