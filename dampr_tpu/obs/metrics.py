"""Run-scoped live metrics registry: counters, gauges, histograms.

The continuous-signal counterpart of :mod:`.trace`'s span timeline.
Spans answer "what happened, when"; the metrics plane answers "what is
the system doing RIGHT NOW" — budget occupancy, writer-pool backlog,
overlap-window depth, records/s — the signals an operator (or an
autoscaler, per the tf.data-service disaggregation argument) needs while
a run is still in flight, not after ``_finalize_obs`` writes the trace.

Design contract, identical to :mod:`.trace`:

1. **Near-zero cost off.**  With no active registry, every module-level
   instrumentation call (``counter_add`` / ``gauge_set`` / ``observe``)
   is one module-global load + ``None`` check and returns.  The engine
   instruments its hot boundaries unconditionally and relies on this;
   ``settings.metrics_interval_ms = 0`` (the default) never starts a
   registry.
2. **Pull-first gauges.**  Load-bearing occupancy gauges (resident
   bytes, queue depth, HBM residency) register a *callback* once at run
   start (:meth:`Metrics.register_gauge`); the hot paths that mutate the
   underlying counters pay nothing extra — the background sampler
   (:mod:`.sampler`) evaluates the callbacks on its cadence.  Pushed
   gauges (``gauge_set``) exist for values with no stable home to poll.
3. **Lock-light.**  Counter/histogram updates take one small lock (they
   are per-block, never per-record); the sampler snapshots under the
   same lock so a snapshot is internally consistent.

The sampler owns the time series (``Metrics.series``): bounded per-series
sample lists with an explicit drop count, timestamps in perf_counter
seconds relative to the registry epoch (monotonic by construction).  The
series feed four consumers: Chrome-trace counter tracks (``"ph":"C"``
events, :mod:`.export`), the live progress line (:mod:`.progress`),
Prometheus text exposition (:mod:`.promtext`), and the flight recorder's
crash timeline (:mod:`.flightrec`).

Scope mirrors the tracer: the active registry is process-global, owned
run-scoped via ``start``/``stop``.  Two concurrent metered runs in one
process would interleave into the innermost registry; run-level summary
numbers stay exact regardless (they come from the runner's own
counters).
"""

import threading
import time

from .. import settings

#: The active registry or None.  Read unlocked on the hot path;
#: start/stop mutate under _lock.
_active = None
_stack = []
_lock = threading.Lock()


class Metrics(object):
    """One run's metric collection.

    - ``counters``: name -> monotonically increasing float (records,
      bytes, stall events).
    - ``gauges``: name -> last pushed value (``gauge_set``).
    - ``gauge_fns``: name -> zero-arg callable returning the live value;
      evaluated by the sampler (and by :meth:`snapshot`).
    - ``hists``: name -> {count, sum, min, max} summary (merge fan-in,
      sample durations) — dependency-free, no bucket math.
    - ``series``: name -> list of ``(t, value)`` samples appended by the
      sampler, each capped at ``settings.metrics_series_cap`` with
      ``series_drops`` counting evictions.
    """

    def __init__(self, run_name):
        self.run = run_name
        self.epoch = time.perf_counter()
        self.wall_start = time.time()
        self._mu = threading.Lock()
        self.counters = {}
        self.gauges = {}
        self.gauge_fns = {}
        self.hists = {}
        self.series = {}
        self.series_drops = 0
        # Sampler self-accounting (the plane measures its own cost):
        # cumulative wall seconds spent inside snapshot passes, and the
        # sample count — overhead() divides by elapsed run time.
        self.sample_count = 0
        self.sample_seconds = 0.0

    # -- recording ----------------------------------------------------------
    def counter_add(self, name, n=1):
        with self._mu:
            self.counters[name] = self.counters.get(name, 0) + n

    def gauge_set(self, name, value):
        with self._mu:
            self.gauges[name] = value

    def observe(self, name, value):
        with self._mu:
            h = self.hists.get(name)
            if h is None:
                h = self.hists[name] = {"count": 0, "sum": 0.0,
                                        "min": value, "max": value}
            h["count"] += 1
            h["sum"] += value
            if value < h["min"]:
                h["min"] = value
            if value > h["max"]:
                h["max"] = value

    def register_gauge(self, name, fn):
        """Install a pull gauge: ``fn()`` is evaluated at sample time.
        Registration happens once per run (runner setup), so the sites
        whose state it reads pay nothing on their hot paths."""
        with self._mu:
            self.gauge_fns[name] = fn

    # -- sampling -----------------------------------------------------------
    def snapshot(self):
        """One consistent gauge read: pull gauges evaluated, pushed
        gauges and counters included (counters ARE the throughput
        series — the consumer differences them).  Broken callbacks are
        dropped for the rest of the run rather than killing the
        sampler."""
        vals = {}
        dead = []
        with self._mu:
            fns = list(self.gauge_fns.items())
            vals.update(self.gauges)
            vals.update(self.counters)
        for name, fn in fns:
            try:
                v = fn()
            except Exception:
                dead.append(name)
                continue
            if v is not None:
                vals[name] = v
        if dead:
            with self._mu:
                for name in dead:
                    self.gauge_fns.pop(name, None)
        return vals

    def record_sample(self, t, vals, cost_seconds):
        """Append one sampler pass to the time series (called by the
        sampler thread only).  ``t`` is perf_counter seconds relative to
        ``epoch``; per-series caps evict the oldest sample and count the
        drop."""
        cap = max(2, settings.metrics_series_cap)
        with self._mu:
            self.sample_count += 1
            self.sample_seconds += cost_seconds
            for name, v in vals.items():
                s = self.series.get(name)
                if s is None:
                    s = self.series[name] = []
                if len(s) >= cap:
                    del s[0]
                    self.series_drops += 1
                s.append((t, v))

    def overhead(self):
        """Sampler wall seconds / run wall seconds so far — the metrics
        plane's self-metric (acceptance: <3% at 100 ms cadence)."""
        elapsed = time.perf_counter() - self.epoch
        if elapsed <= 0:
            return 0.0
        return self.sample_seconds / elapsed

    # -- summary ------------------------------------------------------------
    def summary(self):
        """The ``metrics`` section of stats.json: final counters, last/
        peak gauge values per series, histogram summaries, and the
        sampler's self-accounting."""
        with self._mu:
            counters = dict(self.counters)
            hists = {k: dict(v) for k, v in self.hists.items()}
            series_meta = {}
            for name, s in self.series.items():
                if not s:
                    continue
                vals = [v for _t, v in s]
                series_meta[name] = {
                    "samples": len(s),
                    "last": vals[-1],
                    "peak": max(vals),
                }
            n_samples = self.sample_count
            drops = self.series_drops
            sample_secs = self.sample_seconds
        return {
            "counters": counters,
            "histograms": hists,
            "series": series_meta,
            "sampler": {
                "interval_ms": settings.effective_metrics_interval_ms(),
                "samples": n_samples,
                "series_drops": drops,
                "sample_seconds": round(sample_secs, 6),
                "overhead": round(self.overhead(), 6),
            },
        }


# -- module-level API (the instrumentation surface) -------------------------

def start(metrics):
    """Make ``metrics`` the active registry (run-scoped: pair with
    stop)."""
    global _active
    with _lock:
        _stack.append(metrics)
        _active = metrics


def stop(metrics):
    global _active
    with _lock:
        if metrics in _stack:
            _stack.remove(metrics)
        _active = _stack[-1] if _stack else None


def active():
    return _active


def enabled():
    return _active is not None


def counter_add(name, n=1):
    m = _active
    if m is not None:
        m.counter_add(name, n)


def gauge_set(name, value):
    m = _active
    if m is not None:
        m.gauge_set(name, value)


def observe(name, value):
    m = _active
    if m is not None:
        m.observe(name, value)


def register_gauge(name, fn):
    m = _active
    if m is not None:
        m.register_gauge(name, fn)
