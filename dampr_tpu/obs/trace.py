"""Low-overhead run-scoped span recorder.

Design constraints, in order:

1. **Near-zero cost off.**  With no active tracer, ``span()`` /
   ``complete()`` / ``instant()`` are one module-global load + ``None``
   check; ``span()`` returns a shared no-op context manager (no
   allocation).  The engine's hot loops (per-block codec/fold, per-window
   merges) are instrumented unconditionally and rely on this.
2. **Thread-natural lanes.**  The engine's concurrency units ARE threads:
   map jobs run on pool workers (slots), each overlapped codec runs on its
   own named producer thread, reduce jobs on pool workers, merge
   generations on the stage walker.  Events therefore record the emitting
   thread's ident as their lane (Chrome ``tid``) by default, and the
   tracer remembers each lane's thread name once so the export can emit
   ``thread_name`` metadata — Perfetto then shows one track per slot.  An
   explicit ``lane="..."`` names a synthetic lane instead (used where one
   thread multiplexes logical lanes, e.g. merge generations).
3. **Append-only, lock-light.**  Events append to a plain list (atomic
   under the GIL); only lane-name interning takes a tiny setdefault.

Events are stored as compact tuples and converted to Chrome trace-event
dicts at export time (:mod:`.export`).  Timestamps are
``time.perf_counter()`` seconds relative to the tracer's epoch.

Scope: the active tracer is process-global (runs own it run-scoped via
``start``/``stop``).  Two *concurrent* traced runs in one process would
interleave spans into whichever tracer started last; the runner documents
this and run-level metrics stay exact regardless (they come from
run-scoped counters, not spans).
"""

import threading
import time


class _NoopSpan(object):
    """Shared do-nothing context manager returned when tracing is off."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


_NOOP = _NoopSpan()

#: The active tracer (innermost, when runs nest) or None.  Read unlocked on
#: the hot path; start/stop mutate under _lock.
_active = None
_stack = []
_lock = threading.Lock()


class _Span(object):
    """A live ``with``-span: records one complete ("X") event on exit."""

    __slots__ = ("_tracer", "_cat", "_name", "_lane", "_args", "_t0")

    def __init__(self, tracer, cat, name, lane, args):
        self._tracer = tracer
        self._cat = cat
        self._name = name
        self._lane = lane
        self._args = args
        self._t0 = 0.0

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        self._tracer._record(self._cat, self._name, self._t0,
                             time.perf_counter() - self._t0,
                             self._lane, self._args)
        return False


class Tracer(object):
    """One run's span collection.

    ``events`` holds ``(cat, name, t0, dur, lane, args)`` tuples —
    ``t0``/``dur`` in perf_counter seconds relative to ``epoch``; ``dur``
    is None for instant events; ``lane`` is a thread ident (int) or an
    explicit lane string.
    """

    def __init__(self, run_name):
        self.run = run_name
        self.epoch = time.perf_counter()
        self.wall_start = time.time()
        self.events = []
        self.lane_names = {}   # lane id -> display name
        #: Optional flight recorder (obs.flightrec): every recorded span
        #: is mirrored into its bounded ring so a killed run's crashdump
        #: carries the most recent timeline tail.  None costs one
        #: attribute load per recorded event (never on the disabled
        #: path, which returns before _record).
        self.recorder = None

    # -- recording ---------------------------------------------------------
    def _record(self, cat, name, t0, dur, lane, args):
        if lane is None:
            lane = threading.get_ident()
            if lane not in self.lane_names:
                self.lane_names[lane] = threading.current_thread().name
        elif lane not in self.lane_names:
            self.lane_names[lane] = str(lane)
        self.events.append((cat, name, t0 - self.epoch, dur, lane, args))
        rec = self.recorder
        if rec is not None:
            rec.record_span(cat, name, t0, dur, lane,
                            self.lane_names.get(lane), args)

    def span(self, cat, name, lane=None, **args):
        return _Span(self, cat, name, lane, args or None)

    def complete(self, cat, name, t0, lane=None, **args):
        """Record an already-measured interval (retrofit sites that had
        their own ``t0 = now()``)."""
        self._record(cat, name, t0, time.perf_counter() - t0, lane,
                     args or None)

    def instant(self, cat, name, lane=None, **args):
        self._record(cat, name, time.perf_counter(), None, lane,
                     args or None)

    # -- summary -----------------------------------------------------------
    def span_summary(self):
        """{cat: {"count": n, "seconds": s}} for the stats.json summary.
        Derived from the event list at summary time (one O(n) pass on the
        run's single finalizing thread) — concurrent recorders only ever
        touch the append-atomic event list, so counts here always agree
        with the events in trace.json."""
        agg = {}
        for cat, _name, _t0, dur, _lane, _args in self.events:
            a = agg.setdefault(cat, [0, 0.0])
            a[0] += 1
            if dur is not None:
                a[1] += dur
        return {cat: {"count": a[0], "seconds": round(a[1], 6)}
                for cat, a in sorted(agg.items())}


# -- module-level API (the instrumentation surface) -------------------------

def start(tracer):
    """Make ``tracer`` the active recorder (run-scoped: pair with stop)."""
    global _active
    with _lock:
        _stack.append(tracer)
        _active = tracer


def stop(tracer):
    global _active
    with _lock:
        if tracer in _stack:
            _stack.remove(tracer)
        _active = _stack[-1] if _stack else None


def enabled():
    return _active is not None


def now():
    """perf_counter timestamp for a later ``complete()`` — 0.0 when off so
    disabled call sites skip the clock read entirely."""
    return time.perf_counter() if _active is not None else 0.0


def span(cat, name, lane=None, **args):
    t = _active
    if t is None:
        return _NOOP
    return _Span(t, cat, name, lane, args or None)


def complete(cat, name, t0, lane=None, **args):
    # t0 == 0.0 is the "tracing was off at now()" sentinel: a tracer that
    # started between the paired now()/complete() must not record a span
    # spanning the whole process uptime.
    t = _active
    if t is not None and t0:
        t.complete(cat, name, t0, lane=lane, **args)


def instant(cat, name, lane=None, **args):
    t = _active
    if t is not None:
        t.instant(cat, name, lane=lane, **args)


def timed_iter(items, cat, name, lane=None):
    """Wrap an iterator so each ``next()`` is recorded as one span (the
    overlapped codec producer's per-window accounting).  Returns ``items``
    unchanged when tracing is off — zero per-item overhead."""
    t = _active
    if t is None:
        return items

    def gen():
        it = iter(items)
        while True:
            t0 = time.perf_counter()
            try:
                item = next(it)
            except StopIteration:
                return
            t.complete(cat, name, t0, lane=lane)
            yield item

    return gen()
