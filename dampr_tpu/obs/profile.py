"""Per-operator profiler: attribute fused-stage time to the user's ops.

Plan fusion (:mod:`dampr_tpu.plan.passes`) deliberately collapses chains
of user operators into single executed stages, and device lowering
(:mod:`dampr_tpu.ops.lower`) compiles a whole map->fold shape into one
jitted program — great for throughput, opaque for diagnosis: the trace
can say *stage 2 took 40 s* but not which of the four fused ops the time
went to.  This module is the attribution layer under ``settings.profile``
(env ``DAMPR_TPU_PROFILE=1``):

- **fused host stages**: every composed ``apply_batch`` step of the
  batched-UDF path is timed per call (one clock pair per op per BATCH —
  never per record), codec windows are timed per window and attributed
  to the scanner op that produced them, and map-side partial/final folds
  to the stage's combiner;
- **device stages**: the double-buffered dispatch loop's sub-phases —
  ``build`` (padded-matrix construction), ``h2d`` (program dispatch +
  feed), ``compute`` (blocked-on-program time at drain), ``d2h``
  (result fetch) — accumulate separately, decomposing the aggregate
  ``device`` span the trace records;
- **jobs**: every pool job's wall time lands on its stage, so the
  summary can report *coverage* — the fraction of job thread-seconds the
  per-op attribution explains (the acceptance bar: >= 0.9 on fused
  scanner stages).

Design contract, identical to :mod:`.trace` / :mod:`.metrics`:

1. **Near-zero cost off.**  With no active profiler every module-level
   call site is one module-global load + ``None`` check; hot loops hoist
   even that to one check per job.  No thread is ever started (the
   profiler is passive — it only accumulates under a small lock at
   batch/window/job granularity).
2. **Run-scoped, process-global active instance** via ``start``/``stop``
   (the runner owns the lifecycle); concurrent profiled runs would
   interleave into the innermost profiler, same caveat as the tracer.

The summary ships as ``stats()["profile"]``, feeds the run-history
corpus (:mod:`.history`) and the ``dampr-tpu-doctor`` diagnosis.
"""

import threading

#: The active profiler or None.  Read unlocked on the hot path;
#: start/stop mutate under _lock.
_active = None
_stack = []
_lock = threading.Lock()


def op_label(op, index=None):
    """Stable display label for one operator of a fused chain:
    ``TypeName(fn_name)`` where the wrapped function has a useful name.
    Index-prefixed labels (``"1:ValueMap(tf)"``) keep duplicate op types
    within one chain distinct."""
    fn = None
    for attr in ("mapper", "f", "key_f", "reducer", "sinker", "op"):
        fn = getattr(op, attr, None)
        if fn is not None:
            break
    label = type(op).__name__
    name = getattr(fn, "__name__", None)
    if name and name != "<lambda>":
        label = "{}({})".format(label, name)
    if index is None:
        return label
    return "{}:{}".format(index, label)


def chain_labels(ops):
    """Index-prefixed labels for an ordered operator chain."""
    return [op_label(op, i) for i, op in enumerate(ops)]


class Profiler(object):
    """One run's per-operator attribution.

    Per executed stage (keyed by sid): an ``ops`` table mapping operator
    label -> ``[seconds, records, calls]``, a ``device`` table mapping
    sub-phase -> ``[seconds, bytes, calls]``, and job accounting
    (``jobs``, ``job_seconds`` thread-seconds).  All adds take one small
    lock; granularity is per batch / window / job, so contention is
    negligible next to the work being measured."""

    def __init__(self, run_name):
        self.run = run_name
        self._mu = threading.Lock()
        self._stages = {}
        #: The stage currently executing.  The runner's stage walk is
        #: sequential, so a single run-global current sid is exact; the
        #: stage's concurrent jobs all belong to it.
        self.sid = None

    # -- stage lifecycle (runner's sequential walk) -------------------------
    def begin_stage(self, sid, kind, provenance=None):
        with self._mu:
            self._stages[sid] = {
                "stage": sid, "kind": kind,
                "provenance": list(provenance) if provenance else None,
                "ops": {}, "device": {},
                "jobs": 0, "job_seconds": 0.0,
            }
            self.sid = sid

    def _rec(self, sid):
        if sid is None:
            sid = self.sid
        rec = self._stages.get(sid)
        if rec is None:
            # Attribution from outside a began stage (direct runner use,
            # tests): accumulate under a synthetic stage record instead
            # of dropping the sample.
            rec = self._stages[sid] = {
                "stage": sid, "kind": "?", "provenance": None,
                "ops": {}, "device": {}, "jobs": 0, "job_seconds": 0.0,
            }
        return rec

    # -- accumulation (hot sites; per batch/window/job, never per record) ---
    def op_add(self, label, seconds, records=0, calls=1, sid=None):
        with self._mu:
            ops = self._rec(sid)["ops"]
            cell = ops.get(label)
            if cell is None:
                ops[label] = [seconds, records, calls]
            else:
                cell[0] += seconds
                cell[1] += records
                cell[2] += calls

    def device_add(self, phase, seconds, nbytes=0, sid=None):
        with self._mu:
            dev = self._rec(sid)["device"]
            cell = dev.get(phase)
            if cell is None:
                dev[phase] = [seconds, nbytes, 1]
            else:
                cell[0] += seconds
                cell[1] += nbytes
                cell[2] += 1

    def job_add(self, seconds, sid=None):
        with self._mu:
            rec = self._rec(sid)
            rec["jobs"] += 1
            rec["job_seconds"] += seconds

    def timed_iter(self, items, label, sid=None, records_of=None):
        """Wrap an iterator so each ``next()`` — a codec window's
        decompress/tokenize/parse — is attributed to ``label``.  Records
        one op_add per WINDOW; ``records_of(item)`` overrides the
        default ``len(item)`` record count."""
        import time

        if sid is None:
            sid = self.sid

        def count(item):
            if records_of is not None:
                try:
                    return records_of(item)
                except Exception:
                    return 0
            if item is not None and hasattr(item, "__len__"):
                return len(item)
            return 0

        def gen():
            it = iter(items)
            while True:
                t0 = time.perf_counter()
                try:
                    item = next(it)
                except StopIteration:
                    return
                self.op_add(label, time.perf_counter() - t0,
                            records=count(item), sid=sid)
                yield item

        return gen()

    # -- summary ------------------------------------------------------------
    def summary(self, stage_seconds=None):
        """The ``profile`` section of stats.json.  ``stage_seconds``
        (optional {sid: wall seconds} from StageStats) adds per-stage
        wall so consumers can relate coverage to elapsed time."""
        stage_seconds = stage_seconds or {}
        stages = []
        with self._mu:
            recs = sorted(self._stages.items())
        for sid, rec in recs:
            ops = [{"op": label, "seconds": round(c[0], 6),
                    "records": c[1], "calls": c[2]}
                   for label, c in sorted(rec["ops"].items(),
                                          key=lambda kv: -kv[1][0])]
            device = {phase: {"seconds": round(c[0], 6), "bytes": c[1],
                              "calls": c[2]}
                      for phase, c in sorted(rec["device"].items())}
            attributed = (sum(o["seconds"] for o in ops)
                          + sum(d["seconds"] for d in device.values()))
            job_s = rec["job_seconds"]
            entry = {
                "stage": sid, "kind": rec["kind"],
                "ops": ops, "device": device,
                "jobs": rec["jobs"],
                "job_seconds": round(job_s, 6),
                "attributed_seconds": round(attributed, 6),
                # How much of the stage's job thread-seconds the per-op
                # attribution explains (capped: attribution sites can
                # slightly overlap job timing at the edges).
                "coverage": (round(min(1.0, attributed / job_s), 4)
                             if job_s > 1e-9 else None),
            }
            if rec["provenance"]:
                entry["provenance"] = rec["provenance"]
            if sid in stage_seconds:
                entry["seconds"] = round(stage_seconds[sid], 4)
            stages.append(entry)
        return {"enabled": True, "stages": stages}


# -- module-level API (the instrumentation surface) -------------------------

def start(profiler):
    """Make ``profiler`` the active instance (run-scoped: pair with
    stop)."""
    global _active
    with _lock:
        _stack.append(profiler)
        _active = profiler


def stop(profiler):
    global _active
    with _lock:
        if profiler in _stack:
            _stack.remove(profiler)
        _active = _stack[-1] if _stack else None


def active():
    """The active profiler or None — hot sites hoist this to one load +
    None-check per job."""
    return _active


def enabled():
    return _active is not None


def device_add(phase, seconds, nbytes=0):
    p = _active
    if p is not None:
        p.device_add(phase, seconds, nbytes)
