"""Long-horizon telemetry store: compact per-run metric points.

The run-history corpus (:mod:`.history`) keeps rich per-stage records
for the adaptation layer; trending a fleet over weeks needs something
flatter — one small point per finalized run, keyed by the plan
fingerprint so runs of the same shape form a comparable series::

    <scratch_root>/<run>/telemetry.jsonl   # next to history.jsonl

Each line is one self-contained point (schema ``dampr-tpu-telemetry/1``)
holding the :data:`METRICS` scalars: wall seconds, throughput, spill
volume, fault absorption, straggler skew, reuse yield, and device
residency/handoff fractions.  A metric with no sample that run is simply
absent — the sentry must distinguish "feature off" from "measured zero".

Durability follows history.jsonl's contract exactly: one ``O_APPEND``
write per point (a crash corrupts at most its own line), tolerant
line-validated reads, and tmp + atomic-rename compaction past the
retention bound (``settings.history_entries * 16`` — telemetry points
are ~20x smaller than history records, so the store trends over a much
longer horizon at comparable disk cost).

Consumers: :mod:`.sentry` (MAD regression detection over the trailing
per-fingerprint window), ``dampr-tpu-sentry`` / ``dampr-tpu-doctor``
(regression findings), and the perf-gate CI leg.
"""

import json
import logging
import os
import threading

from .. import settings
from . import history as _history

log = logging.getLogger("dampr_tpu.obs.timeseries")

SCHEMA_PREFIX = "dampr-tpu-telemetry/"
SCHEMA_VERSION = 1
SCHEMA = SCHEMA_PREFIX + str(SCHEMA_VERSION)
FILE = "telemetry.jsonl"

#: Trended metrics -> the direction that is BAD for each.  "high" means
#: a value above baseline is a regression (time, spill, faults, skew);
#: "low" means below baseline is (throughput, cache yield, residency).
METRICS = {
    "wall_seconds": "high",
    "mbps": "low",
    "spill_bytes": "high",
    "retries": "high",
    "quarantined": "high",
    "late_ratio": "high",
    "reuse_hit_rate": "low",
    "device_fraction": "low",
    "handoff_fraction": "low",
}

#: How many points one corpus retains before compaction.
def retention_cap():
    return max(0, settings.history_entries) * 16


_append_lock = threading.Lock()


def store_path(run_name):
    """Where a run name's telemetry series lives (next to history.jsonl,
    under the durable scratch root)."""
    safe = str(run_name).replace("/", "_")
    return os.path.join(settings.scratch_root, safe, FILE)


def _put(point, key, value):
    if isinstance(value, bool):
        return
    if isinstance(value, (int, float)):
        point[key] = round(value, 6) if isinstance(value, float) else value


def point_from_summary(summary):
    """One telemetry point from a finalized run summary (the stats.json
    dict), or None when the run has nothing trendable."""
    if not summary.get("run") or not summary.get("stages"):
        return None
    point = {
        "schema": SCHEMA,
        "run": summary.get("run"),
        "ts": summary.get("started_at"),
        "fingerprint": _history.plan_fingerprint(
            (summary.get("plan") or {}).get("stage_shapes") or []),
    }
    _put(point, "wall_seconds", summary.get("wall_seconds"))
    totals = summary.get("totals") or {}
    wall = summary.get("wall_seconds")
    bytes_out = totals.get("bytes_out")
    if isinstance(bytes_out, int) and isinstance(wall, (int, float)) \
            and wall > 0:
        _put(point, "mbps", bytes_out / 1e6 / wall)
    spill = sum(st.get("spill_bytes") or 0
                for st in summary.get("stages") or ()
                if isinstance(st.get("spill_bytes"), int))
    _put(point, "spill_bytes", spill)
    health = _history._health_section(summary)
    for key in ("retries", "quarantined", "late_ratio", "reuse_hit_rate"):
        if key in health:
            _put(point, key, health[key])
    dev = summary.get("device") or {}
    _put(point, "device_fraction", dev.get("device_fraction"))
    hb = dev.get("handoff_bytes")
    if isinstance(hb, int) and isinstance(bytes_out, int) and bytes_out > 0:
        _put(point, "handoff_fraction", min(1.0, hb / float(bytes_out)))
    return point


def point_from_history(rec):
    """One telemetry point from a (upgraded) history corpus record —
    the rebuild path when a corpus predates the telemetry store."""
    if rec.get("rank"):
        return None  # rank-tagged trail, not a run-level sample
    point = {
        "schema": SCHEMA,
        "run": rec.get("run"),
        "ts": rec.get("ts"),
        "fingerprint": rec.get("fingerprint")
        or _history.plan_fingerprint(rec.get("stage_shapes") or []),
    }
    _put(point, "wall_seconds", rec.get("wall_seconds"))
    _put(point, "mbps", (rec.get("throughput") or {}).get("mbps"))
    spill = sum(st.get("spill_bytes") or 0
                for st in rec.get("stages") or ()
                if isinstance(st, dict)
                and isinstance(st.get("spill_bytes"), int))
    _put(point, "spill_bytes", spill)
    for key in ("retries", "quarantined", "late_ratio", "reuse_hit_rate"):
        if key in (rec.get("health") or {}):
            _put(point, key, rec["health"][key])
    _put(point, "device_fraction", rec.get("device_fraction"))
    hb = (rec.get("handoff") or {}).get("bytes")
    bytes_out = (rec.get("throughput") or {}).get("bytes_out")
    if isinstance(hb, int) and isinstance(bytes_out, int) and bytes_out > 0:
        _put(point, "handoff_fraction", min(1.0, hb / float(bytes_out)))
    return point


def append_point(point):
    """Append one point; best-effort (telemetry must never fail a run)
    and bounded.  Returns the store path or None."""
    if retention_cap() <= 0 or not point or not point.get("run"):
        return None
    try:
        line = json.dumps(point, sort_keys=True,
                          separators=(",", ":"), default=str)
        if "\n" in line:
            return None
        path = store_path(point["run"])
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with _append_lock:
            fd = os.open(path, os.O_WRONLY | os.O_APPEND | os.O_CREAT,
                         0o644)
            try:
                os.write(fd, (line + "\n").encode("utf-8"))
            finally:
                os.close(fd)
            _compact_if_over(path)
        return path
    except Exception:
        log.debug("telemetry append failed for %r", point.get("run"),
                  exc_info=True)
        return None


def append_from_summary(summary):
    """Fold one finalized summary into the store (the runner's hook)."""
    return append_point(point_from_summary(summary))


def _compact_if_over(path):
    cap = retention_cap()
    try:
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            lines = f.readlines()
    except OSError:
        return
    if len(lines) <= cap:
        return
    keep = [ln for ln in lines if _valid_line(ln) is not None][-cap:]
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        f.writelines(keep)
    os.replace(tmp, path)


def _valid_line(line):
    line = line.strip()
    if not line:
        return None
    try:
        point = json.loads(line)
    except ValueError:
        return None
    if not isinstance(point, dict):
        return None
    tag = point.get("schema")
    if not isinstance(tag, str) or not tag.startswith(SCHEMA_PREFIX):
        return None
    if not point.get("run") or not point.get("fingerprint"):
        return None
    return point


def load(run_name):
    """Every valid point for a run name, oldest -> newest.  Never
    raises; a missing or corrupt store is an empty series."""
    path = store_path(run_name) if run_name else None
    if not path or not os.path.isfile(path):
        return []
    out = []
    try:
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            for line in f:
                point = _valid_line(line)
                if point is not None:
                    out.append(point)
    except OSError:
        return []
    return out


def series(points, fingerprint=None):
    """Group points by plan fingerprint -> ordered list.  With a
    fingerprint, just that one series (possibly empty)."""
    by_fp = {}
    for p in points:
        by_fp.setdefault(p.get("fingerprint"), []).append(p)
    if fingerprint is not None:
        return by_fp.get(fingerprint, [])
    return by_fp


def fold(run_name):
    """Rebuild the telemetry store from the run's history corpus (tmp +
    atomic rename) — the migration path for corpora that predate the
    store, and the ``dampr-tpu-sentry --fold`` maintenance verb.
    Returns the number of points written."""
    points = [p for p in (point_from_history(r)
                          for r in _history.load(run_name))
              if p is not None]
    cap = retention_cap()
    if cap > 0:
        points = points[-cap:]
    path = store_path(run_name)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + ".tmp"
    with _append_lock:
        with open(tmp, "w", encoding="utf-8") as f:
            for p in points:
                f.write(json.dumps(p, sort_keys=True,
                                   separators=(",", ":"), default=str))
                f.write("\n")
        os.replace(tmp, path)
    return len(points)
