"""Closed-loop autotuning: measure the model's knob picks, keep the
winner, feed it back to the corpus.

The learned cost model (:mod:`dampr_tpu.plan.model`) can only choose
run-level knob values it has *observed* — a corpus that has always run
``overlap_windows=2`` carries no evidence about 4.  This module is the
loop that manufactures that evidence (ROADMAP item 3's second half):

- ``dampr-tpu-doctor --autotune RUN -- CMD...`` re-executes ``CMD`` (a
  pipeline/bench whose run name is ``RUN``) under a bounded series of
  knob vectors: trial 0 is always the incoming baseline configuration,
  the remaining trials come from the model's variance search, the doctor
  playbook keyed on the run's recorded critical-path verdict, and a
  fixed exploration schedule.  Every trial's wall/throughput comes from
  the run's OWN corpus record (each trial run appends one — that append
  IS the winner write-back: the next fit sees every measured vector).
- **byte-exactness between trials** is asserted when the pipeline
  writes an output directory (``--assert-dir``): trials whose output
  digest differs from trial 0 are disqualified, never crowned.
- the winner's knob vector is persisted to
  ``<scratch_root>/<RUN>/tuned.json``; the next run's cost layer applies
  the engine-level knobs (``n_partitions``) and ``explain()`` renders
  the rest for the operator (they ride env vars).
- the session emits a tuning report that validates against
  ``docs/doctor_schema.json`` (its ``autotune`` section) — checked in
  as ``TUNE_r01.json`` and accepted by ``tools/check_bench.py`` as a
  baseline source.

``settings.autotune`` (``DAMPR_TPU_AUTOTUNE=on``) is the in-process
variant for bench drivers (:func:`tune_settings_session`): the bench
hands over its measured callable and the session applies candidate
vectors to :mod:`dampr_tpu.settings` directly (save/restore), keeping
the fastest byte-identical configuration.  See ``docs/tuning.md``.
"""

import hashlib
import json
import logging
import os
import subprocess
import sys
import time

from .. import settings

log = logging.getLogger("dampr_tpu.obs.autotune")

SCHEMA = "dampr-tpu-doctor/1"

#: Fixed exploration schedule: (knob, candidate-from-current) pairs
#: tried in order when the model has no variance evidence yet.  Each
#: thread-shaped knob explores the OPPOSITE regime first (on a 2-core
#: box background codec/writer threads contend with the fold; on a wide
#: box they win — only a measurement knows), then doubles.  Values are
#: clamped to plan.model.KNOB_BOUNDS before use.
_EXPLORE = (
    ("overlap_windows", lambda cur: 0 if cur else 2),
    ("spill_write_threads", lambda cur: 0 if cur else 2),
    ("spill_read_prefetch", lambda cur: 0 if cur else 2),
    ("overlap_windows", lambda cur: (cur or 1) * 2),
    ("spill_write_threads", lambda cur: (cur or 1) * 2),
    ("merge_fanin", lambda cur: (cur or 512) * 2),
)


def dir_digest(path, mode="lines"):
    """Content digest of every file under ``path`` — the byte-exactness
    witness between trials.  None when the directory is missing.

    ``mode="lines"`` (default) digests the sorted multiset of output
    LINES across all files: partition-count choices legitimately change
    how many part files a sink writes and which records land in which
    part, while the result — the line multiset — must be identical, so
    the witness must not be layout-sensitive.  ``mode="tree"`` digests
    relative paths + raw bytes (strict layout identity, for outputs
    where file boundaries are the contract)."""
    if not path or not os.path.isdir(path):
        return None
    if mode == "tree":
        h = hashlib.sha256()
        for root, dirs, files in os.walk(path):
            dirs.sort()
            for fname in sorted(files):
                fpath = os.path.join(root, fname)
                h.update(os.path.relpath(fpath, path)
                         .encode("utf-8", "replace"))
                try:
                    with open(fpath, "rb") as f:
                        for chunk in iter(lambda: f.read(1 << 20), b""):
                            h.update(chunk)
                except OSError:
                    h.update(b"<unreadable>")
        return h.hexdigest()
    # Commutative multiset digest, O(1) memory: per-line sha256 values
    # sum mod 2^256 (order-free by construction), finalized with the
    # line count so the empty multiset and {""} differ.  Materializing
    # and sorting every line would cost GBs of RSS on the witnesses the
    # spill benches write.
    total = 0
    count = 0
    mod = 1 << 256
    for root, dirs, files in os.walk(path):
        dirs.sort()
        for fname in sorted(files):
            try:
                with open(os.path.join(root, fname), "rb") as f:
                    for ln in f:
                        total = (total + int.from_bytes(
                            hashlib.sha256(ln.rstrip(b"\n")).digest(),
                            "big")) % mod
                        count += 1
            except OSError:
                total = (total + int.from_bytes(
                    hashlib.sha256(b"<unreadable>").digest(),
                    "big")) % mod
                count += 1
    h = hashlib.sha256()
    h.update(count.to_bytes(8, "big"))
    h.update(total.to_bytes(32, "big"))
    return h.hexdigest()


def _corpus(run_name):
    from . import history

    return [r for r in history.load(run_name) if not r.get("rank")]


def _record_key(rec):
    return json.dumps(rec, sort_keys=True, default=str)


def _new_records(run_name, before_keys):
    """Records present now but not in the pre-trial snapshot — selected
    by CONTENT, not list position: at the settings.history_entries cap
    the corpus compacts on append, so its length stays constant while
    records churn, and positional slicing would report an empty
    delta."""
    return [r for r in _corpus(run_name)
            if _record_key(r) not in before_keys]


def _trial_measurement(new_records, fallback_wall):
    """(wall_seconds, mbps, n_partitions) for one trial from the corpus
    records its runs appended (benches run cold+warm under one name, so
    the best record is the trial's steady state), falling back to the
    subprocess wall when the command left no record."""
    walls = [r.get("wall_seconds") for r in new_records
             if isinstance(r.get("wall_seconds"), (int, float))]
    mbps = [
        (r.get("throughput") or {}).get("mbps")
        for r in new_records
        if isinstance((r.get("throughput") or {}).get("mbps"),
                      (int, float))]
    parts = [r.get("n_partitions") for r in new_records
             if isinstance(r.get("n_partitions"), int)]
    return (min(walls) if walls else fallback_wall,
            max(mbps) if mbps else None,
            parts[-1] if parts else None)


def candidate_vectors(run_name, max_candidates):
    """Bounded knob vectors to trial after the baseline, most promising
    first: model variance picks, the doctor playbook keyed on the run's
    recorded critpath verdict, then the static exploration schedule.
    Every value is clamped to the documented knob bounds; vectors keep
    settings-attribute keys (``as_env`` maps them for subprocesses)."""
    from ..plan import model as _model

    records = _corpus(run_name)
    vectors = []
    seen = set()

    def push(vec, why):
        vec = {k: v for k, v in vec.items()
               if _model.in_bounds(k, v)
               and v != getattr(settings, k, None)}
        if not vec:
            return
        key = json.dumps(vec, sort_keys=True, default=str)
        if key in seen or len(vectors) >= max_candidates:
            return
        seen.add(key)
        vectors.append({"knobs": vec, "why": why})

    if records:
        m = _model.build(records, records[-1].get("fingerprint"))
        current = {k: getattr(settings, k, None)
                   for k in _model.VARIANCE_KNOBS}
        model_vec = {c["knob"]: c["chosen"]
                     for c in _model.search_variance_knobs(m, current)
                     if c.get("chosen") != c.get("static")}
        if model_vec:
            push(model_vec, "model: best-measured values over the "
                            "corpus variance")
        # Spill-aware exploration: a run that spilled through a
        # compressing codec should always get one raw-codec trial —
        # high-entropy numeric lanes often don't compress, and the
        # codec pass is core-bound either way (the measurement, not
        # this heuristic, decides).
        spilled = sum((st.get("spill_bytes") or 0)
                      for st in records[-1].get("stages") or ())
        cur_codec = str((records[-1].get("settings") or {})
                        .get("spill_codec", settings.spill_codec))
        if spilled and cur_codec not in ("raw",):
            push({"spill_codec": "raw"},
                 "exploration: {} MB spilled through codec {!r} — "
                 "measure the raw frame path".format(
                     round(spilled / 1e6, 1), cur_codec))
        # Doctor playbook keyed on the newest record's critpath verdict.
        verdict = ((records[-1].get("critpath") or {}).get("run"))
        if verdict:
            from . import doctor as _doctor

            for knob, env, propose, _why in _doctor._PLAYBOOK.get(
                    verdict, ())[:2]:
                if knob not in _model.KNOB_BOUNDS:
                    continue
                cur = getattr(settings, knob, None)
                try:
                    proposed = propose(cur)
                except (TypeError, ValueError):
                    proposed = None
                if proposed is None:
                    continue
                if isinstance(proposed, (int, float)):
                    proposed = _model.clamp(knob, proposed)
                push({knob: proposed},
                     "doctor playbook for verdict {!r}".format(verdict))
    for knob, derive in _EXPLORE:
        cur = getattr(settings, knob, None)
        try:
            val = _model.clamp(knob, derive(cur))
        except (TypeError, ValueError):
            continue
        push({knob: val}, "exploration schedule")
    return vectors[:max_candidates]


def as_env(knobs):
    """Settings-keyed knob vector -> env-var map for a subprocess trial
    (knobs without an env var are dropped — they are engine-applied)."""
    from ..plan import model as _model

    out = {}
    for knob, val in (knobs or {}).items():
        env = _model.ENV_OF.get(knob)
        if env:
            out[env] = str(val)
    return out


def _persist_winner(run_name, session_id, winner):
    """Write the winner vector to ``<scratch_root>/<run>/tuned.json``
    (tmp + atomic rename; the cost layer's ``load_tuned`` reads it
    back).  Returns the path or None."""
    try:
        safe = str(run_name).replace("/", "_")
        run_dir = os.path.join(settings.scratch_root, safe)
        os.makedirs(run_dir, exist_ok=True)
        path = os.path.join(run_dir, "tuned.json")
        doc = {
            "schema": "dampr-tpu-tuned/1",
            "session": session_id,
            "run": run_name,
            "knobs": winner.get("knobs") or {},
            "wall_seconds": winner.get("wall_seconds"),
            "mbps": winner.get("mbps"),
            "trial": winner.get("trial"),
        }
        if winner.get("fingerprint"):
            # Plan-shape scope: the cost layer must never apply this
            # winner to a DIFFERENT pipeline that happens to reuse the
            # run name.
            doc["fingerprint"] = winner["fingerprint"]
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
        os.replace(tmp, path)
        return path
    except OSError:
        log.warning("autotune: could not persist tuned.json for %r",
                    run_name, exc_info=True)
        return None


def _finish_report(run_name, session_id, command, trials, trial0,
                   metric=None):
    """Rank trials, crown the byte-identical winner, persist it, and
    build the doctor-schema-valid session report."""
    qualified = [t for t in trials
                 if t.get("byte_identical", True)
                 and isinstance(t.get("wall_seconds"), (int, float))]
    winner = min(qualified, key=lambda t: t["wall_seconds"]) \
        if qualified else trial0
    improvement = (trial0["wall_seconds"] / winner["wall_seconds"]
                   if winner.get("wall_seconds")
                   and trial0.get("wall_seconds") else 1.0)
    for t in trials:  # schema discipline: optionals omitted, not null
        for key in ("mbps", "digest"):
            if t.get(key) is None:
                t.pop(key, None)
        if t.get("wall_seconds") is None:  # required by the schema
            t["wall_seconds"] = t.get("cmd_seconds") or 0.0
    tuned_path = None
    if winner is not trial0:
        full = dict(winner.get("knobs") or {})
        if winner.get("n_partitions"):
            full["n_partitions"] = winner["n_partitions"]
        recs = _corpus(run_name)
        tuned_path = _persist_winner(
            run_name, session_id,
            {"knobs": full, "wall_seconds": winner.get("wall_seconds"),
             "mbps": winner.get("mbps"), "trial": winner.get("trial"),
             "fingerprint": (recs[-1].get("fingerprint")
                             if recs else None)})
    report = {
        "schema": SCHEMA,
        "run": run_name,
        "wall_seconds": winner.get("wall_seconds") or 0.0,
        "stages": [],
        "findings": [],
        "autotune": {
            "session": session_id,
            "command": command,
            "trials": trials,
            "winner": {k: v for k, v in (
                ("trial", winner.get("trial")),
                ("knobs", winner.get("knobs") or {}),
                ("wall_seconds", winner.get("wall_seconds")),
                ("mbps", winner.get("mbps")),
            ) if v is not None},
            "baseline_wall_seconds": trial0.get("wall_seconds") or 0.0,
            "improvement": round(improvement, 4),
            "byte_identical": all(t.get("byte_identical", True)
                                  for t in trials),
            "corpus_records": len(_corpus(run_name)),
        },
    }
    if tuned_path:
        report["autotune"]["tuned_path"] = tuned_path
    if metric:
        report["metric"] = metric
    if winner.get("mbps") is not None:
        report["value"] = winner["mbps"]
    return report


def session(command, run_name, trials=None, assert_dir=None,
            base_env=None, out=None):
    """One unattended autotune session over a subprocess command.

    Trial 0 runs ``command`` under the incoming environment; each
    further trial exports one candidate knob vector via env vars.
    Returns the session report dict (see :func:`_finish_report`)."""
    out = out or (lambda msg: print(msg, file=sys.stderr, flush=True))
    n_trials = max(2, trials if trials is not None
                   else settings.autotune_trials)
    session_id = "autotune-{}".format(int(time.time()))
    results = []
    baseline_digest = None
    metric = None

    def run_trial(idx, knobs, why):
        nonlocal baseline_digest, metric
        env = dict(base_env if base_env is not None else os.environ)
        env.update(as_env(knobs))
        if assert_dir and os.path.isdir(assert_dir):
            # The witness dir is the trial's output dir: stale part
            # files from the previous trial (a pipeline that does not
            # clear its own sink, or one writing fewer partitions this
            # trial) would poison the digest with phantom diffs.
            import shutil

            shutil.rmtree(assert_dir)
        before = {_record_key(r) for r in _corpus(run_name)}
        t0 = time.monotonic()
        proc = subprocess.run(command, env=env,
                              stdout=subprocess.PIPE,
                              stderr=subprocess.DEVNULL)
        cmd_wall = time.monotonic() - t0
        new = _new_records(run_name, before)
        wall, mbps, n_parts = _trial_measurement(new, cmd_wall)
        if proc.stdout:
            # Bench convention: last stdout line is one JSON record.
            # Its headline value WINS over the corpus-record throughput
            # (input-MB/s vs output-bytes/s — the bench's own scale is
            # what baselines and TUNE_r*.json compare on); the corpus
            # stays the wall-clock source either way.
            try:
                doc = json.loads(
                    proc.stdout.decode("utf-8", "replace")
                    .strip().splitlines()[-1])
                metric = doc.get("metric") or metric
                if isinstance(doc.get("value"), (int, float)) \
                        and not isinstance(doc.get("value"), bool):
                    mbps = float(doc["value"])
            except (ValueError, IndexError, AttributeError):
                pass
        digest = dir_digest(assert_dir)
        trial = {
            "trial": idx, "knobs": knobs, "why": why,
            "wall_seconds": round(wall, 4) if wall is not None else None,
            "cmd_seconds": round(cmd_wall, 4),
            "mbps": mbps,
            "returncode": proc.returncode,
            "corpus_records_added": len(new),
        }
        if n_parts is not None:
            trial["n_partitions"] = n_parts
        if digest is not None:
            trial["digest"] = digest
            if idx == 0:
                baseline_digest = digest
            else:
                trial["byte_identical"] = digest == baseline_digest
        elif idx > 0 and baseline_digest is not None:
            # The baseline produced a witness and this trial did not
            # (the knob vector short-circuited the pipeline's output):
            # a trial with no output must never be crowned on its
            # near-zero wall.
            trial["byte_identical"] = False
        if proc.returncode != 0:
            trial["byte_identical"] = False
        results.append(trial)
        out("autotune trial {}: {} -> {}s{}{}".format(
            idx, knobs or "baseline config", trial["wall_seconds"],
            " ({} MB/s)".format(mbps) if mbps is not None else "",
            "" if trial.get("byte_identical", True)
            else "  DISQUALIFIED (output differs or nonzero exit)"))
        return trial

    trial0 = run_trial(0, {}, "baseline configuration")
    if trial0["returncode"] != 0:
        raise RuntimeError(
            "autotune: baseline trial exited {} — nothing to tune"
            .format(trial0["returncode"]))
    for i, cand in enumerate(candidate_vectors(run_name, n_trials - 1),
                             start=1):
        run_trial(i, cand["knobs"], cand["why"])
    report = _finish_report(run_name, session_id,
                            " ".join(command), results, trial0, metric)
    a = report["autotune"]
    out("autotune winner: trial {} ({}) {:.2f}x over baseline, "
        "byte_identical={}".format(
            a["winner"]["trial"], a["winner"]["knobs"] or "baseline",
            a["improvement"], a["byte_identical"]))
    return report


def tune_settings_session(measure, run_name, trials=None,
                          digest_of=None, out=None):
    """In-process autotune for bench drivers (``settings.autotune``).

    ``measure()`` executes the pipeline once under the CURRENT settings
    and returns ``(wall_seconds, result)``; candidate vectors are
    applied to :mod:`dampr_tpu.settings` attributes around each call
    (always restored).  ``digest_of(result)`` (optional) supplies the
    byte-exactness witness.  Returns ``(best_result, report)`` where
    ``best_result`` is the winning trial's ``measure()`` result."""
    out = out or (lambda msg: print(msg, file=sys.stderr, flush=True))
    n_trials = max(2, trials if trials is not None
                   else settings.autotune_trials)
    session_id = "autotune-inproc-{}".format(int(time.time()))
    results = []
    trial_results = {}
    baseline_digest = None

    def run_trial(idx, knobs, why):
        nonlocal baseline_digest
        saved = {k: getattr(settings, k) for k in knobs
                 if hasattr(settings, k)}
        for k, v in knobs.items():
            if hasattr(settings, k):
                setattr(settings, k, v)
        try:
            before = {_record_key(r) for r in _corpus(run_name)}
            wall, result = measure()
            new = _new_records(run_name, before)
        finally:
            for k, v in saved.items():
                setattr(settings, k, v)
        rec_wall, mbps, n_parts = _trial_measurement(new, wall)
        trial = {"trial": idx, "knobs": knobs, "why": why,
                 "wall_seconds": round(min(wall, rec_wall or wall), 4),
                 "mbps": mbps,
                 "corpus_records_added": len(new)}
        if n_parts is not None:
            trial["n_partitions"] = n_parts
        if digest_of is not None:
            digest = digest_of(result)
            if digest is not None:
                trial["digest"] = digest
            if idx == 0:
                baseline_digest = digest
            elif digest is not None:
                trial["byte_identical"] = digest == baseline_digest
            elif baseline_digest is not None:
                trial["byte_identical"] = False  # witness vanished
        results.append(trial)
        trial_results[idx] = result
        out("autotune trial {}: {} -> {}s".format(
            idx, knobs or "baseline config", trial["wall_seconds"]))
        return trial

    trial0 = run_trial(0, {}, "baseline configuration")
    for i, cand in enumerate(candidate_vectors(run_name, n_trials - 1),
                             start=1):
        run_trial(i, cand["knobs"], cand["why"])
    report = _finish_report(run_name, session_id, "<in-process>",
                            results, trial0)
    best = trial_results[report["autotune"]["winner"]["trial"]]
    return best, report


def main_autotune(args):
    """``dampr-tpu-doctor --autotune`` entry (argparse namespace from
    doctor.main)."""
    command = list(args.runs or ())
    if not command:
        print("doctor: --autotune needs the pipeline command after the "
              "run name: dampr-tpu-doctor RUN --autotune -- CMD ...",
              file=sys.stderr)
        return 2
    try:
        report = session(command, args.run, trials=args.trials,
                         assert_dir=args.assert_dir)
    except RuntimeError as e:
        print("doctor: {}".format(e), file=sys.stderr)
        return 2
    text = json.dumps(report, indent=2, sort_keys=True)
    if args.report:
        with open(args.report, "w") as f:
            f.write(text + "\n")
        print("autotune report written to {}".format(args.report),
              file=sys.stderr)
    if getattr(args, "json", False) or not args.report:
        print(text)
    a = report["autotune"]
    # Exit discipline: 0 = tuned (or already optimal) with every trial
    # byte-identical; 4 = a trial produced different bytes (the winner
    # never crowns such a trial, but the operator must know).
    return 0 if a["byte_identical"] else 4
