"""Live in-run progress reporter: one updating console line per stage.

``settings.progress`` (env ``DAMPR_TPU_PROGRESS=1``) makes every run
print a single stderr status line on ``settings.progress_interval_ms``
cadence::

    [stage 2/5 map] jobs 12/64 · 1.2M rec/s · 85.3 MB/s · backlog 3q/48MB · eta 0:42

- throughput (records/s, MB/s) is differenced from the metrics plane's
  ``store.records`` / ``store.bytes`` counters between ticks;
- spill backlog is the writer pool's live queue depth and in-flight
  bytes (the gauges the sampler also snapshots);
- ETA extrapolates the current stage's per-job rate over its remaining
  jobs — best effort, ``--:--`` until at least one job lands.

On a TTY the line redraws in place (``\\r``); non-interactive streams
(CI logs, piped benches) get one full line per tick so the history
reads as a coarse timeline.  The reporter is read-only: it consumes the
registry and a runner-maintained status dict, never touching engine
state, and its thread is a daemon — a wedged write can't hold a run's
teardown hostage.
"""

import logging
import sys
import threading
import time

log = logging.getLogger("dampr_tpu.obs.progress")


def _fmt_count(n):
    if n >= 1e9:
        return "{:.2f}G".format(n / 1e9)
    if n >= 1e6:
        return "{:.2f}M".format(n / 1e6)
    if n >= 1e3:
        return "{:.1f}k".format(n / 1e3)
    return "{:.0f}".format(n)


def _fmt_eta(secs):
    if secs is None or secs != secs or secs < 0 or secs > 99 * 3600:
        return "--:--"
    secs = int(secs)
    if secs >= 3600:
        return "{}:{:02d}:{:02d}".format(secs // 3600, (secs % 3600) // 60,
                                         secs % 60)
    return "{}:{:02d}".format(secs // 60, secs % 60)


class ProgressReporter(object):
    """Periodic status-line renderer for one run.

    ``status_fn`` returns the runner's live stage dict (stage id/kind,
    jobs done/total, stage start time); ``metrics`` supplies counters
    and pull gauges.  ``stream`` defaults to stderr.
    """

    def __init__(self, metrics, status_fn, interval_ms=500, stream=None):
        self.metrics = metrics
        self.status_fn = status_fn
        self.interval = max(50, int(interval_ms)) / 1000.0
        self.stream = stream if stream is not None else sys.stderr
        self._stop = threading.Event()
        self._thread = None
        self._last = None  # (t, records, bytes) for rate differencing
        self._wrote_inline = False
        self.lines = 0  # ticks rendered (tests observe this)

    # -- lifecycle ----------------------------------------------------------
    def start(self):
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="dampr-tpu-progress")
        self._thread.start()

    def stop(self):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
            self._thread = None
            if t.is_alive():
                log.warning(
                    "progress reporter thread %s did not stop within "
                    "2.0s at shutdown; abandoning it (daemon) — a "
                    "wedged stream write is still in flight", t.name)
        if self._wrote_inline:
            try:
                self.stream.write("\n")
                self.stream.flush()
            except Exception:
                pass

    # -- rendering ----------------------------------------------------------
    def _rates(self):
        m = self.metrics
        with m._mu:
            recs = m.counters.get("store.records", 0)
            nbytes = m.counters.get("store.bytes", 0)
        now = time.perf_counter()
        if self._last is None:
            self._last = (now, recs, nbytes)
            return 0.0, 0.0
        t0, r0, b0 = self._last
        dt = max(1e-6, now - t0)
        self._last = (now, recs, nbytes)
        return (recs - r0) / dt, (nbytes - b0) / dt

    def render_line(self):
        st = self.status_fn() or {}
        rec_s, bytes_s = self._rates()
        parts = ["[stage {}/{} {}]".format(
            st.get("sid", "?"), st.get("n_stages", "?"),
            st.get("kind", "?"))]
        total = st.get("jobs_total") or 0
        done = st.get("jobs_done") or 0
        if total:
            parts.append("jobs {}/{}".format(done, total))
        parts.append("{} rec/s".format(_fmt_count(rec_s)))
        parts.append("{:.1f} MB/s".format(bytes_s / 1e6))
        # Spill backlog: live pull of the writer-pool gauges (cheap; the
        # same callbacks the sampler evaluates).
        snap = self.metrics.snapshot()
        q = snap.get("writer.queue_depth", 0)
        inflight = snap.get("writer.inflight_bytes", 0)
        if q or inflight:
            parts.append("backlog {}q/{:.0f}MB".format(
                int(q), inflight / 1e6))
        eta = None
        t0 = st.get("stage_t0")
        if total and done and t0:
            elapsed = time.time() - t0
            eta = elapsed / done * (total - done)
        parts.append("eta {}".format(_fmt_eta(eta)))
        return " · ".join(parts)

    def _tick(self):
        line = self.render_line()
        self.lines += 1
        try:
            if self.stream.isatty():
                self.stream.write("\r\x1b[2K" + line)
                self._wrote_inline = True
            else:
                self.stream.write(line + "\n")
            self.stream.flush()
        except Exception:
            pass  # a closed/odd stream must never fail the run

    def _loop(self):
        while not self._stop.wait(self.interval):
            try:
                from .. import faults as _faults

                _faults.check("progress_tick")  # slow-stop tests
                self._tick()
            except Exception:
                pass
