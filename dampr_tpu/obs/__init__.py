"""Observability: run-scoped trace spans + per-run metrics.

Enable with ``settings.trace = True`` (env ``DAMPR_TPU_TRACE=1``).  Off
— the default — every instrumentation site costs one module-global
``None`` check, so the engine's hot loops are unaffected.  On, each run
records spans at the hot engine boundaries and persists two artifacts
under ``<scratch_root>/<run>/trace/`` (``settings.trace_dir`` overrides
the root):

**trace.json — the timeline.**  Chrome trace-event JSON (the JSON Array
Format with a ``traceEvents`` envelope).  Load it in Perfetto: open
https://ui.perfetto.dev and drag the file in (chrome://tracing works
too).  Lanes (``tid`` + ``thread_name`` metadata) map to the engine's
concurrency units: one track per map slot (pool worker), per overlapped
codec producer thread, per reduce worker, per merge generation.  Span
categories (event ``cat``):

- ``codec`` — one span per produced codec window (decompress + tokenize/
  parse) on the producer thread's lane;
- ``fold`` — map-side partial/final segment folds;
- ``stall`` — a fold consumer blocked on its producer (the per-slot view
  of devtime's ``codec_wait`` union);
- ``spill`` / ``hbm`` — budget-pressure block spills (on the background
  writer pool's lanes when ``settings.spill_write_threads`` > 0); HBM
  h2d puts and device->host offloads;
- ``spill_queue`` — a queued write's enqueue->write-start latency on its
  writer lane (how long the spill sat behind the pool's backlog);
- ``io_wait`` — a fold/register thread blocked on writer-pool
  backpressure (``writer-backpressure``), or a merge/final-read consumer
  outrunning its frame prefetch (``read-wait``);
- ``merge`` — spill-lean merge generations, streamed merge runs, k-way
  read rounds, compaction markers;
- ``collective`` — mesh keyed folds, byte exchanges, global sums;
- ``checkpoint`` — resume persist/restore/plan/gc decisions;
- ``job`` / ``stage`` — per-job spans on worker lanes; one span per
  stage on the ``stages`` lane;
- ``retry`` — instant markers for re-executed jobs.

The emitted subset is documented (and CI-validated) by
``docs/trace_schema.json`` + ``tools/validate_trace.py``.

**stats.json — the summary** (schema ``dampr-tpu-stats/1``), also
returned in-memory from every run — traced or not — via
``ValueEmitter.stats()``:

- ``stages[]`` — per stage: ``kind``, ``jobs``, ``records_in/out``,
  ``bytes_in/out``, ``spill_count``/``spill_bytes`` (causal attribution:
  charged to the stage whose pressure evicted the block),
  ``merge_gens``/``merge_gen_bytes``, ``retries``, ``seconds``;
- ``devtime`` — run-scoped device/transfer/codec/codec_wait seconds
  (epoch/delta snapshots of :mod:`dampr_tpu.ops.devtime`);
- ``overlap`` — configured windows, ``stall_fraction`` (codec_wait /
  wall: the codec time still on the critical path), peak in-flight bytes;
- ``io`` — the async spill subsystem's shape: ``spill_write_bytes/
  seconds/mbps`` (post-codec disk bandwidth, writer-pool thread-seconds),
  ``spill_read_bytes/seconds/mbps`` (frame reads + inflate),
  ``io_wait_seconds/fraction`` (total) and ``io_wait_write_seconds/
  fraction`` (fold-side writer backpressure only — the stall the pool
  exists to eliminate), ``writer_threads``, ``read_prefetch``,
  ``inflight_peak_bytes``;
- ``store`` — spill/merge/HBM-tier totals; ``mesh`` — collective fold/
  exchange counts and bytes; ``retries``; ``totals``;
- ``trace_file`` / ``stats_file`` — artifact paths (None untraced).

Surfacing: ``dampr-tpu-stats <run>`` pretty-prints a persisted summary;
``dampr-tpu-wc`` / ``dampr-tpu-tfidf`` accept ``--stats``; the TF-IDF
bench emits per-trial spill/trace info and the artifact paths in its
JSON line.

**The live metrics plane** (``settings.metrics_interval_ms`` /
``DAMPR_TPU_METRICS_MS``; traced runs sample at 100 ms even when unset):
a run-scoped registry (:mod:`.metrics`: counters, gauges, histograms —
off costs one None-check per site, same contract as tracing) whose
background sampler (:mod:`.sampler`) snapshots the load-bearing gauges
— budget occupancy, writer-pool queue depth/in-flight bytes, overlap
slots live/stalled, HBM residency, records/bytes throughput, merge
fan-in — into an in-memory time series.  Consumers:

- **counter tracks**: the series embed in ``trace.json`` as Chrome
  ``"ph":"C"`` events, so Perfetto renders each gauge as a counter
  track under the span lanes;
- **live progress** (:mod:`.progress`, ``settings.progress`` /
  ``DAMPR_TPU_PROGRESS=1``): one updating console line per stage —
  records/s, MB/s, spill backlog, ETA;
- **Prometheus text** (:mod:`.promtext`): ``dampr-tpu-stats --prom``
  renders a completed run in text-exposition format; ``render()``
  behind any HTTP handler serves a live one;
- **flight recorder** (:mod:`.flightrec`): a bounded ring of recent
  spans + samples, flushed to ``<run>/trace/crashdump.json`` on the
  kill/exception path — a schema-valid mini-trace (Perfetto-loadable,
  ``tools/validate_trace.py``-checked) whose last samples show e.g. the
  writer-pool queue state at death.  ``dampr-tpu-stats`` exits non-zero
  on a run directory containing one.

``stats()`` gains a ``metrics`` section (final counters, per-series
last/peak, histogram summaries) including the sampler's self-accounting:
sample count, series drops, and the ``overhead`` self-metric (sampler
wall / run wall — the plane measures its own cost).

**The diagnosis layer** (attribution + analysis over everything above):

- **per-operator profiler** (:mod:`.profile`, ``settings.profile`` /
  ``DAMPR_TPU_PROFILE=1``): fused stages attribute wall time and record
  counts to the INDIVIDUAL user ops they were built from (plan fusion
  rides provenance on the fused node); device stages decompose into
  build/h2d/compute/d2h sub-phases; ``stats()`` gains a ``profile``
  section with per-stage coverage.  Off = one None-check per site,
  hoisted to one per job in the hot loops.
- **critical-path analysis** (:mod:`.critpath`): walks the span
  timeline and names the resource that bounds each stage's wall window
  (codec / fold / spill-queue / io-read / merge / device / transfer /
  overlap-stall / mesh / host-compute) via wall-clock interval unions —
  ``stats()["critpath"]`` carries a dominant-bottleneck verdict per
  executed stage and for the whole run.
- **run-history corpus** (:mod:`.history`): every finalized run appends
  one compact record (plan fingerprint + shapes, per-stage IO, critpath
  verdicts, per-op profile, settings snapshot) to a bounded, crash-safe
  JSONL under ``<scratch_root>/<run>/history.jsonl``; ``plan/cost.py``
  adapts from medians over matching runs instead of one stats.json.
- **doctor** (:mod:`.doctor`, ``dampr-tpu-doctor``): reads a run's
  artifacts back and prints a ranked diagnosis — each finding ties a
  bottleneck verdict to concrete ``settings`` knobs; ``--diff A B``
  compares runs, ``--json`` emits the ``docs/doctor_schema.json``
  report.

**The fleet plane** (multi-process runs — docs/parallel.md):

- **rank-tagged telemetry**: every artifact carries a ``process``
  block (``process_id``/``num_processes`` via ``mesh.rank_info()``);
  rank 0 keeps the legacy ``<run>/trace/`` layout, rank k writes
  ``<run>/trace/rank<k>/``, and a killed non-zero rank's crashdump
  lands as ``crashdump.rank<k>.json`` (``dampr-tpu-stats`` scans every
  rank's dump for its exit-3 detection);
- **merged timeline** (:mod:`.fleet`): per-rank traces fold into one
  Perfetto document (one process lane per rank) aligned on the
  ``init_distributed`` barrier-timestamp handshake — no wall-clock
  trust; ``stats()["fleet"]`` carries per-rank totals, the rank x rank
  exchange send/recv matrices, and per-collective-step skew
  (entry-spread over step wall), which names the ``straggler_rank``;
- **straggler diagnosis**: :mod:`.critpath` gains the ``skew``
  resource (injected post-merge via ``apply_skew``) and
  ``dampr-tpu-doctor`` emits fleet verdicts mapping skew to concrete
  knobs; ``dampr-tpu-stats --fleet`` renders (and idempotently
  re-merges) the section;
- **live metrics endpoint** (:mod:`.serve`, ``settings.metrics_port``):
  a stdlib HTTP thread per rank serving ``/metrics`` (Prometheus text,
  rank-labeled) and ``/healthz`` while the run is in flight — rank k
  binds ``metrics_port + k``.

The consolidated guide — schemas, Perfetto counter-track how-to,
Prometheus scrape example, crashdump shape, the diagnosis taxonomy,
the fleet layer, the CI perf gate — is ``docs/observability.md``.

For a profiler-grade XLA kernel timeline (HLO names, TPU counters) use
the existing escape hatch instead: ``settings.profile_dir`` wraps the
run in ``jax.profiler.trace`` for TensorBoard/xprof.

Layering: :mod:`.trace` is the span recorder (``Tracer``, module-level
``span``/``instant``/``complete``/``timed_iter``); :mod:`.metrics` is
the metric registry (module-level ``counter_add``/``gauge_set``/
``observe``/``register_gauge``); :mod:`.sampler`, :mod:`.progress`,
:mod:`.promtext`, :mod:`.flightrec` consume it; :mod:`.export`
serializes (``write_trace``, ``write_stats``, ``load_stats``,
``format_summary``, ``load_series``).  ``MTRunner.run`` owns the
lifecycle: it starts tracer/registry/sampler/recorder, builds the
summary either way, and persists the files for traced runs.
"""

from .trace import Tracer, complete, enabled, instant, now, span  # noqa: F401
from . import export  # noqa: F401
from . import metrics  # noqa: F401
