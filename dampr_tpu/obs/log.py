"""Structured, run-scoped log stream: coded JSONL events for postmortems.

The engine's operational warnings used to be stdlib ``log.warning`` text
scattered across modules — grep-able by a human, useless to tooling.
This module gives every such site a **coded, structured** record::

    {"ts": ..., "level": "warn", "rank": 0, "run": "bench-tfidf",
     "stage": 3, "code": "writer-pool-stuck", "msg": "...", "data": {...}}

appended to ``<run>/trace/events.jsonl`` under the same durability
contract as ``history.jsonl`` (one ``O_APPEND`` write per line — a run
that dies mid-write corrupts at most its own line; tolerant line-validated
reads; bounded by ``settings.log_events_max`` via tmp + atomic-rename
compaction).  This is the per-tenant event log ROADMAP item 1's
``dampr-tpu-serve`` daemon will serve; on batch runs it feeds
``dampr-tpu-stats --log`` and rides the flight recorder into
``crashdump.json`` (WARN+ tail).

Design constraints, in the tracer's order:

1. **Near-zero cost off.**  With no active stream, :func:`debug` /
   :func:`info` are one module-global load + ``None`` check;
   :func:`warn` / :func:`error` additionally forward to the stdlib
   logger they always reached (the pre-existing behavior of the
   migrated sites), so nothing is ever silenced by the stream being off.
2. **Closed event-code registry.**  Every code passed to an emit call in
   the package source must be declared in :data:`EVENT_CODES` and
   documented in ``docs/observability.md`` — enforced by
   ``tools/lint_repo.py`` (same pattern as trace span kinds).  Tooling
   can then match on codes forever; message text stays free to improve.
3. **Crash-visible.**  WARN+ records mirror into the flight recorder's
   bounded log tail (when one is attached), so ``crashdump.json``
   carries the last operational events even for a run that never
   streamed to disk.

Scope: the active stream is process-global (runs own it run-scoped via
``start``/``stop``), the same nesting contract as the tracer.
"""

import json
import logging
import os
import threading
import time

from .. import settings

_stdlog = logging.getLogger("dampr_tpu.obs.log")

FILE = "events.jsonl"

#: Leveled severities, stdlib-aligned.
LEVELS = {"debug": 10, "info": 20, "warn": 30, "error": 40}

#: Closed registry of structured event codes: ``code -> one-line
#: meaning``.  ``tools/lint_repo.py`` fails CI when an emit site uses an
#: undeclared code, when a declared code has no emit site left (dead
#: entry), or when a code is missing from docs/observability.md's event
#: table.  Codes are stable tool-facing identifiers — never rename one
#: that shipped; add a new one and retire the old entry with its last
#: call site.
EVENT_CODES = {
    # -- lifecycle -----------------------------------------------------------
    "run-start": "a run began executing under this name",
    "run-finish": "a run finalized cleanly (wall seconds in data)",
    "run-failed": "a run died; the flight recorder flushes its crashdump",
    # -- shutdown thread joins -----------------------------------------------
    "writer-pool-stuck": "a spill writer thread failed to join at close "
                         "(daemon abandoned; wedged codec or disk write)",
    "overlap-producer-stuck": "an overlapped codec producer thread failed "
                              "to join at shutdown",
    "early-fold-stuck": "the early-fold worker failed to drain at stage "
                        "end; unfolded mappings used",
    # -- degraded execution paths --------------------------------------------
    "early-fold-error": "an early-fold attempt raised; folding disabled "
                        "for the stage (originals kept)",
    "codec-fallback": "a configured compression codec is unavailable; "
                      "encoding fell down the zstd->lz4->zlib ladder",
    "shared-state-udf": "a stateful UDF object could not be deep-copied; "
                        "the instance is shared across concurrent jobs",
    # -- straggler mitigation ------------------------------------------------
    "mitigation-engaged": "skew mitigation engaged: collective exchanges "
                          "degrade in place",
    "mitigation-disengaged": "skew mitigation disengaged after healthy "
                             "probe windows",
    "mitigation-downweight": "a pathological rank's partition share was "
                             "down-weighted for the rest of the run",
    "mitigation-unsafe-skip": "mitigation engaged but window skipping is "
                              "disabled (exchange watchdog off)",
    # -- metrics endpoint ----------------------------------------------------
    "metrics-port-fallback": "the per-rank /metrics port was taken; the "
                             "endpoint bound the next free port",
    "metrics-bind-failed": "no /metrics port could be bound; the endpoint "
                           "is disabled for this run",
    # -- telemetry plane -----------------------------------------------------
    "sentry-regression": "the regression sentry flagged a metric against "
                         "its per-fingerprint baseline window",
    # -- serve daemon (dampr_tpu.serve) --------------------------------------
    "serve-submit": "the serve daemon received a submission from a tenant",
    "serve-admit": "a submission passed the admission gate and reserved "
                   "its byte cost against the tenant budget",
    "serve-reject": "a submission was refused at the door (wire error, "
                    "validation failure, budget, queue depth, or drain)",
    "serve-coalesce": "an identical in-flight fingerprint: the submission "
                      "attached as a follower of the running primary",
    "serve-evict": "retired job records past the retention bound were "
                   "evicted from the daemon's job table",
    "serve-drain": "the daemon began draining: finishing admitted jobs, "
                   "rejecting new submissions",
}


class LogStream(object):
    """One run's structured event stream.

    ``path=None`` runs the stream in recorder-only mode: nothing lands
    on disk, but WARN+ records still mirror into the attached flight
    recorder's log tail (how an untraced-but-metered run gets a crash
    log tail without paying file IO per event).
    """

    def __init__(self, run_name, rank=0, level="info", path=None,
                 recorder=None, capacity=None):
        self.run = run_name
        self.rank = int(rank or 0)
        self.min_level = LEVELS.get(str(level).lower(), LEVELS["info"])
        self.path = path
        self.recorder = recorder
        self.capacity = (settings.log_events_max if capacity is None
                         else int(capacity))
        if self.capacity <= 0:
            self.path = None  # bound of 0 = no on-disk stream
        self.counts = {}      # level name -> records accepted
        self.dropped = 0      # records lost to append failures
        self._appends = 0     # appends since the last compaction check
        self._lock = threading.Lock()

    # -- record path ---------------------------------------------------------
    def emit(self, level, code, msg, stage=None, data=None):
        """Append one structured record (best-effort: a failing event
        log must never fail the run it describes).  Returns the record
        dict, or None when the level is below the stream's floor."""
        lvl = LEVELS.get(level, LEVELS["info"])
        rec = None
        if lvl >= self.min_level:
            rec = {
                "ts": round(time.time(), 3),
                "level": level,
                "rank": self.rank,
                "run": self.run,
                "stage": stage,
                "code": code,
                "msg": msg,
            }
            if data:
                rec["data"] = data
            self.counts[level] = self.counts.get(level, 0) + 1
            if self.path is not None:
                self._append(rec)
        if lvl >= LEVELS["warn"]:
            rec_mirror = rec
            if rec_mirror is None:
                # Level floor above warn never happens (error > warn),
                # but a stream floored at "error" must still mirror the
                # warn into the crash tail — build the record for the
                # ring only.
                rec_mirror = {"ts": round(time.time(), 3), "level": level,
                              "rank": self.rank, "run": self.run,
                              "stage": stage, "code": code, "msg": msg}
                if data:
                    rec_mirror["data"] = data
            recorder = self.recorder
            if recorder is not None:
                recorder.record_log(rec_mirror)
        return rec

    def _append(self, rec):
        try:
            line = json.dumps(rec, sort_keys=True,
                              separators=(",", ":"), default=str)
            if "\n" in line:   # a pathological repr leaked a newline:
                self.dropped += 1  # refuse to corrupt the line index
                return
            with self._lock:
                fd = os.open(self.path,
                             os.O_WRONLY | os.O_APPEND | os.O_CREAT, 0o644)
                try:
                    os.write(fd, (line + "\n").encode("utf-8"))
                finally:
                    os.close(fd)
                self._appends += 1
                # Compaction check is a whole-file read: amortize it.
                if self._appends >= max(64, self.capacity // 8):
                    self._appends = 0
                    self._compact_if_over()
        except Exception:
            self.dropped += 1

    def _compact_if_over(self):
        """Keep the newest ``capacity`` valid lines (tmp + atomic
        replace; called under the stream lock)."""
        try:
            with open(self.path, "r", encoding="utf-8",
                      errors="replace") as f:
                lines = f.readlines()
        except OSError:
            return
        if len(lines) <= self.capacity:
            return
        keep = [ln for ln in lines
                if valid_line(ln) is not None][-self.capacity:]
        tmp = self.path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.writelines(keep)
        os.replace(tmp, self.path)

    # -- summary -------------------------------------------------------------
    def summary(self):
        """The ``stats()["log"]`` section."""
        out = {"level": {v: k for k, v in LEVELS.items()}[self.min_level],
               "counts": dict(sorted(self.counts.items())),
               "records": sum(self.counts.values())}
        if self.path is not None:
            out["file"] = self.path
        if self.dropped:
            out["dropped"] = self.dropped
        return out


# -- reading back ------------------------------------------------------------

def valid_line(line):
    """Parse one events.jsonl line, or None (tolerant reads: corruption
    degrades to fewer events, never a raise)."""
    line = line.strip()
    if not line:
        return None
    try:
        rec = json.loads(line)
    except ValueError:
        return None
    if not isinstance(rec, dict) or not isinstance(rec.get("code"), str):
        return None
    if rec.get("level") not in LEVELS:
        return None
    return rec


def stream_path(run_name, rank=0):
    """Where a run's event stream lives (next to trace.json)."""
    from . import export as _export

    return os.path.join(_export.run_trace_dir(run_name, rank=rank), FILE)


def tail(run_or_path, n=20, min_level=None, rank=0):
    """The last ``n`` valid records of a run's event stream (optionally
    floored at ``min_level``), oldest -> newest.  Never raises."""
    path = run_or_path
    if not os.path.isfile(path):
        path = stream_path(run_or_path, rank=rank)
    if not os.path.isfile(path):
        return []
    floor = LEVELS.get(min_level, 0) if min_level else 0
    out = []
    try:
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            for line in f:
                rec = valid_line(line)
                if rec is not None and LEVELS[rec["level"]] >= floor:
                    out.append(rec)
    except OSError:
        return []
    return out[-n:] if n else out


def format_tail(records):
    """Human-readable event-tail lines for ``dampr-tpu-stats --log``."""
    if not records:
        return "no structured log events (enable with DAMPR_TPU_LOG=info)"
    lines = []
    for rec in records:
        t = time.strftime("%H:%M:%S", time.localtime(rec.get("ts", 0)))
        stage = rec.get("stage")
        lines.append("{} {:<5} r{}{} [{}] {}".format(
            t, rec.get("level", "?").upper(), rec.get("rank", 0),
            " s{}".format(stage) if stage is not None else "",
            rec.get("code", "?"), rec.get("msg", "")))
    return "\n".join(lines)


# -- module-level API (the instrumentation surface) --------------------------

#: The active stream or None.  Read unlocked on the hot path; start/stop
#: mutate under _lock (same contract as trace._active).
_active = None
_lock = threading.Lock()


def start(stream):
    global _active
    with _lock:
        _active = stream


def stop(stream):
    global _active
    with _lock:
        if _active is stream:
            _active = None


def active():
    return _active


def enabled():
    return _active is not None


def _render(msg, args):
    if not args:
        return msg
    try:
        return msg % args
    except (TypeError, ValueError):
        return msg


def debug(code, msg, *args, **kw):
    """Debug-level structured event.  One None-check when no stream is
    active — safe on hot paths."""
    s = _active
    if s is None:
        return
    s.emit("debug", code, _render(msg, args),
           stage=kw.pop("stage", None), data=kw or None)


def info(code, msg, *args, **kw):
    s = _active
    if s is None:
        return
    s.emit("info", code, _render(msg, args),
           stage=kw.pop("stage", None), data=kw or None)


def warn(code, msg, *args, **kw):
    """Warn-level event: ALWAYS reaches the stdlib logger (``logger=``
    names the emitting module's logger so existing log routing and
    capture keep working), plus the structured stream when active."""
    logger = kw.pop("logger", None) or _stdlog
    exc_info = kw.pop("exc_info", False)
    logger.warning(msg, *args, exc_info=exc_info)
    s = _active
    if s is None:
        return
    s.emit("warn", code, _render(msg, args),
           stage=kw.pop("stage", None), data=kw or None)


def error(code, msg, *args, **kw):
    logger = kw.pop("logger", None) or _stdlog
    exc_info = kw.pop("exc_info", False)
    logger.error(msg, *args, exc_info=exc_info)
    s = _active
    if s is None:
        return
    s.emit("error", code, _render(msg, args),
           stage=kw.pop("stage", None), data=kw or None)
