"""Serialize a run's observability artifacts.

Two files, written side by side under the run's trace directory
(``<scratch_root>/<run>/trace/`` by default, ``settings.trace_dir``
overrides the root):

- ``trace.json`` — Chrome trace-event format (the JSON Array Format with a
  ``traceEvents`` envelope), loadable in Perfetto (ui.perfetto.dev) or
  chrome://tracing.  Span categories map to event ``cat``; lanes map to
  ``tid`` with ``thread_name`` metadata, so each map slot / codec producer
  / reduce worker / merge generation renders as its own track.
- ``stats.json`` — the per-run summary (schema ``dampr-tpu-stats/1``):
  per-stage records/bytes in+out, spill volume, merge generations, retry
  counts, run-scoped devtime buckets, overlap stall fraction, store/mesh
  totals, and span aggregates.

The checked-in ``docs/trace_schema.json`` documents (and CI validates) the
trace-event subset this module emits.
"""

import json
import os
import time

from .. import settings

STATS_SCHEMA = "dampr-tpu-stats/1"
TRACE_FILE = "trace.json"
STATS_FILE = "stats.json"


def run_trace_dir(run_name, rank=None):
    """Where a run's artifacts live.  Mirrors RunStore's scratch layout so
    the trace sits next to the run's durable spill/checkpoint outputs.

    Multi-process runs write PER-RANK artifacts: rank 0 keeps the legacy
    ``<run>/trace/`` path (single-process layouts — and every tool that
    reads them — are unchanged), non-zero ranks write under
    ``<run>/trace/rank<k>/``.  ``rank=None`` resolves the calling
    process's own rank via :func:`dampr_tpu.parallel.mesh.rank_info`
    (env/process-group based — never forces a jax init)."""
    safe = run_name.replace("/", "_")
    root = settings.trace_dir or settings.scratch_root
    base = os.path.join(root, safe, "trace")
    if rank is None:
        from ..parallel.mesh import rank_info

        rank = rank_info()[0]
    if rank and rank > 0:
        return os.path.join(base, "rank{}".format(int(rank)))
    return base


def process_section():
    """The ``process`` block stamped into every artifact (stats.json,
    trace otherData, crashdumps, history records): rank identity plus
    the clock-handshake anchor the fleet merge aligns timelines with.
    Once a process group is up (jax already initialized) the device
    shape rides along — the authoritative device->rank mapping for the
    fleet exchange matrices; before that the block stays jax-free."""
    from ..parallel import mesh

    pid, n = mesh.rank_info()
    sec = {"process_id": pid, "num_processes": n}
    if mesh._initialized:
        try:
            import jax

            sec["global_devices"] = len(jax.devices())
            sec["local_devices"] = len(jax.local_devices())
        except Exception:
            pass
    if mesh.clock_sync is not None:
        sec["clock"] = dict(mesh.clock_sync)
    return sec


def chrome_events(tracer):
    """Convert a Tracer's compact event tuples into Chrome trace events."""
    pid = 1
    out = [{"ph": "M", "pid": pid, "tid": 0, "name": "process_name",
            "args": {"name": "dampr_tpu:{}".format(tracer.run)}}]
    # Stable small tids: Perfetto sorts tracks by tid, so number lanes in
    # first-seen order instead of leaking giant thread idents.
    tid_of = {}
    for lane, lname in tracer.lane_names.items():
        tid = tid_of.setdefault(lane, len(tid_of) + 1)
        out.append({"ph": "M", "pid": pid, "tid": tid,
                    "name": "thread_name", "args": {"name": lname}})
    for cat, name, t0, dur, lane, args in tracer.events:
        tid = tid_of.setdefault(lane, len(tid_of) + 1)
        ev = {"name": name, "cat": cat, "pid": pid, "tid": tid,
              "ts": round(t0 * 1e6, 3)}
        if dur is None:
            ev["ph"] = "i"
            ev["s"] = "t"
        else:
            ev["ph"] = "X"
            ev["dur"] = round(dur * 1e6, 3)
        if args:
            ev["args"] = args
        out.append(ev)
    return out


def counter_events(metrics, pid=1):
    """Metrics time series -> Chrome counter-track events (``"ph":"C"``).

    One event per (series, sample): Perfetto groups events sharing a
    counter ``name`` into one counter track rendered under the span
    lanes, so gauge history (budget occupancy, queue depth, throughput
    counters) lines up against the timeline that caused it.  Timestamps
    are the sampler's, relative to the metrics epoch — the runner aligns
    that epoch with the tracer's so both clocks agree in one file."""
    out = []
    with metrics._mu:
        series = {name: list(s) for name, s in metrics.series.items()}
    for name in sorted(series):
        for t, v in series[name]:
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                continue
            # Epoch alignment clamp: the runner points the registry at
            # the tracer's (earlier) epoch, but a registry whose first
            # sample landed before that re-point — or an independently
            # constructed Metrics whose epoch postdates a recorded tick
            # — would yield a NEGATIVE relative timestamp here, which
            # Chrome/Perfetto renders as a broken counter track and the
            # schema validator rejects.  Clamp to the run origin; the
            # sample still carries its value, just pinned to t=0.
            out.append({"ph": "C", "name": name, "cat": "metric",
                        "pid": pid, "tid": 0,
                        "ts": max(0.0, round(t * 1e6, 3)),
                        "args": {"value": v}})
    return out


def write_trace(tracer, path, metrics=None):
    events = chrome_events(tracer)
    if metrics is not None:
        events.extend(counter_events(metrics))
    # Rank-tagged: the process block carries this rank's identity and —
    # when the clock handshake ran — its epoch + barrier anchors, which
    # is everything obs.fleet needs to place this file's events on the
    # fleet-common timeline (epoch_perf + ts_seconds - barrier_perf).
    proc = process_section()
    proc["epoch_perf"] = tracer.epoch
    doc = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "run": tracer.run,
            "wall_start": tracer.wall_start,
            "producer": "dampr_tpu.obs",
            "process": proc,
        },
    }
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
    return path


def write_stats(summary, path):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(summary, f, indent=2, sort_keys=True, default=str)
    os.replace(tmp, path)
    return path


def locate_stats(run):
    """Resolve a run name / run directory / stats.json path to the stats
    file.  Returns the path or None."""
    cands = []
    if os.path.isfile(run):
        cands.append(run)
    if os.path.isdir(run):
        cands.append(os.path.join(run, STATS_FILE))
        cands.append(os.path.join(run, "trace", STATS_FILE))
    cands.append(os.path.join(run_trace_dir(run), STATS_FILE))
    for c in cands:
        if os.path.isfile(c):
            return c
    return None


def load_stats(run):
    """(summary dict, path) for a run name/dir/file, or (None, None)."""
    path = locate_stats(run)
    if path is None:
        return None, None
    with open(path) as f:
        return json.load(f), path


def load_series(trace_path):
    """Read the counter (``"ph":"C"``) events back out of a persisted
    trace.json / crashdump.json: ``{series_name: [(ts_seconds, value)]}``.
    The inverse of :func:`counter_events`, used by ``dampr-tpu-stats
    --series``."""
    with open(trace_path) as f:
        doc = json.load(f)
    series = {}
    for ev in doc.get("traceEvents", ()):
        if ev.get("ph") != "C":
            continue
        args = ev.get("args") or {}
        v = args.get("value")
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            continue
        series.setdefault(ev.get("name", "?"), []).append(
            (float(ev.get("ts", 0)) / 1e6, v))
    for s in series.values():
        s.sort(key=lambda tv: tv[0])
    return series


_SPARK = "▁▂▃▄▅▆▇█"


def _sparkline(values, width=24):
    if not values:
        return ""
    if len(values) > width:
        # strided downsample keeps first and last
        idx = [i * (len(values) - 1) // (width - 1) for i in range(width)]
        values = [values[i] for i in idx]
    lo, hi = min(values), max(values)
    if hi <= lo:
        return _SPARK[0] * len(values)
    return "".join(_SPARK[int((v - lo) / (hi - lo) * (len(_SPARK) - 1))]
                   for v in values)


def format_series(series):
    """Human-readable table of sampled time series (the ``--series``
    view): per series the sample count, min/mean/max/last, and a
    sparkline of the downsampled history."""
    if not series:
        return ("no counter samples in this trace (metrics plane off — "
                "enable with settings.metrics_interval_ms / "
                "DAMPR_TPU_METRICS_MS)")
    lines = []
    name_w = max(len(n) for n in series)
    lines.append("{:<{w}} {:>7} {:>12} {:>12} {:>12} {:>12}  {}".format(
        "series", "samples", "min", "mean", "max", "last", "history",
        w=name_w))
    for name in sorted(series):
        vals = [v for _t, v in series[name]]
        lines.append(
            "{:<{w}} {:>7} {:>12.6g} {:>12.6g} {:>12.6g} {:>12.6g}  {}"
            .format(name, len(vals), min(vals), sum(vals) / len(vals),
                    max(vals), vals[-1], _sparkline(vals), w=name_w))
    return "\n".join(lines)


def format_pipeline_series(summary):
    """The streamed-edge view of ``--series``: per-stage queue-depth
    sparklines from ``stats()["pipeline"]["queue_depth_series"]`` (the
    folder-side backlog each streamed edge carried over time), the
    stall/overlap bottom line, and the exchange overlap counters.
    Returns "" when the run streamed nothing (staged execution)."""
    pipe = summary.get("pipeline") or {}
    series = pipe.get("queue_depth_series") or []
    lines = []
    if series:
        by_sid = {}
        for sid, _t, nbytes in series:
            by_sid.setdefault(sid, []).append(nbytes)
        lines.append("streamed-edge queue depth (bytes):")
        for sid in sorted(by_sid):
            vals = by_sid[sid]
            lines.append(
                "  stage {:<3} {:>9} peak {:>9} last  {}".format(
                    sid, _mb(max(vals)), _mb(vals[-1]),
                    _sparkline(vals)))
    if pipe.get("executed") or pipe.get("degraded"):
        lines.append(
            "pipeline: executed={} degraded={} overlap={:.2f}s "
            "({:.0%} of fold) stall={:.2f}s queue_peak={}".format(
                pipe.get("executed", 0), pipe.get("degraded", 0),
                pipe.get("overlap_seconds", 0.0),
                pipe.get("overlap_fraction", 0.0),
                pipe.get("stall_seconds", 0.0),
                _mb(pipe.get("queue_peak_bytes", 0))))
    ex = (summary.get("mesh") or {}).get("exchange") or {}
    if ex.get("steps"):
        lines.append(
            "exchange: steps={} bytes={} peak_inflight={}".format(
                ex.get("steps", 0), _mb(ex.get("bytes", 0)),
                _mb(ex.get("peak_inflight_bytes", 0))))
    ov = summary.get("overlap") or {}
    if ov.get("windows"):
        lines.append(
            "overlap: windows={} stall_fraction={:.3f}".format(
                ov.get("windows", 0), ov.get("stall_fraction", 0.0)))
    return "\n".join(lines)


def _mb(n):
    return "{:.1f} MB".format(n / 1e6)


def format_summary(summary):
    """Human-readable rendering of a stats.json summary (the
    ``dampr-tpu-stats`` CLI and the workload ``--stats`` flags)."""
    lines = []
    add = lines.append
    add("run: {}  ({:.2f}s wall, {} stages)".format(
        summary.get("run", "?"), summary.get("wall_seconds", 0.0),
        len(summary.get("stages", []))))
    started = summary.get("started_at")
    if started:
        add("started: {}".format(
            time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(started))))
    add("")
    add("{:>5} {:<12} {:>5} {:>12} {:>12} {:>10} {:>10} {:>10} {:>8}".format(
        "stage", "kind", "jobs", "rec_in", "rec_out", "bytes_in",
        "bytes_out", "spill", "secs"))
    for st in summary.get("stages", []):
        add("{:>5} {:<12} {:>5} {:>12} {:>12} {:>10} {:>10} {:>10} {:>8}"
            .format(st.get("stage", "?"), st.get("kind", "?"),
                    st.get("jobs", 0), st.get("records_in", 0),
                    st.get("records_out", 0), _mb(st.get("bytes_in", 0)),
                    _mb(st.get("bytes_out", 0)),
                    _mb(st.get("spill_bytes", 0)),
                    "{:.2f}".format(st.get("seconds", 0.0))))
    plan = summary.get("plan") or {}
    if plan.get("enabled"):
        fired = {k: v for k, v in sorted((plan.get("rules") or {}).items())
                 if v}
        line = "plan: {} -> {} stages".format(
            plan.get("stages_before", "?"), plan.get("stages_after", "?"))
        if fired:
            line += "  ({})".format(", ".join(
                "{}={}".format(k, v) for k, v in fired.items()))
        ad = plan.get("adaptive") or {}
        if ad.get("applied"):
            line += "  · adaptive: {} change(s)".format(len(
                ad.get("changes", ())))
        add(line)
        cst = plan.get("cost") or {}
        if cst.get("enabled"):
            applied = [c for c in cst.get("choices") or ()
                       if c.get("applied")]
            line = "cost model: {} knob choice(s) applied".format(
                len(applied))
            for c in applied:
                line += "  · {}: {} -> {}".format(
                    c.get("knob"), c.get("static"), c.get("chosen"))
            pred = cst.get("predicted") or {}
            if pred.get("mbps"):
                line += "  · predicted {} MB/s (static {})".format(
                    pred["mbps"], pred.get("static_mbps"))
            add(line)
        elif cst.get("reason"):
            add("cost model: {} (source {})".format(
                cst["reason"], cst.get("source")))
    elif plan:
        add("plan: optimizer off (graph executed as constructed)")
    store = summary.get("store", {})
    add("")
    add("spill: {} blocks / {}  ·  merge generations: {} ({})".format(
        store.get("spill_count", 0), _mb(store.get("spilled_bytes", 0)),
        store.get("merge_gens", 0), _mb(store.get("merge_gen_bytes", 0))))
    io = summary.get("io", {})
    if io.get("spill_write_bytes") or io.get("spill_read_bytes"):
        line = ("spill io: wrote {} @ {:.0f} MB/s · read {} @ {:.0f} MB/s "
                "· io_wait {:.2f}s ({:.1%} of wall)".format(
                    _mb(io.get("spill_write_bytes", 0)),
                    io.get("spill_write_mbps", 0.0),
                    _mb(io.get("spill_read_bytes", 0)),
                    io.get("spill_read_mbps", 0.0),
                    io.get("io_wait_seconds", 0.0),
                    io.get("io_wait_fraction", 0.0)))
        if io.get("writer_queue_peak"):
            line += " · writer queue peak {}".format(
                io["writer_queue_peak"])
        add(line)
    met = summary.get("metrics")
    if met:
        sm = met.get("sampler", {})
        add("metrics: {} samples @ {} ms · {} series · drops {} · "
            "sampler overhead {:.2%}".format(
                sm.get("samples", 0), sm.get("interval_ms", 0),
                len(met.get("series", {})), sm.get("series_drops", 0),
                sm.get("overhead", 0.0)))
    if store.get("h2d_bytes") or store.get("hbm_offloads"):
        add("HBM tier: {} up, {} fetched back, {} offloads, peak {}".format(
            _mb(store.get("h2d_bytes", 0)), _mb(store.get("d2h_bytes", 0)),
            store.get("hbm_offloads", 0), _mb(store.get("hbm_peak_bytes",
                                                        0))))
    mesh = summary.get("mesh", {})
    if mesh.get("folds") or mesh.get("exchanges"):
        add("mesh: {} collective folds, {} exchanges ({} moved)".format(
            mesh.get("folds", 0), mesh.get("exchanges", 0),
            _mb(mesh.get("exchange_bytes", 0))))
        ex = mesh.get("exchange") or {}
        if ex.get("steps"):
            add("  exchange schedule: {} step(s), peak in-flight {} "
                "(budget {})".format(
                    ex.get("steps", 0),
                    _mb(ex.get("peak_inflight_bytes", 0)),
                    _mb(ex.get("hbm_budget", 0))))
    devx = summary.get("device", {})
    if devx.get("device_stages") or devx.get("device_fraction"):
        add("device: {} lowered stage(s) · device_fraction {:.2f} · "
            "h2d {} · d2h {}".format(
                devx.get("device_stages", 0),
                devx.get("device_fraction", 0.0),
                _mb(devx.get("h2d_bytes", 0)),
                _mb(devx.get("d2h_bytes", 0))))
    dev = summary.get("devtime", {})
    if dev:
        add("devtime: device {:.2f}s · transfer {:.2f}s · codec {:.2f}s "
            "(non-overlapped {:.2f}s)".format(
                dev.get("device", 0.0), dev.get("transfer", 0.0),
                dev.get("codec", 0.0), dev.get("codec_wait", 0.0)))
    ov = summary.get("overlap", {})
    if ov:
        add("overlap: windows={} stall_fraction={:.3f}".format(
            ov.get("windows", 0), ov.get("stall_fraction", 0.0)))
    if summary.get("retries"):
        add("job retries: {}".format(summary["retries"]))
    ru = summary.get("reuse")
    if ru:
        add("reuse: {} hit(s) / {} miss(es) · {} stage(s) skipped · "
            "mounted {} · published {}".format(
                ru.get("hits", 0), ru.get("misses", 0),
                ru.get("stages_skipped", 0),
                _mb(ru.get("bytes_mounted", 0)),
                _mb(ru.get("bytes_published", 0))))
        extras = []
        if ru.get("incremental_merges"):
            extras.append("{} incremental merge(s)".format(
                ru["incremental_merges"]))
        if ru.get("recompute_fallbacks"):
            extras.append("{} recompute fallback(s)".format(
                ru["recompute_fallbacks"]))
        if ru.get("evictions"):
            extras.append("{} eviction(s)".format(ru["evictions"]))
        if extras:
            add("  " + " · ".join(extras))
        decisions = ru.get("decisions") or ()
        interesting = [d for d in decisions
                       if d.get("decision") not in ("miss",)]
        if interesting:
            add("  decisions: " + ", ".join(
                "s{}={}".format(d.get("stage"), d.get("decision"))
                for d in interesting))
        if ru.get("cache_dir"):
            add("  cache: {}".format(ru["cache_dir"]))
    spans = summary.get("spans")
    if spans:
        add("")
        add("span kinds: " + ", ".join(
            "{} ({}x, {:.2f}s)".format(cat, v.get("count", 0),
                                       v.get("seconds", 0.0))
            for cat, v in sorted(spans.items())))
    tf = summary.get("trace_file")
    add("")
    if tf:
        add("trace: {}  (load in https://ui.perfetto.dev or "
            "chrome://tracing)".format(tf))
    else:
        add("trace: none (enable with settings.trace / DAMPR_TPU_TRACE=1)")
    return "\n".join(lines)
