"""Run-history corpus: an accumulated per-operator telemetry record.

``plan/cost.py`` used to adapt from exactly ONE prior ``stats.json`` —
a single noisy sample, and only when the previous run happened to be
traced.  Following the tf.data-service argument (PAPERS.md, arXiv
2210.14826: auto-tuning needs an accumulated telemetry corpus, not the
last data point), every finalized run now appends one compact summary
record to a bounded JSONL index under its scratch root::

    <scratch_root>/<run>/history.jsonl    # settings.history_entries cap

Each line is one self-contained JSON record (schema
``dampr-tpu-history/<version>`` — see :data:`SCHEMA_VERSION` and the
tolerant :func:`upgrade` path for older lines): the plan fingerprint +
stage shapes (the
match key), per-stage IO measurements, critical-path verdicts
(:mod:`.critpath`), the per-op profile when :mod:`.profile` was on,
run throughput, and a snapshot of the performance-shaping settings.

Durability contract:

- **crash-safe append**: one ``O_APPEND`` write of one line; a run that
  dies mid-write corrupts at most its own line;
- **line-validated read**: unparsable or wrong-schema lines are skipped,
  never fatal — a corrupt corpus degrades to fewer samples;
- **bounded**: past ``settings.history_entries`` the file is compacted
  to the newest entries via tmp + atomic rename.

Consumers: :func:`dampr_tpu.plan.cost.matched_history` (median over >= 3
shape-matching runs, recency-bounded by ``settings.history_window``),
``dampr-tpu-doctor`` (``--diff`` and trend context), and the learned
per-operator cost model (:mod:`dampr_tpu.plan.model`) whose feature
extraction and knob-variance tables this corpus feeds.
"""

import hashlib
import json
import logging
import os
import statistics
import threading

from .. import settings

log = logging.getLogger("dampr_tpu.obs.history")

#: Current corpus record schema.  The version suffix is an integer so
#: feature extraction (plan/model.py) can evolve without invalidating
#: accumulated records: readers accept EVERY ``dampr-tpu-history/<=N``
#: line and upgrade it in memory (:func:`upgrade`) — an old corpus
#: degrades to thinner features, never to an empty history.
SCHEMA_PREFIX = "dampr-tpu-history/"
SCHEMA_VERSION = 3
SCHEMA = SCHEMA_PREFIX + str(SCHEMA_VERSION)
FILE = "history.jsonl"


def schema_version(rec):
    """The integer schema version of a record, or None when the schema
    tag is missing/foreign/newer than this reader understands."""
    tag = (rec or {}).get("schema")
    if not isinstance(tag, str) or not tag.startswith(SCHEMA_PREFIX):
        return None
    try:
        v = int(tag[len(SCHEMA_PREFIX):])
    except ValueError:
        return None
    return v if 1 <= v <= SCHEMA_VERSION else None


def upgrade(rec):
    """In-memory upgrade of an older-version record to the current
    feature surface.  v1 -> v2: per-stage ``shuffle_target`` (absent
    pre-PR-12) defaults to None and the ``v`` field is stamped; the
    record's on-disk line is never rewritten.  Tolerant: missing
    containers become empty, never a raise."""
    v = schema_version(rec) or 1
    rec["v"] = v
    if v < 2:
        for st in rec.get("stages") or ():
            if isinstance(st, dict):
                st.setdefault("shuffle_target", None)
        rec.setdefault("settings", {})
        rec.setdefault("throughput", {})
    if v < 3:
        # v2 -> v3: the "health" block (retries/quarantine/skew/reuse —
        # the regression sentry's inputs) defaults empty; the sentry
        # treats a missing metric as "no sample", never as zero.
        rec.setdefault("health", {})
    return rec

_append_lock = threading.Lock()

#: Settings whose values shape run performance: snapshotted per record so
#: ``doctor --diff`` can attribute a regression to a config change.
_KNOBS = ("analyze", "partitions", "batch_size", "max_memory_per_stage",
          "overlap_windows", "spill_write_threads", "spill_read_prefetch",
          "merge_fanin", "max_processes", "optimize", "profile",
          "mesh_exchange", "exchange_hbm_budget", "exchange_chunk_bytes",
          "exchange_min_bytes", "job_retries", "io_retries",
          "retry_backoff_ms", "max_quarantined", "exchange_timeout_ms",
          "mitigate", "speculate_threshold", "speculate_after_steps",
          "mitigate_probe_windows", "exchange_coding", "cost_model",
          "autotune", "autotune_trials", "handoff", "reuse",
          "reuse_budget_bytes", "pipeline", "pipeline_queue_bytes",
          "exchange_codec")


def corpus_path(run_name):
    """Where a run name's corpus lives (next to its durable scratch
    outputs — NOT under trace_dir, which may point at throwaway test
    directories)."""
    safe = str(run_name).replace("/", "_")
    return os.path.join(settings.scratch_root, safe, FILE)


def plan_fingerprint(stage_shapes):
    """Stable fingerprint of a plan's executed stage-shape sequence (the
    corpus match key, also reusable by the service layer's plan dedupe)."""
    text = "|".join(s.get("shape", "?") for s in stage_shapes or ())
    return hashlib.sha1(text.encode("utf-8")).hexdigest()[:16]


def _settings_snapshot():
    snap = {k: getattr(settings, k, None) for k in _KNOBS}
    snap["lower"] = str(settings.lower)
    snap["metrics_interval_ms"] = settings.metrics_interval_ms
    snap["spill_codec"] = str(settings.spill_codec)
    return snap


def _health_section(summary):
    """The v3 run-health scalars from a finalized summary.  Only keys
    with a real sample land — the sentry must distinguish "feature off"
    from "measured zero"."""
    out = {}
    faults = summary.get("faults") or {}
    if "retries" in faults:
        out["retries"] = faults.get("retries")
    if "quarantined" in faults:
        q = faults.get("quarantined")
        out["quarantined"] = len(q) if isinstance(q, (list, dict)) else q
    skew = (summary.get("fleet") or {}).get("skew") or {}
    mit = summary.get("mitigation") or {}
    late = skew.get("late_ratio")
    if late is None:
        late = mit.get("last_late_ratio")
    if late is not None:
        out["late_ratio"] = late
    reuse = summary.get("reuse") or {}
    hits, misses = reuse.get("hits"), reuse.get("misses")
    if isinstance(hits, int) and isinstance(misses, int) \
            and hits + misses > 0:
        out["reuse_hit_rate"] = round(hits / float(hits + misses), 4)
    return out


def compact_record(summary):
    """One corpus line from a finalized run summary (the stats.json
    dict).  Compact by construction: per-stage scalars, verdict strings,
    and the top per-op timings only — never spans or series."""
    stages = []
    for st in summary.get("stages") or ():
        stages.append({k: st.get(k) for k in (
            "stage", "kind", "target", "shuffle_target", "jobs",
            "records_in", "records_out", "bytes_in", "bytes_out",
            "spill_bytes", "seconds")})
    rec = {
        "schema": SCHEMA,
        "v": SCHEMA_VERSION,
        "run": summary.get("run"),
        "ts": summary.get("started_at"),
        "wall_seconds": summary.get("wall_seconds"),
        "n_partitions": summary.get("n_partitions"),
        "stage_shapes": (summary.get("plan") or {}).get("stage_shapes") or [],
        "stages": stages,
        "throughput": {
            "records_out": (summary.get("totals") or {}).get("records_out"),
            "bytes_out": (summary.get("totals") or {}).get("bytes_out"),
            "mbps": (round((summary.get("totals") or {}).get("bytes_out", 0)
                           / 1e6 / summary["wall_seconds"], 3)
                     if summary.get("wall_seconds") else None),
        },
        "device_fraction": (summary.get("device") or {}).get(
            "device_fraction"),
        # Cross-stage handoff evidence (plan/model.price_handoff learns
        # handoff-vs-spill seconds from these across runs).
        "handoff": {
            "edges": (summary.get("device") or {}).get(
                "handoff_edges", 0),
            "bytes": (summary.get("device") or {}).get(
                "handoff_bytes", 0),
            "d2h_avoided_bytes": (summary.get("device") or {}).get(
                "d2h_avoided_bytes", 0),
            "degrades": (summary.get("device") or {}).get(
                "handoff_degrades", 0),
        },
        "io_wait_fraction": (summary.get("io") or {}).get(
            "io_wait_fraction"),
        # Run-health scalars (v3) — what the regression sentry trends:
        # fault absorption, straggler skew, and cross-run reuse yield.
        "health": _health_section(summary),
        "settings": _settings_snapshot(),
    }
    proc = summary.get("process") or {}
    if proc:
        rec["process"] = {"process_id": proc.get("process_id", 0),
                          "num_processes": proc.get("num_processes", 1)}
    # Multi-rank corpus discipline: only rank 0 appends the RUN-LEVEL
    # record the adaptation layer consumes; non-zero ranks tag theirs
    # with ``rank`` so ``matching()`` excludes them — N ranks appending
    # identical-shape records would otherwise collapse the per-stage
    # medians onto one run's numbers N times over (and, under skew,
    # steer sizing from whichever rank happened to write last).
    if proc.get("process_id"):
        rec["rank"] = proc["process_id"]
    rec["fingerprint"] = plan_fingerprint(rec["stage_shapes"])
    crit = summary.get("critpath")
    if crit:
        rec["critpath"] = {
            "run": (crit.get("run") or {}).get("verdict"),
            "stages": {str(s.get("stage")): s.get("verdict")
                       for s in crit.get("stages") or ()},
        }
    prof = summary.get("profile")
    if prof:
        rec["profile"] = {
            str(s["stage"]): [[o["op"], o["seconds"], o["records"]]
                              for o in (s.get("ops") or [])[:5]]
            for s in prof.get("stages") or ()
        }
    return rec


def append(summary):
    """Append one finalized run's record; best-effort (corpus failures
    must never fail a run) and bounded.  Returns the corpus path or
    None."""
    if settings.history_entries <= 0:
        return None
    run = summary.get("run")
    if not run or not summary.get("stages"):
        return None
    try:
        rec = compact_record(summary)
        line = json.dumps(rec, sort_keys=True,
                          separators=(",", ":"), default=str)
        if "\n" in line:  # a pathological repr leaked a newline: refuse
            return None   # to corrupt the line-oriented index
        path = corpus_path(run)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with _append_lock:
            fd = os.open(path, os.O_WRONLY | os.O_APPEND | os.O_CREAT,
                         0o644)
            try:
                os.write(fd, (line + "\n").encode("utf-8"))
            finally:
                os.close(fd)
            _compact_if_over(path)
        return path
    except Exception:
        log.debug("history corpus append failed for %r", run,
                  exc_info=True)
        return None


def _compact_if_over(path):
    """Rewrite the corpus keeping only the newest ``history_entries``
    valid lines (tmp + atomic replace; called under the append lock)."""
    cap = settings.history_entries
    try:
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            lines = f.readlines()
    except OSError:
        return
    if len(lines) <= cap:
        return
    keep = [ln for ln in lines if _valid_line(ln) is not None][-cap:]
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        f.writelines(keep)
    os.replace(tmp, path)


def _valid_line(line):
    line = line.strip()
    if not line:
        return None
    try:
        rec = json.loads(line)
    except ValueError:
        return None
    if not isinstance(rec, dict) or schema_version(rec) is None:
        return None
    if not isinstance(rec.get("stages"), list):
        return None
    return upgrade(rec)


def load(run_name):
    """Every valid record for a run name, oldest -> newest.  Never
    raises; a missing or corrupt corpus is just an empty history."""
    path = corpus_path(run_name) if run_name else None
    if not path or not os.path.isfile(path):
        return []
    out = []
    try:
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            for line in f:
                rec = _valid_line(line)
                if rec is not None:
                    out.append(rec)
    except OSError:
        return []
    return out


def matching(records, stage_shapes):
    """Records whose stage-shape sequence equals ``stage_shapes`` —
    per-sid measurements are meaningless across plan shapes.  Rank-tagged
    records (non-zero ranks of a multi-process run) are excluded: each
    rank sees the same global collectives, so its record duplicates rank
    0's shape with rank-local timings — feeding them to the medians
    would weight one run once per rank."""
    want = [s.get("shape") for s in stage_shapes or ()]
    return [r for r in records
            if not r.get("rank")
            and [s.get("shape")
                 for s in r.get("stage_shapes") or ()] == want]


def _median(values):
    vals = [v for v in values if isinstance(v, (int, float))
            and not isinstance(v, bool)]
    if not vals:
        return None
    m = statistics.median(vals)
    return int(m) if all(isinstance(v, int) for v in vals) else m


def synthesize(records):
    """Fold shape-matching corpus records into ONE stats-summary-shaped
    history dict the existing adaptation code consumes unchanged.

    - one or two records: the newest record verbatim (byte-equivalent to
      the old single-stats.json behavior — the equivalence pin);
    - three or more: per-stage **medians** of the IO measurements, so a
      single outlier run (cold cache, noisy neighbor) stops steering the
      sizing.
    """
    if not records:
        return None
    newest = records[-1]
    n = len(records)
    if n < 3:
        stages = [dict(st) for st in newest.get("stages") or ()]
    else:
        by_sid = {}
        for rec in records:
            for st in rec.get("stages") or ():
                by_sid.setdefault(st.get("stage"), []).append(st)
        stages = []
        for sid, sts in sorted(by_sid.items()):
            med = dict(sts[-1])  # kind/target/stage from the newest
            for field in ("jobs", "records_in", "records_out", "bytes_in",
                          "bytes_out", "spill_bytes", "seconds"):
                v = _median([st.get(field) for st in sts])
                if v is not None:
                    med[field] = v
            stages.append(med)
    return {
        "run": newest.get("run"),
        "stages": stages,
        "plan": {"stage_shapes": newest.get("stage_shapes") or []},
        "stats_file": "history:{}#n={}".format(
            corpus_path(newest.get("run")), n),
        "history_entries": n,
    }


# -- corpus maintenance CLI (dampr-tpu-history) -----------------------------

def _iter_corpora():
    """Every (run_name, corpus_path) under the scratch root."""
    root = settings.scratch_root
    try:
        names = sorted(os.listdir(root))
    except OSError:
        return
    for name in names:
        path = os.path.join(root, name, FILE)
        if os.path.isfile(path):
            yield name, path


def vacuum(path, cap=None):
    """Rewrite one corpus in place: drop invalid lines, upgrade every
    survivor to the current schema on disk, keep the newest ``cap``
    (``settings.history_entries`` by default).  Returns (kept, dropped).
    Same durability discipline as compaction: tmp + atomic replace."""
    cap = settings.history_entries if cap is None else cap
    try:
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            lines = f.readlines()
    except OSError:
        return (0, 0)
    recs = [r for r in (_valid_line(ln) for ln in lines) if r is not None]
    if cap > 0:
        recs = recs[-cap:]
    for rec in recs:
        # upgrade() already ran in _valid_line (stamping "v"); restamp
        # the schema tag so the rewritten line IS a current-version line.
        rec["schema"] = SCHEMA
        rec["v"] = SCHEMA_VERSION
    tmp = path + ".tmp"
    with _append_lock:
        with open(tmp, "w", encoding="utf-8") as f:
            for rec in recs:
                f.write(json.dumps(rec, sort_keys=True,
                                   separators=(",", ":"), default=str))
                f.write("\n")
        os.replace(tmp, path)
    return (len(recs), len(lines) - len(recs))


def _fmt_record(rec):
    tp = rec.get("throughput") or {}
    return "  {ts:<20} v{v} fp={fp} wall={wall} mbps={mbps}{rank}".format(
        ts=str(rec.get("ts", "?"))[:20], v=rec.get("v", "?"),
        fp=rec.get("fingerprint", "?"),
        wall=("{:.2f}s".format(rec["wall_seconds"])
              if isinstance(rec.get("wall_seconds"), (int, float))
              else "?"),
        mbps=tp.get("mbps", "?"),
        rank=(" rank={}".format(rec["rank"]) if rec.get("rank") else ""))


def main(argv=None):
    """``dampr-tpu-history``: inspect and maintain run-history corpora.

    With no run name, lists every corpus under the scratch root.  With a
    run name, lists its records (newest last); ``--fingerprint`` filters
    to one plan shape.  ``--gc`` compacts to the retention cap and
    ``--vacuum`` additionally drops invalid lines and rewrites old-schema
    records at the current version.
    """
    import argparse

    p = argparse.ArgumentParser(
        prog="dampr-tpu-history",
        description="inspect / maintain dampr_tpu run-history corpora")
    p.add_argument("run", nargs="?", help="run name (default: list all)")
    p.add_argument("--list", action="store_true",
                   help="list corpora under the scratch root")
    p.add_argument("--fingerprint", metavar="F",
                   help="only records with this plan fingerprint")
    p.add_argument("--gc", action="store_true",
                   help="compact to the newest history_entries records")
    p.add_argument("--vacuum", action="store_true",
                   help="gc + drop invalid lines + upgrade old records "
                        "on disk")
    p.add_argument("--json", action="store_true", help="machine output")
    args = p.parse_args(argv)

    if args.run:
        targets = [(args.run, corpus_path(args.run))]
        if not os.path.isfile(targets[0][1]):
            print("no history corpus for run {!r} under {}".format(
                args.run, settings.scratch_root))
            return 1
    else:
        targets = list(_iter_corpora())

    if args.vacuum or args.gc:
        report = []
        for name, path in targets:
            if args.vacuum:
                kept, dropped = vacuum(path)
            else:
                with _append_lock:
                    _compact_if_over(path)
                kept = sum(1 for ln in open(path, encoding="utf-8",
                                            errors="replace")
                           if _valid_line(ln) is not None)
                dropped = 0
            report.append({"run": name, "path": path,
                           "kept": kept, "dropped": dropped})
        if args.json:
            print(json.dumps(report, indent=2, sort_keys=True))
        else:
            for r in report:
                print("{run}: kept {kept} record(s), dropped {dropped} "
                      "({path})".format(**r))
        return 0

    if args.run:
        recs = load(args.run)
        if args.fingerprint:
            recs = [r for r in recs
                    if r.get("fingerprint") == args.fingerprint]
        if args.json:
            print(json.dumps(recs, indent=2, sort_keys=True, default=str))
        else:
            print("{} — {} record(s)".format(args.run, len(recs)))
            for rec in recs:
                print(_fmt_record(rec))
        return 0

    rows = []
    for name, path in targets:
        recs = load(name)
        fps = sorted({r.get("fingerprint") for r in recs
                      if r.get("fingerprint")})
        rows.append({"run": name, "records": len(recs),
                     "fingerprints": fps,
                     "newest": recs[-1].get("ts") if recs else None,
                     "path": path})
    if args.json:
        print(json.dumps(rows, indent=2, sort_keys=True, default=str))
    else:
        if not rows:
            print("no history corpora under {}".format(
                settings.scratch_root))
        for r in rows:
            print("{run:<24} {records:>4} record(s)  {nfp} plan shape(s)"
                  "  newest={newest}".format(
                      nfp=len(r["fingerprints"]), **r))
    return 0


if __name__ == "__main__":
    import sys as _sys

    _sys.exit(main())
