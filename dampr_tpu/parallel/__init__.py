"""Distributed execution over a jax.sharding.Mesh.

This is the TPU-native replacement for the reference's "distributed
communication backend" — which is a shared local filesystem plus
multiprocessing queues (reference base.py:416-433 DefaultShuffler,
stagerunner.py:16-38; see SURVEY §2 'Distributed communication backend').
Here the exchange is XLA collectives over ICI/DCN:

- :func:`dampr_tpu.parallel.shuffle.mesh_keyed_fold` — the keyed shuffle:
  per-device local segment fold, fixed-capacity ``lax.all_to_all`` routed by
  ``hash % n_devices``, then a final per-device fold.
- :func:`dampr_tpu.parallel.shuffle.mesh_global_sum` — degenerate-key
  aggregates (len/sum) as a local reduce + ``psum``.
- :mod:`dampr_tpu.parallel.exchange` — the general byte exchange (object-
  valued blocks as payloads over all_to_all), executed as a budget-bounded
  schedule of chunked collectives.
- :mod:`dampr_tpu.parallel.replan` — the schedule planner: decomposes one
  large redistribution into steps whose in-flight bytes respect
  ``settings.exchange_hbm_budget`` (arXiv 2112.01075).
- :mod:`dampr_tpu.parallel.mesh` — mesh construction + process-group
  setup (``init_distributed`` / ``maybe_init_distributed`` /
  ``process_info``): N processes join one deployment over a coordinator
  (gloo TCP collectives on CPU hosts) and the same programs span every
  rank's devices.

The mesh abstraction is host-count-agnostic: the same program spans one chip,
a v4-8 slice, or multi-host DCN — only the Mesh changes (SURVEY §7 hard
part 5).  See ``docs/parallel.md``.
"""

from . import replan
from .exchange import mesh_blob_exchange, mesh_shuffle_blocks
from .mesh import (data_mesh, default_mesh, init_distributed,
                   maybe_init_distributed, process_info)
from .replan import plan_exchange, step_inflight_bytes
from .shuffle import mesh_global_sum, mesh_keyed_fold

__all__ = ["data_mesh", "default_mesh", "init_distributed",
           "maybe_init_distributed", "process_info",
           "mesh_keyed_fold", "mesh_global_sum",
           "mesh_blob_exchange", "mesh_shuffle_blocks",
           "replan", "plan_exchange", "step_inflight_bytes"]
