"""Straggler mitigation: turn the live skew signal into action.

PR 9 (``obs/fleet.py``) can *name* the straggler rank and its bottleneck
post-hoc; PR 10 (``dampr_tpu.faults``) made duplicate completion safe
via attempt-scoped commits.  This module closes the loop (ROADMAP item
4, in the lineage of MapReduce backup tasks and CAMR-style coded
aggregation, arXiv 1901.07418): a per-run :class:`MitigationController`
consumes the SAME per-step skew computation ``obs/fleet.py`` runs
post-hoc — evaluated live, from per-rank collective-step entry times
shared over a tiny piggybacked all_gather — and acts on it at three
levels:

1. **Work stealing + speculative execution** (host path, no collective
   in flight): :func:`pool_dispatch` replaces the runner's ``pool.map``
   fan-out with rank-owned per-worker job queues.  An idle worker first
   steals unstarted partitions from the most backlogged queue; once
   every queue is drained it *speculatively re-executes* the
   longest-running in-flight job whose elapsed time exceeds
   ``settings.speculate_threshold`` x the median completed-job duration.
   First-result-wins: every attempt runs inside a
   ``store.attempt()`` frame, the winner's commit is claimed under one
   lock, and a loser — **including one that completes after the winner
   committed** — raises out of its frame so its registrations roll back.
   Exactly-once, no budget leaks (the PR-10 contract, now load-bearing
   for *successful* duplicates, not just failed retries).

2. **Degrade-in-place** (collective path): once a rank's step-entry
   lateness stays at or above ``speculate_threshold`` x the other
   ranks' mean (+ the 20 ms jitter floor; the reported ``late_ratio``
   keeps :func:`dampr_tpu.obs.fleet.straggler_of`'s display definition,
   which saturates at the rank count) for
   ``settings.speculate_after_steps`` consecutive windows, the
   controller *skips* subsequent collective exchange windows — the byte exchange is a placement transport whose
   delivered content is byte-identical to its input (the multi-process
   gather replicates everything to every host), so skipping it is exact
   by construction and removes the per-step barrier the fleet was
   serializing on.  Every ``settings.mitigate_probe_windows`` skipped
   windows, one window runs through the mesh as a probe; after
   ``speculate_after_steps`` consecutive healthy probes the mitigation
   disengages cleanly (the ``duration_ms`` windowed-slowness chaos
   schedules pin this).

3. **Sticky down-weighting**: a rank pathological for twice the engage
   count (or whose shared transient-fault rate stays high) gets its
   partition share down-weighted **for the remainder of the run** — the
   pid -> device routing table the exchange uses re-weights away from
   its devices (``route_table``), unlike PR 10's sticky host-shuffle
   degrade which only affects the *next* run.  Recorded as a
   ``mitigation`` event in the faults sidecar and the plan report.

Every rank runs the controller over the SAME shared observations
(entry times + fault counts cross the mesh, so the observation sequence
is identical fleet-wide), which is what makes the skip/route decisions
safe: a collective someone skips and someone enters would hang gloo
forever.  Local-only counters (steals, speculation) never influence
routing.

Zero overhead off (the default): every site is one module-global
None-check, the same contract as tracing/metrics/faults.
"""

import collections
import contextlib
import logging
import threading
import time

from .. import faults as _faults
from .. import settings
from ..obs import log as _obslog
from ..obs import metrics as _metrics
from ..obs import trace as _trace
from ..obs.fleet import straggler_of

log = logging.getLogger("dampr_tpu.parallel.mitigate")

#: Entry spreads under this many seconds never count as pathological:
#: scheduler jitter on a healthy fleet routinely spreads entries by a
#: few milliseconds, and acting on noise would flap the collective path.
MIN_SPREAD_S = 0.02

#: Floor on the elapsed time before a job becomes a speculation
#: candidate — sub-50ms jobs re-execute for less than the dispatch cost.
SPEC_FLOOR_S = 0.05

#: Slots per device in the weighted routing table (weight resolution:
#: a 0.25 down-weight maps to 2 of 8 slots).
_ROUTE_SLOTS = 8

#: Per-window fault bar: a rank that absorbed at least this many NEW
#: transient retries since the previous observation (the shared counts
#: are cumulative; the controller differences them) counts as
#: pathological even when its entries are not late yet.  A rank whose
#: retries STOP goes healthy again — an old burst must never pin a
#: recovered rank bad forever.
_FAULT_FACTOR = 2


class MitigationController(object):
    """One run's mitigation state machine + counters.

    Split-brain discipline: everything that can influence a COLLECTIVE
    decision (``engaged``, the probe counter, ``downweights``) is driven
    only by :meth:`observe_window`, whose inputs are identical on every
    rank (they crossed the mesh).  Steal/speculation counters are
    local-only and never feed back into routing.
    """

    def __init__(self, run_name=None, threshold=None, after=None,
                 probe_every=None, skip_safe=None):
        self.run = run_name
        self.threshold = (settings.speculate_threshold
                          if threshold is None else float(threshold))
        self.after = max(1, int(settings.speculate_after_steps
                                if after is None else after))
        self.probe_every = (settings.mitigate_probe_windows
                            if probe_every is None else int(probe_every))
        # Skipping collective windows is only safe under a BOUNDED
        # collective regime: should controller state ever diverge
        # across ranks (a one-sided share failure), a skipped-vs-entered
        # window must end in the exchange watchdog's bounded abort, not
        # an unbounded gloo hang.  So the degrade-in-place action is
        # gated on settings.exchange_timeout_ms being armed; stealing,
        # speculation, and down-weight routing (whose divergence fails
        # loudly at the unpack assert, never silently) stay available
        # either way.
        self.skip_safe = (settings.exchange_timeout_ms > 0
                          if skip_safe is None else bool(skip_safe))
        self._warned_unsafe_skip = False
        self._lock = threading.RLock()
        # -- shared-observation state (identical on every rank) --------
        self.observations = 0
        self.engaged = False
        self.straggler = None
        self.last_late_ratio = 1.0
        self._consec_late = {}
        self._consec_healthy = 0
        self._skip_counter = 0
        self.windows_skipped = 0
        self.engagements = 0
        self.disengagements = 0
        self.downweights = {}  # rank -> weight in (0, 1), sticky
        self._route_cache = None
        self._last_fault_counts = {}  # rank -> cumulative count seen
        # -- local-only counters (never routing inputs) ----------------
        self.stolen_partitions = 0
        self.speculative_attempts = 0
        self.speculative_wins = 0
        self.speculation_declined = []  # [{stage, evidence}] from analyze
        self.local_retries = 0
        self.events = []  # compact engage/disengage/downweight trail

    # -- live skew ingestion -------------------------------------------------
    def observe_window(self, lateness_by_rank, fault_counts=None):
        """Fold one collective window's shared observation into the
        state machine.  ``lateness_by_rank``: {rank: seconds after the
        first arriver's step entry} (the per-window form of what
        ``fleet.step_skew`` averages post-hoc).  ``fault_counts``:
        {rank: CUMULATIVE transient retries} shared on the same
        collective — differenced here, so only a rank still absorbing
        retries counts as pathological (a burst that ended must not pin
        a recovered rank bad forever).
        """
        with self._lock:
            self.observations += 1
            lateness = dict(lateness_by_rank or {})
            straggler, ratio = straggler_of(lateness)
            spread = (max(lateness.values()) - min(lateness.values())
                      if len(lateness) > 1 else 0.0)
            self.last_late_ratio = round(ratio, 3)
            # Pathological test: the straggler's lateness against the
            # OTHER ranks' mean plus the jitter floor.  Deliberately NOT
            # ``ratio >= threshold``: late_ratio (lateness over the
            # fleet mean INCLUDING the straggler) saturates at the rank
            # count — on a 2-rank fleet it is 2.0 for ANY nonzero
            # spread, so thresholding it would make the knob
            # non-functional there (threshold > 2 could never engage,
            # threshold <= 2 would engage on any 20 ms of jitter).
            # Against the others-mean + floor, the threshold scales a
            # real bar at every fleet size: default 1.5 ~= "more than
            # 1.5x the fleet's typical entry spread late, repeatedly".
            pathological = False
            if straggler is not None and spread >= MIN_SPREAD_S:
                others = [v for r, v in lateness.items()
                          if r != straggler]
                baseline = ((sum(others) / len(others) if others else 0.0)
                            + MIN_SPREAD_S)
                pathological = (lateness[straggler]
                                >= self.threshold * baseline)
            deltas = {}
            for r, c in (fault_counts or {}).items():
                last = self._last_fault_counts.get(r, 0)
                deltas[r] = max(0, c - last)
                self._last_fault_counts[r] = max(last, c)
            fault_ranks = sorted(r for r, d in deltas.items()
                                 if d >= _FAULT_FACTOR)
            bad = set(fault_ranks)
            if pathological:
                bad.add(straggler)
                self.straggler = straggler
            ranks_seen = set(lateness) | set(fault_counts or {})
            for r in ranks_seen:
                if r in bad:
                    self._consec_late[r] = self._consec_late.get(r, 0) + 1
                else:
                    self._consec_late[r] = 0
            if _metrics.enabled():
                _metrics.counter_add("mitigation.windows_observed", 1)
                _metrics.gauge_set("mitigation.late_ratio",
                                   round(ratio, 3))
            if bad:
                self._consec_healthy = 0
                worst = (straggler if pathological
                         else (fault_ranks[0] if fault_ranks else None))
                for r in sorted(bad):
                    n = self._consec_late.get(r, 0)
                    if not self.engaged and n >= self.after:
                        self._engage_locked(r, ratio)
                    if (n >= self.after * 2
                            and r not in self.downweights):
                        self._downweight_locked(r, ratio)
                if worst is not None:
                    self.straggler = worst
            elif self.engaged:
                self._consec_healthy += 1
                if self._consec_healthy >= self.after:
                    self._disengage_locked()

    def _event_locked(self, action, rank=None, **fields):
        ev = {"action": action, "observation": self.observations}
        if rank is not None:
            ev["rank"] = rank
        ev.update(fields)
        self.events.append(ev)
        _trace.instant("mitigation", action,
                       rank=rank if rank is not None else -1, **fields)
        if _metrics.enabled():
            _metrics.counter_add("mitigation.{}".format(action), 1)
        if self.run:
            # The faults sidecar is the cross-run memory: the doctor and
            # the next run's operator see WHAT the engine did about the
            # skew, not just that skew existed.
            _faults.record_event(self.run, "mitigation", action=action,
                                 rank=rank, **fields)

    def _engage_locked(self, rank, ratio):
        self.engaged = True
        self.engagements += 1
        self._consec_healthy = 0
        self._event_locked("engage", rank=rank,
                           late_ratio=round(ratio, 2))
        _obslog.warn(
            "mitigation-engaged",
            "mitigation ENGAGED: rank %s enters collective steps %.2fx "
            "later than the fleet average for %d consecutive windows — "
            "degrading collective exchanges in place (probe every %s "
            "skipped windows)", rank, ratio, self.after,
            self.probe_every or "-", logger=log, straggler=rank,
            late_ratio=round(ratio, 2))

    def _disengage_locked(self):
        self.engaged = False
        self.disengagements += 1
        self._skip_counter = 0
        self._consec_healthy = 0
        self._event_locked("disengage")
        _obslog.warn(
            "mitigation-disengaged",
            "mitigation DISENGAGED: %d consecutive healthy probe "
            "window(s) — collective exchanges resume", self.after,
            logger=log)

    def _downweight_locked(self, rank, ratio):
        w = max(0.25, min(0.75, 1.0 / ratio if ratio > 1.0 else 0.5))
        self.downweights[rank] = round(w, 2)
        self._route_cache = None
        self._event_locked("downweight", rank=rank, weight=round(w, 2),
                           late_ratio=round(ratio, 2))
        _obslog.warn(
            "mitigation-downweight",
            "mitigation: rank %s stays pathological — partition share "
            "down-weighted to %.2f for the remainder of the run",
            rank, w, logger=log, straggler=rank, weight=round(w, 2))

    def note_local_retry(self):
        """One transient retry absorbed on THIS rank (shared with the
        fleet on the next window's piggyback collective)."""
        with self._lock:
            self.local_retries += 1

    def local_fault_count(self):
        with self._lock:
            return self.local_retries

    # -- collective-path actions ---------------------------------------------
    def use_collective(self):
        """Should the next exchange window actually cross the mesh?
        True while disengaged (and on probe windows); False = skip (the
        degrade-in-place action).  Deterministic from shared state, so
        every rank answers identically — the invariant that keeps a
        skipped collective from hanging the ranks that would enter it."""
        with self._lock:
            if not self.engaged:
                return True
            if not self.skip_safe:
                if not self._warned_unsafe_skip:
                    self._warned_unsafe_skip = True
                    _obslog.warn(
                        "mitigation-unsafe-skip",
                        "mitigation engaged but degrade-in-place is "
                        "DISABLED: settings.exchange_timeout_ms is 0, "
                        "so a skipped collective could hang unboundedly "
                        "if rank state ever diverged — arm the exchange "
                        "watchdog to enable window skipping (stealing/"
                        "speculation/down-weighting stay active)",
                        logger=log)
                return True
            self._skip_counter += 1
            if (self.probe_every > 0
                    and self._skip_counter % self.probe_every == 0):
                return True  # probe: re-measure skew through the mesh
            self.windows_skipped += 1
            if _metrics.enabled():
                _metrics.counter_add("mitigation.windows_skipped", 1)
            return False

    def collective_fold_ok(self):
        """Gate for the keyed-fold collective fast path: while the
        mitigation is engaged the fold runs host-side (the collective
        would re-serialize the fleet on the straggler).  Same
        bounded-collective gate as :meth:`use_collective` — declining a
        collective one-sidedly must be watchdog-recoverable."""
        with self._lock:
            return not (self.engaged and self.skip_safe)

    def route_table(self, n_dev, num_processes):
        """Weighted pid -> device routing table, or None when no rank is
        down-weighted (callers keep the ``pid % D`` default).  A rank
        with weight w contributes ``round(w * 8)`` of its 8 per-device
        slots; slots interleave across devices so consecutive pids still
        spread.  Deterministic from (sticky) shared state."""
        with self._lock:
            if not self.downweights:
                return None
            key = (n_dev, num_processes,
                   tuple(sorted(self.downweights.items())))
            if self._route_cache and self._route_cache[0] == key:
                return self._route_cache[1]
            from ..obs.fleet import _rank_of_device

            slots = []
            for d in range(n_dev):
                w = self.downweights.get(
                    _rank_of_device(d, num_processes, n_dev), 1.0)
                slots.append(max(1, int(round(w * _ROUTE_SLOTS)))
                             if w > 0 else 0)
            table = [d for s in range(_ROUTE_SLOTS)
                     for d in range(n_dev) if slots[d] > s]
            if not table:
                table = list(range(n_dev))
            self._route_cache = (key, table)
            return table

    # -- host-path counters --------------------------------------------------
    def note_steal(self):
        with self._lock:
            self.stolen_partitions += 1
        _metrics.counter_add("mitigation.stolen_partitions", 1)

    def note_speculation(self, win):
        with self._lock:
            self.speculative_attempts += 1
            if win:
                self.speculative_wins += 1
        _metrics.counter_add("mitigation.speculative_wins" if win
                             else "mitigation.speculative_losses", 1)

    def note_speculation_declined(self, stage, evidence):
        """The static analyzer (dampr_tpu.analyze) refused speculative
        re-execution for a stage: its UDFs are evidence-nondeterministic
        and first-result-wins would silently commit whichever answer
        happened to finish first.  Recorded so the doctor/fleet report
        can say WHY a straggler stage saw no speculation."""
        with self._lock:
            rec = {"stage": stage, "evidence": list(evidence)[:3]}
            if rec not in self.speculation_declined:
                self.speculation_declined.append(rec)
        _metrics.counter_add("mitigation.speculation_declined", 1)

    # -- reporting -----------------------------------------------------------
    def summary(self):
        """The ``stats()["mitigation"]`` section (rank 0's copy also
        lands in ``stats()["fleet"]["mitigation"]`` on merged runs)."""
        with self._lock:
            return {
                "enabled": True,
                "engaged": self.engaged,
                "observations": self.observations,
                "engagements": self.engagements,
                "disengagements": self.disengagements,
                "windows_skipped": self.windows_skipped,
                "speculative_attempts": self.speculative_attempts,
                "speculative_wins": self.speculative_wins,
                "speculation_declined": [dict(r) for r in
                                         self.speculation_declined],
                "stolen_partitions": self.stolen_partitions,
                "straggler_rank": self.straggler,
                "last_late_ratio": self.last_late_ratio,
                "downweighted_ranks": {str(r): w for r, w in
                                       sorted(self.downweights.items())},
                "events": list(self.events[-8:]),
            }


# -- module-level lifecycle (mirrors obs.trace) ------------------------------

_active = None


def start(controller):
    global _active
    _active = controller


def stop(controller):
    global _active
    if _active is controller:
        _active = None


def active():
    return _active


def enabled():
    return _active is not None


# -- speculative / work-stealing job dispatch --------------------------------

class _SpeculationLost(Exception):
    """Raised INSIDE a losing attempt's ``store.attempt()`` frame so the
    frame's registrations roll back (the PR-10 rollback path, reused for
    successful-but-late duplicates)."""


def pool_dispatch(ctl, fn, jobs, n_workers, store=None, speculative=True,
                  spec_fn=None):
    """Run ``jobs`` through ``fn`` on ``n_workers`` threads with
    rank-owned queues, work stealing, and (optionally) speculative
    re-execution of stragglers.  Returns results in job order; the first
    job failure fails the dispatch (pool.map semantics — a failure only
    counts if no other attempt of that job already committed).

    Exactly-once: every attempt executes inside ``store.attempt()``;
    the committed-flag claim happens inside that frame under one lock,
    so of N racing attempts exactly one exits its frame committed and
    every other — even one completing long after the winner — raises
    :class:`_SpeculationLost` and rolls its registrations back.

    ``spec_fn`` (default ``fn``) runs the speculative duplicates — the
    runner passes its pre-metering wrapper here so a duplicate attempt
    never double-counts the one-call-per-job accounting."""
    if spec_fn is None:
        spec_fn = fn
    n = len(jobs)
    results = [None] * n
    committed = [False] * n
    lock = threading.Lock()
    cond = threading.Condition(lock)
    queues = [collections.deque() for _ in range(n_workers)]
    for i in range(n):
        queues[i % n_workers].append(i)
    inflight = {}   # (job index, is_speculative) -> perf_counter start
    spec_done = set()
    durations = []  # completed-attempt wall times (the speculation bar)
    failure = []
    pending_failures = {}  # job -> exception held while a duplicate
    #                        attempt of that job is still in flight

    def _spec_candidate():
        # Under ``lock``.  The longest-running primary attempt whose
        # elapsed time says "straggler": past the threshold multiple of
        # the median completed duration (and the absolute floor).
        if not speculative or not durations:
            return None
        med = sorted(durations)[len(durations) // 2]
        bar = max(SPEC_FLOOR_S, ctl.threshold * med)
        now = time.perf_counter()
        best, best_elapsed = None, bar
        for (i, spec), t0 in inflight.items():
            if spec or committed[i] or i in spec_done:
                continue
            elapsed = now - t0
            if elapsed >= best_elapsed:
                best, best_elapsed = i, elapsed
        return best

    def execute(i, spec):
        t0 = time.perf_counter()
        won = False
        try:
            cm = (store.attempt() if store is not None
                  else contextlib.nullcontext())
            with cm:
                r = (spec_fn if spec else fn)(jobs[i])
                with lock:
                    if committed[i]:
                        raise _SpeculationLost()
                    committed[i] = True
                    results[i] = r
                    won = True
        except _SpeculationLost:
            pass
        except BaseException as e:  # noqa: BLE001 - pool.map semantics
            with lock:
                if not committed[i]:
                    # Held, not yet fatal: a duplicate attempt of this
                    # job may still be running and may commit — a
                    # failure only counts once no attempt of the job
                    # can land a result (checked below, after this
                    # attempt leaves the inflight set).
                    pending_failures.setdefault(i, e)
        finally:
            with lock:
                inflight.pop((i, spec), None)
                if won:
                    durations.append(time.perf_counter() - t0)
                    pending_failures.pop(i, None)
                elif (i in pending_failures and not committed[i]
                        and not any(k[0] == i for k in inflight)):
                    if not failure:
                        failure.append(pending_failures.pop(i))
                cond.notify_all()
        if spec:
            ctl.note_speculation(win=won)

    def worker(wid):
        while True:
            task, spec = None, False
            with lock:
                if failure:
                    return
                if queues[wid]:
                    task = queues[wid].popleft()
                else:
                    victim = max(range(n_workers),
                                 key=lambda w: len(queues[w]))
                    if queues[victim]:
                        # Steal an unstarted partition from the most
                        # backlogged rank-owned queue (tail end: the
                        # owner keeps its cache-warm head).
                        task = queues[victim].pop()
                        ctl.note_steal()
                if task is None:
                    cand = _spec_candidate()
                    if cand is not None:
                        spec_done.add(cand)
                        task, spec = cand, True
                        inflight[(task, True)] = time.perf_counter()
                    else:
                        if not inflight:
                            return
                        cond.wait(timeout=0.05)
                        continue
                else:
                    inflight[(task, False)] = time.perf_counter()
            execute(task, spec)

    from concurrent.futures import ThreadPoolExecutor

    with ThreadPoolExecutor(max_workers=n_workers,
                            thread_name_prefix="dampr-mitigate") as pool:
        list(pool.map(worker, range(n_workers)))
    if failure:
        raise failure[0]
    return results
