"""General mesh exchange: the universal shuffle over ``lax.all_to_all``.

The reference routes *every* keyed exchange — non-associative group_by,
both join families, sort_by redistribution — through one shuffler writing
partition files to a shared filesystem (reference dampr/base.py:416-433,
runner.py:322-335).  :mod:`.shuffle` covers the associative-numeric case with
a fused fold+exchange; this module covers everything else: records whose
values are arbitrary Python objects cross the mesh as *byte payloads* inside
a fixed-shape ``all_to_all``.

Design:

- **Routing** is by partition id: partition ``pid`` lives on device
  ``pid % D``, so a partition's records (from both sides of a join) always
  land on the same device — co-partitioning is preserved by construction,
  exactly like the reference's shared ``Splitter``.
- **Payload** is host-marshalled: each (source shard, destination) pair's
  blocks serialize once per window (columnar pickle — numpy lanes serialize
  as raw buffers), not per record.  The collective moves the real bytes;
  the host only packs/unpacks at the boundary, which is where any system
  marshals opaque Python payloads.
- **Shape** is static per compile bucket: a ``[D*D, C]`` uint8 buffer
  (row ``s*D + d`` = source s's bytes for destination d) plus an int32
  length row, both sharded over the mesh axis.  ``C`` is the pow2 bucket of
  the largest blob in the window, so XLA compiles one program per (mesh, C).
- **Windows**: callers stream bounded windows through the exchange (the
  engine bounds them by the run-store budget), so working memory never
  depends on the total shuffled volume.

There is no overflow/retry here (unlike the capacity-factor scheme in
:func:`.shuffle.mesh_keyed_fold`): the host packs exact sizes, so the buffer
always fits by construction.

- **Budget**: one window is never one collective.  The planner
  (:mod:`.replan`) decomposes each window into a schedule of chunked
  all_to_all steps whose per-step in-flight bytes respect
  ``settings.exchange_hbm_budget`` — blob slices round-robin across
  steps and reassemble in order on the receive side, so peak device
  memory is bounded by configuration while results stay byte-identical.
"""

import functools
import logging
import os
import pickle
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from .. import faults as _faults
from .. import settings
from ..io import codecs as _codecs
from . import mitigate as _mitigate
from . import replan
from .mesh import mesh_size, shard_map as _shard_map

log = logging.getLogger("dampr_tpu.parallel.exchange")


def wire_codec():
    """The per-route payload codec (``settings.exchange_codec``), or None
    for uncompressed wire bytes.  ``auto`` resolves down a zstd -> lz4 ->
    OFF ladder — unlike the spill codec, the exchange never falls back to
    zlib: on an in-memory wire path a slow stdlib DEFLATE costs more
    step latency than the bytes it saves, while the spill path is
    amortized against disk.  Every blob carries a one-byte codec id, so
    a blob whose compressed form isn't smaller ships raw under the same
    framing."""
    name = str(settings.exchange_codec).lower()
    if name in ("off", "0", "false", "no", "none", "raw"):
        return None
    if name == "auto":
        for cand in ("zstd", "lz4"):
            if _codecs.available(cand):
                return _codecs.resolve(cand)
        return None
    try:
        codec = _codecs.resolve(name)
    except ValueError:
        log.warning("unknown exchange_codec %r; sending raw", name)
        return None
    return None if codec.cid == _codecs.RAW else codec


@functools.lru_cache(maxsize=None)
def _build_exchange(mesh, axis, capacity, gather=False):
    """One all_to_all program per (mesh, capacity) bucket: moves the byte
    buffer and the valid-length row across the mesh axis.  ``gather``
    (multi-process runs) replicates the delivered buffers with an
    all_gather so every host process can read the full result — the same
    scheme as mesh_keyed_fold (shuffle.py)."""
    import jax
    from jax import lax
    from jax.sharding import PartitionSpec as P

    def per_device(bb, ln):
        # bb: [D, C] uint8 — row j is this device's payload for device j.
        # After all_to_all, row i is what device i sent us.
        rb = lax.all_to_all(bb, axis, split_axis=0, concat_axis=0)
        rl = lax.all_to_all(ln, axis, split_axis=0, concat_axis=0)
        if gather:
            rb = lax.all_gather(rb, axis, tiled=True)
            rl = lax.all_gather(rl, axis, tiled=True)
        return rb, rl

    out_spec = P() if gather else P(axis)
    kwargs = {}
    if gather:
        # all_gather output IS replicated; the varying-axes inference
        # can't prove it, so disable the check for this variant (same as
        # mesh_keyed_fold's gather path).
        kwargs["check_vma"] = False

    def program(bb, ln):
        return _shard_map(
            per_device, mesh=mesh,
            in_specs=(P(axis), P(axis)),
            out_specs=(out_spec, out_spec), **kwargs)(bb, ln)

    return jax.jit(program)


#: Shape of the LAST exchange this process ran (observability): steps,
#: payload bytes, peak in-flight bytes (per the replan cost model),
#: whether the budget clamped at the capacity floor, and per-device
#: sent/received payload byte counts.  The runner folds these into its
#: per-run ``stats()["mesh"]["exchange"]`` section; the multichip dryrun
#: prints them per device.
last_info = None

#: Process-cumulative exchange-timeout near-misses (steps that finished
#: but only after the watchdog was armed).  Purely observational.
watchdogs_armed = 0


def _step_watchdog(step_i, timeout_ms):
    """Bounded deadline for one collective step: a dead rank wedges a
    gloo collective FOREVER — no Python-level interrupt can break the
    native call — so the only clean abort for the surviving ranks is to
    flush their flight recorders (schema-valid crashdump per rank),
    record the timeout in the run's fault-event sidecar (the next run's
    shuffle routing degrades this stage to the host path), and exit the
    process nonzero.  Returns the event the step sets on completion."""
    done = threading.Event()
    ctx = dict(_faults.run_context)

    def expire():
        if done.wait(timeout_ms / 1000.0):
            return
        from ..obs import flightrec as _flightrec

        exc = TimeoutError(
            "collective exchange step {} exceeded "
            "exchange_timeout_ms={} — a peer rank is dead or wedged; "
            "aborting this rank rather than hanging the gloo "
            "collective".format(step_i, timeout_ms))
        log.error("%s (run=%r stage=%r)", exc, ctx.get("run"),
                  ctx.get("stage"))
        _flightrec.flush_active("exchange-timeout", exc)
        _faults.record_event(
            ctx.get("run"), "exchange_timeout", stage=ctx.get("stage"),
            step=step_i, timeout_ms=timeout_ms)
        os._exit(70)  # EX_SOFTWARE: bounded abort, never a hang

    t = threading.Thread(target=expire, daemon=True,
                         name="dampr-exchange-watchdog")
    t.start()
    return done


def mesh_blob_exchange(mesh, blobs, budget=None, coding=None):
    """Move arbitrary byte blobs across the mesh, under an HBM budget.

    ``blobs``: {(src_device, dst_device): bytes}.  Returns the delivered
    {(src_device, dst_device): bytes} — every blob crossed a collective
    (row ``s*D+d`` of a send buffer lives on device s; the matching row of
    the receive buffer lives on device d).

    The transfer runs as a :mod:`.replan` schedule of chunked all_to_all
    steps whose modeled in-flight bytes respect ``budget`` (default
    ``settings.exchange_hbm_budget``); blob slices reassemble in piece
    order, so the result is byte-identical to a single collective.  Each
    step emits ``exchange`` spans for its pack (h2d staging), collective,
    and unpack (d2h fetch) phases.

    Straggler mitigation (``dampr_tpu.parallel.mitigate``): when an
    engaged controller says to skip this window (degrade-in-place), the
    blobs are returned verbatim — the exchange is a placement transport
    whose delivered content equals its input byte-for-byte (the
    multi-process gather replicates everything to every host), so the
    skip is exact by construction and ``last_info["skipped"]`` records
    it.  On multi-process runs each executed window also piggybacks a
    tiny all_gather of per-rank step-entry times (on the
    ``mesh.clock_sync`` barrier-aligned clock), feeding the controller
    the LIVE form of the skew signal ``obs.fleet.step_skew`` computes
    post-hoc.
    """
    import jax

    from ..obs import trace as _trace

    global last_info
    D = mesh_size(mesh)
    ctl = _mitigate.active()
    if ctl is not None and not ctl.use_collective():
        # Degrade-in-place: the fleet stops serializing on the straggler
        # at every chunked step; content is identical by construction.
        _trace.instant("mitigation", "window-skipped",
                       bytes=sum(len(b) for b in blobs.values()))
        last_info = {
            "steps": 0, "bytes": 0, "peak_inflight_bytes": 0,
            "budget": (budget if budget is not None
                       else settings.exchange_hbm_budget),
            "clamped": False, "skipped": True,
            "sent_per_device": [0] * D, "received_per_device": [0] * D,
            "pair_bytes": {},
        }
        return dict(blobs)
    gather = jax.process_count() > 1
    # Per-route wire compression (settings.exchange_codec): blobs
    # compress BEFORE planning, so the schedule's cells slice WIRE bytes
    # and every downstream byte count (sent/received/pair/steps) is what
    # actually crossed the collective.  One-byte codec id per blob;
    # blobs that don't shrink ship raw under the same framing; empty
    # blobs stay empty (they deliver nothing, coded or not).
    codec = wire_codec()
    codec_info = None
    if codec is not None and blobs:
        raw_total = wire_total = 0
        wire = {}
        for sd, b in blobs.items():
            if not b:
                wire[sd] = b
                continue
            cb = codec.compress(b)
            if len(cb) + 1 < len(b):
                wire[sd] = bytes((codec.cid,)) + cb
            else:
                wire[sd] = bytes((_codecs.RAW,)) + b
            raw_total += len(b)
            wire_total += len(wire[sd])
        blobs = wire
        codec_info = {"name": codec.name, "raw_bytes": raw_total,
                      "wire_bytes": wire_total}
        global codec_raw_bytes, codec_wire_bytes
        codec_raw_bytes += raw_total
        codec_wire_bytes += wire_total
    sched = replan.plan_exchange(
        D, {sd: len(b) for sd, b in blobs.items()},
        budget=budget, gather=gather, coding=coding)
    sent = [0] * D
    received = [0] * D
    pair = {}  # (src_device, dst_device) -> payload bytes this exchange
    for s, d in blobs:
        n = len(blobs[(s, d)])
        if n:
            pair[(s, d)] = pair.get((s, d), 0) + n
    parts = {}
    entry_perf = None

    def pack_step(step):
        """Host-side staging of one step's send buffer.  Pure over
        (blobs, step) — safe to run one step ahead on the packer thread
        while the current step's collective is in flight."""
        t0 = time.perf_counter()
        buf = np.zeros((D * D, step.capacity), dtype=np.uint8)
        lens = np.zeros(D * D, dtype=np.int32)
        sent_inc = [0] * D
        for s, d, start, stop in step.cells:
            row = s * D + d
            n = stop - start
            lens[row] = n
            if n:
                buf[row, :n] = np.frombuffer(
                    blobs[(s, d)], dtype=np.uint8, count=n,
                    offset=start)
                sent_inc[s] += n
        pack_acct["seconds"] += time.perf_counter() - t0
        return buf, lens, sent_inc

    # Double-buffered schedule execution (settings.pipeline,
    # docs/pipeline.md): step k+1's h2d staging packs on a background
    # thread while step k's collective runs, so the host-side copy cost
    # hides behind device time.  The watchdog and fault sites stay
    # strictly per step on the dispatching thread — only the pure pack
    # moved off it.  DAMPR_TPU_PIPELINE=0 restores the serial loop.
    pack_acct = {"seconds": 0.0, "exposed": 0.0}
    packer = None
    if settings.pipeline_enabled() and len(sched.steps) > 1:
        packer = ThreadPoolExecutor(max_workers=1,
                                    thread_name_prefix="dampr-tpu-xpack")
    try:
        nxt = (packer.submit(pack_step, sched.steps[0])
               if packer is not None and sched.steps else None)
        for i, step in enumerate(sched.steps):
            with _trace.span("exchange", "h2d:{}".format(i),
                             step=i, capacity=int(step.capacity)):
                if packer is not None:
                    wait0 = time.perf_counter()
                    buf, lens, sent_inc = nxt.result()
                    pack_acct["exposed"] += time.perf_counter() - wait0
                    if i + 1 < len(sched.steps):
                        nxt = packer.submit(pack_step, sched.steps[i + 1])
                else:
                    s0 = pack_acct["seconds"]
                    buf, lens, sent_inc = pack_step(step)
                    pack_acct["exposed"] += pack_acct["seconds"] - s0
            for s in range(D):
                sent[s] += sent_inc[s]
            prog = _build_exchange(mesh, settings.mesh_axis, step.capacity,
                                   gather=gather)
            # Fault sites: ``rank_kill`` (exit action — the multi-process
            # chaos tests kill one rank mid-exchange here, precisely where
            # a real dead rank would leave its peers hanging) and
            # ``exchange_step`` (classified failures on the step itself).
            _faults.check("rank_kill")
            _faults.check("exchange_step")
            if i == 0:
                # First-step collective entry on this rank's monotonic
                # clock — AFTER the fault checks, so an injected slow
                # stretch (sleep_ms) shows up as entry lateness exactly
                # like real host-side straggling would.  Shared below.
                entry_perf = time.perf_counter()
            timeout_ms = settings.exchange_timeout_ms
            guard = None
            if timeout_ms > 0:
                global watchdogs_armed
                watchdogs_armed += 1
                guard = _step_watchdog(i, timeout_ms)
            try:
                with _trace.span("exchange", "step:{}".format(i), step=i,
                                 bytes=int(step.payload_bytes()),
                                 capacity=int(step.capacity),
                                 inflight_bytes=int(step.inflight_bytes)):
                    rb, rl = prog(buf, lens)
                    rb.block_until_ready()
            finally:
                if guard is not None:
                    guard.set()
            with _trace.span("exchange", "d2h:{}".format(i), step=i):
                rb = np.asarray(rb)
                rl = np.asarray(rl)
                for s, d, _start, _stop in step.cells:
                    row = d * D + s  # device d's local row s = sent by s
                    n = int(rl[row])
                    if n:
                        parts.setdefault((s, d), []).append(
                            rb[row, :n].tobytes())
                        received[d] += n
    finally:
        if packer is not None:
            packer.shutdown(wait=True)
    hidden = max(0.0, pack_acct["seconds"] - pack_acct["exposed"])
    global pack_seconds_total, pack_hidden_seconds_total
    pack_seconds_total += pack_acct["seconds"]
    pack_hidden_seconds_total += hidden
    out = {sd: b"".join(ps) for sd, ps in parts.items()}
    if codec is not None:
        out = {sd: _codecs.decompress(b[0], b[1:])
               for sd, b in out.items()}
    if ctl is not None and gather and entry_perf is not None:
        # Live skew observation: one tiny all_gather of (entry time,
        # transient-fault count) per rank — every rank receives the SAME
        # vector, so controller state transitions stay identical
        # fleet-wide (the invariant the skip/route decisions rely on).
        # The share is a collective like any step, so it gets the same
        # rank-death watchdog: a peer dying between its last payload
        # step and this gather must produce the bounded abort, never a
        # hung gloo collective.
        # Divergence discipline: the except branch below is only safe
        # because everything inside the try is either DETERMINISTIC
        # (the jit build — a compile error fails every rank
        # identically, so every controller misses the same
        # observation) or a COLLECTIVE (whose runtime failures are the
        # watchdog's jurisdiction, same as any payload step).  The
        # pure host-side fold of the gathered vector happens inside
        # _share_skew after the materialization and cannot fail
        # one-sided short of a 64-byte MemoryError.
        timeout_ms = settings.exchange_timeout_ms
        guard = None
        if timeout_ms > 0:
            watchdogs_armed += 1
            guard = _step_watchdog("skew-share", timeout_ms)
        try:
            _share_skew(mesh, D, ctl, entry_perf)
        except Exception:
            log.warning("mitigation skew share failed", exc_info=True)
        finally:
            if guard is not None:
                guard.set()
    for d in range(D):
        if sent[d]:
            sent_bytes_per_device[d] = (
                sent_bytes_per_device.get(d, 0) + sent[d])
        if received[d]:
            received_bytes_per_device[d] = (
                received_bytes_per_device.get(d, 0) + received[d])
    for sd, n in pair.items():
        pair_bytes_per_route[sd] = pair_bytes_per_route.get(sd, 0) + n
    last_info = {
        "steps": sched.n_steps,
        "bytes": sched.total_bytes,
        "peak_inflight_bytes": sched.peak_inflight_bytes,
        "budget": sched.budget,
        "clamped": sched.clamped,
        "sent_per_device": sent,
        "received_per_device": received,
        # (src, dst) -> payload bytes: the full routing matrix of this
        # exchange — obs.fleet folds device routes into the rank-level
        # send/recv matrix the straggler diagnosis reads.
        "pair_bytes": pair,
        # Double-buffer evidence: host pack seconds, the share of them
        # hidden behind the previous step's collective, and whether the
        # overlapped executor ran at all (>1 step + pipeline on).
        "overlap": {
            "pack_seconds": round(pack_acct["seconds"], 6),
            "hidden_seconds": round(hidden, 6),
            "hidden_fraction": (round(hidden / pack_acct["seconds"], 4)
                                if pack_acct["seconds"] > 1e-9 else 0.0),
            "pipelined": packer is not None,
        },
    }
    if codec_info is not None:
        last_info["codec"] = codec_info
    if sched.coding:
        last_info["coding"] = dict(sched.coding)
    return out


@functools.lru_cache(maxsize=None)
def _build_share(mesh, axis):
    """Tiny all_gather program for the mitigation piggyback: every
    device contributes one (entry time, fault count) row; every host
    reads the full per-device matrix."""
    import jax
    from jax import lax
    from jax.sharding import PartitionSpec as P

    def per_device(t):
        return lax.all_gather(t, axis, tiled=True)

    return jax.jit(_shard_map(per_device, mesh=mesh, in_specs=(P(axis),),
                              out_specs=P(), check_vma=False))


_warned_no_clock = False


def _share_skew(mesh, D, ctl, entry_perf):
    """Share this rank's first-step entry time (barrier-aligned clock)
    and cumulative transient-retry count across the fleet, then feed the
    controller's live observation (which differences the retry counts
    per window).  ~D*12 bytes per window — noise next to the payload
    schedule it rides behind."""
    import jax

    from ..obs.fleet import _rank_of_device
    from .mesh import clock_sync

    global _warned_no_clock
    if clock_sync is None:
        # No common clock anchor (the init_distributed barrier
        # handshake failed, symmetrically — it is itself a collective):
        # raw per-host monotonic clocks measure time since each host's
        # BOOT, so cross-rank differences would be pure garbage that
        # could engage on a perfectly healthy fleet.  No observation is
        # strictly better than a wrong one.
        if not _warned_no_clock:
            _warned_no_clock = True
            log.warning(
                "mitigation: no clock handshake (mesh.clock_sync is "
                "None) — live skew observation disabled for this "
                "process; host-path stealing/speculation stay active")
        return
    nproc = jax.process_count()
    base = clock_sync["barrier_perf"]
    # Split integer/fraction lanes: jax truncates float64 inputs to
    # float32 with x64 off, whose ~8 ms quantization past a day of
    # barrier-relative time would dwarf the 20 ms jitter floor.  The
    # integer-seconds lane is exact below 2^24 s and the fraction lane
    # keeps sub-microsecond resolution at any run length.
    t = entry_perf - base
    vec = np.zeros((D, 3), dtype=np.float32)
    vec[:, 0] = np.float32(int(t))
    vec[:, 1] = np.float32(t - int(t))
    vec[:, 2] = np.float32(ctl.local_fault_count())
    out = np.asarray(_build_share(mesh, settings.mesh_axis)(vec))
    entries, fault_counts = {}, {}
    # One authoritative device->rank mapping (the same helper the fleet
    # merge and the weighted route table use — three copies of the
    # ownership assumption could silently disagree).
    for d in range(D):
        r = _rank_of_device(d, nproc, D)
        if r not in entries:
            entries[r] = float(out[d, 0]) + float(out[d, 1])
            fault_counts[r] = int(out[d, 2])
    first = min(entries.values())
    ctl.observe_window({r: t - first for r, t in entries.items()},
                       fault_counts=fault_counts)


def _pack_group(items):
    """[(seq, pid, Block)] -> blob.  Columnar: numpy lanes pickle as raw
    buffers, one serialization per group, never per record."""
    payload = [(seq, pid, (b.keys, b.values, b.h1, b.h2))
               for seq, pid, b in items]
    return pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL)


def _unpack_group(blob):
    from ..blocks import Block

    return [(seq, pid, Block(k, v, h1, h2))
            for seq, pid, (k, v, h1, h2) in pickle.loads(blob)]


#: Process-level cumulative stats (observability; tests assert engagement).
total_exchanges = 0
total_bytes = 0
total_steps = 0
peak_inflight_bytes = 0  # high-water mark across every schedule run
#: Cumulative payload bytes by device index (process-level): what each
#: source device put on the wire and each destination drained — the
#: per-device view the multichip dryrun reports instead of only the
#: aggregate total.
sent_bytes_per_device = {}
received_bytes_per_device = {}
#: Cumulative (src_device, dst_device) -> payload bytes across every
#: exchange this process ran: the device-route matrix.  The runner
#: snapshots per-run deltas into ``stats()["mesh"]["exchange"]`` and
#: obs.fleet aggregates routes into the rank x rank matrix.
pair_bytes_per_route = {}
#: Cumulative per-route codec accounting (settings.exchange_codec):
#: pre-compression payload bytes vs what actually crossed the wire.
codec_raw_bytes = 0
codec_wire_bytes = 0
#: Cumulative double-buffer accounting: host pack seconds across every
#: schedule, and the share that hid behind an in-flight collective.
pack_seconds_total = 0.0
pack_hidden_seconds_total = 0.0


def mesh_shuffle_blocks(mesh, routed, coding=None):
    """Exchange one window of routed blocks across the mesh.

    ``routed``: iterable of (seq, src_shard, pid, Block) — seq is a caller
    sequence number used to restore deterministic per-partition block order
    on the receive side (the engine's group-value order is arrival order,
    reference semantics).  Destination device is ``pid % D`` — unless a
    mitigation controller holds a sticky down-weight, in which case the
    weighted routing table re-maps partitions away from the slow rank's
    devices (content-neutral: placement only, every host reads the full
    delivered set).

    Returns ``(received, bytes_moved)``: received is a list of (pid, Block)
    sorted by seq; bytes_moved counts payload bytes that crossed the
    collective (0 for a mitigation-skipped window — nothing moved).
    """
    from ..obs import trace as _trace

    global total_exchanges, total_bytes, total_steps, peak_inflight_bytes
    D = mesh_size(mesh)
    route = None
    ctl = _mitigate.active()
    if ctl is not None:
        import jax

        route = ctl.route_table(D, jax.process_count())

    def dst(pid):
        return route[pid % len(route)] if route else pid % D

    groups = {}
    for seq, src, pid, blk in routed:
        groups.setdefault((src % D, dst(pid)), []).append((seq, pid, blk))
    blobs = {sd: _pack_group(items) for sd, items in groups.items()}
    moved = sum(len(b) for b in blobs.values())
    with _trace.span("collective", "exchange", bytes=moved,
                     blobs=len(blobs)):
        recv = mesh_blob_exchange(mesh, blobs, coding=coding)
    if last_info is not None and last_info.get("skipped"):
        moved = 0  # degrade-in-place: nothing crossed the mesh
    total_exchanges += 1
    total_bytes += moved
    if last_info is not None:
        total_steps += last_info["steps"]
        peak_inflight_bytes = max(peak_inflight_bytes,
                                  last_info["peak_inflight_bytes"])
    out = []
    for (s, d), blob in recv.items():
        for seq, pid, blk in _unpack_group(blob):
            assert dst(pid) == d, (pid, d)
            out.append((seq, pid, blk))
    out.sort(key=lambda t: t[0])
    return [(pid, blk) for _seq, pid, blk in out], moved
