"""General mesh exchange: the universal shuffle over ``lax.all_to_all``.

The reference routes *every* keyed exchange — non-associative group_by,
both join families, sort_by redistribution — through one shuffler writing
partition files to a shared filesystem (reference dampr/base.py:416-433,
runner.py:322-335).  :mod:`.shuffle` covers the associative-numeric case with
a fused fold+exchange; this module covers everything else: records whose
values are arbitrary Python objects cross the mesh as *byte payloads* inside
a fixed-shape ``all_to_all``.

Design:

- **Routing** is by partition id: partition ``pid`` lives on device
  ``pid % D``, so a partition's records (from both sides of a join) always
  land on the same device — co-partitioning is preserved by construction,
  exactly like the reference's shared ``Splitter``.
- **Payload** is host-marshalled: each (source shard, destination) pair's
  blocks serialize once per window (columnar pickle — numpy lanes serialize
  as raw buffers), not per record.  The collective moves the real bytes;
  the host only packs/unpacks at the boundary, which is where any system
  marshals opaque Python payloads.
- **Shape** is static per compile bucket: a ``[D*D, C]`` uint8 buffer
  (row ``s*D + d`` = source s's bytes for destination d) plus an int32
  length row, both sharded over the mesh axis.  ``C`` is the pow2 bucket of
  the largest blob in the window, so XLA compiles one program per (mesh, C).
- **Windows**: callers stream bounded windows through the exchange (the
  engine bounds them by the run-store budget), so working memory never
  depends on the total shuffled volume.

There is no overflow/retry here (unlike the capacity-factor scheme in
:func:`.shuffle.mesh_keyed_fold`): the host packs exact sizes, so the buffer
always fits by construction.

- **Budget**: one window is never one collective.  The planner
  (:mod:`.replan`) decomposes each window into a schedule of chunked
  all_to_all steps whose per-step in-flight bytes respect
  ``settings.exchange_hbm_budget`` — blob slices round-robin across
  steps and reassemble in order on the receive side, so peak device
  memory is bounded by configuration while results stay byte-identical.
"""

import functools
import logging
import os
import pickle
import threading

import numpy as np

from .. import faults as _faults
from .. import settings
from . import replan
from .mesh import mesh_size, shard_map as _shard_map

log = logging.getLogger("dampr_tpu.parallel.exchange")


@functools.lru_cache(maxsize=None)
def _build_exchange(mesh, axis, capacity, gather=False):
    """One all_to_all program per (mesh, capacity) bucket: moves the byte
    buffer and the valid-length row across the mesh axis.  ``gather``
    (multi-process runs) replicates the delivered buffers with an
    all_gather so every host process can read the full result — the same
    scheme as mesh_keyed_fold (shuffle.py)."""
    import jax
    from jax import lax
    from jax.sharding import PartitionSpec as P

    def per_device(bb, ln):
        # bb: [D, C] uint8 — row j is this device's payload for device j.
        # After all_to_all, row i is what device i sent us.
        rb = lax.all_to_all(bb, axis, split_axis=0, concat_axis=0)
        rl = lax.all_to_all(ln, axis, split_axis=0, concat_axis=0)
        if gather:
            rb = lax.all_gather(rb, axis, tiled=True)
            rl = lax.all_gather(rl, axis, tiled=True)
        return rb, rl

    out_spec = P() if gather else P(axis)
    kwargs = {}
    if gather:
        # all_gather output IS replicated; the varying-axes inference
        # can't prove it, so disable the check for this variant (same as
        # mesh_keyed_fold's gather path).
        kwargs["check_vma"] = False

    def program(bb, ln):
        return _shard_map(
            per_device, mesh=mesh,
            in_specs=(P(axis), P(axis)),
            out_specs=(out_spec, out_spec), **kwargs)(bb, ln)

    return jax.jit(program)


#: Shape of the LAST exchange this process ran (observability): steps,
#: payload bytes, peak in-flight bytes (per the replan cost model),
#: whether the budget clamped at the capacity floor, and per-device
#: sent/received payload byte counts.  The runner folds these into its
#: per-run ``stats()["mesh"]["exchange"]`` section; the multichip dryrun
#: prints them per device.
last_info = None

#: Process-cumulative exchange-timeout near-misses (steps that finished
#: but only after the watchdog was armed).  Purely observational.
watchdogs_armed = 0


def _step_watchdog(step_i, timeout_ms):
    """Bounded deadline for one collective step: a dead rank wedges a
    gloo collective FOREVER — no Python-level interrupt can break the
    native call — so the only clean abort for the surviving ranks is to
    flush their flight recorders (schema-valid crashdump per rank),
    record the timeout in the run's fault-event sidecar (the next run's
    shuffle routing degrades this stage to the host path), and exit the
    process nonzero.  Returns the event the step sets on completion."""
    done = threading.Event()
    ctx = dict(_faults.run_context)

    def expire():
        if done.wait(timeout_ms / 1000.0):
            return
        from ..obs import flightrec as _flightrec

        exc = TimeoutError(
            "collective exchange step {} exceeded "
            "exchange_timeout_ms={} — a peer rank is dead or wedged; "
            "aborting this rank rather than hanging the gloo "
            "collective".format(step_i, timeout_ms))
        log.error("%s (run=%r stage=%r)", exc, ctx.get("run"),
                  ctx.get("stage"))
        _flightrec.flush_active("exchange-timeout", exc)
        _faults.record_event(
            ctx.get("run"), "exchange_timeout", stage=ctx.get("stage"),
            step=step_i, timeout_ms=timeout_ms)
        os._exit(70)  # EX_SOFTWARE: bounded abort, never a hang

    t = threading.Thread(target=expire, daemon=True,
                         name="dampr-exchange-watchdog")
    t.start()
    return done


def mesh_blob_exchange(mesh, blobs, budget=None):
    """Move arbitrary byte blobs across the mesh, under an HBM budget.

    ``blobs``: {(src_device, dst_device): bytes}.  Returns the delivered
    {(src_device, dst_device): bytes} — every blob crossed a collective
    (row ``s*D+d`` of a send buffer lives on device s; the matching row of
    the receive buffer lives on device d).

    The transfer runs as a :mod:`.replan` schedule of chunked all_to_all
    steps whose modeled in-flight bytes respect ``budget`` (default
    ``settings.exchange_hbm_budget``); blob slices reassemble in piece
    order, so the result is byte-identical to a single collective.  Each
    step emits ``exchange`` spans for its pack (h2d staging), collective,
    and unpack (d2h fetch) phases.
    """
    import jax

    from ..obs import trace as _trace

    global last_info
    D = mesh_size(mesh)
    gather = jax.process_count() > 1
    sched = replan.plan_exchange(
        D, {sd: len(b) for sd, b in blobs.items()},
        budget=budget, gather=gather)
    sent = [0] * D
    received = [0] * D
    pair = {}  # (src_device, dst_device) -> payload bytes this exchange
    for s, d in blobs:
        n = len(blobs[(s, d)])
        if n:
            pair[(s, d)] = pair.get((s, d), 0) + n
    parts = {}
    for i, step in enumerate(sched.steps):
        buf = np.zeros((D * D, step.capacity), dtype=np.uint8)
        lens = np.zeros(D * D, dtype=np.int32)
        with _trace.span("exchange", "h2d:{}".format(i),
                         step=i, capacity=int(step.capacity)):
            for s, d, start, stop in step.cells:
                row = s * D + d
                n = stop - start
                lens[row] = n
                if n:
                    buf[row, :n] = np.frombuffer(
                        blobs[(s, d)], dtype=np.uint8, count=n,
                        offset=start)
                    sent[s] += n
        prog = _build_exchange(mesh, settings.mesh_axis, step.capacity,
                               gather=gather)
        # Fault sites: ``rank_kill`` (exit action — the multi-process
        # chaos tests kill one rank mid-exchange here, precisely where a
        # real dead rank would leave its peers hanging) and
        # ``exchange_step`` (classified failures on the step itself).
        _faults.check("rank_kill")
        _faults.check("exchange_step")
        timeout_ms = settings.exchange_timeout_ms
        guard = None
        if timeout_ms > 0:
            global watchdogs_armed
            watchdogs_armed += 1
            guard = _step_watchdog(i, timeout_ms)
        try:
            with _trace.span("exchange", "step:{}".format(i), step=i,
                             bytes=int(step.payload_bytes()),
                             capacity=int(step.capacity),
                             inflight_bytes=int(step.inflight_bytes)):
                rb, rl = prog(buf, lens)
                rb.block_until_ready()
        finally:
            if guard is not None:
                guard.set()
        with _trace.span("exchange", "d2h:{}".format(i), step=i):
            rb = np.asarray(rb)
            rl = np.asarray(rl)
            for s, d, _start, _stop in step.cells:
                row = d * D + s  # device d's local row s = sent by s
                n = int(rl[row])
                if n:
                    parts.setdefault((s, d), []).append(
                        rb[row, :n].tobytes())
                    received[d] += n
    out = {sd: b"".join(ps) for sd, ps in parts.items()}
    for d in range(D):
        if sent[d]:
            sent_bytes_per_device[d] = (
                sent_bytes_per_device.get(d, 0) + sent[d])
        if received[d]:
            received_bytes_per_device[d] = (
                received_bytes_per_device.get(d, 0) + received[d])
    for sd, n in pair.items():
        pair_bytes_per_route[sd] = pair_bytes_per_route.get(sd, 0) + n
    last_info = {
        "steps": sched.n_steps,
        "bytes": sched.total_bytes,
        "peak_inflight_bytes": sched.peak_inflight_bytes,
        "budget": sched.budget,
        "clamped": sched.clamped,
        "sent_per_device": sent,
        "received_per_device": received,
        # (src, dst) -> payload bytes: the full routing matrix of this
        # exchange — obs.fleet folds device routes into the rank-level
        # send/recv matrix the straggler diagnosis reads.
        "pair_bytes": pair,
    }
    return out


def _pack_group(items):
    """[(seq, pid, Block)] -> blob.  Columnar: numpy lanes pickle as raw
    buffers, one serialization per group, never per record."""
    payload = [(seq, pid, (b.keys, b.values, b.h1, b.h2))
               for seq, pid, b in items]
    return pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL)


def _unpack_group(blob):
    from ..blocks import Block

    return [(seq, pid, Block(k, v, h1, h2))
            for seq, pid, (k, v, h1, h2) in pickle.loads(blob)]


#: Process-level cumulative stats (observability; tests assert engagement).
total_exchanges = 0
total_bytes = 0
total_steps = 0
peak_inflight_bytes = 0  # high-water mark across every schedule run
#: Cumulative payload bytes by device index (process-level): what each
#: source device put on the wire and each destination drained — the
#: per-device view the multichip dryrun reports instead of only the
#: aggregate total.
sent_bytes_per_device = {}
received_bytes_per_device = {}
#: Cumulative (src_device, dst_device) -> payload bytes across every
#: exchange this process ran: the device-route matrix.  The runner
#: snapshots per-run deltas into ``stats()["mesh"]["exchange"]`` and
#: obs.fleet aggregates routes into the rank x rank matrix.
pair_bytes_per_route = {}


def mesh_shuffle_blocks(mesh, routed):
    """Exchange one window of routed blocks across the mesh.

    ``routed``: iterable of (seq, src_shard, pid, Block) — seq is a caller
    sequence number used to restore deterministic per-partition block order
    on the receive side (the engine's group-value order is arrival order,
    reference semantics).  Destination device is ``pid % D``.

    Returns ``(received, bytes_moved)``: received is a list of (pid, Block)
    sorted by seq; bytes_moved counts payload bytes that crossed the
    collective.
    """
    from ..obs import trace as _trace

    global total_exchanges, total_bytes, total_steps, peak_inflight_bytes
    D = mesh_size(mesh)
    groups = {}
    for seq, src, pid, blk in routed:
        groups.setdefault((src % D, pid % D), []).append((seq, pid, blk))
    blobs = {sd: _pack_group(items) for sd, items in groups.items()}
    moved = sum(len(b) for b in blobs.values())
    with _trace.span("collective", "exchange", bytes=moved,
                     blobs=len(blobs)):
        recv = mesh_blob_exchange(mesh, blobs)
    total_exchanges += 1
    total_bytes += moved
    if last_info is not None:
        total_steps += last_info["steps"]
        peak_inflight_bytes = max(peak_inflight_bytes,
                                  last_info["peak_inflight_bytes"])
    out = []
    for (s, d), blob in recv.items():
        for seq, pid, blk in _unpack_group(blob):
            assert pid % D == d, (pid, d)
            out.append((seq, pid, blk))
    out.sort(key=lambda t: t[0])
    return [(pid, blk) for _seq, pid, blk in out], moved
