"""General mesh exchange: the universal shuffle over ``lax.all_to_all``.

The reference routes *every* keyed exchange — non-associative group_by,
both join families, sort_by redistribution — through one shuffler writing
partition files to a shared filesystem (reference dampr/base.py:416-433,
runner.py:322-335).  :mod:`.shuffle` covers the associative-numeric case with
a fused fold+exchange; this module covers everything else: records whose
values are arbitrary Python objects cross the mesh as *byte payloads* inside
a fixed-shape ``all_to_all``.

Design:

- **Routing** is by partition id: partition ``pid`` lives on device
  ``pid % D``, so a partition's records (from both sides of a join) always
  land on the same device — co-partitioning is preserved by construction,
  exactly like the reference's shared ``Splitter``.
- **Payload** is host-marshalled: each (source shard, destination) pair's
  blocks serialize once per window (columnar pickle — numpy lanes serialize
  as raw buffers), not per record.  The collective moves the real bytes;
  the host only packs/unpacks at the boundary, which is where any system
  marshals opaque Python payloads.
- **Shape** is static per compile bucket: a ``[D*D, C]`` uint8 buffer
  (row ``s*D + d`` = source s's bytes for destination d) plus an int32
  length row, both sharded over the mesh axis.  ``C`` is the pow2 bucket of
  the largest blob in the window, so XLA compiles one program per (mesh, C).
- **Windows**: callers stream bounded windows through the exchange (the
  engine bounds them by the run-store budget), so working memory never
  depends on the total shuffled volume.

There is no overflow/retry here (unlike the capacity-factor scheme in
:func:`.shuffle.mesh_keyed_fold`): the host packs exact sizes, so the buffer
always fits by construction.
"""

import functools
import pickle

import numpy as np

from .. import settings
from .mesh import mesh_size, shard_map as _shard_map
from .shuffle import _pad_pow2


@functools.lru_cache(maxsize=None)
def _build_exchange(mesh, axis, capacity, gather=False):
    """One all_to_all program per (mesh, capacity) bucket: moves the byte
    buffer and the valid-length row across the mesh axis.  ``gather``
    (multi-process runs) replicates the delivered buffers with an
    all_gather so every host process can read the full result — the same
    scheme as mesh_keyed_fold (shuffle.py)."""
    import jax
    from jax import lax
    from jax.sharding import PartitionSpec as P

    def per_device(bb, ln):
        # bb: [D, C] uint8 — row j is this device's payload for device j.
        # After all_to_all, row i is what device i sent us.
        rb = lax.all_to_all(bb, axis, split_axis=0, concat_axis=0)
        rl = lax.all_to_all(ln, axis, split_axis=0, concat_axis=0)
        if gather:
            rb = lax.all_gather(rb, axis, tiled=True)
            rl = lax.all_gather(rl, axis, tiled=True)
        return rb, rl

    out_spec = P() if gather else P(axis)
    kwargs = {}
    if gather:
        # all_gather output IS replicated; the varying-axes inference
        # can't prove it, so disable the check for this variant (same as
        # mesh_keyed_fold's gather path).
        kwargs["check_vma"] = False

    def program(bb, ln):
        return _shard_map(
            per_device, mesh=mesh,
            in_specs=(P(axis), P(axis)),
            out_specs=(out_spec, out_spec), **kwargs)(bb, ln)

    return jax.jit(program)


def mesh_blob_exchange(mesh, blobs):
    """Move arbitrary byte blobs across the mesh.

    ``blobs``: {(src_device, dst_device): bytes}.  Returns the delivered
    {(src_device, dst_device): bytes} — every blob crossed the collective
    (row ``s*D+d`` of the send buffer lives on device s; the matching row of
    the receive buffer lives on device d).
    """
    D = mesh_size(mesh)
    max_len = max((len(b) for b in blobs.values()), default=0)
    capacity = _pad_pow2(max(1, max_len), floor=64)
    buf = np.zeros((D * D, capacity), dtype=np.uint8)
    lens = np.zeros(D * D, dtype=np.int32)
    for (s, d), blob in blobs.items():
        row = s * D + d
        lens[row] = len(blob)
        if blob:
            buf[row, : len(blob)] = np.frombuffer(blob, dtype=np.uint8)
    import jax

    prog = _build_exchange(mesh, settings.mesh_axis, capacity,
                           gather=jax.process_count() > 1)
    rb, rl = prog(buf, lens)
    rb = np.asarray(rb)
    rl = np.asarray(rl)
    out = {}
    for d in range(D):
        for s in range(D):
            row = d * D + s  # device d's local row s = what s sent to d
            n = int(rl[row])
            if n:
                out[(s, d)] = rb[row, :n].tobytes()
    return out


def _pack_group(items):
    """[(seq, pid, Block)] -> blob.  Columnar: numpy lanes pickle as raw
    buffers, one serialization per group, never per record."""
    payload = [(seq, pid, (b.keys, b.values, b.h1, b.h2))
               for seq, pid, b in items]
    return pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL)


def _unpack_group(blob):
    from ..blocks import Block

    return [(seq, pid, Block(k, v, h1, h2))
            for seq, pid, (k, v, h1, h2) in pickle.loads(blob)]


#: Process-level cumulative stats (observability; tests assert engagement).
total_exchanges = 0
total_bytes = 0


def mesh_shuffle_blocks(mesh, routed):
    """Exchange one window of routed blocks across the mesh.

    ``routed``: iterable of (seq, src_shard, pid, Block) — seq is a caller
    sequence number used to restore deterministic per-partition block order
    on the receive side (the engine's group-value order is arrival order,
    reference semantics).  Destination device is ``pid % D``.

    Returns ``(received, bytes_moved)``: received is a list of (pid, Block)
    sorted by seq; bytes_moved counts payload bytes that crossed the
    collective.
    """
    from ..obs import trace as _trace

    global total_exchanges, total_bytes
    D = mesh_size(mesh)
    groups = {}
    for seq, src, pid, blk in routed:
        groups.setdefault((src % D, pid % D), []).append((seq, pid, blk))
    blobs = {sd: _pack_group(items) for sd, items in groups.items()}
    moved = sum(len(b) for b in blobs.values())
    with _trace.span("collective", "exchange", bytes=moved,
                     blobs=len(blobs)):
        recv = mesh_blob_exchange(mesh, blobs)
    total_exchanges += 1
    total_bytes += moved
    out = []
    for (s, d), blob in recv.items():
        for seq, pid, blk in _unpack_group(blob):
            assert pid % D == d, (pid, d)
            out.append((seq, pid, blk))
    out.sort(key=lambda t: t[0])
    return [(pid, blk) for _seq, pid, blk in out], moved
