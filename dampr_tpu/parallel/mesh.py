"""Mesh construction helpers.

One logical axis (``settings.mesh_axis``) carries data-parallel record shards;
the same axis carries the all_to_all shuffle.  Multi-host topologies reuse the
identical program: jax enumerates global devices and XLA routes ICI within a
host/slice and DCN across, so nothing here is host-count-aware.
"""

import numpy as np

from .. import settings


def data_mesh(devices=None, n=None):
    """A 1-D mesh over ``devices`` (default: all) named by settings.mesh_axis."""
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    if n is not None:
        assert n <= len(devices), (
            "requested {} devices, have {}".format(n, len(devices)))
        devices = devices[:n]
    return Mesh(np.asarray(devices), (settings.mesh_axis,))


def default_mesh():
    return data_mesh()


def mesh_size(mesh):
    return int(np.prod(list(mesh.shape.values())))
