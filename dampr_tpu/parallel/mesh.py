"""Mesh construction helpers.

One logical axis (``settings.mesh_axis``) carries data-parallel record shards;
the same axis carries the all_to_all shuffle.  Multi-host topologies reuse the
identical program: jax enumerates global devices and XLA routes ICI within a
host/slice and DCN across, so nothing here is host-count-aware.
"""

import numpy as np

from .. import settings


def data_mesh(devices=None, n=None):
    """A 1-D mesh over ``devices`` (default: all) named by settings.mesh_axis."""
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    if n is not None:
        assert n <= len(devices), (
            "requested {} devices, have {}".format(n, len(devices)))
        devices = devices[:n]
    return Mesh(np.asarray(devices), (settings.mesh_axis,))


def default_mesh():
    return data_mesh()


def mesh_size(mesh):
    return int(np.prod(list(mesh.shape.values())))


def init_distributed(coordinator_address=None, num_processes=None,
                     process_id=None):
    """Join a multi-host deployment: after this, ``jax.devices()`` spans every
    host's chips and the same mesh programs run with XLA routing ICI within a
    slice and DCN across hosts — no other code changes (the mesh abstraction
    is host-count-agnostic by design, SURVEY §7 hard part 5).

    Arguments default to the DAMPR_TPU_COORDINATOR / DAMPR_TPU_NUM_PROCESSES
    / DAMPR_TPU_PROCESS_ID environment variables (the engine's own spelling,
    set per rank by launchers and the multi-process benches), falling back
    to JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID (read
    here — jax itself only reads the coordinator address) or to full
    auto-detection on managed clusters (cloud TPU pods, Slurm, k8s).  Call
    once per process before any jax use.
    """
    import os

    import jax

    if coordinator_address is None:
        coordinator_address = (os.environ.get("DAMPR_TPU_COORDINATOR")
                               or os.environ.get("JAX_COORDINATOR_ADDRESS")
                               or None)
    if num_processes is None:
        raw = (os.environ.get("DAMPR_TPU_NUM_PROCESSES")
               or os.environ.get("JAX_NUM_PROCESSES"))
        if raw:
            num_processes = int(raw)
    if process_id is None:
        raw = (os.environ.get("DAMPR_TPU_PROCESS_ID")
               or os.environ.get("JAX_PROCESS_ID"))
        if raw is not None and raw != "":
            process_id = int(raw)

    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id

    # CPU-only deployments need an explicit cross-process collectives
    # implementation: the XLA CPU client ships none by default ("
    # Multiprocess computations aren't implemented on the CPU backend"),
    # but jaxlib bundles gloo TCP collectives — selecting them here makes
    # the same mesh programs span processes on plain CPUs (the tier-1
    # two-process backend test runs exactly this path).  Guarded: older
    # jax without the flag, or non-CPU platforms, are left untouched.
    try:
        platforms = jax.config.jax_platforms or ""
        first = platforms.split(",")[0]
        # Engage unless a non-CPU platform is explicitly selected: with
        # platform auto-detection (JAX_PLATFORMS unset) the flag is still
        # safe — it configures only the CPU client's collectives, which
        # accelerator deployments never route through.
        if (first in ("", "cpu")
                and "jax_cpu_collectives_implementation"
                in jax.config.values):
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # noqa: BLE001 - best-effort; initialize() decides
        pass
    jax.distributed.initialize(**kwargs)
    global _initialized
    _initialized = True
    _clock_handshake()


_initialized = False

#: Clock-alignment record from the post-init barrier handshake, or None
#: (single-process, or the handshake collective failed).  Keys:
#: ``barrier_perf`` — this process's ``time.perf_counter()`` captured the
#: instant the post-init barrier collective RETURNED (every rank exits a
#: barrier within network latency of the same wall moment, so this value
#: is the per-rank anchor of one fleet-common instant — no wall-clock
#: trust, NTP drift never enters the merged timeline); ``barrier_wall``
#: — ``time.time()`` at the same instant (display only, never used for
#: alignment); ``method`` — which collective produced the barrier.
clock_sync = None


def _clock_handshake():
    """Barrier-timestamp handshake: run one collective every rank must
    enter, and record the per-rank monotonic clock at its exit.  The
    fleet trace merge (:mod:`dampr_tpu.obs.fleet`) subtracts each rank's
    ``barrier_perf`` from its span timestamps, so per-rank timelines
    align on the barrier instant instead of trusting wall clocks.
    Best-effort: a failed handshake leaves ``clock_sync`` None and the
    merge falls back to wall-start alignment (recorded as degraded)."""
    global clock_sync
    import time

    import jax

    if jax.process_count() <= 1:
        return
    method = None
    try:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("dampr_tpu_clock_handshake")
        method = "sync_global_devices"
    except Exception:
        try:
            # Older jax without multihost_utils: a tiny psum across all
            # devices is an equivalent barrier (every process must
            # contribute before any result materializes).
            import numpy as np

            val = jax.pmap(lambda x: jax.lax.psum(x, "i"), axis_name="i")(
                np.ones((len(jax.local_devices()),), dtype=np.float32))
            np.asarray(val)
            method = "psum"
        except Exception:
            clock_sync = None
            return
    clock_sync = {
        "barrier_perf": time.perf_counter(),
        "barrier_wall": time.time(),
        "method": method,
    }


def rank_info():
    """``(process_id, num_processes)`` WITHOUT forcing a jax backend
    init: once the process group is up the authoritative jax values are
    used; before that (or in never-distributed processes) the launcher
    env (``DAMPR_TPU_PROCESS_ID`` / ``DAMPR_TPU_NUM_PROCESSES``, JAX_*
    fallback) is read, defaulting to ``(0, 1)``.  This is the gate the
    observability plane tags every artifact with — it must stay safe to
    call from finalizers, crash paths, and CLI tools that never touch
    jax."""
    import os

    if _initialized:
        import jax

        return jax.process_index(), jax.process_count()
    raw_n = (os.environ.get("DAMPR_TPU_NUM_PROCESSES")
             or os.environ.get("JAX_NUM_PROCESSES"))
    raw_id = (os.environ.get("DAMPR_TPU_PROCESS_ID")
              or os.environ.get("JAX_PROCESS_ID"))
    try:
        n = int(raw_n) if raw_n else 1
        pid = int(raw_id) if raw_id not in (None, "") else 0
    except ValueError:
        return 0, 1
    if n <= 1:
        return 0, 1
    return pid, n


def maybe_init_distributed():
    """Join a multi-process deployment IF the environment configures one
    (``DAMPR_TPU_COORDINATOR`` / ``JAX_COORDINATOR_ADDRESS`` set), else
    no-op.  Idempotent — safe to call from every CLI entry point and
    bench main, so any dampr_tpu process dropped onto a pod rank with the
    coordinator env wired joins the process group before its first jax
    use with zero code changes (the pjit-spans-processes property,
    SNIPPETS [1]).  Returns True when this call performed the init."""
    import os

    if _initialized:
        return False
    if not (os.environ.get("DAMPR_TPU_COORDINATOR")
            or os.environ.get("JAX_COORDINATOR_ADDRESS")):
        return False
    init_distributed()
    return True


def process_info():
    """This process's view of the deployment, for reports and logs:
    process id/count, local vs global device counts, and whether the
    backend actually spans processes.  Touches jax (initializes the
    backend if needed) — call it for reporting, not gating; gates use
    ``settings.device_count_for_auto`` which never forces an init."""
    import jax

    return {
        "process_id": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
        "multiprocess": jax.process_count() > 1,
    }


def shard_map(f, mesh, in_specs, out_specs, **kwargs):
    """``jax.shard_map`` across jax versions.  Newer jax exposes it
    top-level with the vma-typed replication check (``check_vma``); 0.4.x
    ships ``jax.experimental.shard_map`` with ``check_rep`` instead.
    Callers always pass the new-API kwargs; the legacy spelling is mapped
    here so every mesh program has exactly one compatibility seam."""
    import jax

    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kwargs)
    from jax.experimental.shard_map import shard_map as _legacy

    if "check_vma" in kwargs:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    return _legacy(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   **kwargs)
