"""Mini-batch SGD via data-parallel gradient psum — the stretch workload from
BASELINE.md (mini-batch logistic regression with map/reduce gradients).

The reference has no ML layer at all; this is the TPU-native expression of
its "aggregate partial results per partition, combine globally" pattern
(SURVEY §2 parallelism item 3): per-device gradient = the map-side partial,
``lax.psum`` = the reduce.  The matmuls are MXU-shaped: features on the
contracting dimension, batch sharded over the mesh axis.
"""

import functools

import numpy as np

from .. import settings
from .mesh import mesh_size, shard_map as _shard_map


def init_params(n_features, seed=0):
    rng = np.random.RandomState(seed)
    return {"w": (rng.randn(n_features) * 0.01).astype(np.float32),
            "b": np.float32(0.0)}


def _loss_fn(params, X, y):
    import jax.numpy as jnp

    logits = X @ params["w"] + params["b"]
    # numerically-stable logistic loss, mean over the *local* shard
    return jnp.mean(jnp.maximum(logits, 0) - logits * y
                    + jnp.log1p(jnp.exp(-jnp.abs(logits))))


@functools.lru_cache(maxsize=None)
def _build_train_step(mesh, lr, axis):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    # Batch shards over the mesh axis, params replicated.  The shard_map
    # computes per-device shard losses; differentiation happens OUTSIDE, so
    # the cross-device gradient combine is inserted by the transpose rules
    # (an automatic psum over the replicated params) rather than hand-written
    # — hand-psum'ing inside would double-count under vma-typed shard_map.
    per_shard_loss = _shard_map(
        lambda p, xs, ys: jnp.expand_dims(_loss_fn(p, xs, ys), 0),
        mesh=mesh,
        in_specs=(P(), P(axis), P(axis)),
        out_specs=P(axis),
    )

    def global_loss(params, X, y):
        return jnp.mean(per_shard_loss(params, X, y))

    def step(params, X, y):
        loss, grads = jax.value_and_grad(global_loss)(params, X, y)
        new_params = jax.tree.map(lambda p, g: p - lr * g, params, grads)
        return new_params, loss

    return jax.jit(step)


def train_step(mesh, params, X, y, lr=0.1):
    """One DP SGD step over the mesh: X [B, F] and y [B] sharded on batch,
    params replicated, gradients psum'd over ICI."""
    step = _build_train_step(mesh, float(lr), settings.mesh_axis)
    return step(params, X, y)


def train(mesh, X, y, n_steps=50, lr=0.5, seed=0):
    """Full training loop; returns (params, final_loss)."""
    n_dev = mesh_size(mesh)
    n = (len(X) // n_dev) * n_dev  # equal shards
    X = np.asarray(X, dtype=np.float32)[:n]
    y = np.asarray(y, dtype=np.float32)[:n]
    params = init_params(X.shape[1], seed)
    loss = None
    for _ in range(n_steps):
        params, loss = train_step(mesh, params, X, y, lr)
    return params, float(loss)
