"""The mesh keyed shuffle: local fold → all_to_all → final fold.

This is the TPU-native ``DefaultShuffler`` (reference base.py:416-433): where
the reference hash-routes every record to partition files on a shared
filesystem, this routes compacted (hash, value) pairs across the device mesh
with a single fixed-shape ``lax.all_to_all`` over the ICI, inside one
``shard_map`` program:

1. **Local combine** (communication avoidance — the reference's
   ``PartialReduceCombiner``/``ReducedWriter`` map-side pass, SURVEY §3.3):
   sort the device-local records by their 64-bit hash pair and segment-fold,
   so at most one record per distinct key crosses the wire.
2. **Route**: destination device = ``h1 % n_devices``.  Each device packs a
   ``[D, C]`` capacity buffer per destination (MoE-style fixed capacity,
   ``settings.shuffle_capacity_factor``); overflow is *detected* (psum'd
   count) and the host wrapper retries with doubled capacity, so results are
   never silently dropped.
3. **Exchange**: ``lax.all_to_all`` — row j of the receive buffer is what
   device j sent us.
4. **Final fold**: flatten, sort, segment-fold the received pairs.

Exactness: grouping is on the full (h1, h2) 64-bit pair.  Distinct real keys
colliding in all 64 bits are astronomically rare and are repaired at the host
boundary when real keys materialize (same contract as the single-chip path,
ops/segment.py).

Everything is shape-static and data-independent-control-flow, so XLA compiles
one program per (N_local, D, C, dtype) bucket.
"""

import functools

import numpy as np

from .. import settings
from .mesh import mesh_size, shard_map as _shard_map

_INVALID_SLOT_PAD = 1  # extra scatter slot that swallows dropped writes


def _segments(inv, h1, h2):
    """Boolean starts for runs of equal (inv, h1, h2) over sorted arrays."""
    import jax.numpy as jnp

    n = h1.shape[0]
    iota = jnp.arange(n, dtype=jnp.int32)
    prev_ne = ((h1 != jnp.roll(h1, 1)) | (h2 != jnp.roll(h2, 1))
               | (inv != jnp.roll(inv, 1)))
    return jnp.where(iota == 0, True, prev_ne)


def _local_fold(inv, h1, h2, v, kind, nonneg_sum=False):
    """Sort by (validity, h1, h2) and fold values per segment.  Returns
    (inv, h1, h2, v) arrays of the same length: one live entry per segment,
    dead entries marked invalid.

    Two lowerings, selected statically:

    - ``nonneg_sum`` (the count/len/doc-freq hot path): pure scan fold —
      sort, then segment totals land at segment *end* positions via
      ``cumsum`` + a ``cummax``-carried start offset.  No scatter at all;
      on a v5e this runs 6.7x faster than the scatter lowering because XLA's
      TPU scatter serializes random updates while sort and scan are
      bandwidth-bound (measured: 279 vs 42 M records/s at 4M records —
      benchmarks/RESULTS.md).  Exact because the host wrapper only sets the
      flag for signed integer values whose *global* sum fits the lane dtype,
      so the running cumsum cannot wrap and is order-exact.
    - otherwise: segment_sum/min/max scatters into segment-id slots (handles
      negative sums and min/max, where a monotone carried scan doesn't
      apply).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    n = h1.shape[0]
    inv, h1, h2, v = lax.sort((inv, h1, h2, v), num_keys=3, is_stable=True)
    starts = _segments(inv, h1, h2)

    if nonneg_sum and kind == "sum":
        return _scan_fold_sorted(inv, h1, h2, v, starts)

    seg_id = jnp.cumsum(starts.astype(jnp.int32)) - 1
    if kind == "sum":
        folded = jax.ops.segment_sum(v, seg_id, num_segments=n)
    elif kind == "min":
        folded = jax.ops.segment_min(v, seg_id, num_segments=n)
    elif kind == "max":
        folded = jax.ops.segment_max(v, seg_id, num_segments=n)
    else:
        raise ValueError(kind)

    ns = n  # segments indexed [0, n)
    seg_h1 = jax.ops.segment_max(h1, seg_id, num_segments=ns)
    seg_h2 = jax.ops.segment_max(h2, seg_id, num_segments=ns)
    # A segment is live iff it contains at least one valid record; invalid
    # records sort last so any segment containing them is all-invalid.
    live = jax.ops.segment_max(
        jnp.where(inv == 0, jnp.int32(1), jnp.int32(0)), seg_id,
        num_segments=ns)
    n_segs = jnp.sum(starts.astype(jnp.int32))
    in_range = jnp.arange(ns, dtype=jnp.int32) < n_segs
    live = (live == 1) & in_range
    return (jnp.where(live, jnp.uint32(0), jnp.uint32(1)),
            seg_h1, seg_h2, folded)


def _scan_fold_sorted(inv, h1, h2, v, starts=None):
    """The post-sort scan chain of the nonneg-sum lowering (see
    _local_fold): segment totals land at segment-end positions via cumsum +
    a cummax-carried start offset, no scatters.  Exposed separately so
    benchmarks/pallas_bench.py can compare it against the fused Pallas
    kernel on identical pre-sorted inputs."""
    import jax.numpy as jnp
    from jax import lax

    if starts is None:
        starts = _segments(inv, h1, h2)
    ends = jnp.concatenate(
        [starts[1:], jnp.ones((1,), dtype=starts.dtype)])
    csum = jnp.cumsum(v)
    ex = csum - v  # exclusive prefix, nonneg + monotone by assumption
    start_ex = lax.cummax(jnp.where(starts, ex, -1))
    tot = jnp.where(ends, csum - start_ex, 0).astype(v.dtype)
    # The end entry of a segment carries the segment's own (h1, h2);
    # invalid records sort last and form all-invalid segments.
    live = ends & (inv == 0)
    return (jnp.where(live, jnp.uint32(0), jnp.uint32(1)), h1, h2, tot)


def _pack_by_dest(inv, h1, h2, v, n_dev, capacity):
    """Scatter live entries into fixed [D, C] per-destination buffers.
    Returns (send_valid, send_h1, send_h2, send_v, n_dropped)."""
    import jax.numpy as jnp
    from jax import lax

    n = h1.shape[0]
    dest = (h1 % jnp.uint32(n_dev)).astype(jnp.uint32)
    # Sort by (validity, dest) so each destination's entries are contiguous.
    inv, dest, h1, h2, v = lax.sort((inv, dest, h1, h2, v), num_keys=2,
                                    is_stable=True)
    iota = jnp.arange(n, dtype=jnp.int32)
    new_group = jnp.where(
        iota == 0, True,
        (dest != jnp.roll(dest, 1)) | (inv != jnp.roll(inv, 1)))
    start_iota = lax.cummax(jnp.where(new_group, iota, 0))
    rank = iota - start_iota

    valid = inv == 0
    keep = valid & (rank < capacity)
    dropped = jnp.sum(valid & (rank >= capacity)).astype(jnp.int32)

    flat = n_dev * capacity
    slot = jnp.where(keep, dest.astype(jnp.int32) * capacity + rank, flat)
    buf_h1 = jnp.zeros(flat + _INVALID_SLOT_PAD, dtype=h1.dtype).at[slot].set(h1)
    buf_h2 = jnp.zeros(flat + _INVALID_SLOT_PAD, dtype=h2.dtype).at[slot].set(h2)
    buf_v = jnp.zeros(flat + _INVALID_SLOT_PAD, dtype=v.dtype).at[slot].set(v)
    buf_ok = jnp.zeros(flat + _INVALID_SLOT_PAD, dtype=jnp.uint32).at[slot].set(
        jnp.where(keep, jnp.uint32(1), jnp.uint32(0)))

    shape = (n_dev, capacity)
    return (buf_ok[:flat].reshape(shape), buf_h1[:flat].reshape(shape),
            buf_h2[:flat].reshape(shape), buf_v[:flat].reshape(shape), dropped)


@functools.lru_cache(maxsize=None)
def _build_fold_program(mesh, n_dev, n_local, capacity, kind, v_dtype_name,
                        axis, nonneg_sum=False, gather=False):
    """Compile the full shard_map keyed-fold program for one shape bucket.
    ``mesh`` participates in the cache key so re-meshing recompiles."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    v_dtype = jnp.dtype(v_dtype_name)

    def per_device(h1, h2, v, valid):
        # shapes: [n_local] each (the device-local shard)
        inv = jnp.where(valid == 1, jnp.uint32(0), jnp.uint32(1))

        # 1. local combine
        inv, h1, h2, v = _local_fold(inv, h1, h2, v, kind, nonneg_sum)

        # 2. pack per destination
        ok, sh1, sh2, sv, dropped = _pack_by_dest(inv, h1, h2, v, n_dev,
                                                  capacity)

        # 3. exchange over the mesh axis
        rok = lax.all_to_all(ok, axis, split_axis=0, concat_axis=0)
        rh1 = lax.all_to_all(sh1, axis, split_axis=0, concat_axis=0)
        rh2 = lax.all_to_all(sh2, axis, split_axis=0, concat_axis=0)
        rv = lax.all_to_all(sv, axis, split_axis=0, concat_axis=0)

        # 4. final fold over everything received (partial sums of nonneg
        # values stay nonneg, so the scan lowering remains applicable)
        flat = n_dev * capacity
        inv2 = jnp.where(rok.reshape(flat) == 1, jnp.uint32(0), jnp.uint32(1))
        inv2, fh1, fh2, fv = _local_fold(
            inv2, rh1.reshape(flat), rh2.reshape(flat), rv.reshape(flat),
            kind, nonneg_sum)

        total_dropped = lax.psum(dropped, axis)
        out_valid = jnp.where(inv2 == 0, jnp.uint32(1), jnp.uint32(0))
        if gather:
            # Multi-process runs cannot fetch axis-sharded outputs at the
            # host boundary (shards live on other hosts' devices), so
            # replicate results with one all_gather ring over ICI/DCN.
            fh1 = lax.all_gather(fh1, axis, tiled=True)
            fh2 = lax.all_gather(fh2, axis, tiled=True)
            fv = lax.all_gather(fv, axis, tiled=True)
            out_valid = lax.all_gather(out_valid, axis, tiled=True)
        return fh1, fh2, fv, out_valid, total_dropped

    def program(h1, h2, v, valid):
        out_spec = P() if gather else P(axis)
        kwargs = {}
        if gather:
            # all_gather output IS replicated; the varying-axes inference
            # can't prove it, so disable the check for this variant.
            kwargs["check_vma"] = False
        return _shard_map(
            per_device,
            mesh=mesh,
            in_specs=(P(axis), P(axis), P(axis), P(axis)),
            out_specs=(out_spec, out_spec, out_spec, out_spec, P()),
            **kwargs)(h1, h2, v, valid)

    return jax.jit(program)


def _pad_pow2(n, floor=8):
    return max(floor, 1 << max(0, (n - 1).bit_length()))


_I32_MAX = 2 ** 31 - 1
_I64_MAX = 2 ** 63 - 1


def _lane_safe_values(v, kind):
    """Make values exact in the device lanes, or refuse loudly.

    With jax_enable_x64 off the mesh program runs 32-bit lanes; silent
    truncation would corrupt folds, so every dtype is whitelisted: floats
    ride as float32 (float64 refuses — precision), every integer dtype
    (signed, unsigned, any width) exact-casts into the checked int32 lane or
    refuses (same contract as the single-chip path, which falls back to
    exact host folds — ops/segment.py _device_fold_exact)."""
    import jax

    if v.dtype == object:
        raise ValueError("object values cannot ride the mesh fold lanes")
    if jax.config.jax_enable_x64:
        return v
    if v.dtype == np.float32:
        return v
    if v.dtype == np.float16:
        return v.astype(np.float32)  # exact widening
    if v.dtype == np.float64:
        raise ValueError(
            "float64 values would silently fold at float32 precision on "
            "device; pass float32 explicitly or enable jax_enable_x64")
    if v.dtype == np.bool_ or v.dtype.kind in "iu":
        if v.dtype == np.uint64 and len(v) and int(v.max()) > _I64_MAX:
            raise ValueError(
                "uint64 values exceed the device fold lanes; "
                "enable jax_enable_x64 or pre-scale")
        v64 = v.astype(np.int64)
        if not len(v64):
            return v64.astype(np.int32)
        lo, hi = int(v64.min()), int(v64.max())
        in_range = lo >= -_I32_MAX - 1 and hi <= _I32_MAX
        if in_range and (kind != "sum"
                         or int(np.abs(v64).sum()) <= _I32_MAX):
            return v64.astype(np.int32)
        raise ValueError(
            "integer values exceed the 32-bit device fold lanes "
            "(min={}, max={}); enable jax_enable_x64 or pre-scale".format(
                lo, hi))
    raise ValueError(
        "unsupported value dtype {} for mesh folds".format(v.dtype))


def mesh_keyed_fold(mesh, h1, h2, v, kind="sum", capacity_factor=None,
                    raw=False):
    """Distributed keyed fold over a device mesh.

    ``h1``/``h2``: uint32 hash lanes, ``v``: numeric values (int32/int64/
    float32 — int64 values fold in int32 lanes unless x64 is enabled).
    Returns ``(h1, h2, v)`` numpy arrays with one entry per distinct (h1, h2)
    pair, in unspecified order.  Retries with doubled capacity on overflow, so
    the result is complete regardless of key skew.

    ``raw=True`` keeps the result DEVICE-RESIDENT: returns the padded
    ``(h1, h2, v, ok)`` jax arrays (ok == 1 marks live entries) without the
    host fetch/compact, so callers accumulating partials across windows
    (runner._mesh_reduce) never round-trip intermediates through the host —
    they re-fold partials with :func:`mesh_keyed_refold` and fetch once.
    """
    import jax

    n_dev = mesh_size(mesh)
    total = len(h1)
    if total == 0:
        if raw:
            import jax.numpy as jnp

            z = jnp.zeros(0, jnp.uint32)
            # lane-cast even when empty so the dtype matches non-empty
            # partials a caller may mix this with in mesh_keyed_refold
            ev = _lane_safe_values(np.asarray(v)[:0], kind)
            return z, z, jnp.asarray(ev), z
        return (np.empty(0, np.uint32), np.empty(0, np.uint32),
                np.asarray(v)[:0])

    n_local = _pad_pow2(-(-total // n_dev))
    padded = n_local * n_dev
    ph1 = np.zeros(padded, dtype=np.uint32)
    ph2 = np.zeros(padded, dtype=np.uint32)
    v = _lane_safe_values(np.asarray(v), kind)
    pv = np.zeros(padded, dtype=v.dtype)
    pvalid = np.zeros(padded, dtype=np.uint32)
    ph1[:total] = h1
    ph2[:total] = h2
    pv[:total] = v
    pvalid[:total] = 1

    factor = capacity_factor or settings.shuffle_capacity_factor
    # Integer nonneg sums (count/len/doc-freq — the hot aggregations) take
    # the scan fold lowering (padding rows are zero, so they cannot break
    # the nonneg invariant).  The lowering needs (a) a signed dtype — its -1
    # start sentinel wraps on unsigned lanes — and (b) a global-cumsum bound
    # in the lane dtype, not just per-key bounds: with x64 off the
    # _lane_safe_values cast above already proved abs-sum <= int32 max; with
    # x64 on the values passed through unchecked, so bound them here.
    nonneg = False
    if (kind == "sum" and v.dtype.kind == "i"
            and (not len(v) or int(v.min()) >= 0)):
        if not len(v):
            nonneg = True
        elif v.dtype == np.int32:
            if jax.config.jax_enable_x64:
                nonneg = int(v.sum(dtype=np.int64)) <= _I32_MAX
            else:
                nonneg = True  # abs-sum check ran in _lane_safe_values
        elif v.dtype == np.int64:
            nonneg = len(v) * int(v.max()) <= _I64_MAX
    fh1, fh2, fv, ok = _run_fold_padded(
        mesh, ph1, ph2, pv, pvalid, n_dev, n_local, kind, nonneg, factor)
    if raw:
        return fh1, fh2, fv, ok
    mask = np.asarray(ok) == 1
    return (np.asarray(fh1)[mask], np.asarray(fh2)[mask],
            np.asarray(fv)[mask])


def _run_fold_padded(mesh, h1, h2, v, valid, n_dev, n_local, kind, nonneg,
                     factor):
    """Shared capacity-retry loop over already-padded (host or device)
    arrays: compile the program for the current capacity bucket, run,
    double on overflow."""
    import jax

    from ..obs import trace as _trace
    from ..ops import devtime

    capacity = max(8, int(-(-n_local // n_dev) * factor))
    axis = settings.mesh_axis
    gather = jax.process_count() > 1
    while True:
        prog = _build_fold_program(mesh, n_dev, n_local, capacity, kind,
                                   np.dtype(v.dtype).name, axis, nonneg,
                                   gather)
        with devtime.track("device"), _trace.span(
                "collective", "keyed-fold:{}".format(kind),
                records=int(n_local * n_dev), capacity=int(capacity)):
            fh1, fh2, fv, ok, dropped = prog(h1, h2, v, valid)
            dropped = int(dropped)
        if dropped == 0:
            return fh1, fh2, fv, ok
        capacity *= 2


def mesh_keyed_refold(mesh, parts, kind, nonneg=False, capacity_factor=None):
    """Re-fold device-resident partials from ``mesh_keyed_fold(raw=True)``.

    ``parts``: list of (h1, h2, v, ok) jax arrays.  Everything — concat,
    padding, the collective fold — stays on device; only the overflow
    scalar is fetched per retry.  Lane safety is the CALLER's contract: it
    must bound the elementwise abs-sum across every window it folded (the
    engine tracks the running bound host-side before uploading windows),
    because partial magnitudes are bounded by element magnitudes.  All
    parts must share one value dtype (the engine guards this)."""
    import jax
    import jax.numpy as jnp

    h1 = jnp.concatenate([p[0] for p in parts])
    h2 = jnp.concatenate([p[1] for p in parts])
    v = jnp.concatenate([p[2] for p in parts])
    valid = jnp.concatenate([p[3] for p in parts])

    n_dev = mesh_size(mesh)
    total = h1.shape[0]
    n_local = _pad_pow2(-(-total // n_dev))
    padded = n_local * n_dev
    if padded != total:
        pad = padded - total
        h1 = jnp.pad(h1, (0, pad))
        h2 = jnp.pad(h2, (0, pad))
        v = jnp.pad(v, (0, pad))
        valid = jnp.pad(valid, (0, pad))

    factor = capacity_factor or settings.shuffle_capacity_factor
    return _run_fold_padded(mesh, h1, h2, v, valid, n_dev, n_local, kind,
                            nonneg, factor)


@functools.lru_cache(maxsize=None)
def _live_prefix_sort(n):
    """Stable sort moving live (ok == 1) entries to a prefix — the
    device-side half of :func:`compact_partial`."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    def kernel(h1, h2, v, ok):
        inv = jnp.where(ok == 1, jnp.uint32(0), jnp.uint32(1))
        _, sh1, sh2, sv = lax.sort((inv, h1, h2, v), num_keys=1,
                                   is_stable=True)
        return sh1, sh2, sv

    return jax.jit(kernel)


def compact_partial(part):
    """Shrink a device-resident ``(h1, h2, v, ok)`` partial to (a pow2
    pad of) its LIVE entries.

    Fold programs return capacity-padded lanes — ~1.5x their input,
    dead rows included — so accumulating partials through repeated
    ``mesh_keyed_refold`` rounds grows the padded garbage geometrically
    even when the distinct-key count is tiny (each round re-feeds the
    previous round's dead pad).  One validity sort + a prefix slice per
    compaction round bounds every partial at the distinct-key count
    instead.  Costs one scalar fetch (the live count); shapes stay pow2,
    so compile buckets stay bounded."""
    import jax.numpy as jnp

    h1, h2, v, ok = part
    n = int(h1.shape[0])
    if n == 0:
        return part
    nlive = int(jnp.sum(jnp.where(ok == 1, 1, 0)))
    m = _pad_pow2(max(1, nlive))
    if m >= n:
        return part
    sh1, sh2, sv = _live_prefix_sort(n)(h1, h2, v, ok)
    okc = (jnp.arange(m, dtype=jnp.int32)
           < jnp.int32(nlive)).astype(jnp.uint32)
    return sh1[:m], sh2[:m], sv[:m], okc


def mesh_global_sum(mesh, v):
    """Global aggregate over the mesh: local sum + psum (the degenerate-key
    case — the reference's ``len``/global ``sum`` pipelines)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    n_dev = mesh_size(mesh)
    v = _lane_safe_values(np.asarray(v), "sum")
    total = len(v)
    n_local = max(1, -(-total // n_dev))
    padded = n_local * n_dev
    pv = np.zeros(padded, dtype=v.dtype)
    pv[:total] = v

    axis = settings.mesh_axis

    def per_device(x):
        return jax.lax.psum(jnp.sum(x), axis)

    from ..obs import trace as _trace

    with _trace.span("collective", "global-sum", records=int(total)):
        out = jax.jit(_shard_map(
            per_device, mesh=mesh,
            in_specs=(P(axis),), out_specs=P()))(pv)
    return np.asarray(out).item()
