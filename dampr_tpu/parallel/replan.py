"""HBM-bounded collective redistribution: plan one large all_to_all as a
schedule of chunked collectives.

The naive byte exchange (:func:`.exchange.mesh_blob_exchange`) sizes its
``[D*D, C]`` buffer by the LARGEST blob in the window: one fat
(src, dst) pair amplifies to a ``D*D``-row buffer of that blob's pow2
bucket on every device, and the gather-replicated multi-process variant
triples it.  On a real pod that peak is the number that OOMs — the
shuffle's working set must be bounded by a *budget*, not by the data.

This module is the planning half of the fix, after "Memory-efficient
array redistribution" (arXiv 2112.01075): instead of emitting one
collective sized by the data, decompose the redistribution into a
*schedule* of steps whose per-step in-flight bytes provably respect
``settings.exchange_hbm_budget``:

1. From the budget, derive the largest pow2 cell capacity ``C_max`` whose
   step buffers fit (:func:`max_capacity_for`, via the deterministic cost
   model :func:`step_inflight_bytes`).
2. Slice every blob into pieces of at most ``C_max`` bytes.
3. Round-robin the pieces: step ``i`` carries piece ``i`` of every
   (src, dst) pair — each step is one well-formed ``[D*D, C_i]``
   all_to_all with ``C_i <= C_max`` (tail steps shrink to their own
   largest piece, so short schedules don't pay the full bucket).

The executor (:func:`.exchange.mesh_blob_exchange`) walks the schedule,
reusing one compiled program per (mesh, capacity) bucket, and reassembles
pieces in order on the receive side.  Everything here is pure host-side
planning — no jax imports — so the schedule invariants are cheaply
property-testable (tests/test_multiprocess.py).
"""

from .. import settings

#: Smallest cell capacity a step may use: below this the int32 length row
#: and dispatch overhead dominate the payload.  A budget too small for
#: even this floor is *clamped* (recorded on the schedule), never honored
#: by silently dropping data.
MIN_CAPACITY = 64

#: Length-row bytes per cell (int32 valid-length lane riding each step).
_LEN_BYTES = 4


def _pow2(n, floor=MIN_CAPACITY):
    return max(floor, 1 << max(0, (int(n) - 1).bit_length()))


def _pow2_floor(n, floor=MIN_CAPACITY):
    """Largest pow2 at or UNDER n (an upper bound must never round up:
    the explicit chunk knob exists for memory-pressured operators, so a
    piece may not exceed what they asked for)."""
    return max(floor, 1 << max(0, int(n).bit_length() - 1))


def step_inflight_bytes(n_dev, capacity, gather=False):
    """Deterministic peak-bytes model for one exchange step at cell
    capacity ``capacity``: the send buffer and the delivered buffer are
    both live across the collective (``2 *``), and the multi-process
    gather variant replicates the delivered buffer once more so every
    host can read the full result (``3 *``).  Each cell also carries an
    int32 length lane.  This is the number schedules are planned and
    reported against (``peak_inflight_bytes``)."""
    copies = 3 if gather else 2
    cells = n_dev * n_dev
    return copies * cells * (int(capacity) + _LEN_BYTES)


def max_capacity_for(n_dev, budget, gather=False):
    """The largest pow2 cell capacity whose step fits ``budget`` under
    :func:`step_inflight_bytes`.  Returns ``(capacity, clamped)`` —
    ``clamped`` is True when even :data:`MIN_CAPACITY` exceeds the budget
    (the schedule still runs at the floor; refusing would drop data)."""
    cap = MIN_CAPACITY
    if step_inflight_bytes(n_dev, cap, gather) > budget:
        return cap, True
    while step_inflight_bytes(n_dev, cap * 2, gather) <= budget:
        cap *= 2
    return cap, False


class ExchangeStep(object):
    """One collective step: ``cells`` is ``[(src, dst, start, stop)]`` —
    the byte slice of blob ``(src, dst)`` this step carries — and
    ``capacity`` the pow2 cell bucket the step's program compiles at."""

    __slots__ = ("cells", "capacity", "inflight_bytes")

    def __init__(self, cells, capacity, inflight_bytes):
        self.cells = cells
        self.capacity = capacity
        self.inflight_bytes = inflight_bytes

    def payload_bytes(self):
        return sum(stop - start for _s, _d, start, stop in self.cells)


class ExchangeSchedule(object):
    """The planned step sequence plus the invariants callers report:
    ``peak_inflight_bytes`` (max of the per-step model) and ``clamped``
    (budget below the capacity floor — the only case where
    ``peak_inflight_bytes > budget``).  ``coding`` carries the CAMR-style
    coded-aggregation record when the caller pre-folded sum-combinable
    partials before planning (``settings.exchange_coding``): mode,
    ``raw_bytes`` (what the uncoded schedule would have moved) and
    ``coded_bytes`` (what this schedule moves) — replicated map-side
    fold work traded for shuffle bytes, arXiv 1901.07418."""

    def __init__(self, n_dev, steps, budget, gather, clamped,
                 coding=None):
        self.n_dev = n_dev
        self.steps = steps
        self.budget = budget
        self.gather = gather
        self.clamped = clamped
        self.coding = coding
        self.total_bytes = sum(s.payload_bytes() for s in steps)
        self.peak_inflight_bytes = max(
            (s.inflight_bytes for s in steps), default=0)

    @property
    def n_steps(self):
        return len(self.steps)


def plan_exchange(n_dev, sizes, budget=None, gather=False,
                  chunk_bytes=None, coding=None):
    """Plan a budget-bounded exchange of ``sizes`` ({(src, dst): nbytes})
    across an ``n_dev`` mesh.

    ``budget`` defaults to ``settings.exchange_hbm_budget``;
    ``chunk_bytes`` (default ``settings.exchange_chunk_bytes``, 0 = off)
    additionally caps the per-piece size below what the budget allows —
    the explicit chunk-size knob the doctor playbook points at when a
    device is memory-pressured beyond what the budget models.
    ``coding`` (optional dict) records a coded-aggregation pre-fold on
    the returned schedule — the *sizes already reflect* the coded
    payload; the record keeps the raw-vs-coded byte evidence with the
    schedule it shaped.
    """
    if budget is None:
        budget = settings.exchange_hbm_budget
    if chunk_bytes is None:
        chunk_bytes = settings.exchange_chunk_bytes
    cap, clamped = max_capacity_for(n_dev, budget, gather)
    if chunk_bytes:
        cap = min(cap, _pow2_floor(chunk_bytes))

    # Round-robin piece assignment: piece i of every blob rides step i.
    pairs = sorted(sizes.items())
    n_steps = max((-(-n // cap) if n else 1 for _sd, n in pairs),
                  default=0)
    steps = []
    for i in range(n_steps):
        cells = []
        largest = 0
        for (s, d), n in pairs:
            start = i * cap
            if start > 0 and start >= n:
                continue  # this blob finished in an earlier step
            stop = min(n, start + cap)
            cells.append((s, d, start, stop))
            largest = max(largest, stop - start)
        capacity = min(cap, _pow2(max(1, largest)))
        steps.append(ExchangeStep(
            cells, capacity,
            step_inflight_bytes(n_dev, capacity, gather)))
    return ExchangeSchedule(n_dev, steps, budget, gather, clamped,
                            coding=coding)
