"""Ring collectives over the mesh axis via ``lax.ppermute``.

``psum`` lets XLA pick the all-reduce algorithm; these explicit ring
implementations express the bandwidth-optimal pattern directly — each step
moves one shard to the ring neighbor, so every link carries ``(D-1)/D`` of
the payload total regardless of device count.  This is the building block
behind ring attention / ring all-reduce formulations (sequence-parallel
passes of per-shard state around the ICI/DCN ring), provided here as the
framework's ring-communication primitive and validated against ``psum``.
"""

import functools

import numpy as np

from .. import settings
from .mesh import mesh_size, shard_map as _shard_map


@functools.lru_cache(maxsize=None)
def _ring_allreduce_program(mesh, axis, op):
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    n_dev = mesh_size(mesh)
    perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]

    def combine(a, b):
        if op == "sum":
            return a + b
        if op == "max":
            return jnp.maximum(a, b)
        if op == "min":
            return jnp.minimum(a, b)
        raise ValueError(op)

    def per_device(x):
        # accumulate while rotating shards around the ring; after D-1 hops
        # every device holds the full reduction of all shards.
        acc = x
        rot = x
        for _ in range(n_dev - 1):
            rot = lax.ppermute(rot, axis, perm)
            acc = combine(acc, rot)
        return acc

    def program(x):
        return _shard_map(
            per_device, mesh=mesh, in_specs=(P(axis),), out_specs=P(axis))(x)

    return jax.jit(program)


def ring_allreduce(mesh, x, op="sum"):
    """All-reduce a [D, ...] device-sharded array around the ring; every
    device's output shard holds the elementwise reduction across shards."""
    n_dev = mesh_size(mesh)
    x = np.asarray(x)
    assert x.shape[0] % n_dev == 0, (
        "leading dim {} must divide across {} devices".format(
            x.shape[0], n_dev))
    prog = _ring_allreduce_program(mesh, settings.mesh_axis, op)
    return np.asarray(prog(x))


@functools.lru_cache(maxsize=None)
def _ring_allgather_program(mesh, axis):
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    n_dev = mesh_size(mesh)
    perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]

    def per_device(x):
        idx = lax.axis_index(axis)
        parts = [jnp.zeros_like(x) for _ in range(n_dev)]
        rot = x
        rid = idx  # owner id of the shard currently held in `rot`
        for _step in range(n_dev):
            hot = [(rid == j).astype(x.dtype) for j in range(n_dev)]
            parts = [p + h * rot for p, h in zip(parts, hot)]
            rot = lax.ppermute(rot, axis, perm)
            # perm sends i -> i+1, so the shard we *receive* came from our
            # ring predecessor: the held shard's owner id decreases each hop.
            rid = (rid - 1) % n_dev
        return jnp.concatenate(parts, axis=0)

    def program(x):
        return _shard_map(
            per_device, mesh=mesh, in_specs=(P(axis),), out_specs=P(axis))(x)

    return jax.jit(program)


def ring_allgather(mesh, x):
    """All-gather shards around the ring: input sharded [D*n, ...] ->
    output [D, D*n, ...]-equivalent where every device holds all shards
    (returned globally as [D * total, ...])."""
    prog = _ring_allgather_program(mesh, settings.mesh_axis)
    return np.asarray(prog(np.asarray(x)))
