"""Durable checkpoint/resume for named runs (crash recovery).

The reference has no fault tolerance: a crashed worker deadlocks the join
loop (reference stagerunner.py:35-38) and every run restarts from zero.
SURVEY §5 observes that because every stage output is already a named
on-disk artifact, resume-from-stage is "latent in the design but
unimplemented" — this module implements it for the TPU engine.

With ``run(name=..., resume=True)`` every completed stage persists its
output partition set (RAM-resident blocks are *also* written to a ckpt
directory — they stay hot for the next stage and become free spill
victims) plus an atomic per-stage manifest carrying a **chained structural
fingerprint**.  A rerun under the same name reloads the longest valid
manifest prefix as disk-backed partition sets and skips those stages.

Fingerprints chain through the DAG::

    fp(stage) = H(stage structure, input-tap identity, fp(inputs))

so editing an upstream stage — or touching an input file — invalidates
every downstream manifest.  Structure fingerprinting is best-effort but
sharp for the common case: Python functions hash their bytecode, constants
and closure-cell values, so editing a lambda body or a captured constant
re-executes its stage.  Captured containers hash by CONTENT (a changed
stopword list must invalidate its stage) — the corollary is that a
closure accumulating state into a captured list defeats resume for its
stage, which errs on the safe side: lost reuse, never stale reuse.
Objects that defy fingerprinting entirely mark the stage *volatile*: it
always re-executes (correctness is never traded for reuse).
"""

import functools
import glob
import hashlib
import json
import logging
import os
import pickle
import types
import uuid

import numpy as np

log = logging.getLogger("dampr_tpu.resume")

_VOLATILE = "volatile"
_MAX_DEPTH = 6
_MAX_SEQ = 1000


def _h(*parts):
    m = hashlib.sha1()
    for p in parts:
        m.update(p if isinstance(p, bytes) else str(p).encode("utf-8"))
        m.update(b"\x00")
    return m.hexdigest()


def _volatile():
    return "{}:{}".format(_VOLATILE, uuid.uuid4().hex)


def is_volatile(fp):
    return fp.startswith(_VOLATILE)


def _fp_function(f, depth):
    code = f.__code__
    cells = ()
    if f.__closure__:
        cells = tuple(
            _fp(c.cell_contents, depth + 1) for c in f.__closure__)
    consts = tuple(_fp(c, depth + 1) for c in code.co_consts)
    defaults = tuple(_fp(d, depth + 1) for d in (f.__defaults__ or ()))
    kwdefaults = _fp(f.__kwdefaults__, depth + 1)
    # Referenced globals are part of the function's behavior: hash each
    # co_names binding that resolves, so both *which* helper a lambda calls
    # (the name) and *what that helper does* (its own fp, recursively up to
    # the depth bound) invalidate the stage when edited.
    globs = []
    for name in code.co_names:
        if name in f.__globals__:
            v = f.__globals__[name]
            if isinstance(v, types.ModuleType):
                globs.append((name, _h("module", v.__name__)))
            else:
                globs.append((name, _fp(v, depth + 1)))
    return _h("fn", f.__qualname__, code.co_code, code.co_names, consts,
              cells, defaults, kwdefaults, tuple(globs))


def _fp(obj, depth=0):
    """Best-effort structural fingerprint.  Deterministic across processes
    for code + plain data; ``volatile:`` (never matches) when it cannot be."""
    if depth > _MAX_DEPTH:
        return _h("deep", type(obj).__qualname__)
    if obj is None or isinstance(obj, (bool, int, float, str, bytes)):
        return _h("prim", repr(obj))
    if isinstance(obj, types.CodeType):
        return _h("code", obj.co_code, obj.co_names,
                  tuple(_fp(c, depth + 1) for c in obj.co_consts))
    if isinstance(obj, types.FunctionType):
        return _fp_function(obj, depth)
    if isinstance(obj, types.BuiltinFunctionType):
        return _h("builtin", getattr(obj, "__module__", ""), obj.__qualname__)
    if isinstance(obj, types.MethodType):
        return _h("method", _fp(obj.__self__, depth + 1), obj.__func__.__name__)
    if isinstance(obj, functools.partial):
        return _h("partial", _fp(obj.func, depth + 1),
                  _fp(obj.args, depth + 1), _fp(obj.keywords, depth + 1))
    if isinstance(obj, np.ndarray):
        if obj.nbytes <= 1 << 20:
            return _h("ndarray", obj.shape, str(obj.dtype), obj.tobytes())
        return _h("bigarray", obj.shape, str(obj.dtype))
    if isinstance(obj, np.generic):
        return _h("npscalar", str(obj.dtype), obj.item())
    if isinstance(obj, (tuple, frozenset)):
        kind = type(obj).__name__
        items = sorted(obj, key=repr) if isinstance(obj, frozenset) else obj
        if len(items) > _MAX_SEQ:
            return _fp_bulk(kind, obj)
        return _h(kind, tuple(_fp(x, depth + 1) for x in items))
    if isinstance(obj, (list, set)):
        # Content identity: a changed captured parameter list must
        # invalidate its stage.  (Closures that accumulate state into a
        # captured container therefore defeat resume for their stage —
        # safe direction: recompute, never reuse stale.)
        kind = type(obj).__name__
        items = sorted(obj, key=repr) if isinstance(obj, set) else obj
        if len(items) > _MAX_SEQ:
            return _fp_bulk(kind, items)
        return _h(kind, tuple(_fp(x, depth + 1) for x in items))
    if isinstance(obj, dict):
        items = sorted(obj.items(), key=lambda kv: repr(kv[0]))
        if len(items) > _MAX_SEQ:
            return _fp_bulk("dict", items)
        return _h("dict", tuple(
            (_fp(k, depth + 1), _fp(v, depth + 1)) for k, v in items))
    if isinstance(obj, type):
        return _h("type", obj.__module__, obj.__qualname__)
    # Generic object: type + attribute walk (slots and dict).  An object
    # exposing NO attributes (C-implemented callables and the like) hides
    # its state from the walk — hash its pickle if possible, else mark the
    # stage volatile rather than risk two differently-configured objects
    # fingerprinting alike (stale reuse).
    names = _attr_names(obj)
    if not names:
        try:
            return _h("opaque", type(obj).__module__, type(obj).__qualname__,
                      pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL))
        except Exception:
            return _volatile()
    state = []
    for name in names:
        try:
            v = getattr(obj, name)
        except AttributeError:
            continue
        state.append((name, _fp(v, depth + 1)))
    return _h("obj", type(obj).__module__, type(obj).__qualname__,
              tuple(state))


def _fp_bulk(kind, items):
    """Large payloads: one pickle pass instead of per-item recursion."""
    try:
        return _h("bulk-" + kind,
                  pickle.dumps(items, protocol=pickle.HIGHEST_PROTOCOL))
    except Exception:
        return _volatile()


def _attr_names(obj):
    names = []
    for klass in type(obj).__mro__:
        names.extend(getattr(klass, "__slots__", ()))
    names.extend(getattr(obj, "__dict__", ()))
    return sorted(set(n for n in names if not n.startswith("__")))


# -- taps --------------------------------------------------------------------

def _stat_fp(path):
    st = os.stat(path)
    return (path, st.st_size, st.st_mtime_ns)


def _fp_tap(tap):
    """Input identity: the tap's chunk plan + per-file (size, mtime).  Any
    added/removed/grown/edited input file changes the fingerprint."""
    name = type(tap).__qualname__
    try:
        path = getattr(tap, "path", None)
        if isinstance(path, str):
            files = sorted(
                p for p in glob.glob(path) or [path] if os.path.isfile(p))
            if not files and os.path.isdir(path):
                files = sorted(
                    os.path.join(d, f)
                    for d, _dirs, fs in os.walk(path) for f in fs)
            return _h("tap", name, tuple(_stat_fp(p) for p in files),
                      getattr(tap, "chunk_size", 0))
        items = getattr(tap, "items", None)
        if items is not None:
            return _h("tap-mem", name, _fp(items),
                      getattr(tap, "partitions", 0))
        urls = getattr(tap, "urls", None)
        if urls is not None:
            return _h("tap-urls", name, tuple(urls))
        return _h("tap-obj", _fp(tap))
    except Exception:
        log.warning("tap %r is not fingerprintable; stage is volatile", name,
                    exc_info=True)
        return _volatile()


# -- per-stage chained fingerprints ------------------------------------------

def stage_fingerprints(graph, salt=""):
    """{sid: chained fp} for every non-input stage, in schedule order.

    ``salt`` carries engine configuration that shapes stage OUTPUT layout
    (the partition count: restored partition sets must co-partition with
    re-executed join sides), so a config change invalidates checkpoints.
    """
    from .graph import GInput, GMap, GReduce, GSink

    src_fp = {}
    out = {}
    for sid, stage in enumerate(graph.stages):
        if isinstance(stage, GInput):
            src_fp[stage.output] = _h("tap-salted", salt, _fp_tap(stage.tap))
            continue
        inputs = tuple(src_fp.get(s, "missing") for s in stage.inputs)
        if isinstance(stage, GMap):
            body = ("map", _fp(stage.mapper), _fp(stage.combiner),
                    _fp(stage.shuffler))
        elif isinstance(stage, GReduce):
            body = ("reduce", _fp(stage.reducer))
        elif isinstance(stage, GSink):
            body = ("sink", _fp(stage.sinker), stage.path)
        else:
            body = ("other", _fp(stage))
        opts = _fp(getattr(stage, "options", None) or {})
        if any(is_volatile(x) for x in inputs) or is_volatile(opts):
            fp = _volatile()
        else:
            fp = _h("stage", sid, body, opts, inputs)
        src_fp[stage.output] = fp
        out[sid] = fp
    return out


# -- manifests ---------------------------------------------------------------

def _manifest_dir(root):
    return os.path.join(root, "manifest")


def _manifest_path(root, sid):
    return os.path.join(_manifest_dir(root), "stage_{}.json".format(sid))


def _ensure_on_disk(ref, directory):
    """Return a durable file path holding this ref's block, writing one if
    the block only lives in RAM.  Resident blocks KEEP their RAM copy (the
    next stage reads hot); BlockRef.spill() skips rewriting refs that
    already have a path, so persisted blocks spill for free later."""
    from .storage import save_block

    if ref.pin:
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory, uuid.uuid4().hex + ".blk")
        with open(path, "wb") as f:
            f.write(ref._packed)  # gzip'd single-window stream: the spill
            # wire format readers already sniff and stream
        return path
    if ref.path is None:
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory, uuid.uuid4().hex + ".blk")
        save_block(ref._block, path)
        ref.path = path
        return path
    return ref.path


def persist_stage(store, sid, fp, result, nrec):
    """Write the stage's blocks to disk + an atomic manifest.  Volatile
    stages persist nothing (they can never be resumed)."""
    from .runner import _SinkOutput
    from .storage import PartitionSet

    if is_volatile(fp):
        return
    root = store.root
    if isinstance(result, _SinkOutput):
        manifest = {"fp": fp, "kind": "sink", "paths": result.paths,
                    "nrec": nrec}
    elif isinstance(result, PartitionSet):
        directory = os.path.join(root, "ckpt", "stage_{}".format(sid))
        blocks = []
        for pid in sorted(result.parts):
            for ref in result.parts[pid]:
                path = _ensure_on_disk(ref, directory)
                blocks.append([pid, os.path.relpath(path, root),
                               ref.nrecords, int(ref.nbytes),
                               str(ref.key_dtype), str(ref.value_dtype)])
        manifest = {"fp": fp, "kind": "pset",
                    "n_partitions": result.n_partitions,
                    "blocks": blocks, "nrec": nrec}
    else:  # raw tap handles pass through _run untouched; nothing to persist
        return
    old_paths = _manifest_files(root, sid)
    os.makedirs(_manifest_dir(root), exist_ok=True)
    tmp = _manifest_path(root, sid) + ".tmp"
    with open(tmp, "w") as f:
        json.dump(manifest, f)
    os.replace(tmp, _manifest_path(root, sid))
    _prune(root, old_paths)


def _manifest_files(root, sid):
    """Absolute block/part paths referenced by stage sid's manifest ({} if
    none)."""
    try:
        with open(_manifest_path(root, sid)) as f:
            m = json.load(f)
    except (OSError, ValueError):
        return set()
    if m.get("kind") == "sink":
        return set(m.get("paths", ()))
    return set(os.path.join(root, b[1]) for b in m.get("blocks", ()))


def _prune(root, candidates):
    """Delete superseded checkpoint files: ``candidates`` (the replaced
    manifest's files) minus every path still referenced by any current
    manifest.  Keeps edit-rerun cycles at one retained copy per stage
    instead of one per edit.  Only paths under ``root`` are touched
    (sink part files live in user directories and are never pruned)."""
    if not candidates:
        return
    rootp = os.path.join(os.path.abspath(root), "")
    live = set()
    mdir = _manifest_dir(root)
    if os.path.isdir(mdir):
        for name in os.listdir(mdir):
            if name.startswith("stage_") and name.endswith(".json"):
                sid = name[len("stage_"):-len(".json")]
                if sid.isdigit():
                    live |= _manifest_files(root, int(sid))
    for path in candidates - live:
        if os.path.abspath(path).startswith(rootp):
            try:
                os.unlink(path)
            except OSError:
                pass


def load_plan(root, fps):
    """{sid: manifest} for every stage whose manifest exists, fingerprint-
    matches this graph, and whose referenced files all still exist."""
    plan = {}
    for sid, fp in fps.items():
        if is_volatile(fp):
            continue
        mpath = _manifest_path(root, sid)
        if not os.path.exists(mpath):
            continue
        try:
            with open(mpath) as f:
                m = json.load(f)
        except (OSError, ValueError):
            continue
        if m.get("fp") != fp:
            continue
        if m["kind"] == "sink":
            paths = m["paths"]
        else:
            paths = [os.path.join(root, b[1]) for b in m["blocks"]]
        if not all(os.path.exists(p) for p in paths):
            continue
        plan[sid] = m
    return plan


def restore_stage(root, manifest):
    """Rebuild the stage output (PartitionSet or _SinkOutput) from its
    manifest.  Returns (result, nrec)."""
    from .runner import _SinkOutput
    from .storage import BlockRef, PartitionSet

    if manifest["kind"] == "sink":
        return _SinkOutput(manifest["paths"]), manifest["nrec"]
    pset = PartitionSet(manifest["n_partitions"])
    for pid, rel, nrecords, nbytes, kdt, vdt in manifest["blocks"]:
        pset.add(pid, BlockRef.from_disk(
            os.path.join(root, rel), nrecords, nbytes, kdt, vdt))
    return pset, manifest["nrec"]
