"""Durable checkpoint/resume for named runs (crash recovery).

The reference has no fault tolerance: a crashed worker deadlocks the join
loop (reference stagerunner.py:35-38) and every run restarts from zero.
SURVEY §5 observes that because every stage output is already a named
on-disk artifact, resume-from-stage is "latent in the design but
unimplemented" — this module implements it for the TPU engine.

With ``run(name=..., resume=True)`` every completed stage persists its
output partition set (RAM-resident blocks are *also* written to a ckpt
directory — they stay hot for the next stage and become free spill
victims) plus an atomic per-stage manifest carrying a **chained structural
fingerprint**.  A rerun under the same name reloads the longest valid
manifest prefix as disk-backed partition sets and skips those stages.

Fingerprints chain through the DAG::

    fp(stage) = H(stage structure, input-tap identity, fp(inputs))

so editing an upstream stage — or touching an input file — invalidates
every downstream manifest.  Structure fingerprinting is best-effort but
sharp for the common case: Python functions hash their bytecode, constants
and closure-cell values, so editing a lambda body or a captured constant
re-executes its stage.  Captured containers hash by CONTENT (a changed
stopword list must invalidate its stage) — the corollary is that a
closure accumulating state into a captured list defeats resume for its
stage, which errs on the safe side: lost reuse, never stale reuse.
Objects that defy fingerprinting entirely mark the stage *volatile*: it
always re-executes (correctness is never traded for reuse).
"""

import functools
import glob
import hashlib
import json
import logging
import os
import pickle
import threading
import types
import uuid

import numpy as np

log = logging.getLogger("dampr_tpu.resume")

_VOLATILE = "volatile"
_MAX_DEPTH = 6
_MAX_SEQ = 1000
_TUPLE_END = object()


def _h(*parts):
    """Hash ``parts`` (recursing into tuples) — but if any part is itself a
    ``volatile:`` fingerprint, the combination is volatile too.  Without
    this propagation a container holding an unfingerprintable leaf would
    hash to a random-but-unmarked value: safe (it never matches) but
    invisible to ``is_volatile`` callers that decide whether a stage's
    checkpoint files are worth persisting at all."""
    m = hashlib.sha1()
    stack = [parts]
    while stack:
        p = stack.pop()
        if p is _TUPLE_END:
            # Close marker: without it nesting is not injective —
            # _h(a, (b,), c) and _h(a, (b, c)) would emit identical
            # byte streams, and a collision here is stale checkpoint
            # reuse.
            m.update(b"\x02")
            continue
        if isinstance(p, tuple):
            m.update(b"\x01")
            stack.append(_TUPLE_END)
            stack.extend(reversed(p))
            continue
        if isinstance(p, str) and is_volatile(p):
            return _volatile()
        m.update(p if isinstance(p, (bytes, bytearray, memoryview))
                 else str(p).encode("utf-8"))
        m.update(b"\x00")
    return m.hexdigest()


def _volatile():
    return "{}:{}".format(_VOLATILE, uuid.uuid4().hex)


def is_volatile(fp):
    """Match the exact out-of-band sentinel form ``volatile:<32 hex>`` —
    a bare prefix test would misfire on user identifiers that happen to
    start with "volatile" (a function named ``volatile_mapper``, a sink
    path), silently disabling resume for their stage."""
    return (len(fp) == len(_VOLATILE) + 33
            and fp.startswith(_VOLATILE + ":")
            and all(c in "0123456789abcdef" for c in fp[len(_VOLATILE) + 1:]))


def _fp_function(f, depth):
    code = f.__code__
    cells = ()
    if f.__closure__:
        cells = tuple(
            _fp(c.cell_contents, depth + 1) for c in f.__closure__)
    consts = tuple(_fp(c, depth + 1) for c in code.co_consts)
    defaults = tuple(_fp(d, depth + 1) for d in (f.__defaults__ or ()))
    kwdefaults = _fp(f.__kwdefaults__, depth + 1)
    # Referenced globals are part of the function's behavior: hash each
    # co_names binding that resolves, so both *which* helper a lambda calls
    # (the name) and *what that helper does* (its own fp, recursively up to
    # the depth bound) invalidate the stage when edited.
    globs = []
    for name in code.co_names:
        if name in f.__globals__:
            v = f.__globals__[name]
            if isinstance(v, types.ModuleType):
                globs.append((name, _h("module", v.__name__)))
            else:
                globs.append((name, _fp(v, depth + 1)))
    return _h("fn", f.__qualname__, code.co_code, code.co_names, consts,
              cells, defaults, kwdefaults, tuple(globs))


def _is_composed(obj):
    """A fused-chain link node (base.ComposedMapper/ComposedStreamable) —
    type check by name avoids importing base at module load."""
    from . import base

    return type(obj) in (base.ComposedMapper, base.ComposedStreamable)


def _fp(obj, depth=0):
    """Best-effort structural fingerprint.  Deterministic across processes
    for code + plain data; ``volatile:`` (never matches) when it cannot be."""
    if depth > _MAX_DEPTH:
        # State buried past the depth cap is invisible to the walk; a
        # stable hash here would let deep edits reuse stale checkpoints.
        # Volatile is the documented safe direction: lost reuse, never
        # stale reuse.
        return _volatile()
    if obj is None or isinstance(obj, (bool, int, float, str, bytes)):
        return _h("prim", repr(obj))
    if isinstance(obj, types.CodeType):
        return _h("code", obj.co_code, obj.co_names,
                  tuple(_fp(c, depth + 1) for c in obj.co_consts))
    if isinstance(obj, types.FunctionType):
        return _fp_function(obj, depth)
    if isinstance(obj, types.BuiltinFunctionType):
        return _h("builtin", getattr(obj, "__module__", ""), obj.__qualname__)
    if isinstance(obj, types.MethodType):
        return _h("method", _fp(obj.__self__, depth + 1), obj.__func__.__name__)
    if isinstance(obj, functools.partial):
        return _h("partial", _fp(obj.func, depth + 1),
                  _fp(obj.args, depth + 1), _fp(obj.keywords, depth + 1))
    if isinstance(obj, np.ma.MaskedArray):
        # The mask is semantic state the data buffer doesn't carry: two
        # arrays with equal data but different masks must not share a
        # fingerprint.  nomask stays cheap (no materialized mask array).
        base = np.asarray(obj.data)
        mask = np.ma.getmask(obj)
        mfp = ("nomask" if mask is np.ma.nomask
               else _array_digest(np.asarray(mask)))
        if base.dtype.hasobject:
            return _fp_bulk("ma-obj", (obj.shape, str(obj.dtype),
                                       base.tolist(), mfp,
                                       repr(obj.fill_value)))
        return _h("ndarray-masked", obj.shape, str(obj.dtype),
                  _array_digest(base), mfp, repr(obj.fill_value))
    if isinstance(obj, np.ndarray):
        # Content hash at every size: shape+dtype alone would let a
        # same-shaped array with different CONTENTS match its old
        # checkpoint (stale reuse).  sha1 streams at ~GB/s — negligible
        # next to running the stage.  Memoized per fingerprint pass: the
        # same weight array referenced by several mappers hashes once.
        cache = getattr(_tls, "cache", None)
        cached = None if cache is None else cache.get(id(obj))
        if cached is not None:
            return cached[1]
        if obj.dtype.hasobject:
            # Object buffers hold PyObject POINTERS — hashing them would
            # miss in-place mutation of the pointees (stale reuse) and
            # never match across processes; hash the pickled elements.
            fp = _fp_bulk("ndarray-obj",
                          (obj.shape, str(obj.dtype), obj.tolist()))
        else:
            fp = _h("ndarray", obj.shape, str(obj.dtype),
                    _array_digest(obj))
        if cache is not None:
            cache[id(obj)] = (obj, fp)  # hold obj: pins its id
        return fp
    if isinstance(obj, np.generic):
        return _h("npscalar", str(obj.dtype), obj.item())
    if isinstance(obj, (tuple, frozenset)):
        kind = type(obj).__name__
        items = sorted(obj, key=repr) if isinstance(obj, frozenset) else obj
        if len(items) > _MAX_SEQ:
            return _fp_bulk(kind, obj)
        return _h(kind, tuple(_fp(x, depth + 1) for x in items))
    if isinstance(obj, (list, set)):
        # Content identity: a changed captured parameter list must
        # invalidate its stage.  (Closures that accumulate state into a
        # captured container therefore defeat resume for their stage —
        # safe direction: recompute, never reuse stale.)
        kind = type(obj).__name__
        items = sorted(obj, key=repr) if isinstance(obj, set) else obj
        if len(items) > _MAX_SEQ:
            return _fp_bulk(kind, items)
        return _h(kind, tuple(_fp(x, depth + 1) for x in items))
    if isinstance(obj, dict):
        items = sorted(obj.items(), key=lambda kv: repr(kv[0]))
        if len(items) > _MAX_SEQ:
            return _fp_bulk("dict", items)
        return _h("dict", tuple(
            (_fp(k, depth + 1), _fp(v, depth + 1)) for k, v in items))
    if isinstance(obj, type):
        return _h("type", obj.__module__, obj.__qualname__)
    if _is_composed(obj):
        # Fused op chains nest one Composed node per DSL op; walking them
        # recursively would charge the depth budget per chain LINK, so a
        # pipeline with >= _MAX_DEPTH chained per-record ops between
        # checkpoints silently fingerprinted volatile (resume lost).
        # Flatten iteratively: links fingerprint at THIS depth — the
        # budget charges only genuinely nested user state.
        links, stack = [], [obj]
        while stack:
            node = stack.pop()
            if _is_composed(node):
                stack.append(node.right)
                stack.append(node.left)
            else:
                links.append(node)
        return _h("opchain", tuple(_fp(x, depth) for x in links))
    # Generic object: type + attribute walk (slots and dict).  An object
    # exposing NO attributes (C-implemented callables and the like) hides
    # its state from the walk — hash its pickle if possible, else mark the
    # stage volatile rather than risk two differently-configured objects
    # fingerprinting alike (stale reuse).
    names = _attr_names(obj)
    if not names:
        try:
            return _h("opaque", type(obj).__module__, type(obj).__qualname__,
                      pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL))
        except Exception:
            return _volatile()
    state = []
    for name in names:
        try:
            v = getattr(obj, name)
        except AttributeError:
            continue
        state.append((name, _fp(v, depth + 1)))
    return _h("obj", type(obj).__module__, type(obj).__qualname__,
              tuple(state))


def _array_digest(a):
    """sha1 of an array's element bytes.  Non-contiguous views are copied
    in ~16MB row chunks, not whole — fingerprinting a multi-GB strided
    view must not transiently double its memory."""
    m = hashlib.sha1()
    if a.flags.c_contiguous:
        m.update(a.data)
    elif a.ndim == 0 or a.shape[0] == 0:
        m.update(np.ascontiguousarray(a).data)
    else:
        row_bytes = max(1, a.nbytes // a.shape[0])
        rows = max(1, (1 << 24) // row_bytes)
        for i in range(0, a.shape[0], rows):
            m.update(np.ascontiguousarray(a[i:i + rows]).data)
    return m.hexdigest()


_tls = threading.local()  # per-thread fingerprint-pass cache (two
# concurrent stage_fingerprints calls must not stomp each other's cache)


def _fp_bulk(kind, items):
    """Large payloads: one pickle pass instead of per-item recursion."""
    try:
        return _h("bulk-" + kind,
                  pickle.dumps(items, protocol=pickle.HIGHEST_PROTOCOL))
    except Exception:
        return _volatile()


def _attr_names(obj):
    names = []
    for klass in type(obj).__mro__:
        names.extend(getattr(klass, "__slots__", ()))
    names.extend(getattr(obj, "__dict__", ()))
    return sorted(set(n for n in names if not n.startswith("__")))


# -- taps --------------------------------------------------------------------

def _stat_fp(path):
    """Input-file identity: (path, size, mtime_ns) + a content probe over
    the first and last 64KB.  stat alone misses edits that preserve both
    size and mtime (rsync -t restores, mtime-coarse filesystems, tools
    that reset timestamps); the probe catches any such edit that touches
    either end of the file.  A same-size interior-only edit with a reset
    mtime remains undetectable without a full read — documented in
    Dampr.run(resume=...)."""
    st = os.stat(path)
    probe = b""
    try:
        with open(path, "rb") as f:
            probe = f.read(65536)
            if st.st_size > 131072:
                f.seek(-65536, os.SEEK_END)
                probe += f.read(65536)
            elif st.st_size > 65536:
                f.seek(65536)
                probe += f.read()
    except OSError:
        pass
    return (path, st.st_size, st.st_mtime_ns,
            hashlib.sha1(probe).hexdigest())


def _fp_tap(tap):
    """Input identity: the tap's chunk plan + per-file (size, mtime).  Any
    added/removed/grown/edited input file changes the fingerprint."""
    name = type(tap).__qualname__
    try:
        path = getattr(tap, "path", None)
        if isinstance(path, str):
            files = sorted(
                p for p in glob.glob(path) or [path] if os.path.isfile(p))
            if not files and os.path.isdir(path):
                files = sorted(
                    os.path.join(d, f)
                    for d, _dirs, fs in os.walk(path) for f in fs)
            return _h("tap", name, tuple(_stat_fp(p) for p in files),
                      getattr(tap, "chunk_size", 0))
        items = getattr(tap, "items", None)
        if items is not None:
            return _h("tap-mem", name, _fp(items),
                      getattr(tap, "partitions", 0))
        urls = getattr(tap, "urls", None)
        if urls is not None:
            return _h("tap-urls", name, tuple(urls))
        return _h("tap-obj", _fp(tap))
    except Exception:
        log.warning("tap %r is not fingerprintable; stage is volatile", name,
                    exc_info=True)
        return _volatile()


# -- per-stage chained fingerprints ------------------------------------------

def stage_fingerprints(graph, salt=""):
    """{sid: chained fp} for every non-input stage, in schedule order.

    ``salt`` carries engine configuration that shapes stage OUTPUT layout
    (the partition count: restored partition sets must co-partition with
    re-executed join sides), so a config change invalidates checkpoints.
    """
    from .graph import GInput, GMap, GReduce, GSink

    src_fp = {}
    out = {}
    _tls.cache = {}  # one content hash per distinct captured array
    try:
        for sid, stage in enumerate(graph.stages):
            if isinstance(stage, GInput):
                src_fp[stage.output] = _h(
                    "tap-salted", salt, _fp_tap(stage.tap))
                continue
            inputs = tuple(src_fp.get(s, "missing") for s in stage.inputs)
            if isinstance(stage, GMap):
                body = ("map", _fp(stage.mapper), _fp(stage.combiner),
                        _fp(stage.shuffler))
            elif isinstance(stage, GReduce):
                body = ("reduce", _fp(stage.reducer))
            elif isinstance(stage, GSink):
                body = ("sink", _fp(stage.sinker), stage.path)
            else:
                body = ("other", _fp(stage))
            opts = _fp(getattr(stage, "options", None) or {})
            # _h propagates volatility: any volatile part (inputs, opts,
            # body fps) makes the combination volatile.
            fp = _h("stage", sid, body, opts, inputs)
            src_fp[stage.output] = fp
            out[sid] = fp
    finally:
        _tls.cache = None
    return out


# -- manifests ---------------------------------------------------------------

def _manifest_dir(root):
    return os.path.join(root, "manifest")


def _manifest_path(root, sid):
    return os.path.join(_manifest_dir(root), "stage_{}.json".format(sid))


def _ensure_on_disk(ref, directory, pool=None):
    """Return a durable file path holding this ref's block, scheduling a
    write if the block only lives in RAM.  Resident blocks KEEP their RAM
    copy (the next stage reads hot); BlockRef.spill() skips rewriting refs
    that already have a path, so persisted blocks spill for free later.

    With ``pool`` (the store's background spill writer) the write enqueues
    — checkpoint persistence of a wide stage runs its codec+disk across
    the writer threads — and the returned path is the write's target;
    the caller MUST ``drain_writes()`` before referencing it in a
    manifest (fsync + rename happen inside the pool, so a drained
    manifest never points at a half-written file)."""
    from .storage import _spill_codec, save_block

    if ref.pin:
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory, uuid.uuid4().hex + ".blk")
        with open(path, "wb") as f:
            f.write(ref._packed)  # gzip'd single-window stream: the spill
            # wire format readers already sniff and stream
        return path, ref.nbytes
    if ref.path is None:
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory, uuid.uuid4().hex + ".blk")
        # get() covers every residency: RAM blocks return as-is;
        # HBM-resident refs materialize via one counted value-lane fetch
        # (their device copy stays live for the consuming reduce).
        blk = ref.get()
        if pool is not None:
            pool.submit(ref, blk, path,
                        _spill_codec(ref.key_dtype, ref.value_dtype),
                        clear_block=False)
        else:
            from . import faults as _faults

            def write_once():
                _faults.check("checkpoint_persist")
                save_block(blk, path)

            # Transient-retry like every other spill write ("wb"
            # truncates: idempotent).
            _faults.retry_io(write_once, "checkpoint_persist")
            ref.path = path
        return path, blk.nbytes()
    return ref.path, ref.nbytes


def persist_stage(store, sid, fp, result, nrec):
    """Write the stage's blocks to disk + an atomic manifest.  Volatile
    stages persist nothing (they can never be resumed)."""
    from .runner import _SinkOutput
    from .storage import PartitionSet

    from .obs import trace as _trace

    if is_volatile(fp):
        _trace.instant("checkpoint", "skip-volatile", stage=sid)
        return
    _t0 = _trace.now()
    root = store.root
    if isinstance(result, _SinkOutput):
        manifest = {"fp": fp, "kind": "sink", "paths": result.paths,
                    "nrec": nrec}
    elif isinstance(result, PartitionSet):
        directory = os.path.join(root, "ckpt", "stage_{}".format(sid))
        blocks = []
        # Unwritten blocks fan out across the store's background writer
        # pool; the drain below is the durability barrier — the manifest
        # lands only after every referenced file has been fsync'd and
        # renamed into place, so a crash between the two leaves a
        # restorable previous manifest, never a dangling one.
        pool = store.writer_pool()
        for pid in sorted(result.parts):
            for ref in result.parts[pid]:
                path, nbytes = _ensure_on_disk(ref, directory, pool)
                blocks.append([pid, os.path.relpath(path, root),
                               ref.nrecords, int(nbytes),
                               str(ref.key_dtype), str(ref.value_dtype)])
        store.drain_writes()
        manifest = {"fp": fp, "kind": "pset",
                    "n_partitions": result.n_partitions,
                    "blocks": blocks, "nrec": nrec,
                    # provenance flags survive the round-trip so a resumed
                    # output keeps its fast read/alias paths
                    "flags": [bool(result.hash_routed),
                              bool(result.hash_sorted),
                              bool(result.key_sorted_runs)]}
    else:  # raw tap handles pass through _run untouched; nothing to persist
        return
    old_paths = _manifest_files(root, sid)
    os.makedirs(_manifest_dir(root), exist_ok=True)
    tmp = _manifest_path(root, sid) + ".tmp"

    def write_manifest():
        from . import faults as _faults

        _faults.check("checkpoint_persist")
        with open(tmp, "w") as f:
            json.dump(manifest, f)
        os.replace(tmp, _manifest_path(root, sid))

    from . import faults as _faults

    # tmp -> atomic replace: a transient failure (or injected
    # ``checkpoint_persist`` fault) retries in place; a crash between
    # retries leaves the previous manifest restorable, never a dangler.
    _faults.retry_io(write_manifest, "checkpoint_persist")
    _prune(root, old_paths)
    _trace.complete("checkpoint", "persist", _t0, stage=sid,
                    records=nrec, kind=manifest["kind"])


def _manifest_files(root, sid):
    """Absolute block/part paths referenced by stage sid's manifest ({} if
    none)."""
    try:
        with open(_manifest_path(root, sid)) as f:
            m = json.load(f)
    except (OSError, ValueError):
        return set()
    if m.get("kind") == "sink":
        return set(m.get("paths", ()))
    return set(os.path.join(root, b[1]) for b in m.get("blocks", ()))


def _live_paths(root):
    """Absolute paths of every file referenced by any current manifest."""
    live = set()
    mdir = _manifest_dir(root)
    if os.path.isdir(mdir):
        for name in os.listdir(mdir):
            if name.startswith("stage_") and name.endswith(".json"):
                sid = name[len("stage_"):-len(".json")]
                if sid.isdigit():
                    live |= set(map(os.path.abspath,
                                    _manifest_files(root, int(sid))))
    return live


def _prune(root, candidates):
    """Delete superseded checkpoint files: ``candidates`` (the replaced
    manifest's files) minus every path still referenced by any current
    manifest.  Keeps edit-rerun cycles at one retained copy per stage
    instead of one per edit.  Only paths under ``root`` are touched
    (sink part files live in user directories and are never pruned)."""
    if not candidates:
        return
    rootp = os.path.join(os.path.abspath(root), "")
    live = _live_paths(root)
    for path in candidates:
        path = os.path.abspath(path)
        if path not in live and path.startswith(rootp):
            try:
                os.unlink(path)
            except OSError:
                pass


class RunGuard(object):
    """Advisory liveness lock for a named scratch root.  Every resumable
    run holds a SHARED flock on ``<root>/.run.lock`` for its duration; the
    start-of-run GC sweep only fires when an EXCLUSIVE probe succeeds —
    i.e. no other live process is mid-run under this name, so no in-flight
    (not-yet-manifested) spill blocks can be swept.  flock releases on
    process death, so a crashed run never wedges the GC forever."""

    def __init__(self, root):
        import errno
        import fcntl
        os.makedirs(root, exist_ok=True)
        self._fcntl = fcntl
        self._fd = os.open(os.path.join(root, ".run.lock"),
                           os.O_CREAT | os.O_RDWR, 0o644)
        self.exclusive = False
        try:
            fcntl.flock(self._fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            self.exclusive = True
        except OSError as e:
            if e.errno in (errno.EWOULDBLOCK, errno.EAGAIN):
                # Another live run holds the lock: join it shared.
                fcntl.flock(self._fd, fcntl.LOCK_SH)
            else:
                # Filesystem without flock support (NFS sans lockd,
                # some container mounts): degrade to no-GC rather than
                # fail the run — locking is an optimization guard, not
                # a correctness requirement for a single process.
                log.warning("flock unsupported on %s (%s): skipping "
                            "start-of-run GC", root, e)
                os.close(self._fd)
                self._fd = None

    def share(self):
        """Downgrade to shared so later runs can probe while we execute."""
        if self.exclusive and self._fd is not None:
            self._fcntl.flock(self._fd, self._fcntl.LOCK_SH)
            self.exclusive = False

    def close(self):
        if self._fd is not None:
            try:
                self._fcntl.flock(self._fd, self._fcntl.LOCK_UN)
            finally:
                os.close(self._fd)
                self._fd = None


def gc_unreferenced(root):
    """Delete ``.blk`` files under ``root`` not referenced by any current
    manifest.  Called at run START (nothing in flight): volatile stages —
    including a pipeline's FINAL output when its fingerprint is volatile —
    persist no manifest, so their spilled blocks from previous runs are
    unreachable garbage; without this sweep the named scratch root grows
    without bound across reruns.

    Contract (documented on ``run``): starting a new run under a name
    invalidates any still-unread OutputDataset from a PREVIOUS run of
    that name whose final stage was volatile — its backing blocks are
    exactly the unreachable files this sweep removes."""
    if not os.path.isdir(root):
        return
    live = _live_paths(root)
    n = 0
    swept = []
    for d, _dirs, fs in os.walk(root):
        for f in fs:
            if not f.endswith(".blk"):
                continue
            path = os.path.join(d, f)
            if os.path.abspath(path) not in live:
                try:
                    os.unlink(path)
                    n += 1
                    if len(swept) < 20:
                        swept.append(path)
                except OSError:
                    pass
    if n:
        # WARNING level with the paths: the run-then-stream-lazily pattern
        # (holding an unread OutputDataset from a previous volatile-tailed
        # run of this name) loses exactly these files — make the loss
        # visible and attributable, not silent.
        log.warning(
            "resume gc: removed %d unreferenced block file(s) under %s "
            "(previous runs' unread volatile outputs are invalidated): %s%s",
            n, root, ", ".join(swept), "…" if n > len(swept) else "")


def load_plan(root, fps):
    """{sid: manifest} for every stage whose manifest exists, fingerprint-
    matches this graph, and whose referenced files all still exist."""
    plan = {}
    for sid, fp in fps.items():
        if is_volatile(fp):
            continue
        mpath = _manifest_path(root, sid)
        if not os.path.exists(mpath):
            continue
        try:
            with open(mpath) as f:
                m = json.load(f)
        except (OSError, ValueError):
            continue
        if m.get("fp") != fp:
            continue
        if m["kind"] == "sink":
            paths = m["paths"]
        else:
            paths = [os.path.join(root, b[1]) for b in m["blocks"]]
        if not all(os.path.exists(p) for p in paths):
            continue
        plan[sid] = m
    from .obs import trace as _trace

    _trace.instant("checkpoint", "plan", restorable=len(plan),
                   stages=len(fps))
    return plan


def restore_stage(root, manifest):
    """Rebuild the stage output (PartitionSet or _SinkOutput) from its
    manifest.  Returns (result, nrec)."""
    from .obs import trace as _trace
    from .runner import _SinkOutput
    from .storage import BlockRef, PartitionSet

    _t0 = _trace.now()
    if manifest["kind"] == "sink":
        _trace.complete("checkpoint", "restore", _t0, kind="sink",
                        records=manifest["nrec"])
        return _SinkOutput(manifest["paths"]), manifest["nrec"]
    flags = manifest.get("flags", [False, False, False])
    pset = PartitionSet(manifest["n_partitions"], hash_routed=flags[0],
                        hash_sorted=flags[1], key_sorted_runs=flags[2])
    for pid, rel, nrecords, nbytes, kdt, vdt in manifest["blocks"]:
        pset.add(pid, BlockRef.from_disk(
            os.path.join(root, rel), nrecords, nbytes, kdt, vdt))
    _trace.complete("checkpoint", "restore", _t0, kind="pset",
                    records=manifest["nrec"],
                    blocks=len(manifest["blocks"]))
    return pset, manifest["nrec"]
