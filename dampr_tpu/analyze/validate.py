"""Pre-flight plan validator: walk the stage IR, emit coded diagnostics.

Diagnostic codes (stable API — tests and docs/analysis.md pin them):

==========  ========  ====================================================
code        severity  meaning
==========  ========  ====================================================
``DTA101``  error     non-associative fold binop under combiner
                      decomposition (algebraic counterexample attached) —
                      results would depend on chunking
``DTA102``  info      opaque fold binop passed the randomized
                      associativity probe (probabilistic, not a proof)
``DTA201``  warn      impure UDF (evidence attached): fusion declines to
                      fuse across it, retries/resume re-execute it, and a
                      checkpoint alias may skip its side effects
``DTA301``  warn      nondeterministic UDF: speculative re-execution is
                      declined for its stage, and retried/resumed runs
                      may produce different results
``DTA401``  warn      unpicklable captured state (the closure variable is
                      named): breaks process-pool/mesh dispatch and makes
                      checkpoint fingerprints volatile.  Promoted to a
                      HARD ERROR at dispatch time on multi-process runs
                      (:func:`preflight_dispatch_check`).
``DTA402``  warn      fingerprint-unstable operator under ``resume=`` /
                      ``cached()``: the stage can never reuse its
                      checkpoint (recomputes every run)
``DTA501``  info      certified jax-traceable numeric chain (the widened
                      device-lowering vocabulary, ROADMAP 5a)
==========  ========  ====================================================

Suppressions ride per-stage options (``custom_mapper(m,
assume_pure=True)``-style; any op-adding DSL call accepting ``options``
works): ``assume_pure``, ``assume_deterministic``,
``assume_associative``, ``assume_picklable``.
"""

from ..graph import GInput, GMap, GReduce, GSink
from . import assoc as _assoc
from . import pickleprobe, props

SEVERITIES = ("error", "warn", "info")


class Diagnostic(object):
    __slots__ = ("code", "severity", "sid", "stage", "message", "evidence")

    def __init__(self, code, severity, sid, stage, message, evidence=()):
        assert severity in SEVERITIES
        self.code = code
        self.severity = severity
        self.sid = sid
        self.stage = stage
        self.message = message
        self.evidence = list(evidence)

    def to_dict(self):
        return {"code": self.code, "severity": self.severity,
                "sid": self.sid, "stage": self.stage,
                "message": self.message, "evidence": list(self.evidence)}

    def render(self):
        head = "{}: {} [s{}: {}] {}".format(
            self.severity, self.code, self.sid, self.stage, self.message)
        return "\n".join([head] + ["    - " + e for e in self.evidence])

    def __repr__(self):
        return "Diagnostic({}, {}, s{})".format(
            self.code, self.severity, self.sid)


class PreflightError(RuntimeError):
    """A validator error promoted to a hard failure at dispatch time.
    Carries the diagnostics on ``.diagnostics``."""

    def __init__(self, diagnostics):
        self.diagnostics = list(diagnostics)
        super(PreflightError, self).__init__(
            "pre-flight validation failed:\n" + "\n".join(
                d.render() for d in self.diagnostics))


def _stage_ops(stage):
    from ..plan import ir

    if isinstance(stage, GMap):
        parts = list(ir.flatten_mapper(stage.mapper))
        if stage.combiner is not None:
            parts.append(stage.combiner)
        return parts
    if isinstance(stage, GReduce):
        return [stage.reducer]
    if isinstance(stage, GSink):
        return list(ir.flatten_mapper(stage.sinker))
    return []


def _fold_binop(stage):
    """The raw fold binop a stage carries (combiner or binop option)."""
    from .. import base

    opts = getattr(stage, "options", None) or {}
    if isinstance(getattr(stage, "combiner", None),
                  base.PartialReduceCombiner):
        return stage.combiner.op
    if "binop" in opts:
        return opts["binop"]
    red = getattr(stage, "reducer", None)
    if isinstance(red, base.AssocFoldReducer):
        return red.op
    return None


def stage_analysis(stage, sid, probe_traceable=False, probe_assoc=False,
                   probe_pickle=True):
    """One stage's merged analysis record (the plan report row).

    ``probe_pickle=False`` skips the serialization probe (it pickles
    captured state — the per-run report section stays bytecode-only;
    ``picklable`` is then None = unprobed, never a diagnostic)."""
    from ..plan import ir

    opts = getattr(stage, "options", None) or {}
    v = props.stage_verdict(stage)
    rec = {
        "sid": sid,
        "kind": ir.stage_kind(stage),
        "stage": ir.describe_stage(stage),
        "pure": v.pure,
        "deterministic": v.deterministic,
        "impure_evidence": list(v.impure_evidence),
        "nondet_evidence": list(v.nondet_evidence),
    }
    if not probe_pickle:
        rec["picklable"] = None
        rec["pickle_problems"] = []
    else:
        problems = []
        if not opts.get("assume_picklable"):
            for op in _stage_ops(stage):
                problems.extend(pickleprobe.probe_operator(op))
        rec["picklable"] = not problems
        rec["pickle_problems"] = problems
    binop = _fold_binop(stage)
    if binop is not None:
        if opts.get("assume_associative"):
            rec["fold_assoc"] = {"assoc": "yes", "kind": None,
                                 "evidence": "assume_associative override"}
        elif probe_assoc:
            rec["fold_assoc"] = _assoc.classify_binop(binop)
        else:
            from ..ops import segment

            op = segment.as_assoc_op(binop)
            rec["fold_assoc"] = {
                "assoc": "yes" if op.kind is not None else "unknown",
                "kind": op.kind,
                "evidence": ("recognized associative kind {!r}".format(
                    op.kind) if op.kind is not None
                    else "opaque binop (unprobed at run time; "
                         "dampr-tpu-lint runs the algebraic probe)")}
    if probe_traceable and isinstance(stage, GMap) \
            and len(stage.inputs) == 1:
        from . import jaxtrace

        spec, why = jaxtrace.chain_claims(stage.mapper)
        rec["traceable"] = spec is not None
        rec["traceable_why"] = why
    return rec


def _diagnose_stage(rec, stage, diagnostics):
    sid, desc = rec["sid"], rec["stage"]
    if not rec["pure"]:
        diagnostics.append(Diagnostic(
            "DTA201", "warn", sid, desc,
            "impure UDF: fusion will not fuse across this stage, retries "
            "and resume re-execute its side effects, and a checkpoint "
            "alias may skip them (suppress with assume_pure=True)",
            rec["impure_evidence"]))
    if not rec["deterministic"]:
        diagnostics.append(Diagnostic(
            "DTA301", "warn", sid, desc,
            "nondeterministic UDF: speculative re-execution is declined "
            "for this stage; retried or resumed runs may differ "
            "(suppress with assume_deterministic=True)",
            rec["nondet_evidence"]))
    if rec["picklable"] is False:
        diagnostics.append(Diagnostic(
            "DTA401", "warn", sid, desc,
            "unpicklable captured state: a multi-process dispatch of "
            "this stage fails (hard error at dispatch time), and its "
            "checkpoint fingerprint is volatile",
            ["{}: {} is unpicklable ({})".format(
                p["where"], p["variable"], p["error"])
             for p in rec["pickle_problems"]]))
    fold = rec.get("fold_assoc")
    if fold is not None:
        if fold["assoc"] == "no":
            diagnostics.append(Diagnostic(
                "DTA101", "error", sid, desc,
                "non-associative fold binop under map-side combine -> "
                "shuffle -> final-fold decomposition: results depend on "
                "chunking (use group_by(...).reduce for order-sensitive "
                "folds, or assume_associative=True to override)",
                [fold["evidence"]]))
        elif fold["assoc"] == "probably":
            diagnostics.append(Diagnostic(
                "DTA102", "info", sid, desc,
                "opaque fold binop passed the randomized associativity "
                "probe", [fold["evidence"]]))
    if rec.get("traceable"):
        diagnostics.append(Diagnostic(
            "DTA501", "info", sid, desc,
            "certified jax-traceable numeric chain: device-lowerable "
            "through the widened vocabulary",
            [rec.get("traceable_why", "")]))


def analyze_stages(graph, probe_traceable=False, probe_assoc=False,
                   probe_pickle=True):
    """Per-executed-stage analysis records for a graph."""
    out = []
    for sid, stage in enumerate(graph.stages):
        if isinstance(stage, GInput):
            continue
        out.append(stage_analysis(stage, sid,
                                  probe_traceable=probe_traceable,
                                  probe_assoc=probe_assoc,
                                  probe_pickle=probe_pickle))
    return out


def validate_graph(graph, resume=False, num_processes=1,
                   probe_traceable=True, probe_assoc=True,
                   probe_pickle=True):
    """Full pre-flight validation -> ordered [Diagnostic] (errors first).

    ``resume`` adds the fingerprint-stability checks; ``num_processes >
    1`` promotes unpicklable captures to errors (they WILL fail at the
    process boundary)."""
    diagnostics = []
    records = analyze_stages(graph, probe_traceable=probe_traceable,
                             probe_assoc=probe_assoc,
                             probe_pickle=probe_pickle)
    by_sid = {r["sid"]: r for r in records}
    # Fingerprinting pickles captured state — computed lazily so a
    # probe-free validate() (and any graph with no resume/cached()
    # stage) never serializes a byte.
    fps_cache = []
    producer = {s.output: i for i, s in enumerate(graph.stages)}
    for sid, stage in enumerate(graph.stages):
        rec = by_sid.get(sid)
        if rec is None:
            continue
        _diagnose_stage(rec, stage, diagnostics)
        opts = getattr(stage, "options", None) or {}
        wants_fp = resume or opts.get("memory") or opts.get("barrier")
        if wants_fp and not fps_cache:
            fps_cache.append(_fingerprints(graph))
        fps = fps_cache[0] if fps_cache else None
        if wants_fp and fps and not opts.get("assume_picklable"):
            from .. import resume as _resume

            # Volatility propagates downstream through input chaining;
            # attribute the diagnostic to the FIRST volatile stage (its
            # own body is the cause, not an inherited upstream one).
            inherited = any(
                _resume.is_volatile(fps.get(producer.get(src), ""))
                for src in stage.inputs if producer.get(src) in fps)
            if _resume.is_volatile(fps.get(sid, "")) and not inherited:
                diagnostics.append(Diagnostic(
                    "DTA402", "warn", sid, rec["stage"],
                    "fingerprint-unstable operator under resume=/"
                    "cached(): the stage can never match its checkpoint "
                    "and recomputes every run (capture only plain data "
                    "and functions, or pass a fresh run name)",
                    []))
    # A fold's binop rides both halves of the decomposition (the
    # combiner-carrying map and the final-fold reduce): one user fold,
    # one diagnostic.
    seen_folds = set()
    deduped = []
    for d in diagnostics:
        if d.code in ("DTA101", "DTA102"):
            key = (d.code, tuple(d.evidence))
            if key in seen_folds:
                continue
            seen_folds.add(key)
        deduped.append(d)
    diagnostics = deduped
    if num_processes > 1:
        for d in diagnostics:
            if d.code == "DTA401":
                d.severity = "error"
                d.message = ("unpicklable captured state on a "
                             "multi-process run: dispatch across ranks "
                             "WILL fail — " + d.message)
    order = {s: i for i, s in enumerate(SEVERITIES)}
    diagnostics.sort(key=lambda d: (order[d.severity], d.sid, d.code))
    return diagnostics


def _fingerprints(graph):
    """One full-graph fingerprint pass (None on any failure — the
    fingerprint checks are best-effort)."""
    from .. import resume as _resume

    try:
        return _resume.stage_fingerprints(graph)
    except Exception:
        return None


def preflight_dispatch_check(graph, num_processes):
    """The dispatch-time promotion: on a multi-process run, an
    unpicklable UDF capture raises :class:`PreflightError` naming the
    stage, the UDF, and the closure variable — replacing the raw
    ``PicklingError`` traceback from deep inside the dispatch."""
    from . import enabled

    if num_processes <= 1 or not enabled():
        return
    errors = [d for d in validate_graph(
        graph, num_processes=num_processes, probe_traceable=False,
        probe_assoc=False) if d.code == "DTA401"]
    if errors:
        raise PreflightError(errors)


def report_section(graph, probe_traceable=False):
    """The plan report's ``analysis`` section (rendered by
    ``explain()``, shipped in ``stats()["plan"]["analysis"]``).
    Bytecode-only on purpose: the pickle and associativity probes cost
    real work (serialization, sampled evaluation) and belong to the
    explicit ``validate()``/lint surfaces, not every run."""
    records = analyze_stages(graph, probe_traceable=probe_traceable,
                             probe_assoc=False, probe_pickle=False)
    diagnostics = []
    for sid, stage in enumerate(graph.stages):
        rec = next((r for r in records if r["sid"] == sid), None)
        if rec is not None:
            _diagnose_stage(rec, stage, diagnostics)
    return {
        "enabled": True,
        "stages": records,
        "diagnostics": [d.to_dict() for d in diagnostics],
        "counts": {s: sum(1 for d in diagnostics if d.severity == s)
                   for s in SEVERITIES},
    }


def empty_section():
    return {"enabled": False, "stages": [], "diagnostics": [],
            "counts": {s: 0 for s in SEVERITIES}}
