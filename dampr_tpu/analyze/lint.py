"""``dampr-tpu-lint``: pre-flight pipeline diagnostics from the shell.

Lints the pipelines a Python module *constructs* — no pipeline runs.
(One deliberate exception to "static": a fold binop the classifier
finds *pure* is probed for associativity by executing it on a few
synthetic int/float/str triples; impure binops are never executed.)
Two discovery modes, in priority order:

1. the module defines ``lint_pipelines()`` returning an iterable of
   pipeline handles (or ``(name, handle)`` pairs) — the explicit
   convention the shipped examples and benchmarks follow;
2. otherwise, every pipeline handle the module constructed at import
   time is discovered through the DSL's live-handle registry, reduced
   to the *maximal* handles (one whose source no other discovered
   graph consumes — intermediates are prefixes of their consumers and
   would only duplicate diagnostics).

Each pipeline runs the FULL probe set of :func:`..validate.validate_graph`
(bytecode classification + serialization probe + randomized
associativity probe + jax-traceability probe) regardless of
``settings.analyze`` — invoking the linter is its own opt-in.

Exit codes: 0 = clean (or only warn/info without ``--strict``), 1 = any
error-severity diagnostic (with ``--strict``: any warning too), 2 =
import failure or no pipelines found.  ``--json`` emits the machine
report (schema ``dampr-tpu-lint/1``, docs/lint_schema.json, validated
by ``tools/validate_lint.py`` — the same discipline as the doctor).
"""

import argparse
import importlib
import importlib.util
import json
import os
import re
import sys

SCHEMA = "dampr-tpu-lint/1"


def _import_target(target):
    """Import a lint target: a ``.py`` path or a dotted module name."""
    if os.path.exists(target):
        path = os.path.abspath(target)
        mod_name = "_dampr_lint_" + re.sub(
            r"\W", "_", os.path.splitext(os.path.basename(path))[0])
        d = os.path.dirname(path)
        sys.path.insert(0, d)
        try:
            spec = importlib.util.spec_from_file_location(mod_name, path)
            mod = importlib.util.module_from_spec(spec)
            sys.modules[mod_name] = mod
            spec.loader.exec_module(mod)
        finally:
            try:
                sys.path.remove(d)
            except ValueError:
                pass
        return mod
    return importlib.import_module(target)


def _maximal_handles(handles):
    """Drop handles whose source another discovered graph consumes —
    they are construction prefixes of their consumers."""
    consumed = set()
    for h in handles:
        for stage in h.pmer.graph.stages:
            consumed.update(getattr(stage, "inputs", ()))
    return [h for h in handles if h.source not in consumed]


def collect_pipelines(target):
    """``[(name, handle)]`` for one lint target (see module docstring)."""
    from .. import dampr as _dampr

    before = set(_dampr._live_handles)
    mod = _import_target(target)
    hook = getattr(mod, "lint_pipelines", None)
    if callable(hook):
        out = []
        for i, item in enumerate(hook()):
            if isinstance(item, tuple) and len(item) == 2:
                out.append((str(item[0]), item[1]))
            else:
                out.append(("pipeline{}".format(i), item))
        return out
    fresh = [h for h in set(_dampr._live_handles) - before]
    maximal = _maximal_handles(fresh)
    # Stable order: by construction (stage count, then repr) — sets have
    # no order and lint output must be diffable.
    maximal.sort(key=lambda h: (len(h.pmer.graph.stages), repr(h.source)))
    return [("pipeline{}".format(i), h) for i, h in enumerate(maximal)]


def lint_target(target, num_processes=1, resume=False):
    """Lint one module: ``(target_record, [diagnostic_dict])``."""
    rec = {"target": str(target), "pipelines": [], "error": None}
    try:
        pipelines = collect_pipelines(target)
    except Exception as e:  # import errors are the result — but Ctrl-C /
        #                     SystemExit must still abort the whole run
        rec["error"] = "{}: {}".format(type(e).__name__, str(e)[:300])
        return rec, []
    diagnostics = []
    seen = set()
    for name, handle in pipelines:
        rec["pipelines"].append(name)
        for d in handle.validate(resume=resume,
                                 num_processes=num_processes):
            dd = d.to_dict()
            # Shared prefixes across one module's pipelines produce the
            # same diagnostic once per consumer — dedupe on content.
            key = (dd["code"], dd["stage"], dd["message"],
                   tuple(dd["evidence"]))
            if key in seen:
                continue
            seen.add(key)
            dd["pipeline"] = name
            diagnostics.append(dd)
    return rec, diagnostics


def _counts(diagnostics):
    from .validate import SEVERITIES

    return {s: sum(1 for d in diagnostics if d["severity"] == s)
            for s in SEVERITIES}


def run_lint(targets, num_processes=1, resume=False, strict=False):
    """The whole-invocation report dict (docs/lint_schema.json)."""
    target_recs = []
    diagnostics = []
    failed = False
    for t in targets:
        rec, diags = lint_target(t, num_processes=num_processes,
                                 resume=resume)
        target_recs.append(rec)
        diagnostics.extend(diags)
        if rec["error"] is not None or not rec["pipelines"]:
            failed = True
    counts = _counts(diagnostics)
    if failed:
        exit_code = 2
    elif counts["error"] or (strict and counts["warn"]):
        exit_code = 1
    else:
        exit_code = 0
    return {
        "schema": SCHEMA,
        "targets": target_recs,
        "diagnostics": diagnostics,
        "counts": counts,
        "strict": bool(strict),
        "exit_code": exit_code,
    }


def _render(report):
    lines = []
    for rec in report["targets"]:
        if rec["error"] is not None:
            lines.append("{}: IMPORT FAILED: {}".format(
                rec["target"], rec["error"]))
        elif not rec["pipelines"]:
            lines.append("{}: no pipelines found (define "
                         "lint_pipelines() or construct handles at "
                         "import time)".format(rec["target"]))
        else:
            lines.append("{}: {} pipeline(s): {}".format(
                rec["target"], len(rec["pipelines"]),
                ", ".join(rec["pipelines"])))
    for d in report["diagnostics"]:
        lines.append("{}: {} [{} s{}: {}] {}".format(
            d["severity"], d["code"], d["pipeline"], d["sid"],
            d["stage"], d["message"]))
        for e in d["evidence"]:
            lines.append("    - " + e)
    c = report["counts"]
    lines.append("lint: {} error(s), {} warning(s), {} info".format(
        c["error"], c["warn"], c["info"]))
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="dampr-tpu-lint",
        description="static pre-flight diagnostics for dampr_tpu "
                    "pipelines (docs/analysis.md)")
    ap.add_argument("targets", nargs="+",
                    help="Python files (or dotted module names) that "
                         "construct pipelines at import time or define "
                         "lint_pipelines()")
    ap.add_argument("--json", action="store_true",
                    help="emit the machine report "
                         "(schema dampr-tpu-lint/1)")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 on warnings too, not only errors")
    ap.add_argument("--processes", type=int, default=1, metavar="N",
                    help="lint as if dispatching across N ranks "
                         "(promotes unpicklable captures to errors)")
    ap.add_argument("--resume", action="store_true",
                    help="add the resume=/cached() fingerprint-"
                         "stability checks")
    args = ap.parse_args(argv)
    report = run_lint(args.targets, num_processes=args.processes,
                      resume=args.resume, strict=args.strict)
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        print(_render(report))
    return report["exit_code"]


if __name__ == "__main__":
    sys.exit(main())
