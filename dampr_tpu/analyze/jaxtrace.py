"""The jax-traceability probe: certify numeric map/filter chains
device-lowerable by abstract evaluation (the DrJAX recipe, arXiv
2403.07128 — trace the primitives through JAX's abstract interpreter
instead of maintaining an allowlist).

``chain_claims`` inspects a (possibly fused) mapper chain: every leaf
must be a value-wise RecordOp (``ValueMap``/``Filter``; identity links
drop out), every UDF must classify pure + deterministic
(:mod:`.props`), and every UDF must *trace*: ``jax.eval_shape`` over a
``ShapeDtypeStruct`` lane must produce an elementwise result (same
leading shape; numeric out for maps, bool/integer out for filters)
without concretization errors.  A chain that passes is **certified**:
:mod:`dampr_tpu.plan.lower` assigns it ``exec_target="device"`` and the
runner executes it as one vectorized lane program instead of per-record
Python.

Execution semantics (the exactness contract, docs/analysis.md):

- The authoritative result is the **vectorized host evaluation** of the
  same certified program over the lane upcast to 64-bit — element-for-
  element what the per-record Python path computes (records box to
  Python int/float, i.e. 64-bit, on the host path; the upcast mirrors
  that).  Masks apply at the end: a certified elementwise op applied to
  a record a prior filter dropped cannot change surviving records.
- The **device dispatch** runs the identical program under ``jax.jit``
  (32-bit compute when ``jax_enable_x64`` is off, gated on the lane
  fitting int32) and is *verified per block* against the host
  evaluation; a mismatch silently keeps the host result and counts a
  fallback — the same fall-back-per-batch discipline as the lowered
  scanner programs' collision check.  Until a real-hardware trajectory
  justifies trusting unverified XLA output, the verify pass rides along
  (float lanes therefore skip dispatch when x64 is off: 32-bit rounding
  would fail verification every block).
- Residual risk, documented: Python ints are arbitrary-precision and
  int64 lane arithmetic wraps where per-record Python would grow a
  bignum.  The first batch of every lowered stage is additionally
  differential-tested against the per-record path at the runner level.
"""

import itertools
import logging
import threading
import weakref

import numpy as np

from .. import settings

log = logging.getLogger("dampr_tpu.analyze.jaxtrace")

_CERT_LOCK = threading.Lock()
_CERT_CACHE = weakref.WeakKeyDictionary()  # f -> {"map": ok, "filter": ok,
#                                                "why": str}

#: Lane dtypes the vectorized executor accepts (what Python-built blocks
#: actually carry, plus the narrow lanes block mappers emit).
_LANE_DTYPES = ("int64", "int32", "float64", "float32")

_INT32_MAX = np.int64(2 ** 31 - 1)
_INT32_MIN = np.int64(-(2 ** 31))


def _eval_ok(f, dtype, kind):
    """Abstract-eval ``f`` over an (8,) lane of ``dtype``; returns None
    on success or the reason string."""
    import jax

    try:
        out = jax.eval_shape(f, jax.ShapeDtypeStruct((8,), dtype))
    except Exception as e:  # noqa: BLE001 - any trace failure is the answer
        return "{}: {}".format(type(e).__name__, str(e)[:160])
    ok_shapes = (((8,), ()) if kind == "value" else ((8,),))
    if not hasattr(out, "shape") or tuple(out.shape) not in ok_shapes:
        return "not elementwise: input (8,) -> output {!r}".format(
            getattr(out, "shape", type(out).__name__))
    odt = np.dtype(out.dtype)
    if kind == "filter":
        if odt != np.dtype(bool) and odt.kind not in ("i", "u"):
            return "filter predicate traced to dtype {} (need bool/int)" \
                .format(odt)
    elif odt.kind not in ("i", "u", "f", "b"):
        return "map traced to non-numeric dtype {}".format(odt)
    return None


def certify_callable(f, kind):
    """Is ``f`` jax-traceable as an elementwise lane ``kind`` ("map" /
    "filter")?  Returns ``(ok, why)``; cached per function object."""
    with _CERT_LOCK:
        try:
            hit = _CERT_CACHE.get(f)
        except TypeError:
            hit = None  # unweakrefable callable (e.g. __slots__)
        if hit is not None and kind in hit:
            return hit[kind], hit.get("why_" + kind, "")
    import numpy as _np

    reasons = []
    ok = False
    for dt in (_np.int32, _np.float32):
        why = _eval_ok(f, dt, kind)
        if why is None:
            ok = True
        else:
            reasons.append(why)
    why = "" if ok else "; ".join(reasons[:1])
    try:
        with _CERT_LOCK:
            entry = _CERT_CACHE.setdefault(f, {})
            entry[kind] = ok
            entry["why_" + kind] = why
    except TypeError:
        pass  # unweakrefable callable: skip the cache
    return ok, why


class ChainSpec(object):
    """A certified chain: ordered ``(kind, f)`` lane ops, plus an
    optional trailing re-key — ``rekey`` is ``(key_f, value_f_or_None)``
    when the chain ends in a certified ``Rekey`` (the re-key every
    ``fold_by``/``count``/``a_group_by`` plants), so a numeric chain can
    feed a keyed device fold without leaving the lane program."""

    __slots__ = ("ops", "names", "rekey")

    def __init__(self, ops, names, rekey=None):
        self.ops = ops
        self.names = names
        self.rekey = rekey

    def describe(self):
        return " . ".join(self.names)


def chain_claims(mapper, classify=True):
    """``ChainSpec`` when the mapper chain is a certified jax-traceable
    numeric chain, else ``(None, reason)``.  Returns ``(spec, reason)``.

    ``classify=False`` skips the purity/determinism gate (callers that
    already ran :func:`props.stage_verdict`)."""
    from .. import base
    from ..plan import ir
    from . import props

    def _gate(f, kind):
        """Classify + certify one UDF; returns the reason or None."""
        if classify:
            v = props.classify_callable(f)
            if not v.pure:
                return "UDF {} impure: {}".format(
                    props.callable_name(f), "; ".join(v.impure_evidence))
            if not v.deterministic:
                return "UDF {} nondeterministic: {}".format(
                    props.callable_name(f), "; ".join(v.nondet_evidence))
        ok, why = certify_callable(f, kind)
        if not ok:
            return "UDF {} not traceable: {}".format(
                props.callable_name(f), why)
        return None

    ops = []
    names = []
    rekey = None
    for leaf in ir.flatten_mapper(mapper):
        if type(leaf) is base.Map and leaf.mapper is base._identity:
            continue
        if rekey is not None:
            return None, "op {} follows the re-key — only a TRAILING " \
                "Rekey certifies (records leave the value lane there)" \
                .format(type(leaf).__name__)
        if type(leaf) is base.ValueMap:
            kind = "map"
        elif type(leaf) is base.Filter:
            kind = "filter"
        elif type(leaf) is base.Rekey:
            # Trailing re-key (fold_by/count/a_group_by): the key fn —
            # and the value fn when present — certify as elementwise
            # numeric maps over the value lane, so (key_f(v),
            # value_f(v)) records build from two lanes of the same
            # program.
            why = _gate(leaf.key_f, "map")
            if why is not None:
                return None, "re-key " + why
            if leaf.value_f is not None:
                # "value" admits scalar outputs too (count()'s constant
                # ``lambda v: 1`` broadcasts over the lane).
                why = _gate(leaf.value_f, "value")
                if why is not None:
                    return None, "re-key value " + why
            rekey = (leaf.key_f, leaf.value_f)
            names.append("Rekey[{}]".format(
                props.callable_name(leaf.key_f)))
            continue
        else:
            return None, "op {} outside the certified lane vocabulary " \
                "(ValueMap/Filter + trailing Rekey)".format(
                    type(leaf).__name__)
        f = leaf.f
        why = _gate(f, kind)
        if why is not None:
            return None, why
        ops.append((kind, f))
        names.append("{}[{}]".format(type(leaf).__name__,
                                     props.callable_name(f)))
    if not ops and rekey is None:
        return None, "identity chain (nothing to lower)"
    return ChainSpec(ops, names, rekey=rekey), \
        "certified jax-traceable numeric chain: " + " . ".join(names)


# ---------------------------------------------------------------------------
# Execution
# ---------------------------------------------------------------------------

def _pow2(n):
    return max(8, 1 << max(0, (n - 1).bit_length()))


class ChainProgram(object):
    """Executable form of a certified chain, with per-program counters
    (surfaced in stats / tests)."""

    def __init__(self, spec):
        self.spec = spec
        self._jits = {}  # (dtype str) -> jitted program
        self.counters = {"batches": 0, "device_dispatched": 0,
                         "device_verified": 0, "device_mismatch": 0,
                         "host_vectorized": 0, "fallback": 0,
                         "diff_checked": 0, "diff_diverged": 0}
        self._lock = threading.Lock()

    def count(self, key, n=1):
        """Locked counter bump: one cached program is shared by every
        concurrent map job of its stage, and ``+=`` is a lost-update
        race across threads (the counters are stats/test surface)."""
        with self._lock:
            self.counters[key] += n

    # -- host (authoritative) evaluation ------------------------------------
    def run_host(self, vals):
        """Vectorized 64-bit evaluation: ``(keys_or_None, out_vals,
        mask_or_None)``.  ``vals`` is a 1-D numeric numpy array; ``keys``
        is the re-key lane when the chain ends in a certified Rekey."""
        if vals.dtype.kind == "i":
            cur = vals.astype(np.int64, copy=False)
        else:
            cur = vals.astype(np.float64, copy=False)
        mask = None
        keys = None
        # divide/invalid RAISE: numpy would silently emit inf/nan where
        # the authoritative per-record Python path raises
        # ZeroDivisionError — the FloatingPointError lands in
        # run_batch's fallback except, so the batch re-runs per-record
        # and surfaces the genuine exception (byte-identity contract).
        # Overflow/underflow stay IEEE-silent, matching Python floats.
        with np.errstate(divide="raise", invalid="raise",
                         over="ignore", under="ignore"):
            for kind, f in self.spec.ops:
                out = np.asarray(f(cur)) if kind == "map" else None
                if kind == "map":
                    cur = out
                else:
                    m = np.asarray(f(cur))
                    m = m if m.dtype == bool else (m != 0)
                    mask = m if mask is None else (mask & m)
            if self.spec.rekey is not None:
                key_f, value_f = self.spec.rekey
                keys = np.asarray(key_f(cur))
                if value_f is not None:
                    cur = np.asarray(value_f(cur))
                    if cur.ndim == 0:  # constant value fn (count())
                        cur = np.broadcast_to(cur, keys.shape).copy()
        return keys, cur, mask

    # -- device dispatch -----------------------------------------------------
    def _jit_for(self, dtype):
        key = str(dtype)
        fn = self._jits.get(key)
        if fn is None:
            import jax

            ops = self.spec.ops
            rekey = self.spec.rekey

            def program(lane):
                cur = lane
                mask = None
                for kind, f in ops:
                    if kind == "map":
                        cur = f(cur)
                    else:
                        m = f(cur)
                        m = m.astype(bool) if m.dtype != bool else m
                        mask = m if mask is None else mask & m
                import jax.numpy as jnp

                if mask is None:
                    mask = jnp.ones(lane.shape, dtype=bool)
                keys = None
                if rekey is not None:
                    key_f, value_f = rekey
                    keys = key_f(cur)
                    if value_f is not None:
                        cur = jnp.broadcast_to(jnp.asarray(value_f(cur)),
                                               keys.shape)
                return keys, cur, mask

            fn = jax.jit(program)
            with self._lock:
                self._jits[key] = fn
        return fn

    def _device_dtype(self, vals):
        """The dtype the device program computes in, or None when no
        exact dispatch exists for this lane under the current backend."""
        import jax

        x64 = jax.config.jax_enable_x64
        k = vals.dtype.kind
        if k == "i":
            if x64:
                return np.dtype(np.int64)
            if len(vals) and (vals.max() > _INT32_MAX
                              or vals.min() < _INT32_MIN):
                return None
            return np.dtype(np.int32)
        if k == "f":
            # 32-bit float compute rounds differently from the 64-bit
            # host authority: verification would fail every block.
            return np.dtype(np.float64) if x64 else None
        return None

    def run_batch(self, ks, vs):
        """Execute the chain over one record batch (parallel Python
        lists — the batched-UDF protocol).  Returns ``(keys_out,
        values_out)`` as plain Python lists with the filter mask
        applied, or None when the batch is outside the vectorized
        contract (non-numeric lane, a UDF that rejects array input,
        non-elementwise output) — the caller falls back to the
        per-record path, which is always authoritative."""
        try:
            vals = np.asarray(vs)
        except Exception:  # noqa: BLE001 - mixed/unconvertible values
            self.count("fallback")
            return None
        if vals.ndim != 1 or vals.dtype.name not in _LANE_DTYPES \
                or vals.dtype.hasobject:
            self.count("fallback")
            return None
        try:
            host_keys, host_vals, mask = self.run_host(vals)
            host_vals = np.asarray(host_vals)
        except Exception:  # noqa: BLE001 - the UDF rejected the lane form
            self.count("fallback")
            return None
        if host_vals.ndim != 1 or len(host_vals) != len(vals) \
                or host_vals.dtype.hasobject:
            self.count("fallback")
            return None
        if self.spec.rekey is not None and (
                host_keys is None or host_keys.ndim != 1
                or len(host_keys) != len(vals)
                or host_keys.dtype.hasobject):
            self.count("fallback")
            return None
        self.count("batches")
        ddt = self._device_dtype(vals) if (
            settings.use_device and settings.use_device_for(len(vals))) \
            else None
        if ddt is not None:
            try:
                self._dispatch_and_verify(vals, ddt, host_keys,
                                          host_vals, mask)
            except Exception as e:  # noqa: BLE001 - host result stands
                self.count("device_mismatch")
                log.debug("device chain dispatch failed (%s); host "
                          "vectorized result stands", e)
        else:
            self.count("host_vectorized")
        out_vals = host_vals.tolist()
        out_ks = (host_keys.tolist() if host_keys is not None
                  else list(ks))
        if mask is None:
            return out_ks, out_vals
        keep = mask.tolist()
        return (list(itertools.compress(out_ks, keep)),
                list(itertools.compress(out_vals, keep)))

    def _dispatch_and_verify(self, vals, ddt, host_keys, host_vals,
                             mask):
        from ..obs import trace as _trace
        from ..ops import devtime

        n = len(vals)
        n_pad = _pow2(n)
        lane = vals.astype(ddt, copy=False)
        if n_pad != n:
            lane = np.pad(lane, (0, n_pad - n), mode="edge")
        fn = self._jit_for(ddt)
        with _trace.span("device", "numeric-chain", records=n):
            with devtime.track("device"):
                okeys, out, omask = fn(lane)
                out = np.asarray(out)[:n]
                omask = np.asarray(omask)[:n]
                if okeys is not None:
                    okeys = np.asarray(okeys)[:n]
        self.count("device_dispatched")
        hmask = (np.ones(n, dtype=bool) if mask is None else mask)

        def _up(a, ref):
            return a.astype(np.int64 if ref.dtype.kind == "i"
                            else np.float64)

        verified = (np.array_equal(omask, hmask) and np.array_equal(
            _up(out, host_vals)[hmask], host_vals[hmask]))
        if verified and host_keys is not None:
            verified = okeys is not None and np.array_equal(
                _up(okeys, host_keys)[hmask], host_keys[hmask])
        if verified:
            self.count("device_verified")
        else:
            self.count("device_mismatch")
            log.debug("device chain result mismatched the 64-bit host "
                      "evaluation; host result stands (exactness gate)")


import collections

#: Chain-identity -> ChainProgram.  Stage nodes are slotted (no weakrefs)
#: so programs key on the ordered (kind, id(f)) chain identity; each
#: entry holds strong refs to its UDFs (via the spec), which keeps the
#: ids valid for exactly as long as the entry lives.  LRU-bounded: a
#: long-lived session constructing fresh lambdas per run can't grow it
#: without bound, and an evicted entry only costs a re-jit.
_PROGRAMS = collections.OrderedDict()
_PROGRAMS_CAP = 256
_PROG_LOCK = threading.Lock()


def _chain_key(spec):
    """Cache key for one certified chain.  The trailing re-key is part
    of the program identity: two bare ``fold_by``/``count`` chains have
    identical (empty) lane ops but different key/value functions — an
    ops-only key would hand the second stage the first one's compiled
    program."""
    key = tuple((kind, id(f)) for kind, f in spec.ops)
    if spec.rekey is not None:
        key_f, value_f = spec.rekey
        key += (("rekey", id(key_f),
                 id(value_f) if value_f is not None else None),)
    return key


def stage_program(stage):
    """Cached :class:`ChainProgram` for a certified stage (None when the
    stage's chain does not certify — the runner re-checks so a stale
    ``exec_target`` annotation can never dispatch an unknown op)."""
    spec, _why = chain_claims(stage.mapper)
    if spec is None:
        return None
    key = _chain_key(spec)
    with _PROG_LOCK:
        prog = _PROGRAMS.get(key)
        if prog is None:
            prog = ChainProgram(spec)
            _PROGRAMS[key] = prog
        else:
            _PROGRAMS.move_to_end(key)
        while len(_PROGRAMS) > _PROGRAMS_CAP:
            _PROGRAMS.popitem(last=False)
    return prog
