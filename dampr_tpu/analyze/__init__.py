"""Static pipeline analysis: checked preconditions for the machinery that
used to trust the user.

The engine's highest-leverage passes all rest on UDF properties nothing
verified: map fusion and checkpoint aliasing assume purity, speculative
first-result-wins assumes determinism, coded/`a_group_by` aggregation
assumes associative folds, and device lowering's vocabulary was a
hand-maintained allowlist.  This package turns each assumption into a
static verdict with evidence:

- :mod:`.props` — UDF property classifier: bytecode inspection (global/
  closure writes, I/O, ``time``/``random``/``uuid`` calls, unseeded RNG)
  producing purity & determinism verdicts with the offending
  instructions as evidence.  Evidence-based: a callable with no visible
  hazard classifies pure/deterministic — the zero-false-positive
  direction (suppressions exist for the rest, see docs/analysis.md).
- :mod:`.pickleprobe` — dispatch-safety probe: every closure cell and
  operator attribute must pickle (a process-pool/mesh deployment ships
  them); failures name the exact closure variable instead of the raw
  ``PicklingError`` traceback from deep inside a fork.
- :mod:`.assoc` — fold-function associativity: recognized ``AssocOp``
  kinds are associative by construction; opaque Python binops get a
  randomized algebraic probe that hunts counterexample triples.
- :mod:`.jaxtrace` — the DrJAX-style traceability probe (arXiv
  2403.07128): numeric map/filter chains abstract-eval on
  ``jax.ShapeDtypeStruct`` lanes; chains that trace are *certified*
  device-lowerable and :mod:`dampr_tpu.plan.lower` widens its
  vocabulary with them (ROADMAP item 5a).
- :mod:`.validate` — the pre-flight plan validator: walks the stage IR
  and emits coded diagnostics (``DTA...``, error/warn/info) for hazards
  that today surface mid-run or never: impure UDFs in fused/speculated
  stages, non-associative folds under combiner decomposition,
  unpicklable closures headed for a multi-process dispatch,
  fingerprint-unstable operators under ``resume=``/``cached()``.
- :mod:`.lint` — the ``dampr-tpu-lint`` console script +
  ``PBase.validate()`` surface (``--json`` validated by
  ``docs/lint_schema.json``, same discipline as the doctor).

Master switch: ``settings.analyze`` (env ``DAMPR_TPU_ANALYZE``; default
on).  Off, every hook is a single flag check: plans, fingerprints, and
results are byte-identical to the pre-analysis engine (CI pins it).
"""

from .. import settings


def enabled():
    """Is the analysis layer in force (settings.analyze)?"""
    return settings.analyze


from .assoc import classify_binop  # noqa: E402
from .pickleprobe import probe_operator  # noqa: E402
from .props import classify_callable, stage_verdict  # noqa: E402
from .validate import (Diagnostic, PreflightError,  # noqa: E402
                       preflight_dispatch_check, report_section,
                       validate_graph)

__all__ = [
    "enabled", "classify_callable", "stage_verdict", "probe_operator",
    "classify_binop", "Diagnostic", "PreflightError", "validate_graph",
    "preflight_dispatch_check", "report_section",
]
