"""Fold-function associativity recognition.

``a_group_by``/``fold_by`` decompose every fold into map-side partial
combine -> shuffle -> reduce-side final combine; the decomposition is
only correct for associative binops, and a non-associative one produces
*silently wrong* results that depend on chunking.  Three tiers:

1. **known ops**: :class:`~dampr_tpu.ops.segment.AssocOp` descriptors
   with a recognized ``kind`` (sum/min/max/first/pair_sum) are
   associative by construction — the segment kernels are built on it.
2. **algebraic probe** (opaque Python binops): a randomized search for
   counterexample triples ``f(f(a,b),c) != f(a,f(b,c))`` over small int,
   float, and string samples.  A found counterexample is a *proof* of
   non-associativity (the verdict carries it); survival is only
   evidence, so the verdict stays ``"probably"`` — the validator maps
   that to an info diagnostic, never an error.
3. **unknown**: binops that reject every probe domain (they need
   user-typed operands) stay ``"unknown"``.

The probe is deterministic (fixed seed) so lint output is stable.
"""

import random


def _probe_domains():
    rnd = random.Random(0xDA17)
    ints = [rnd.randint(-40, 40) for _ in range(9)]
    floats = [rnd.uniform(-8.0, 8.0) for _ in range(9)]
    strs = ["a", "bc", "", "d", "ee", "f", "gh", "i", "jk"]
    return [ints, floats, strs]


def probe_binop(fn, triples=12):
    """Randomized associativity probe over one opaque binop.

    Returns ``(verdict, evidence)`` where verdict is ``"probably"`` (no
    counterexample over any accepting domain), ``"no"`` (counterexample
    found — evidence carries the triple), or ``"unknown"`` (every probe
    domain raised: the binop needs operand types we cannot guess)."""
    any_domain_ok = False
    for domain in _probe_domains():
        tried = 0
        for i in range(len(domain)):
            for j in range(len(domain)):
                for k in range(len(domain)):
                    if tried >= triples:
                        break
                    a, b, c = domain[i], domain[j], domain[k]
                    try:
                        left = fn(fn(a, b), c)
                        right = fn(a, fn(b, c))
                    except Exception:
                        tried = -1
                        break
                    tried += 1
                    eq = (left == right) or (
                        isinstance(left, float) and isinstance(right, float)
                        and abs(left - right) <= 1e-9 * max(
                            1.0, abs(left), abs(right)))
                    if not eq:
                        return "no", (
                            "counterexample: f(f({a!r}, {b!r}), {c!r}) = "
                            "{l!r} but f({a!r}, f({b!r}, {c!r})) = {r!r}"
                            .format(a=a, b=b, c=c, l=left, r=right))
                if tried < 0 or tried >= triples:
                    break
            if tried < 0 or tried >= triples:
                break
        if tried > 0:
            any_domain_ok = True
    if any_domain_ok:
        return "probably", ("no counterexample over {} sampled triples "
                            "(probabilistic — not a proof)".format(triples))
    return "unknown", ("binop rejected every probe domain (int/float/str) "
                      "— needs user-typed operands")


def classify_binop(binop):
    """Associativity verdict for a fold binop (raw callable or AssocOp).

    Returns ``{"assoc": "yes"|"probably"|"no"|"unknown", "kind",
    "evidence"}``."""
    from ..ops import segment

    op = segment.as_assoc_op(binop)
    if op.kind is not None:
        return {"assoc": "yes", "kind": op.kind,
                "evidence": "recognized associative kind {!r} (segment "
                            "kernel contract)".format(op.kind)}
    fn = getattr(op, "fn", None) or binop
    name = getattr(fn, "__name__", type(fn).__name__)
    # The probe EXECUTES the binop on synthetic operands — an
    # evidence-impure binop (writes an audit line, mutates external
    # state) must not perform those effects under a "static" lint.
    from . import props

    v = props.classify_callable(fn)
    if not v.pure:
        return {"assoc": "unknown", "kind": None,
                "evidence": "opaque binop {}: classified impure ({}) — "
                            "the randomized probe executes the binop and "
                            "is skipped for impure ones".format(
                                name, "; ".join(v.impure_evidence[:1]))}
    verdict, evidence = probe_binop(fn)
    return {"assoc": verdict, "kind": None,
            "evidence": "opaque binop {}: {}".format(name, evidence)}
