"""Dispatch-safety probe: will this operator's captured state survive a
process boundary?

This engine runs jobs on threads, so UDFs themselves never pickle — but
their *captured state* does cross serialization boundaries: checkpoint
fingerprints hash pickled opaque objects (an unpicklable capture makes
the stage fingerprint volatile, silently disabling ``resume=``), and any
process-pool / multi-rank mesh deployment ships closures to workers the
way the fork-based reference did.  Today the failure is a raw
``PicklingError`` traceback from deep inside the dispatch machinery;
the probe surfaces it pre-flight, naming the stage, the UDF, and the
exact closure variable.

The probe deliberately does NOT require the function object itself to
pickle (plain functions/lambdas ship by code under fork or re-import);
it probes what the function *carries*: closure cells, defaults, and —
for callable objects — instance attributes.
"""

import functools
import pickle
import types


class _NullSink(object):
    """Discarding pickle sink: the probe needs the serialization
    ATTEMPT, not the bytes — a multi-hundred-MB broadcast table must
    not be materialized twice just to learn it pickles."""

    __slots__ = ()

    def write(self, b):
        return len(b)


def _try_pickle(v):
    """None when ``v`` pickles; the one-line error otherwise."""
    try:
        pickle.Pickler(_NullSink(),
                       protocol=pickle.HIGHEST_PROTOCOL).dump(v)
        return None
    except Exception as e:  # noqa: BLE001 - any failure is the answer
        return "{}: {}".format(type(e).__name__, str(e)[:200])


def _is_plain_function(v):
    return isinstance(v, (types.FunctionType, types.BuiltinFunctionType,
                          types.BuiltinMethodType, types.MethodType,
                          functools.partial, type))


def probe_callable(f, label=None):
    """Probe one callable's captured state.  Returns a list of problem
    dicts ``{"where", "variable", "error"}`` (empty = dispatch-safe)."""
    problems = []
    label = label or getattr(f, "__qualname__", type(f).__name__)
    if isinstance(f, functools.partial):
        for i, a in enumerate(f.args):
            err = None if _is_plain_function(a) else _try_pickle(a)
            if err:
                problems.append({"where": label, "variable":
                                 "partial arg {}".format(i), "error": err})
        for k, a in (f.keywords or {}).items():
            err = None if _is_plain_function(a) else _try_pickle(a)
            if err:
                problems.append({"where": label, "variable":
                                 "partial kwarg '{}'".format(k),
                                 "error": err})
        return problems + probe_callable(f.func, label)
    if isinstance(f, types.MethodType):
        recv = f.__self__
        if not isinstance(recv, type):
            err = _try_pickle(recv)
            if err:
                problems.append({"where": label,
                                 "variable": "bound receiver ({})".format(
                                     type(recv).__name__),
                                 "error": err})
        return problems
    code = getattr(f, "__code__", None)
    if code is not None:
        closure = getattr(f, "__closure__", None) or ()
        for name, cell in zip(code.co_freevars, closure):
            try:
                val = cell.cell_contents
            except ValueError:
                continue
            if _is_plain_function(val):
                # Captured helper functions ship by code, and their own
                # captures get probed when the classifier reaches them.
                continue
            err = _try_pickle(val)
            if err:
                problems.append({"where": label,
                                 "variable": "closure variable "
                                 "'{}' ({})".format(name,
                                                    type(val).__name__),
                                 "error": err})
        for i, d in enumerate(f.__defaults__ or ()):
            if _is_plain_function(d):
                continue
            err = _try_pickle(d)
            if err:
                problems.append({"where": label,
                                 "variable": "default arg {}".format(i),
                                 "error": err})
        return problems
    # Callable object: its instance attributes are the captured state.
    held = getattr(f, "__dict__", None) or {}
    for name, val in held.items():
        if _is_plain_function(val) or callable(val):
            continue
        err = _try_pickle(val)
        if err:
            problems.append({"where": label,
                             "variable": "attribute '{}' ({})".format(
                                 name, type(val).__name__),
                             "error": err})
    return problems


def probe_operator(op):
    """Probe every UDF an operator holds.  Returns the merged problem
    list (empty = the whole operator is dispatch-safe)."""
    from .props import iter_udfs

    problems = []
    seen = set()
    for label, f in iter_udfs(op):
        key = id(f)
        if key in seen:
            continue
        seen.add(key)
        problems.extend(probe_callable(f, label))
    # Operator-held non-callable state (a BlockMapper's config) probes
    # through the same attribute walk.
    for name, val in (getattr(op, "__dict__", None) or {}).items():
        if callable(val) or _is_plain_function(val):
            continue
        err = _try_pickle(val)
        if err:
            problems.append({"where": type(op).__name__,
                             "variable": "attribute '{}' ({})".format(
                                 name, type(val).__name__),
                             "error": err})
    return problems
