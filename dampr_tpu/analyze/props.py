"""UDF property classifier: purity and determinism verdicts from bytecode.

The classifier is *evidence-based*: it walks a callable's compiled
bytecode (and the bytecode of every nested code object — lambdas,
comprehensions, genexps) looking for concrete hazard witnesses, and only
an actual witness downgrades the verdict.  A callable the walk cannot
see through (C builtins, callable objects without ``__code__``) gets the
benefit of the doubt — the zero-false-positive direction the validator
needs, with ``assume_pure=False``-style overrides left to the user.

Witness catalog (each carries the instruction that proved it):

- **purity**: ``STORE_GLOBAL``/``DELETE_GLOBAL``; calls to ``open``/
  ``print``/``input``; writes through OS/file handles (``os.remove``,
  ``.write`` on a closure-held handle); mutating-method calls
  (``append``/``update``/``add``/...) on closure or global receivers;
  ``STORE_ATTR``/``STORE_SUBSCR`` whose receiver was loaded from a
  closure cell or module global.
- **determinism**: any reach into ``random``/``secrets``/``uuid``/
  ``time``/``datetime``/``numpy.random`` (module attribute access or a
  direct global bound to one of their functions), plus closure cells
  holding live RNG instances (``random.Random``, numpy ``Generator`` /
  ``RandomState``) — an unseeded RNG is the canonical speculation
  hazard.

Local mutation is *not* impurity: a UDF that builds and mutates its own
locals (the dedupe filter's fresh set, an accumulator list) is pure in
every sense the engine cares about.  Instance state on ``self``
(``STORE_ATTR`` on a method's first argument) is also exempt — the
BlockMapper/BlockReducer lifecycle is deep-copied per job by contract.
"""

import dis
import types

#: Module roots whose use marks a callable nondeterministic.  Matched
#: against ``module.__name__`` prefixes so ``numpy.random.mtrand`` and
#: friends resolve too.
NONDET_MODULES = ("random", "secrets", "uuid", "time", "numpy.random")

#: ``datetime`` is deterministic except for the clock readers.
NONDET_DATETIME_ATTRS = frozenset(("now", "today", "utcnow"))

#: ``os`` members that read entropy or the clock.
NONDET_OS_ATTRS = frozenset(("urandom", "getrandbits", "times"))

#: ``os`` members that mutate the world (impurity witnesses).
IMPURE_OS_ATTRS = frozenset((
    "remove", "unlink", "rename", "replace", "rmdir", "mkdir", "makedirs",
    "system", "popen", "chmod", "chown", "truncate", "environ", "putenv",
    "kill", "removedirs", "symlink", "link", "open", "write"))

#: Bare global names whose *call* is an I/O side effect.
IMPURE_GLOBAL_CALLS = frozenset(("open", "print", "input", "exec"))

#: Mutating method names: calling one on a closure/global receiver is a
#: shared-state write.  Deliberately excludes names that are commonly
#: pure on other types (``count``, ``index``, ``get``, ``copy``...).
MUTATOR_METHODS = frozenset((
    "append", "extend", "insert", "remove", "pop", "popitem", "clear",
    "update", "add", "discard", "setdefault", "sort", "reverse",
    "appendleft", "extendleft", "popleft", "write", "writelines",
    "writerow", "writerows", "send", "put", "put_nowait"))

#: RNG instance types recognized in closure cells / defaults.
_RNG_TYPE_NAMES = (
    ("random", "Random"), ("random", "SystemRandom"),
    ("numpy.random", "Generator"), ("numpy.random", "RandomState"),
    ("numpy.random.mtrand", "RandomState"),
)

_GLOBAL_LOADS = ("LOAD_GLOBAL", "LOAD_NAME")
_DEREF_LOADS = ("LOAD_DEREF", "LOAD_CLASSDEREF")
_ATTR_LOADS = ("LOAD_ATTR", "LOAD_METHOD")


class Verdict(object):
    """Classification result for one callable (or one operator/stage,
    when merged).  ``pure``/``deterministic`` stay True until a witness
    lands in the matching evidence list."""

    __slots__ = ("name", "pure", "deterministic", "impure_evidence",
                 "nondet_evidence", "opaque")

    def __init__(self, name):
        self.name = name
        self.pure = True
        self.deterministic = True
        self.impure_evidence = []
        self.nondet_evidence = []
        self.opaque = False  # no bytecode to inspect (builtin / C callable)

    def impure(self, why):
        self.pure = False
        if why not in self.impure_evidence:
            self.impure_evidence.append(why)

    def nondet(self, why):
        self.deterministic = False
        if why not in self.nondet_evidence:
            self.nondet_evidence.append(why)

    def merge(self, other):
        if not other.pure:
            self.pure = False
            for e in other.impure_evidence:
                self.impure(e)
        if not other.deterministic:
            self.deterministic = False
            for e in other.nondet_evidence:
                self.nondet(e)
        return self

    def clone(self):
        v = Verdict(self.name)
        v.pure = self.pure
        v.deterministic = self.deterministic
        v.impure_evidence = list(self.impure_evidence)
        v.nondet_evidence = list(self.nondet_evidence)
        v.opaque = self.opaque
        return v

    def to_dict(self):
        return {
            "name": self.name,
            "pure": self.pure,
            "deterministic": self.deterministic,
            "impure_evidence": list(self.impure_evidence),
            "nondet_evidence": list(self.nondet_evidence),
        }

    def __repr__(self):
        return "Verdict({}, pure={}, deterministic={})".format(
            self.name, self.pure, self.deterministic)


def callable_name(f):
    return getattr(f, "__qualname__", None) or getattr(
        f, "__name__", None) or type(f).__name__


def _module_root(mod):
    name = getattr(mod, "__name__", "") or ""
    for root in NONDET_MODULES:
        if name == root or name.startswith(root + "."):
            return root
    return None


def _is_rng_instance(v):
    for mod, cls in _RNG_TYPE_NAMES:
        t = type(v)
        if t.__name__ == cls and (t.__module__ or "").startswith(mod):
            return True
    return False


def _resolved_bindings(f):
    """{name: value} for every global and closure binding the function
    can reach — what LOAD_GLOBAL / LOAD_DEREF would actually load."""
    out = {}
    code = getattr(f, "__code__", None)
    g = getattr(f, "__globals__", None) or {}
    if code is not None:
        for name in code.co_names:
            if name in g:
                out[name] = g[name]
        closure = getattr(f, "__closure__", None) or ()
        free = code.co_freevars
        for name, cell in zip(free, closure):
            try:
                out[name] = cell.cell_contents
            except ValueError:
                pass  # empty cell (still being built)
    return out


def _builtin_verdict(f, v):
    """Known C-level callables: classify by qualified name."""
    mod = getattr(f, "__module__", "") or ""
    qual = callable_name(f)
    for root in NONDET_MODULES:
        if mod == root or mod.startswith(root + "."):
            v.nondet("calls {}.{} (nondeterministic source)".format(
                mod, qual))
            return v
    # Bound methods of RNG instances (random.Random().random).
    recv = getattr(f, "__self__", None)
    if recv is not None and _is_rng_instance(recv):
        v.nondet("bound method {} of RNG instance {}".format(
            qual, type(recv).__name__))
    if qual in ("open", "print", "input"):
        v.impure("calls builtin {}() (I/O)".format(qual))
    v.opaque = True
    return v


def _scan_code(code, bindings, v, self_name=None, depth=0):
    """One code object's instruction walk.  ``bindings`` resolves names
    to live objects so module-attribute hazards classify precisely;
    ``self_name`` exempts instance-attribute writes on methods."""
    if depth > 4:
        return
    last = None  # previous meaningful instruction
    # What the receiver of an ATTR/SUBSCR write most plausibly was:
    # tracked as the source kind of the most recent non-const load.
    recent_loads = []
    # The object most plausibly on top of the stack after the previous
    # load, when statically resolvable — lets attribute CHAINS classify
    # (np.random.rand, datetime.datetime.now): each LOAD_ATTR hop over a
    # module/class receiver resolves one level deeper.  Only modules and
    # classes resolve (getattr on arbitrary objects could run property
    # code).
    tos_obj = None
    # Augmented subscript (``d[k] += v``) loads container+key BEFORE the
    # read (BINARY_SUBSCR) with no value load first — snapshot the loads
    # there so STORE_SUBSCR can find the receiver in either pattern.
    aug = None
    aug_nonconst = 0
    for ins in dis.get_instructions(code):
        op = ins.opname
        arg = ins.argval
        new_tos = None
        if op == "BINARY_SUBSCR":
            aug = list(recent_loads)
            aug_nonconst = 0
        if op in ("STORE_GLOBAL", "DELETE_GLOBAL"):
            v.impure("{} of global '{}'".format(
                "write" if op == "STORE_GLOBAL" else "delete", arg))
        elif op in _GLOBAL_LOADS:
            if arg in IMPURE_GLOBAL_CALLS and arg not in bindings:
                v.impure("calls builtin {}() (I/O)".format(arg))
            bound = bindings.get(arg)
            if bound is not None and not isinstance(
                    bound, types.ModuleType):
                if callable(bound):
                    m = getattr(bound, "__module__", "") or ""
                    for root in NONDET_MODULES:
                        if m == root or m.startswith(root + "."):
                            v.nondet("calls {} from module '{}'".format(
                                arg, root))
                            break
                    else:
                        # C-level bound methods (random.random is a
                        # method of a hidden Random()) report no module;
                        # classify by their receiver.
                        if _is_rng_instance(getattr(bound, "__self__",
                                                    None)):
                            v.nondet("calls {} (bound method of an RNG "
                                     "instance)".format(arg))
                if _is_rng_instance(bound):
                    v.nondet("uses RNG instance '{}' ({})".format(
                        arg, type(bound).__name__))
            new_tos = bound
            recent_loads.append(("global", arg))
        elif op in _DEREF_LOADS:
            bound = bindings.get(arg)
            if bound is not None and _is_rng_instance(bound):
                v.nondet("closure variable '{}' holds an RNG instance "
                         "({})".format(arg, type(bound).__name__))
            new_tos = bound
            recent_loads.append(("closure", arg))
        elif op in _ATTR_LOADS:
            src = last
            recv = tos_obj
            if recv is not None and src is not None \
                    and src.opname in _ATTR_LOADS:
                # Chained receiver (module.module.f / module.Class.m):
                # the direct-load cases below see only one hop.
                if isinstance(recv, types.ModuleType):
                    root = _module_root(recv)
                    if root is not None and arg != "seed":
                        v.nondet("calls {}.{}".format(recv.__name__, arg))
                    if recv.__name__ == "datetime" \
                            and arg in NONDET_DATETIME_ATTRS:
                        v.nondet("calls datetime.{}".format(arg))
                elif isinstance(recv, type):
                    if getattr(recv, "__module__", "") == "datetime" \
                            and arg in NONDET_DATETIME_ATTRS:
                        v.nondet("calls datetime.{}.{}".format(
                            recv.__name__, arg))
            if isinstance(recv, (types.ModuleType, type)):
                try:
                    new_tos = getattr(recv, arg, None)
                except Exception:  # noqa: BLE001 - exotic module getattr
                    new_tos = None
            if src is not None and src.opname in (
                    _GLOBAL_LOADS + _DEREF_LOADS):
                recv_name = src.argval
                bound = bindings.get(recv_name)
                if isinstance(bound, types.ModuleType):
                    root = _module_root(bound)
                    if root is not None and arg != "seed":
                        v.nondet("calls {}.{}".format(
                            bound.__name__, arg))
                    if bound.__name__ == "datetime" \
                            and arg in NONDET_DATETIME_ATTRS:
                        v.nondet("calls datetime.{}".format(arg))
                    if bound.__name__ == "os":
                        if arg in NONDET_OS_ATTRS:
                            v.nondet("calls os.{}".format(arg))
                        if arg in IMPURE_OS_ATTRS:
                            v.impure("calls os.{} (filesystem/process "
                                     "side effect)".format(arg))
                elif bound is not None and _is_rng_instance(bound):
                    v.nondet("calls {}.{} on an RNG instance".format(
                        recv_name, arg))
                elif arg in MUTATOR_METHODS:
                    kind = ("closure" if src.opname in _DEREF_LOADS
                            else "global")
                    if not isinstance(bound, types.ModuleType) and (
                            bound is None or not callable(bound)):
                        v.impure(
                            "mutates {} variable '{}' via .{}()".format(
                                kind, recv_name, arg))
                # datetime classes: datetime.datetime.now()
                if isinstance(bound, type) and getattr(
                        bound, "__module__", "") == "datetime" \
                        and arg in NONDET_DATETIME_ATTRS:
                    v.nondet("calls datetime.{}.{}".format(
                        bound.__name__, arg))
            recent_loads.append(("attr", arg))
        elif op in ("STORE_ATTR", "DELETE_ATTR"):
            src = last
            if src is not None:
                if src.opname in _DEREF_LOADS:
                    v.impure("writes attribute '{}' of closure variable "
                             "'{}'".format(arg, src.argval))
                elif src.opname in _GLOBAL_LOADS:
                    v.impure("writes attribute '{}' of global "
                             "'{}'".format(arg, src.argval))
                elif (src.opname == "LOAD_FAST" and self_name is not None
                        and src.argval == self_name):
                    pass  # instance state on self: per-job-copied contract
        elif op in ("STORE_SUBSCR", "DELETE_SUBSCR"):
            # ``d[k] = v`` loads value, then CONTAINER, then key — the
            # receiver is the second-to-last load.  ``d[k] += v`` loads
            # container, then key, before the BINARY_SUBSCR read: the
            # snapshot taken there (still clean = only consts since)
            # holds the same [container, key] tail.  Checking exactly
            # the receiver position (not a window) keeps a nonlocal
            # VALUE assigned into a local container from flagging;
            # computed keys hide the receiver and err toward no-flag —
            # the zero-false-positive direction.
            if aug is not None and aug_nonconst == 0:
                loads = aug
            else:
                loads = recent_loads
            if len(loads) >= 2:
                kind, name = loads[-2]
                if kind in ("closure", "global"):
                    bound = bindings.get(name)
                    if not (isinstance(bound, types.ModuleType)
                            or callable(bound)):
                        v.impure("subscript write into {} variable "
                                 "'{}'".format(kind, name))
            aug = None
        elif op == "LOAD_FAST":
            recent_loads.append(("local", arg))
        elif op == "LOAD_CONST":
            if isinstance(arg, types.CodeType):
                _scan_code(arg, bindings, v, depth=depth + 1)
            recent_loads.append(("const", None))
        if aug is not None and op != "BINARY_SUBSCR" and op in (
                _GLOBAL_LOADS + _DEREF_LOADS + _ATTR_LOADS
                + ("LOAD_FAST",)):
            aug_nonconst += 1
        if op not in ("CACHE", "PRECALL", "RESUME", "PUSH_NULL", "COPY",
                      "NOP", "EXTENDED_ARG"):
            last = ins
            tos_obj = new_tos
        if len(recent_loads) > 8:
            del recent_loads[:-8]


import threading as _threading
import weakref as _weakref

_VERDICT_CACHE = _weakref.WeakKeyDictionary()  # f -> Verdict (frozen copy)
_VERDICT_LOCK = _threading.Lock()


def classify_callable(f, _depth=0):
    """Purity/determinism :class:`Verdict` for one callable.  Cached per
    function object (the plan passes, the speculation gate, and the
    report section may all classify the same UDF in one run); callers
    get a fresh clone, so renaming/merging never poisons the cache."""
    try:
        with _VERDICT_LOCK:
            hit = _VERDICT_CACHE.get(f)
    except TypeError:
        hit = None
    if hit is not None:
        return hit.clone()
    v = _classify_uncached(f, _depth)
    try:
        with _VERDICT_LOCK:
            _VERDICT_CACHE[f] = v.clone()
    except TypeError:
        pass  # unweakrefable callable: classify each time
    return v


def _classify_uncached(f, _depth=0):
    import functools

    v = Verdict(callable_name(f))
    if isinstance(f, functools.partial):
        return v.merge(classify_callable(f.func, _depth))
    if isinstance(f, types.MethodType):
        inner = classify_callable(f.__func__, _depth)
        inner.name = v.name
        recv = f.__self__
        if _is_rng_instance(recv):
            inner.nondet("bound method of RNG instance {}".format(
                type(recv).__name__))
        return inner
    code = getattr(f, "__code__", None)
    if code is None:
        if callable(f):
            call = getattr(type(f), "__call__", None)
            inner_code = getattr(call, "__code__", None)
            if inner_code is not None and _depth < 3:
                inner = classify_callable(call, _depth + 1)
                inner.name = v.name
                return inner
            return _builtin_verdict(f, v)
        return v
    # Methods' first positional arg ('self' by convention) is the
    # per-job-copied receiver; attribute writes on it are lifecycle
    # state, not shared-state impurity.
    self_name = (code.co_varnames[0]
                 if (code.co_argcount >= 1 and code.co_varnames
                     and code.co_varnames[0] == "self") else None)
    bindings = _resolved_bindings(f)
    _scan_code(code, bindings, v, self_name=self_name)
    # Closure cells holding RNGs are a hazard even when this code object
    # never touches them directly (a nested lambda might).
    for name, val in bindings.items():
        if name in code.co_freevars and _is_rng_instance(val):
            v.nondet("closure variable '{}' holds an RNG instance "
                     "({})".format(name, type(val).__name__))
    return v


#: Operator attributes that hold user callables — shared with
#: :func:`dampr_tpu.plan.ir._part_name`'s probe list.
UDF_ATTRS = ("mapper", "f", "key_f", "value_f", "streamer_f", "reducer",
             "stream_f", "crosser", "sinker", "joiner_f", "load_f")


def iter_udfs(op, _seen=None, _depth=0):
    """Yield ``(label, callable)`` for every user callable reachable from
    an operator (composed chains flatten; wrapper attrs walk one level)."""
    if _seen is None:
        _seen = set()
    if id(op) in _seen or _depth > 6 or op is None:
        return
    _seen.add(id(op))
    from .. import base

    if type(op) in (base.ComposedMapper, base.ComposedStreamable):
        for part in (op.left, op.right):
            for item in iter_udfs(part, _seen, _depth + 1):
                yield item
        return
    label = type(op).__name__
    found = False
    for attr in UDF_ATTRS:
        f = getattr(op, attr, None)
        if f is None:
            continue
        if isinstance(f, base.Mapper) or isinstance(f, base.Reducer) \
                or isinstance(f, base.Streamable):
            for item in iter_udfs(f, _seen, _depth + 1):
                yield item
            found = True
        elif callable(f):
            yield "{}.{}[{}]".format(label, attr, callable_name(f)), f
            found = True
    if not found and callable(op) and not isinstance(op, type):
        yield label, op


def operator_verdict(op):
    """Merged verdict over every UDF an operator holds, plus op-level
    knowledge the bytecode can't see (Sample's RNG, Inspect's print)."""
    from .. import base, settings

    v = Verdict(type(op).__name__)
    if isinstance(op, base.Sample):
        if settings.seed is None:
            v.nondet("Sample draws from a time-seeded per-thread RNG "
                     "(set settings.seed for reproducible sampling)")
    if isinstance(op, base.Inspect):
        v.impure("Inspect prints every record (debug passthrough)")
    for label, f in iter_udfs(op):
        fv = classify_callable(f)
        fv.name = label
        v.merge(fv)
    return v


def stage_verdict(stage):
    """Merged purity/determinism verdict for one graph stage, honoring
    the per-stage ``assume_pure`` / ``assume_deterministic`` overrides
    (``custom_mapper(m, assume_pure=True)``-style options)."""
    from ..graph import GMap, GReduce, GSink
    from ..plan import ir

    opts = getattr(stage, "options", None) or {}
    v = Verdict(ir.describe_stage(stage) if hasattr(stage, "inputs")
                else repr(stage))
    parts = []
    if isinstance(stage, GMap):
        parts.extend(ir.flatten_mapper(stage.mapper))
        if stage.combiner is not None:
            parts.append(stage.combiner)
    elif isinstance(stage, GReduce):
        parts.append(stage.reducer)
    elif isinstance(stage, GSink):
        parts.extend(ir.flatten_mapper(stage.sinker))
    for p in parts:
        v.merge(operator_verdict(p))
    if "binop" in opts:
        from ..ops import segment

        op = segment.as_assoc_op(opts["binop"])
        if op.kind is None and op.fn is not None:
            v.merge(operator_verdict(op.fn))
    if opts.get("assume_pure"):
        v.pure = True
        v.impure_evidence = []
    if opts.get("assume_deterministic"):
        v.deterministic = True
        v.nondet_evidence = []
    return v
