"""dampr_tpu — a TPU-native out-of-core dataflow/MapReduce framework.

Same capabilities and fluent API as the reference Dampr (single-machine,
pure-Python, fork+disk — reference dampr/__init__.py:1-33), re-designed
TPU-first: records batch into columnar blocks, keyed work (hashing, sorting,
grouping, folding) runs as vectorized XLA kernels, shuffles ride device
collectives on a `jax.sharding.Mesh` (see dampr_tpu.parallel), and the memory
hierarchy is HBM -> host RAM -> disk instead of RAM -> disk.

    >>> from dampr_tpu import Dampr
    >>> Dampr.memory([1, 2, 3, 4, 5]).map(lambda x: x + 1).read()
    [2, 3, 4, 5, 6]
"""

import logging

from .base import (BlockMapper, BlockReducer, Map, Mapper, Reduce, Reducer,
                   StreamMapper, StreamReducer, Streamable)
from .blocks import Block, BlockBuilder
from .dampr import (ARReduce, Dampr, PBase, PJoin, PMap, PReduce, RunStats,
                    ValueEmitter, setup_logging)
from .dataset import (BlockDataset, CatDataset, Chunker, Dataset, EmptyDataset,
                      GzipLineDataset, MemoryDataset, StreamDataset,
                      TextLineDataset)
from .graph import Graph, Source
from .inputs import MemoryInput, PathInput, TextInput, UrlsInput
from .runner import MTRunner

__version__ = "0.2.0"

__all__ = [
    "Dampr", "PBase", "PMap", "PReduce", "PJoin", "ARReduce", "ValueEmitter",
    "RunStats",
    "Mapper", "Streamable", "Map", "BlockMapper", "StreamMapper",
    "Reducer", "Reduce", "BlockReducer", "StreamReducer",
    "Graph", "Source", "MTRunner",
    "Dataset", "Chunker", "EmptyDataset", "MemoryDataset", "TextLineDataset",
    "GzipLineDataset", "CatDataset", "StreamDataset", "BlockDataset",
    "MemoryInput", "PathInput", "TextInput", "UrlsInput",
    "Block", "BlockBuilder",
    "setup_logging",
]

logging.getLogger("dampr_tpu").addHandler(logging.NullHandler())
