"""Columnar KV record blocks — the data substrate.

Replaces the reference's pickled-batch-in-gzip record streams (reference
dampr/dataset.py:20-41 ``dump_pickle``/``gzip_reader``) with columnar batches:

- ``keys``:   numpy array — int64/float64 fast lanes, or object dtype (strings,
              tuples, arbitrary Python).
- ``values``: numpy array — int64/float64 fast lanes (device-reducible), or object.
- ``h1/h2``:  cached dual uint32 hash lanes (ops/hashing.py) used for partition
              routing and sort-based grouping.

Blocks are the unit of streaming, spill, and shard exchange.  The numeric lanes stay
eligible for device kernels end-to-end; object lanes ride along host-side while all
keyed *routing* decisions (hash, partition id, sort permutation) still come from the
vectorized path.

Exactness: blocks always carry the real key column, so sort-based grouping verifies
that records sharing a 64-bit hash also share a key (adjacent vectorized compare) and
sub-groups on the astronomically-rare mismatch — grouping is exact, never
hash-approximate.
"""

import numpy as np

from .ops import hashing

_INT_TYPES = (int, bool)

# int64-representable bounds for Python ints (reference values are arbitrary
# precision; anything outside drops to the object lane).
_I64_MIN = -(2 ** 63)
_I64_MAX = 2 ** 63 - 1


def _tuple_column(xs):
    """Type-uniform numeric tuples -> a 2D composite lane, so pair-shaped
    accumulators — mean's (sum, count) being the canonical one — ride the
    same segment kernels and reduceat folds as scalar lanes.  STRICT type
    fidelity: every element of every tuple must be the same plain type
    (all int -> int64 matrix, all float -> float64 matrix).  Anything
    mixed, bool, or out-of-int64 stays on the object lane — a promotion
    would change what the user reads back ((0, 6.0) must not become
    (0.0, 6.0)).  Returns None when the tuples don't qualify."""
    w = len(xs[0])
    if not 2 <= w <= 8 or set(map(len, xs)) != {w}:
        return None
    ts = set()
    for x in xs:
        ts.update(map(type, x))
        if len(ts) > 1:
            return None
    if ts == {int}:
        try:
            return np.array(xs, dtype=np.int64)
        except OverflowError:
            return None
    if ts == {float}:
        return np.array(xs, dtype=np.float64)
    return None


def _column_from_list(xs, composite=False):
    """Build the tightest column for a list of Python values.
    ``composite=True`` (VALUE columns only) lets type-uniform numeric
    tuples build a 2D lane; key columns must stay 1D — the hash/sort/
    group machinery is lane-shaped, so tuple keys ride the object lane
    and hash via their canonical encoding."""
    n = len(xs)
    ts = set(map(type, xs))
    if composite and ts == {tuple}:
        col2d = _tuple_column(xs)
        if col2d is not None:
            return col2d
        # fall through to the object lane below
    if ts == {bool}:
        # Preserve bool values exactly (True round-trips as True, not 1); the
        # reference's pickled streams preserve bools and so do we.  Mixed
        # bool/number columns drop to the object lane below for the same
        # reason — casting would read True back as 1.
        return np.fromiter(xs, dtype=np.bool_, count=n)
    if ts == {int}:
        try:
            arr = np.empty(n, dtype=np.int64)
            for i, x in enumerate(xs):
                arr[i] = x
            return arr
        except OverflowError:
            pass
    elif ts == {float}:
        return np.fromiter(xs, dtype=np.float64, count=n)
    elif ts == {float, int}:
        # Mixed int/float: float64 only when every int is exactly representable
        # (|i| <= 2**53); otherwise the object lane preserves precision.
        if all(isinstance(x, float) or abs(x) <= 2 ** 53 for x in xs):
            return np.array([float(x) for x in xs], dtype=np.float64)
    out = np.empty(n, dtype=object)
    out[:] = xs
    return out


def is_numeric(col):
    return col.dtype != object


def pylist(col):
    """Column -> plain-Python list.  One C-level tolist per lane; object
    lanes get one extra pass unboxing stray numpy scalars, so consumers
    (user binops, result readers) always see pure Python values.  2D
    composite lanes restore the tuples they were built from."""
    lst = col.tolist()
    if col.ndim == 2:
        return [tuple(r) for r in lst]
    if col.dtype == object:
        lst = [x.item() if isinstance(x, np.generic) else x for x in lst]
    return lst


class Block(object):
    __slots__ = ("keys", "values", "h1", "h2")

    def __init__(self, keys, values, h1=None, h2=None):
        assert len(keys) == len(values)
        self.keys = keys
        self.values = values
        self.h1 = h1
        self.h2 = h2

    # -- constructors ------------------------------------------------------
    @classmethod
    def from_pairs(cls, pairs):
        """Build a block from a list of (key, value) tuples."""
        n = len(pairs)
        ks = [None] * n
        vs = [None] * n
        for i, (k, v) in enumerate(pairs):
            ks[i] = k
            vs[i] = v
        return cls(_column_from_list(ks),
                   _column_from_list(vs, composite=True))

    @classmethod
    def from_lists(cls, ks, vs):
        """Build a block from parallel key/value lists (the batched-UDF
        path's native shape — no per-record tuple boxing)."""
        assert len(ks) == len(vs)
        return cls(_column_from_list(ks),
                   _column_from_list(vs, composite=True))

    @classmethod
    def empty(cls):
        return cls(np.empty(0, dtype=object), np.empty(0, dtype=object),
                   np.empty(0, dtype=np.uint32), np.empty(0, dtype=np.uint32))

    @classmethod
    def concat(cls, blocks):
        blocks = [b for b in blocks if len(b)]
        if not blocks:
            return cls.empty()
        if len(blocks) == 1:
            return blocks[0]
        keys = _concat_cols([b.keys for b in blocks])
        values = _concat_cols([b.values for b in blocks])
        if all(b.h1 is not None for b in blocks):
            h1 = np.concatenate([b.h1 for b in blocks])
            h2 = np.concatenate([b.h2 for b in blocks])
        else:
            h1 = h2 = None
        return cls(keys, values, h1, h2)

    # -- basics ------------------------------------------------------------
    def __len__(self):
        return len(self.keys)

    @property
    def numeric_values(self):
        return is_numeric(self.values)

    @property
    def numeric_keys(self):
        return is_numeric(self.keys)

    def nbytes(self):
        kb = self.keys.nbytes if self.numeric_keys else len(self.keys) * 64
        vb = self.values.nbytes if self.numeric_values else len(self.values) * 64
        hb = 0 if self.h1 is None else self.h1.nbytes * 2
        return kb + vb + hb

    def to_lists(self):
        """Plain-Python parallel (keys, values) lists (see ``pylist``)."""
        return pylist(self.keys), pylist(self.values)

    def iter_pairs(self, _window=8192):
        """Iterate (k, v) pairs with C-level lane conversion, materializing
        at most ``_window`` boxed records at a time — the over-budget k-way
        merge holds one in-flight iter_pairs per partition, so a full-block
        tolist here would multiply the tight-memory path's footprint."""
        n = len(self.keys)
        if n <= _window:
            kl, vl = self.to_lists()
            return zip(kl, vl)

        def gen():
            for i in range(0, n, _window):
                sub = Block(self.keys[i:i + _window],
                            self.values[i:i + _window])
                kl, vl = sub.to_lists()
                yield from zip(kl, vl)

        return gen()

    # -- hashing / routing -------------------------------------------------
    def hashes(self):
        if self.h1 is None:
            self.h1, self.h2 = hashing.hash_keys(self.keys)
        return self.h1, self.h2

    def h64(self):
        h1, h2 = self.hashes()
        return hashing.combine64(h1, h2)

    def take(self, idx):
        return Block(
            self.keys.take(idx),
            # fancy indexing, not take: composite value lanes are 2D and
            # must gather whole rows
            self.values[idx],
            None if self.h1 is None else self.h1.take(idx),
            None if self.h2 is None else self.h2.take(idx),
        )

    def sort_by_hash(self):
        """Stable sort by the (h1, h2) lanes — makes the block a mergeable
        run; equal keys (equal hashes) keep arrival order."""
        h1, h2 = self.hashes()
        order = np.lexsort((h2, h1))
        return self.take(order)

    def partition_ids(self, n_partitions):
        h1, _ = self.hashes()
        return (h1 % np.uint32(n_partitions)).astype(np.int32)

    def split_by_partition(self, n_partitions):
        """Route records to shuffle partitions by h1 % P (the reference's
        ``Splitter.partition``, base.py:6-8, vectorized).  Returns {pid: Block}
        for non-empty partitions only."""
        if not len(self):
            return {}
        pids = self.partition_ids(n_partitions)
        order = np.argsort(pids, kind="stable")
        sorted_pids = pids[order]
        bounds = np.flatnonzero(np.diff(sorted_pids)) + 1
        out = {}
        start = 0
        for end in list(bounds) + [len(sorted_pids)]:
            if end > start:
                pid = int(sorted_pids[start])
                out[pid] = self.take(order[start:end])
            start = end
        return out


def _concat_cols(cols):
    widths = {c.shape[1] if c.ndim == 2 else 0 for c in cols}
    if len(widths) > 1:
        # Mixed composite widths / composite-with-scalar: rows box back to
        # tuples on the object lane (pylist round-trip semantics).
        return _as_object_concat(cols)
    if widths != {0}:
        dtypes = {c.dtype for c in cols}
        if len(dtypes) == 1:
            return np.concatenate(cols)
        # Mixed-dtype composite lanes box back to tuples on the object lane:
        # _tuple_column promises strict type fidelity, so an int tuple
        # (1, 2) must never read back as (1.0, 2.0) after compaction with a
        # float-tuple block (the reference's pickled streams preserve the
        # types exactly).
        return _as_object_concat(cols)
    dtypes = {c.dtype for c in cols}
    if len(dtypes) == 1 and object not in dtypes:
        return np.concatenate(cols)
    if object not in dtypes:
        # Mixed numeric dtypes.  Promotion must obey the same value-preserving
        # rules as _column_from_list: bools never silently become numbers, and
        # int64 joins float64 only when every int is float-exact.
        if any(dt == np.bool_ for dt in dtypes):
            return _as_object_concat(cols)
        target = np.result_type(*dtypes)
        if target.kind == "f":
            for c in cols:
                if c.dtype.kind in "iu" and len(c) and (
                        np.abs(c).max() > 2 ** 53):
                    return _as_object_concat(cols)
        return np.concatenate([c.astype(target) for c in cols])
    return _as_object_concat(cols)


def _as_object_concat(cols):
    total = sum(len(c) for c in cols)
    out = np.empty(total, dtype=object)
    at = 0
    for c in cols:
        if c.dtype == object:
            out[at: at + len(c)] = c
        elif c.ndim == 2:
            out[at: at + len(c)] = [tuple(r) for r in c.tolist()]
        else:
            # .item()-ize so downstream sees Python scalars, matching
            # iter_pairs semantics for values that started in object lanes.
            out[at: at + len(c)] = [x.item() for x in c]
        at += len(c)
    return out


def merge_sorted_streams(streams):
    """Vectorized k-way merge over streams of KEY-sorted blocks.

    Each stream's concatenated key sequence must be non-decreasing (a
    spilled sorted run read back window by window) and NaN-free — NaN
    poisons the bound comparisons, so run registration (try_sorted_run)
    rejects NaN keys up front.  Memory holds one
    in-flight window per stream — never a whole run — so merging hundreds
    of spilled runs stays budget-bounded while every run file is read
    strictly sequentially.  Spilled runs in the chunked-frame format
    additionally keep ``settings.spill_read_prefetch`` frames of bounded
    readahead in flight per stream on the shared read executor
    (storage.iter_block_windows), so frame decompression across the k
    runs proceeds in parallel underneath this merge instead of
    serializing on each ``next()``; the merge planner's fan-in clamp
    already budgets that extra window of headroom per run.

    Round structure: the *bound* is the smallest last-key among the
    streams' current windows.  Every record ``<= bound`` anywhere is
    already buffered (later windows of any stream hold only keys
    ``>= their predecessor's last``), so each round gathers those records,
    stable-sorts the gathered slice, and emits it — at least one full
    window per round, so rounds number O(total windows).  A stream whose
    window ends exactly at the bound extends through ties: its next
    window(s)' ``== bound`` prefixes append straight to the round's
    output (never re-buffered or re-concatenated), so equal keys do not
    straddle an emission boundary and ties across streams keep stream
    order (stable sort over the gathered concat).  One exception keeps
    the memory bound honest: a giant tie group (one key spanning more
    bytes than a quarter of the stage budget in extension windows) stops
    extending and drains over subsequent rounds — the emitted key
    sequence stays non-decreasing, only tie ORDER degrades, and memory
    never exceeds the per-round budget plus one window per stream.
    """
    from . import settings
    from .obs import metrics as _metrics
    from .obs import trace as _trace

    its = [iter(s) for s in streams]
    n = len(its)
    # Merge fan-in, observed per merge instance: the distribution the
    # planner's fan-in clamp is supposed to bound (histogram in stats,
    # sampled counter track in the trace).
    _metrics.observe("merge.kway_streams", n)

    def slice_of(blk, a, b):
        return Block(
            blk.keys[a:b], blk.values[a:b],
            None if blk.h1 is None else blk.h1[a:b],
            None if blk.h2 is None else blk.h2[a:b])

    def gen():
        buf = [None] * n  # current (trimmed) window per stream
        last = [None] * n  # python-scalar last key per buffer

        def load(i):
            while True:
                try:
                    b = next(its[i])
                except StopIteration:
                    buf[i] = None
                    last[i] = None
                    return
                if len(b):
                    buf[i] = b
                    k = b.keys[-1]
                    last[i] = k.item() if isinstance(k, np.generic) else k
                    return

        for i in range(n):
            load(i)
        while True:
            _t0 = _trace.now()
            bound = None
            for i in range(n):
                if buf[i] is not None and (bound is None or last[i] < bound):
                    bound = last[i]
            if bound is None:
                return
            pieces = []
            ext_budget = max(settings.max_memory_per_stage // 4, 1 << 20)
            for i in range(n):
                b = buf[i]
                if b is None:
                    continue
                end = int(np.searchsorted(b.keys, bound, side="right"))
                if end < len(b):
                    if end:
                        pieces.append(slice_of(b, 0, end))
                        buf[i] = slice_of(b, end, len(b))
                    continue  # last[i] unchanged: still this window's last
                # Window consumed (last[i] == bound): emit it whole and
                # extend through ties — the stream's NEXT window(s) may
                # continue the same key.  Their ``== bound`` prefixes go
                # straight into the output pieces (no re-buffering), the
                # first ``> bound`` suffix becomes the new window.
                pieces.append(b)
                buf[i] = None
                last[i] = None
                while True:
                    try:
                        nxt = next(its[i])
                    except StopIteration:
                        break  # stream exhausted mid-tie
                    if not len(nxt):
                        continue
                    e2 = int(np.searchsorted(nxt.keys, bound, side="right"))
                    if e2:
                        p = slice_of(nxt, 0, e2)
                        pieces.append(p)
                        ext_budget -= p.nbytes()
                    if e2 < len(nxt):
                        buf[i] = slice_of(nxt, e2, len(nxt))
                        k = buf[i].keys[-1]
                        last[i] = (k.item()
                                   if isinstance(k, np.generic) else k)
                        break
                    if ext_budget <= 0:
                        # Giant tie group: stop extending so the round's
                        # emission stays budget-bounded.  The key's
                        # remaining records drain over the next round(s)
                        # (same bound) — order holds, tie order degrades.
                        load(i)
                        break
            merged = Block.concat(pieces)
            if len(merged):
                # One span per merge round (each round drains at least a
                # full window, so these are chunky, not per-record); the
                # interval covers gather+sort, not the consumer's time.
                _trace.complete("merge", "k-way-round", _t0,
                                records=len(merged), streams=n)
                _metrics.counter_add("merge.kway_records", len(merged))
                yield merged.take(np.argsort(merged.keys, kind="stable"))

    return gen()


class BlockBuilder(object):
    """Accumulates (k, v) pairs and emits Blocks of ~settings.batch_size records.

    The streaming analog of the reference's DatasetWriter buffering
    (dataset.py:59-82), but batch-oriented so downstream kernels see large
    vectorizable chunks.
    """

    def __init__(self, batch_size):
        self.batch_size = batch_size
        self._buf = []

    def add(self, k, v):
        self._buf.append((k, v))
        if len(self._buf) >= self.batch_size:
            return self.flush()
        return None

    def flush(self):
        if not self._buf:
            return None
        blk = Block.from_pairs(self._buf)
        self._buf = []
        return blk
