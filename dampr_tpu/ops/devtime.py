"""Wall-time attribution of device work (the bench's ``device_fraction``).

Three buckets, accumulated process-wide behind one lock:

- ``device``:   jitted kernel dispatch+result sites (segment folds, the
                hash lexsort, mesh collective programs);
- ``transfer``: explicit host<->device lane movement (HBM tier puts,
                value-lane fetches, final fold-result fetches);
- ``codec``:    the native C text/hash/parse codec (host, but worth
                separating from generic Python time).

Times are dispatch-site THREAD-seconds: concurrent pool workers each
add their own elapsed time, so a bucket divided by wall time reads like
CPU utilization (2.0 = two cores' worth per wall second) and can exceed
1.0 on multi-core hosts — same convention as `top`.  A jax call that
returns an unrealized array charges its sync cost to whichever site
forces it (usually a ``transfer`` fetch).  Attribution-accurate at the
boundaries users can act on, not a profiler-grade kernel timeline (use
settings.profile_dir -> jax.profiler for that).
"""

import contextlib
import threading
import time

_lock = threading.Lock()
_counters = {"device": 0.0, "transfer": 0.0, "codec": 0.0}


@contextlib.contextmanager
def track(kind):
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        with _lock:
            _counters[kind] += dt


def add(kind, seconds):
    with _lock:
        _counters[kind] += seconds


def snapshot():
    with _lock:
        return dict(_counters)


def reset():
    with _lock:
        for k in _counters:
            _counters[k] = 0.0
