"""Wall-time attribution of device work (the bench's ``device_fraction``).

Buckets, accumulated process-wide behind one lock:

- ``device``:   jitted kernel dispatch+result sites (segment folds, the
                hash lexsort, mesh collective programs);
- ``transfer``: explicit host<->device lane movement (HBM tier puts,
                value-lane fetches, final fold-result fetches);
- ``codec``:    the native C text/hash/parse codec (host, but worth
                separating from generic Python time);
- ``codec_wait``: WALL-CLOCK union of intervals during which EVERY live
                map slot was blocked on its codec — each slot's fold
                consumer waiting for the next block while that slot's
                producer thread was inside the native codec (the overlap
                executor, runner._overlap_stream, via slot_stall/
                slot_unstall below).  This is the codec time still on
                the engine's critical path after overlapping: whenever
                at least one slot is folding, the codec seconds
                elsewhere are covered by useful work and do NOT count.
                Consumer wait caused by producer-side IO or Python
                (window reads, block building) is not codec-
                attributable and is excluded, matching what the
                ``codec`` bucket itself counts.  With the overlap
                executor off there are no slots and the bucket stays 0;
                the serial non-overlapped codec cost is then the whole
                ``codec`` bucket, since the job thread that runs the
                codec is by construction not folding meanwhile.

Times are dispatch-site THREAD-seconds (``codec_wait`` excepted — it is
a wall-clock interval union, never exceeding elapsed wall): concurrent
pool workers each add their own elapsed time, so a bucket divided by
wall time reads like CPU utilization (2.0 = two cores' worth per wall
second) and can exceed 1.0 on multi-core hosts — same convention as
`top`.  Thread-seconds inside ``track`` regions include any GIL waits
the region suffers, so under core contention the ``codec`` bucket is an
UPPER bound on codec CPU — one more reason the critical-path question
needs the interval-union bucket.  A jax call that returns an unrealized
array charges its sync cost to whichever site forces it (usually a
``transfer`` fetch).  Attribution-accurate at the boundaries users can
act on, not a profiler-grade kernel timeline (use settings.profile_dir
-> jax.profiler for that).
"""

import contextlib
import threading
import time

_lock = threading.Lock()
_counters = {"device": 0.0, "transfer": 0.0, "codec": 0.0,
             "codec_wait": 0.0}
_active = {}  # (thread ident, kind) -> nesting depth inside track(kind)

# codec_wait state: live overlap slots vs slots currently blocked on
# their own producer's codec.  The union interval is open exactly while
# every live slot is stalled (_all_since is its start timestamp).
_slots = 0
_stalled = 0
_all_since = None


def _roll_union_locked():
    """Close/open the all-slots-stalled interval after a state change."""
    global _all_since
    all_stalled = _slots > 0 and _stalled >= _slots
    if _all_since is None and all_stalled:
        _all_since = time.perf_counter()
    elif _all_since is not None and not all_stalled:
        _counters["codec_wait"] += time.perf_counter() - _all_since
        _all_since = None


def slot_enter():
    """A map slot's overlapped fold consumer came alive."""
    global _slots
    with _lock:
        _slots += 1
        _roll_union_locked()


def slot_exit():
    global _slots
    with _lock:
        _slots -= 1
        _roll_union_locked()


def slot_stall():
    """This slot's consumer is blocked waiting while its producer is in
    the native codec."""
    global _stalled
    with _lock:
        _stalled += 1
        _roll_union_locked()


def slot_unstall():
    global _stalled
    with _lock:
        _stalled -= 1
        _roll_union_locked()


def live_slots():
    """Overlap fold consumers currently alive (unlocked read: a sampled
    gauge tolerates a one-off torn value)."""
    return _slots


def stalled_slots():
    """Slots currently blocked on their producer's codec — the live
    consumer-stall state the metrics sampler snapshots."""
    return _stalled


@contextlib.contextmanager
def track(kind):
    t0 = time.perf_counter()
    if kind != "codec":
        # Only codec regions feed active_in() (the overlap executor's
        # stall attribution); device/transfer sites skip the entry lock
        # and the _active bookkeeping — one lock take on exit, as before
        # the overlap work landed.
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            with _lock:
                _counters[kind] += dt
        return
    key = (threading.get_ident(), kind)
    with _lock:
        _active[key] = _active.get(key, 0) + 1
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        with _lock:
            depth = _active.get(key, 1) - 1
            if depth:
                _active[key] = depth
            else:
                _active.pop(key, None)
            _counters[kind] += dt


def active_in(thread_ident, kind):
    """Is the given thread currently inside ``track(kind)``?  Lets a
    waiter attribute its blocked time to the SPECIFIC producer it waits
    on (a consumer blocked on its own job's codec, not a sibling job's)."""
    with _lock:
        return _active.get((thread_ident, kind), 0) > 0


def add(kind, seconds):
    with _lock:
        _counters[kind] += seconds


def union_seconds(intervals):
    """Total length of the union of ``(t0, t1)`` intervals — the same
    wall-clock-union discipline the live ``codec_wait`` bucket applies to
    slot stalls, as a pure function over recorded spans.  Concurrent
    lanes doing the same kind of work (two codec producers tokenizing at
    once) count the covered WALL time once, never their thread-seconds
    summed; this is what lets the critical-path analyzer
    (:mod:`dampr_tpu.obs.critpath`) compare resources against elapsed
    wall on an equal footing."""
    total = 0.0
    end = None
    for t0, t1 in sorted(i for i in intervals if i[1] > i[0]):
        if end is None or t0 > end:
            total += t1 - t0
            end = t1
        elif t1 > end:
            total += t1 - end
            end = t1
    return total


def snapshot():
    with _lock:
        out = dict(_counters)
        if _all_since is not None:  # fold in the open stall interval
            out["codec_wait"] += time.perf_counter() - _all_since
        return out


def epoch():
    """Run-scoped accounting without ``reset()``: capture the cumulative
    counters now and difference them later with :func:`delta`.  Unlike
    ``reset()`` this never disturbs other in-flight work's view — two
    sequential or concurrent ``run()`` calls each hold their own epoch and
    read their own deltas, while the process-wide counters stay monotone
    (``snapshot()`` folds any open stall interval in, so ``codec_wait`` is
    monotone across snapshots too)."""
    return snapshot()


def delta(since):
    """Per-bucket seconds accumulated since an :func:`epoch` snapshot.
    Clamped at zero so an interleaved ``reset()`` (legacy callers) can
    produce a short read, never a negative one."""
    now = snapshot()
    return {k: max(0.0, now[k] - since.get(k, 0.0)) for k in now}


def reset():
    global _all_since
    with _lock:
        for k in _counters:
            _counters[k] = 0.0
        if _all_since is not None:  # an open interval restarts at zero
            _all_since = time.perf_counter()
