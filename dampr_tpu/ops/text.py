"""Vectorized text kernels: raw chunk bytes -> token hash lanes -> folded
(token, count) blocks with zero per-record Python.

This is the dual-path execution SURVEY §7 plans for ("a vectorized fast path
for recognized ops"): the reference streams every token through Python
generators (dampr/base.py:30-33); here a 16MB text chunk becomes a uint8
tensor, token boundaries come from vectorized class lookups, hashing runs the
same dual-lane FNV kernel used everywhere (ops/hashing.py — so tokens group
with equal Python-string keys), and counting is a hash-sort + segment fold.
Token *strings* materialize only for the distinct keys that survive folding
(vocabulary-sized, not corpus-sized).

Two mappers implement the block protocol (``map_blocks``) the runner
recognizes, each with an exact per-record fallback for inputs that don't
expose raw bytes:

- :class:`TokenCounts` — word count: (token, occurrences).
- :class:`DocFreq` — per-line-deduplicated counts: (token, number of lines
  containing it) — the reference TF-IDF benchmark's map+count shape
  (benchmarks/tf-idf-dampr.py:13-15: ``flat_map(lambda x:
  set(RX.split(x.lower()))).count()``).

ASCII semantics note: 'word' mode matches ``re.split(r'[^\\w]+')`` and
``.lower()`` byte-wise, which is exact for ASCII text; non-ASCII bytes are
treated as word characters (utf-8 continuation bytes stay inside tokens).
"""

import numpy as np

from ..base import Mapper
from . import hashing

# --- byte classification tables -------------------------------------------

_WS = np.zeros(256, dtype=bool)
for _b in b" \t\n\r\x0b\x0c":
    _WS[_b] = True

_WORD = np.zeros(256, dtype=bool)
for _b in range(256):
    c = chr(_b)
    if c.isalnum() and _b < 128 or c == "_":
        _WORD[_b] = True
_WORD[128:] = True  # utf-8 continuation/lead bytes ride inside tokens

_LOWER = np.arange(256, dtype=np.uint8)
_LOWER[65:91] += 32  # A-Z -> a-z


def _token_bounds(buf, mode):
    """starts[int64], lens[int32] of maximal token runs in a uint8 buffer."""
    if mode == "word":
        in_tok = _WORD[buf]
    else:
        in_tok = ~_WS[buf]
    if not len(buf):
        return np.empty(0, np.int64), np.empty(0, np.int32)
    # boundaries where in_tok changes
    change = np.empty(len(buf) + 1, dtype=bool)
    change[0] = in_tok[0]
    np.not_equal(in_tok[1:], in_tok[:-1], out=change[1:-1])
    change[-1] = in_tok[-1]
    bounds = np.flatnonzero(change)
    # bounds alternate start, end, start, end... beginning with a start
    starts = bounds[0::2].astype(np.int64)
    ends = bounds[1::2].astype(np.int64)
    return starts, (ends - starts).astype(np.int32)


# Tokens at most this long go through the padded-matrix unique path; longer
# ones (rare in text) fall to a Python dict so the matrix stays bounded.
_SHORT_TOKEN = 255


def _numpy_counts_block(data, mode, lower, dedup_per_line,
                        pair_values=True):
    """Pure-numpy fallback for the fused native pass.  Exact by construction:
    grouping is ``np.unique`` over length-prefixed token byte rows (not over
    hashes), so colliding hashes can never merge distinct tokens."""
    from ..blocks import Block

    buf = np.frombuffer(data, dtype=np.uint8)
    if lower:
        buf = _LOWER[buf]
    starts, lens = _token_bounds(buf, mode)
    n = len(starts)
    if n == 0:
        return Block.empty()

    line_id = None
    if dedup_per_line:
        nl = np.flatnonzero(buf == 10)
        line_starts = np.concatenate(([0], nl + 1)).astype(np.int64)
        line_id = (np.searchsorted(line_starts, starts, side="right") - 1)

    bb = buf.tobytes()
    keys, counts = [], []

    short = lens <= _SHORT_TOKEN
    long_idx = np.flatnonzero(~short)
    if len(long_idx):
        agg = {}
        seen = set()
        for i in long_idx:
            tok = bb[starts[i]:starts[i] + lens[i]].decode("utf-8", "replace")
            if dedup_per_line:
                key = (int(line_id[i]), tok)
                if key in seen:
                    continue
                seen.add(key)
            agg[tok] = agg.get(tok, 0) + 1
        keys.extend(agg.keys())
        counts.extend(agg.values())

    sidx = np.flatnonzero(short)
    if len(sidx):
        s_starts = starts[sidx]
        s_lens = lens[sidx]
        L = int(s_lens.max())
        idx = s_starts[:, None] + np.arange(L, dtype=np.int64)[None, :]
        np.clip(idx, 0, len(buf) - 1, out=idx)
        mat = np.where(np.arange(L, dtype=np.int32)[None, :]
                       < s_lens[:, None], buf[idx], 0)
        rows = np.empty((len(sidx), L + 1), dtype=np.uint8)
        rows[:, 0] = s_lens  # length prefix keeps zero-padding unambiguous
        rows[:, 1:] = mat
        uniq, inverse = np.unique(rows, axis=0, return_inverse=True)
        inverse = inverse.reshape(-1)
        if dedup_per_line:
            combined = line_id[sidx].astype(np.int64) * len(uniq) + inverse
            uc = np.unique(combined)
            ucounts = np.bincount(uc % len(uniq), minlength=len(uniq))
        else:
            ucounts = np.bincount(inverse, minlength=len(uniq))
        for i in range(len(uniq)):
            ln = int(uniq[i, 0])
            keys.append(uniq[i, 1:1 + ln].tobytes().decode("utf-8", "replace"))
            counts.append(int(ucounts[i]))

    ng = len(keys)
    kcol = np.empty(ng, dtype=object)
    if pair_values:
        vcol = np.empty(ng, dtype=object)
        for i in range(ng):
            kcol[i] = keys[i]
            vcol[i] = (keys[i], counts[i])
    else:
        for i in range(ng):
            kcol[i] = keys[i]
        vcol = np.asarray(counts, dtype=np.int64)
    h1, h2 = hashing.hash_keys(kcol)
    return Block(kcol, vcol, h1, h2)


# ASCII-only case fold for representative decoding — must match the byte
# semantics the native hash pass uses (A-Z only; multibyte chars untouched).
_ASCII_LOWER = bytes.maketrans(bytes(range(65, 91)), bytes(range(97, 123)))


def _native_counts_block(data, mode, lower, dedup_per_line,
                         pair_values=True):
    """Fused native tokenize(+case-fold)+count -> Block, or None.  Case
    folding happens inside the native hash pass; representative strings
    ASCII-fold the original bytes (vocabulary-sized work instead of a
    buffer-sized table pass), keeping keys byte-identical to the numpy
    fallback's case-folded buffer."""
    from .. import native
    from ..blocks import Block

    buf = np.frombuffer(data, dtype=np.uint8)
    res = native.token_counts(buf, 1 if mode == "word" else 0,
                              1 if lower else 0, dedup_per_line)
    if res is None:
        return None
    h1, h2, counts, rep_start, rep_len = res
    n = len(h1)
    keys = np.empty(n, dtype=object)
    if pair_values:
        vals = np.empty(n, dtype=object)
    else:
        vals = np.asarray(counts, dtype=np.int64)
    lossy = []
    for i in range(n):
        s = rep_start[i]
        raw = bytes(data[s:s + rep_len[i]])  # bytes() also accepts memoryview
        if lower:
            raw = raw.translate(_ASCII_LOWER)
        tok = raw.decode("utf-8", "replace")
        keys[i] = tok
        if pair_values:
            vals[i] = (tok, int(counts[i]))
        if "�" in tok:
            lossy.append(i)
    if lossy:
        # The native pass hashed the *raw* bytes, but a lossy decode means the
        # materialized key is the U+FFFD-substituted string — recompute those
        # lanes from the key so the engine invariant (cached lanes ==
        # hash_keys(key), relied on by partition routing and sorted-run
        # merging) holds for every record.  A token that legitimately contains
        # U+FFFD re-encodes to the same bytes, so recomputing is a no-op.
        idx = np.asarray(lossy, dtype=np.int64)
        rh1, rh2 = hashing.hash_keys(keys.take(idx))
        h1 = np.array(h1, dtype=np.uint32, copy=True)
        h2 = np.array(h2, dtype=np.uint32, copy=True)
        h1[idx] = rh1
        h2[idx] = rh2
    return Block(keys, vals, h1, h2)


def _iter_aligned_windows(blocks):
    """Re-chop a bounded byte-block stream at newlines with ZERO large
    copies: each incoming block yields (a) a small straddle buffer — the
    carried partial line plus this block's head through its first newline —
    and (b) the block's interior through its last newline as a memoryview
    (no copy).  Per-line and per-token scanner state therefore never spans
    a yielded buffer.  A block with no newline at all folds into the carry
    (memory degrades to the longest line, never the chunk).

    This exists because materializing a multi-GB chunk as ONE buffer is
    pathological on this platform: measured at 10.7 GB, one-shot
    ``f.read()`` = 196 s and windowed-read-plus-join = 108 s, while 64 MB
    windowed reads stream at 1.6 GB/s — the giant contiguous allocation /
    copy itself is the cost, so scanning mappers must never build it (and
    avoidable window copies cost ~0.2 s per 128 MB on this host's
    ~1.4 GB/s memcpy)."""
    tail = []  # list of pending fragments: joined once per straddle, so a
    #            newline-free stream costs one linear join, not quadratic +=
    for b in blocks:
        mv = memoryview(b)
        start = 0
        if tail:
            nl = b.find(b"\n")
            if nl < 0:
                tail.append(b)
                continue
            tail.append(bytes(mv[:nl + 1]))
            yield b"".join(tail)
            tail = []
            start = nl + 1
        last = b.rfind(b"\n")
        if last < start:
            if start < len(b):
                tail.append(bytes(mv[start:]))
            continue
        yield mv[start:last + 1]
        if last + 1 < len(b):
            tail.append(bytes(mv[last + 1:]))
    if tail:
        yield b"".join(tail)


def _scan_windows(dataset):
    """Line-aligned byte windows of a chunk (bytes or memoryview buffers):
    bounded via iter_byte_blocks when the tap supports it, one whole-chunk
    window otherwise."""
    from .. import settings

    if hasattr(dataset, "iter_byte_blocks"):
        blocks = dataset.iter_byte_blocks(settings.scan_window_bytes)
    else:
        blocks = iter((dataset.read_bytes(),))
    return _iter_aligned_windows(blocks)


class _StatelessWindowSink(object):
    """Window-sink adapter for scanners with no cross-window state: each
    window maps to blocks independently."""

    def __init__(self, fn):
        self._fn = fn

    def add(self, win):
        return self._fn(win)

    def finish(self):
        return ()


def _drive_windows(mapper, dataset, sink=None):
    """Shared map_blocks body: run the mapper's window sink over the
    chunk's line-aligned windows.  The runner's scan-sharing group executor
    drives several sinks over ONE window pass instead (runner.py
    run_map_group), so fused co-source stages read the tap once.
    ``sink`` overrides the mapper's own sink (the device-lowered scan:
    the runner passes ops.lower.device_window_sink's sink here)."""
    if sink is None:
        sink = mapper.window_sink()
    for win in _scan_windows(dataset):
        for blk in sink.add(win) or ():
            yield blk
    for blk in sink.finish() or ():
        yield blk


def chunk_token_counts(data, mode="whitespace", lower=False,
                       pair_values=True):
    """bytes -> Block of (token, count) with cached hash lanes."""
    blk = _native_counts_block(data, mode, lower, dedup_per_line=0,
                               pair_values=pair_values)
    if blk is not None:
        return blk
    return _numpy_counts_block(data, mode, lower, dedup_per_line=0,
                               pair_values=pair_values)


def chunk_doc_freq(data, mode="word", lower=True, pair_values=True):
    """bytes -> Block of (token, n_lines_containing) — per-line dedup then
    count, i.e. ``flat_map(lambda line: set(tokenize(line))).count()``."""
    blk = _native_counts_block(data, mode, lower, dedup_per_line=1,
                               pair_values=pair_values)
    if blk is None:
        blk = _numpy_counts_block(data, mode, lower, dedup_per_line=1,
                                  pair_values=pair_values)
    if any(isinstance(k, str) and "�" in k for k in blk.keys):
        # Lossy decode breaks the per-line *set* contract: distinct invalid
        # byte tokens on one line all materialize as the same U+FFFD string,
        # but byte-level dedup counted them separately.  Re-run on the
        # round-trip-clean re-encoding, where byte dedup == string dedup.
        # (A legitimate U+FFFD round-trips, so this re-run is idempotent.)
        data = bytes(data)  # rare path; windows may arrive as memoryviews
        clean = data.decode("utf-8", "replace").encode("utf-8")
        if clean != data:
            blk = _native_counts_block(clean, mode, lower, dedup_per_line=1,
                                       pair_values=pair_values)
            if blk is None:
                blk = _numpy_counts_block(clean, mode, lower,
                                          dedup_per_line=1,
                                          pair_values=pair_values)
    return blk


class CountRecords(Mapper):
    """Record-count map stage with a vectorized path for text chunks: the
    chunk's record count is its owned newline count (+1 for an unterminated
    final line), no per-line Python.  Emits the same ``(1, count)`` record
    the DSL's generic ``len()`` map emits (reference dampr.py:254-259)."""

    streams_bytes = True  # prefers the bounded iter_byte_blocks scan

    class _Sink(object):
        """Stateful window sink: newline count accumulates across windows
        (_iter_aligned_windows preserves every chunk byte, so counting over
        aligned windows equals counting over the raw stream)."""

        def __init__(self):
            self.n = 0
            self.last = b"\n"

        def add(self, win):
            if isinstance(win, memoryview):
                # memoryview has no substring count; a numpy view counts
                # without copying the window
                buf = np.frombuffer(win, dtype=np.uint8)
                self.n += int(np.count_nonzero(buf == 10))
            else:
                self.n += win.count(b"\n")
            if len(win):
                self.last = bytes(win[-1:])
            return ()

        def finish(self):
            from ..blocks import Block

            if self.last != b"\n" and self.last != b"":
                self.n += 1
            return (Block.from_pairs([(1, self.n)]),)

    def window_sink(self):
        return CountRecords._Sink()

    def map_blocks(self, dataset):
        return _drive_windows(self, dataset)

    def map(self, *datasets):
        assert len(datasets) == 1
        ds = datasets[0]
        if hasattr(ds, "iter_blocks"):
            # Block-backed chunks count at block granularity: blocks know
            # their length, so no record is ever materialized.
            yield 1, sum(len(b) for b in ds.iter_blocks())
        else:
            yield 1, sum(1 for _ in ds.read())


class ParseNumbers(Mapper):
    """Vectorized numeric-line parser: each line holds one number; records
    come out keyed by the parsed value (so a bare ``checkpoint()`` after this
    mapper yields a globally sorted read — the vectorized external-sort
    path).  ``dtype`` is int64 or float64."""

    def __init__(self, dtype=np.int64):
        self.dtype = np.dtype(dtype)

    streams_bytes = True  # bounded line-aligned windows, never one buffer

    def window_sink(self):
        from .. import native
        from ..blocks import Block

        # Window-streamed (windows break at newlines and each line holds
        # one number, so no value spans a boundary); concatenated window
        # order equals whole-chunk order.
        def scan(data):
            if self.dtype == np.int64:
                # one native pass: no 50M-element Python token list
                arr = native.parse_i64(np.frombuffer(data, dtype=np.uint8))
                if arr is not None:
                    return (Block(arr, arr.copy()),) if len(arr) else ()
            # Fallback (non-int64 / no native codec): bytes() copies the
            # window — memoryview has no split(); the cost is confined to
            # this path.  np.array parses each token in C and raises on the
            # first unparsable one — the same hard error the per-record
            # path gives.
            toks = bytes(data).split()
            if not toks:
                return ()
            arr = np.array(toks, dtype=self.dtype)
            return (Block(arr, arr.copy()),)
        return _StatelessWindowSink(scan)

    def map_blocks(self, dataset):
        return _drive_windows(self, dataset)

    def map(self, *datasets):
        assert len(datasets) == 1
        caster = int if self.dtype.kind == "i" else float
        for _k, line in datasets[0].read():
            if line.strip():
                v = caster(line)
                yield v, v


class TokenCounts(Mapper):
    """Vectorized word count over raw text chunks: each record downstream is
    a ``(token, count)`` tuple, pre-folded per chunk.  Chain ``.fold_by(lambda
    kv: kv[0], operator.add, lambda kv: kv[1])`` for the global count — its
    Python cost is vocabulary-sized, not corpus-sized."""

    def __init__(self, mode="whitespace", lower=False, pair_values=True):
        self.mode = mode
        self.lower = lower
        #: pair_values=False emits plain int counts as values (keys stay the
        #: tokens) — pair with PMap.fold_values for the zero-per-record path.
        self.pair_values = pair_values

    streams_bytes = True  # bounded line-aligned windows, never one buffer

    def window_sink(self):
        # One partial-counts block per window; the downstream fold merges
        # them (associative), so results are identical to a whole-chunk
        # pass with memory bounded by the window.
        def scan(win):
            blk = chunk_token_counts(win, self.mode, self.lower,
                                     self.pair_values)
            return (blk,) if blk is not None and len(blk) else ()
        return _StatelessWindowSink(scan)

    def map_blocks(self, dataset):
        return _drive_windows(self, dataset)

    def map(self, *datasets):
        # exact per-record fallback for datasets without raw bytes
        assert len(datasets) == 1
        import collections
        import re

        counts = collections.Counter()
        rx = re.compile(r"[^\w]+") if self.mode == "word" else None
        for _k, line in datasets[0].read():
            if self.lower:
                line = line.lower()
            toks = rx.split(line) if rx else line.split()
            counts.update(t for t in toks if t)
        if self.pair_values:
            return iter((t, (t, c)) for t, c in counts.items())
        return iter(counts.items())


class DocFreq(Mapper):
    """Vectorized per-line token document frequency (the reference TF-IDF
    benchmark's hot map: tf-idf-dampr.py:13-15)."""

    def __init__(self, mode="word", lower=True, pair_values=True):
        self.mode = mode
        self.lower = lower
        self.pair_values = pair_values

    streams_bytes = True  # bounded line-aligned windows, never one buffer

    def window_sink(self):
        # Windows break at newlines (_iter_aligned_windows), so the
        # per-LINE dedup never spans a window; per-window partial doc
        # frequencies merge exactly in the downstream fold.
        def scan(win):
            blk = chunk_doc_freq(win, self.mode, self.lower,
                                 self.pair_values)
            return (blk,) if blk is not None and len(blk) else ()
        return _StatelessWindowSink(scan)

    def map_blocks(self, dataset):
        return _drive_windows(self, dataset)

    def map(self, *datasets):
        assert len(datasets) == 1
        import collections
        import re

        counts = collections.Counter()
        rx = re.compile(r"[^\w]+") if self.mode == "word" else None
        for _k, line in datasets[0].read():
            if self.lower:
                line = line.lower()
            toks = rx.split(line) if rx else line.split()
            counts.update(set(t for t in toks if t))
        if self.pair_values:
            return iter((t, (t, c)) for t, c in counts.items())
        return iter(counts.items())
