"""Pallas TPU kernel for the dual-lane FNV-1a string hash.

The hot device scan in this framework is hashing padded token/key byte
matrices (ops/hashing.py `_fnv_jit` uses a `fori_loop` of full-array ops, so
every column step round-trips the whole [N] state through HBM-visible
buffers).  This kernel tiles rows into VMEM and keeps both hash lanes in
registers/VMEM across the entire column scan — one HBM read of the byte
matrix, one write of each lane.

Grid: one program per row tile.  Inside a tile the column scan is a
`fori_loop` over the padded width; masking by per-row length keeps exact
equality with the scalar FNV definition in ops/hashing.py (and the C++
codec).  Lanes are computed in int32 (uint32 wraparound == int32 wraparound
for mul/xor) and bitcast on the way out.

Use `fnv_pallas(..., interpret=True)` on CPU for tests; the real kernel
compiles for TPU.  **Measured result (round 3, real v5e, 128k x 16B
tokens): 43.5 Mtok/s vs the portable _fnv_jit's 74.7 Mtok/s (0.58x)** —
the transpose+widen layout prep plus tiny (16, 512) tiles leave it
overhead-bound, so the engine does NOT dispatch to it (ops/hashing.py
keeps the XLA fori-loop path).  Kept as a benchmarked negative result;
benchmarks/pallas_bench.py re-measures it on demand.
"""

import functools

import numpy as np

_ROW_TILE = 512


@functools.lru_cache(maxsize=None)
def _build(L, interpret):
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from .hashing import _FNV_OFFSET1, _FNV_OFFSET2, _FNV_PRIME1, _FNV_PRIME2

    # Python int literals (int32 bit patterns) — traced jnp constants would
    # be captured consts, which pallas kernels reject.  Derived from the
    # canonical constants so every hash lane in the framework agrees.
    OFF1 = int(np.int32(_FNV_OFFSET1))
    OFF2 = int(np.int32(_FNV_OFFSET2))
    P1 = int(np.int32(_FNV_PRIME1))
    P2 = int(np.int32(_FNV_PRIME2))

    def kernel(mat_ref, lens_ref, h1_ref, h2_ref):
        # Layout is transposed — mat_ref is (L, ROW_TILE): the column scan
        # walks the *sublane* dimension with a dynamic index, which Mosaic
        # supports; rows live on the 128-wide lane dimension.
        rows = mat_ref.shape[1]
        lens = lens_ref[0, :]

        def body(c, hs):
            h1, h2 = hs
            b = mat_ref[c, :]
            active = c < lens
            nh1 = (h1 ^ b) * jnp.int32(P1)
            nh2 = (h2 ^ b) * jnp.int32(P2)
            return (jnp.where(active, nh1, h1),
                    jnp.where(active, nh2, h2))

        h1 = jnp.full((rows,), OFF1, dtype=jnp.int32)
        h2 = jnp.full((rows,), OFF2, dtype=jnp.int32)
        h1, h2 = lax.fori_loop(0, L, body, (h1, h2))
        h1_ref[0, :] = h1
        h2_ref[0, :] = h2

    def run(mat_t, lens):
        n = mat_t.shape[1]
        grid = (n // _ROW_TILE,)
        return pl.pallas_call(
            kernel,
            out_shape=(jax.ShapeDtypeStruct((1, n), jnp.int32),
                       jax.ShapeDtypeStruct((1, n), jnp.int32)),
            grid=grid,
            in_specs=[
                pl.BlockSpec((L, _ROW_TILE), lambda i: (0, i)),
                pl.BlockSpec((1, _ROW_TILE), lambda i: (0, i)),
            ],
            out_specs=(
                pl.BlockSpec((1, _ROW_TILE), lambda i: (0, i)),
                pl.BlockSpec((1, _ROW_TILE), lambda i: (0, i)),
            ),
            interpret=interpret,
        )(mat_t, lens)

    return jax.jit(run)


def fnv_pallas(mat, lens, interpret=False):
    """Dual-lane FNV over a padded uint8 matrix [N, L] with lengths [N].
    Returns (h1, h2) uint32 arrays.  Rows pad to the tile multiple; width
    stays as given."""
    n, L = mat.shape
    npad = -(-n // _ROW_TILE) * _ROW_TILE
    if npad != n:
        mat = np.pad(mat, ((0, npad - n), (0, 0)))
        lens = np.pad(lens, (0, npad - n))
    # int32 byte lanes, transposed to (L, N): TPU vector units compute 32-bit
    # int ops natively and rows map onto the 128-wide lane dimension; the
    # widened input trades HBM bytes for a simple exact kernel (a
    # uint8-native load path is a later refinement).
    mat_t = mat.T.astype(np.int32, order="C")  # single transpose+widen copy
    lens32 = np.ascontiguousarray(lens, dtype=np.int32).reshape(1, npad)
    run = _build(L, bool(interpret))
    h1, h2 = run(mat_t, lens32)
    h1 = np.asarray(h1).reshape(npad)[:n].view(np.uint32)
    h2 = np.asarray(h2).reshape(npad)[:n].view(np.uint32)
    return h1.copy(), h2.copy()
