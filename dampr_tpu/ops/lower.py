"""Jitted device programs for lowered map->fold stages.

This is the execution half of the device-lowering pass
(:mod:`dampr_tpu.plan.lower`): a fused per-record stage built from the
native scanner vocabulary (``ops.text.TokenCounts`` / ``DocFreq``) feeding
a keyed associative fold compiles into ONE jitted JAX program per shape
bucket — DrJAX's blueprint (PAPERS.md, arXiv 2403.07128): the MapReduce
primitive is *lowered through JAX*, not interpreted per record.

Division of labor per line-aligned scan window:

- **host (feed)**: byte classification + token bounds (the vectorized
  table lookups from :mod:`.text`), case fold, per-line ids, and the
  padded token byte matrix — the h2d payload, built for the NEXT batch
  while the previous batch's program runs (double buffering);
- **device (one program)**: dual-lane FNV hash of the matrix (byte-exact
  with :mod:`.hashing`), stable sort by ``(validity, h1, h2[, line])``,
  per-line first-occurrence dedup (DocFreq), segment counts via an
  in-program prefix scan (or the Pallas fused segfold kernel when
  ``settings.lower_pallas_segfold`` opts in), segment-representative
  indices, and a collision check;
- **host (drain)**: compact the vocabulary-sized survivors, decode their
  representative strings from the original buffer, and build the Block
  the normal fold/spill machinery consumes.

Exactness contract: grouping is by the engine's 64-bit dual hash lanes,
and the program *verifies* every record's token bytes equal its segment
representative's bytes — any mismatch (a 64-bit collision) falls that
batch back to the exact host grouping, so results are byte-identical to
the host path by construction.  Windows that are not round-trip-clean
UTF-8 (lossy-decode tokens would break the per-line set contract — see
``text.chunk_doc_freq``) and lines longer than a program batch fall back
whole, for the same reason.

Per-batch partial counts merge in the downstream combiner exactly like
the host scanners' per-window partials: the fold is associative, so
batch boundaries are unobservable in the results.
"""

import functools
import time

import numpy as np

from .. import settings
from ..obs import profile as _profile
from ..obs import trace as _trace
from . import devtime
from .text import (_LOWER, _SHORT_TOKEN, _token_bounds, chunk_doc_freq,
                   chunk_token_counts)

# ---------------------------------------------------------------------------
# Stage claims: which mappers have a device lowering
# ---------------------------------------------------------------------------


def claims(mapper):
    """Lowering params for a mapper the device programs can execute, or
    None.  Exact types only — a subclass may have changed semantics the
    program would silently miss."""
    from .text import DocFreq, TokenCounts

    if type(mapper) is TokenCounts:
        if mapper.mode in ("word", "whitespace"):
            return {"mode": mapper.mode, "lower": bool(mapper.lower),
                    "dedup": False, "pair_values": bool(mapper.pair_values)}
        return None
    if type(mapper) is DocFreq:
        if mapper.mode in ("word", "whitespace"):
            return {"mode": mapper.mode, "lower": bool(mapper.lower),
                    "dedup": True, "pair_values": bool(mapper.pair_values)}
        return None
    return None


# ---------------------------------------------------------------------------
# The jitted program
# ---------------------------------------------------------------------------


def _pow2(n):
    return max(8, 1 << max(0, (n - 1).bit_length()))


def _len_bucket(max_len):
    from .hashing import _len_bucket as hb

    return hb(max(1, int(max_len)))


@functools.lru_cache(maxsize=None)
def _token_fold_jit(n, L, dedup, pallas, interpret):
    """One compiled program: hash -> sort -> dedup -> segment count ->
    collision check over a padded [n, L] token byte matrix.  Cached per
    shape bucket so recompilations stay bounded."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from .hashing import _FNV_OFFSET1, _FNV_OFFSET2, _FNV_PRIME1, _FNV_PRIME2

    def program(mat, lens, lines):
        # -- dual-lane FNV over the byte columns (== hashing._fnv_jit) --
        h1 = jnp.full((n,), _FNV_OFFSET1, dtype=jnp.uint32)
        h2 = jnp.full((n,), _FNV_OFFSET2, dtype=jnp.uint32)

        def body(c, hs):
            a, b = hs
            active = c < lens
            byte = mat[:, c].astype(jnp.uint32)
            na = (a ^ byte) * _FNV_PRIME1
            nb = (b ^ byte) * _FNV_PRIME2
            return (jnp.where(active, na, a), jnp.where(active, nb, b))

        h1, h2 = lax.fori_loop(0, L, body, (h1, h2))

        # -- stable sort by (validity, h1, h2[, line]) ------------------
        inv = jnp.where(lens > 0, 0, 1).astype(jnp.int32)  # pad rows last
        iota = jnp.arange(n, dtype=jnp.int32)
        if dedup:
            keys = (inv, h1, h2, lines.astype(jnp.int32), iota)
            num_keys = 4
        else:
            keys = (inv, h1, h2, iota)
            num_keys = 3
        sorted_ = lax.sort(keys, num_keys=num_keys, is_stable=True)
        sinv, sh1, sh2 = sorted_[0], sorted_[1], sorted_[2]
        sline = sorted_[3] if dedup else None
        perm = sorted_[-1]

        def adj_new(*lanes):
            """True where any lane differs from its predecessor (position
            0 inclusive)."""
            out = jnp.ones((n,), dtype=bool)
            neq = jnp.zeros((n - 1,), dtype=bool)
            for lane in lanes:
                neq = neq | (lane[1:] != lane[:-1])
            return out.at[1:].set(neq)

        starts = adj_new(sinv, sh1, sh2)          # token segments
        if dedup:
            # contribution: first occurrence of (token, line) counts 1
            v = jnp.where(adj_new(sinv, sh1, sh2, sline)
                          & (sinv == 0), 1, 0).astype(jnp.int32)
        else:
            v = jnp.where(sinv == 0, 1, 0).astype(jnp.int32)

        pos = jnp.arange(n, dtype=jnp.int32)
        start_pos = lax.cummax(jnp.where(starts, pos, -1), axis=0)

        use_pallas = pallas and n >= 8192 and n % 8192 == 0
        if use_pallas:
            from . import pallas_segfold as SF

            tot, live = SF._segfold_call(n // SF._tile_elems(), interpret)(
                sh1.astype(jnp.int32).reshape(-1, 128),
                sh2.astype(jnp.int32).reshape(-1, 128),
                v.reshape(-1, 128), sinv.reshape(-1, 128))
            tot = tot.reshape(n)
            live = live.reshape(n).astype(bool)
        else:
            csum = jnp.cumsum(v, dtype=jnp.int32)
            ex = csum - v
            # exclusive prefix at the segment start: ex is nondecreasing,
            # so a running max over start-marked values carries it
            start_ex = lax.cummax(jnp.where(starts, ex, -1), axis=0)
            ends = jnp.ones((n,), dtype=bool).at[:-1].set(starts[1:])
            tot = jnp.where(ends, csum - start_ex, 0)
            live = ends & (sinv == 0)

        # -- collision check: every token's bytes == its segment rep's --
        smat = jnp.take(mat, perm, axis=0)
        slens = jnp.take(lens, perm)
        rep_rows = jnp.take(smat, start_pos, axis=0)
        rep_lens = jnp.take(slens, start_pos)
        same = (slens == rep_lens) & jnp.all(smat == rep_rows, axis=1)
        collisions = jnp.sum(jnp.where((sinv == 0) & ~same, 1, 0))

        rep_orig = jnp.take(perm, start_pos)  # original index of each rep
        return sh1, sh2, tot, live, rep_orig, collisions

    return jax.jit(program)


def _lower_interpret():
    """Pallas interpret mode is required off-TPU; resolve once."""
    import jax

    return jax.default_backend() not in ("tpu",)


def _handoff_enter_frac():
    from .handoff import _TABLE_ENTER_NEW_FRAC

    return _TABLE_ENTER_NEW_FRAC


class _Batch(object):
    """One dispatched program invocation plus the host metadata needed to
    drain it: the window-local token starts/lens the reps decode from."""

    __slots__ = ("out", "starts", "lens", "n")

    def __init__(self, out, starts, lens, n):
        self.out = out
        self.starts = starts
        self.lens = lens
        self.n = n


def _batch_bounds(lines, n_tokens, limit):
    """Batch cut points (token indices) honoring line boundaries so the
    per-line dedup never straddles a batch.  Returns None when a single
    line exceeds the limit (caller falls back to the host path)."""
    if n_tokens <= limit:
        return [(0, n_tokens)]
    cuts = [0]
    at = 0
    while at < n_tokens:
        end = min(at + limit, n_tokens)
        if end < n_tokens and lines is not None:
            # retreat to the last token of the previous line
            line_at_end = lines[end]
            while end > at and lines[end - 1] == line_at_end:
                end -= 1
            if end == at:
                return None  # one line wider than a whole batch
        cuts.append(end)
        at = end
    return list(zip(cuts[:-1], cuts[1:]))


class DeviceTokenFoldSink(object):
    """Window-sink adapter running the lowered tokenize+hash+fold program
    (drop-in for the scanners' ``window_sink()``).  ``add(win)`` feeds the
    window through double-buffered program dispatches and yields resolved
    partial-count Blocks; per-batch collision fallbacks and whole-window
    host fallbacks keep results byte-identical to the host scanner.

    ``handoff=True`` (the plan's ``handoff="device"`` edge,
    :mod:`.handoff`): emitted partials stay DEVICE-RESIDENT in a per-job
    vocabulary accumulator instead of draining to host blocks — classic
    batches bootstrap the vocabulary, later batches run the cheap
    table-probe program, and ``finalize_handoff`` registers the
    accumulated counts as HBM-resident BlockRefs the consuming fold
    reads in place.  Any degrade flushes the accumulator into one
    hash-sorted block and reverts to the classic emit path,
    byte-identically."""

    def __init__(self, params, store=None, handoff=False, jobs=1):
        self.mode = params["mode"]
        self.lower = params["lower"]
        self.dedup = params["dedup"]
        self.pair_values = params["pair_values"]
        self.store = store
        self.batches = 0
        self.fallbacks = 0
        self._hv = None
        if handoff and store is not None and not self.pair_values:
            from . import handoff as _handoff

            # Each concurrent job gets an equal share of the run's
            # handoff budget: N parallel vocabularies can never hold
            # N x budget of device memory between them.
            share = (settings.effective_handoff_budget()
                     // max(1, int(jobs)))
            self._hv = _handoff.HandoffVocab(store, self.dedup,
                                             budget=share)

    # -- host fallbacks ----------------------------------------------------
    def _host_window(self, win):
        """Exact host path for one whole window (non-UTF-8 windows, lines
        wider than a batch)."""
        self.fallbacks += 1
        if self.dedup:
            blk = chunk_doc_freq(win, self.mode, self.lower,
                                 self.pair_values)
        else:
            blk = chunk_token_counts(win, self.mode, self.lower,
                                     self.pair_values)
        return (blk,) if blk is not None and len(blk) else ()

    def _host_batch(self, buf, starts, lens, lines):
        """Exact host grouping for one collided batch:
        ``handoff.group_token_rows`` (np.unique over length-prefixed
        token byte rows — colliding hashes can never merge distinct
        tokens; the ONE copy shared with the handoff miss path)."""
        from . import hashing
        from .handoff import group_token_rows

        self.fallbacks += 1
        uniq, counts = group_token_rows(buf, starts, lens, lines,
                                        self.dedup)
        keys = np.empty(len(uniq), dtype=object)
        for i in range(len(uniq)):
            ln = int(uniq[i, 0])
            keys[i] = uniq[i, 1:1 + ln].tobytes().decode("utf-8", "replace")
        h1, h2 = hashing.hash_keys(keys)
        return self._emit(keys, counts.astype(np.int64), h1, h2)

    def _emit(self, keys, counts, h1, h2):
        from ..blocks import Block

        n = len(keys)
        if self.pair_values:
            vals = np.empty(n, dtype=object)
            for i in range(n):
                vals[i] = (keys[i], int(counts[i]))
        else:
            vals = np.asarray(counts, dtype=np.int64)
        return Block(keys, vals, h1, h2)

    # -- long tokens (host dict, window-scoped like the numpy path) --------
    def _long_tokens(self, buf, starts, lens, line_id, long_idx):
        from . import hashing

        bb_get = buf.tobytes if len(long_idx) > 1024 else None
        bb = bb_get() if bb_get else None
        agg = {}
        seen = set()
        for i in long_idx:
            s = int(starts[i])
            ln = int(lens[i])
            raw = (bb[s:s + ln] if bb is not None
                   else buf[s:s + ln].tobytes())
            tok = raw.decode("utf-8", "replace")
            if self.dedup:
                key = (int(line_id[i]), tok)
                if key in seen:
                    continue
                seen.add(key)
            agg[tok] = agg.get(tok, 0) + 1
        keys = np.empty(len(agg), dtype=object)
        counts = np.empty(len(agg), dtype=np.int64)
        for i, (k, c) in enumerate(agg.items()):
            keys[i] = k
            counts[i] = c
        h1, h2 = hashing.hash_keys(keys)
        return self._emit(keys, counts, h1, h2)

    # -- the pipeline ------------------------------------------------------
    @property
    def _handoff_live(self):
        return self._hv is not None and not self._hv.degraded

    def _absorb_or_out(self, blocks, out):
        """Route host-path blocks: into the handoff accumulator while it
        is live (a refused absorb degrades — the flushed accumulator and
        the unabsorbed block both land in ``out``), else straight into
        the emitted stream."""
        for blk in blocks:
            if blk is None or not len(blk):
                continue
            if self._handoff_live:
                if self._hv.absorb_block(blk):
                    continue
                fb = self._hv.degrade("vocabulary or lane budget "
                                      "exceeded")
                if fb is not None and len(fb):
                    out.append(fb)
            out.append(blk)

    def _degrade_to(self, out, reason):
        fb = self._hv.degrade(reason)
        if fb is not None and len(fb):
            out.append(fb)

    def _pad_batch(self, buf, starts, lens, lines):
        """Shared padded-matrix construction for both program shapes."""
        n = len(starts)
        prof = _profile.active()
        t0p = time.perf_counter() if prof is not None else 0.0
        with devtime.track("codec"):
            L = _len_bucket(lens.max())
            npad = max(_pow2(n),
                       8192 if settings.lower_pallas_segfold else 8)
            idx = starts[:, None] + np.arange(L, dtype=np.int64)[None, :]
            np.clip(idx, 0, len(buf) - 1, out=idx)
            mat = np.zeros((npad, L), dtype=np.uint8)
            mat[:n] = np.where(np.arange(L, dtype=np.int32)[None, :]
                               < lens[:, None], buf[idx], 0)
            lens_p = np.zeros(npad, dtype=np.int32)
            lens_p[:n] = lens
            lines_p = np.zeros(npad, dtype=np.int32)
            if lines is not None:
                lines_p[:n] = lines
        if prof is not None:
            prof.device_add("build", time.perf_counter() - t0p,
                            mat.nbytes)
        return mat, lens_p, lines_p

    def _dispatch(self, buf, starts, lens, lines):
        """Pad one batch to its shape bucket and launch the classic
        program; h2d payload bytes are charged to the store's HBM
        counters.  Under ``settings.profile`` the loop's sub-phases
        decompose: ``build`` (padded-matrix construction, host) and
        ``h2d`` (program dispatch + argument feed) here, ``compute``/
        ``d2h`` at drain."""
        n = len(starts)
        from .. import faults as _faults

        # Fault site: a classified failure here surfaces through the map
        # job and rides the job retry loop (the whole-chunk fallback
        # paths keep results byte-identical on re-execution).
        _faults.check("device_dispatch")
        prof = _profile.active()
        mat, lens_p, lines_p = self._pad_batch(buf, starts, lens, lines)
        npad, L = mat.shape
        fn = _token_fold_jit(npad, L, self.dedup,
                             settings.lower_pallas_segfold,
                             _lower_interpret())
        nbytes = mat.nbytes + lens_p.nbytes + lines_p.nbytes
        if self.store is not None:
            self.store.count_h2d(nbytes)
        t0p = time.perf_counter() if prof is not None else 0.0
        with devtime.track("device"), _trace.span(
                "device", "map-fold", tokens=n, bytes=nbytes):
            out = fn(mat, lens_p, lines_p)
        if prof is not None:
            # Dispatch is async: this phase is the launch + feed cost;
            # the program's run time surfaces as ``compute`` at drain.
            prof.device_add("h2d", time.perf_counter() - t0p, nbytes)
        self.batches += 1
        return _Batch(out, starts, lens, n)

    def _next_batch(self, buf, starts, lens, lines, out):
        """Dispatch one batch through whichever program the vocabulary
        state calls for (table probe once the vocabulary converged,
        classic otherwise).  A refused table dispatch (overflow/budget
        guard) degrades the job and falls back to classic."""
        if self._handoff_live and self._hv.table_mode:
            from .. import faults as _faults

            _faults.check("device_dispatch")
            mat, lens_p, lines_p = self._pad_batch(buf, starts, lens,
                                                   lines)
            prof = _profile.active()
            t0p = time.perf_counter() if prof is not None else 0.0
            batch = self._hv.dispatch(mat, lens_p, lines_p, starts, lens,
                                      lines, len(starts))
            if prof is not None:
                prof.device_add("h2d", time.perf_counter() - t0p,
                                mat.nbytes)
            if batch is not None:
                self.batches += 1
                return batch
            self._degrade_to(out, "count-lane overflow guard or hbm "
                                  "budget exceeded mid-stage")
        return self._dispatch(buf, starts, lens, lines)

    def _resolve(self, buf, batch, out):
        """Drain one in-flight dispatch of either shape into ``out`` (or
        into the device accumulator when the handoff is live)."""
        from .handoff import _TABLE_REVERT_MISS_FRAC, _TableBatch

        if isinstance(batch, _TableBatch):
            if not self._handoff_live:
                # The vocabulary degraded while this dispatch was in
                # flight: its HIT counts left with the accumulator
                # flush (they scattered at dispatch time), but its
                # misses never landed anywhere — emit them through the
                # exact host grouping or they are lost.
                self._emit_table_misses(buf, batch, out, count_d2h=True)
                return
            ok, miss_frac = self._hv.drain(buf, batch)
            if not ok:
                # The absorb refused (vocabulary/lane budget): no miss
                # count landed, so the degrade flush holds only this
                # batch's hits — the misses emit exactly on host.
                self._degrade_to(out, "vocabulary or lane budget "
                                      "exceeded")
                self._emit_table_misses(buf, batch, out,
                                        count_d2h=False)
            elif miss_frac > _TABLE_REVERT_MISS_FRAC:
                # Vocabulary shift: bootstrap again through the classic
                # program until the table converges once more.
                self._hv.table_mode = False
            return
        blk = self._drain(buf, batch, out)
        if blk is not None and len(blk):
            out.append(blk)

    def _emit_table_misses(self, buf, batch, out, count_d2h):
        """Missed tokens of a table dispatch that can no longer enter
        the (degraded) accumulator: group them exactly on host —
        ``_host_batch``, the same grouping the classic collision
        fallback uses — and emit the block.  ``count_d2h`` charges the
        miss-evidence fetch when :meth:`HandoffVocab.drain` has not
        already done so."""
        n_miss = int(batch.n_miss)
        if count_d2h and self.store is not None:
            self.store.count_d2h((batch.npad if n_miss else 0) + 4)
        if not n_miss:
            return
        if batch.miss_idx is None:
            miss = np.asarray(batch.miss)[:batch.n]
            batch.miss_idx = np.flatnonzero(miss)
        idx = batch.miss_idx
        blk = self._host_batch(
            buf, batch.starts[idx], batch.lens[idx],
            batch.lines[idx] if batch.lines is not None else None)
        if blk is not None and len(blk):
            out.append(blk)

    def _drain(self, buf, batch, out=None):
        """Fetch one classic program's results and build the
        partial-count Block (vocabulary-sized).  Collisions re-group the
        batch on host.  With the handoff live, survivors seed the device
        vocabulary instead of emitting (returns None)."""
        prof = _profile.active()
        with devtime.track("device"), _trace.span("device", "drain",
                                                  tokens=batch.n):
            if prof is not None:
                # Split blocked-on-program time from the result fetch:
                # block_until_ready waits for the compute, the asarray
                # conversions below are then pure d2h movement.
                import jax

                t0p = time.perf_counter()
                jax.block_until_ready(batch.out)
                t1p = time.perf_counter()
                prof.device_add("compute", t1p - t0p)
            sh1, sh2, tot, live, rep_orig, collisions = (
                np.asarray(a) for a in batch.out)
        d2h_bytes = (sh1.nbytes + sh2.nbytes + tot.nbytes
                     + live.nbytes + rep_orig.nbytes)
        if prof is not None:
            prof.device_add("d2h", time.perf_counter() - t1p, d2h_bytes)
        if self.store is not None:
            self.store.count_d2h(d2h_bytes)
        if int(collisions):
            lines = None
            if self.dedup:
                # line ids were consumed by the program; rebuild them for
                # the host regroup from the batch's token starts
                lines = self._line_ids(buf, batch.starts)
            blk = self._host_batch(buf, batch.starts, batch.lens, lines)
            if self._handoff_live and out is not None:
                self._absorb_or_out((blk,), out)
                return None
            return blk
        idx = np.flatnonzero(live)
        if not len(idx):
            return None
        counts = tot[idx].astype(np.int64)
        h1g = sh1[idx]
        h2g = sh2[idx]
        reps = rep_orig[idx]
        keys = np.empty(len(idx), dtype=object)
        starts, lens = batch.starts, batch.lens
        for i, r in enumerate(reps):
            s = int(starts[r])
            keys[i] = buf[s:s + int(lens[r])].tobytes().decode(
                "utf-8", "replace")
        if self._handoff_live:
            ok, new_frac = self._hv.absorb_drain(keys, counts, h1g, h2g,
                                                 batch.n)
            if not ok:
                if out is not None:
                    self._degrade_to(out, "vocabulary or lane budget "
                                          "exceeded")
                    out.append(self._emit(keys, counts, h1g, h2g))
                    return None
                blk = self._emit(keys, counts, h1g, h2g)
                return blk
            if new_frac < _handoff_enter_frac():
                self._hv.table_mode = True
            return None
        return self._emit(keys, counts, h1g, h2g)

    def _line_ids(self, buf, starts):
        nl = np.flatnonzero(buf == 10)
        line_starts = np.concatenate(([0], nl + 1)).astype(np.int64)
        return (np.searchsorted(line_starts, starts, side="right")
                - 1).astype(np.int32)

    def add(self, win):
        data = bytes(win) if isinstance(win, memoryview) else win
        buf = np.frombuffer(data, dtype=np.uint8)
        if not len(buf):
            return ()
        out = []
        if (buf > 127).any():
            # Only valid-UTF-8 windows lower: token substrings of valid
            # UTF-8 decode losslessly (boundaries are ASCII), so no
            # U+FFFD substitution can desync keys from their raw-byte
            # hash lanes or the per-line byte-dedup contract.  A strict
            # decode attempt is the one-pass equivalent of the
            # replace-decode round-trip test.
            try:
                data.decode("utf-8")
            except UnicodeDecodeError:
                self._absorb_or_out(self._host_window(win), out)
                return out
        if self._handoff_live and not self._hv.table_mode \
                and not self._hv.nslots:
            from .handoff import _host_bootstrap

            if _host_bootstrap():
                # CPU-backend bootstrap: the job's first window seeds the
                # vocabulary through the NATIVE host codec — its blocks
                # carry cached hash lanes, so the absorb never re-hashes
                # or re-sorts, and this window's tokenize/pad/dispatch is
                # skipped outright (~20x the classic bootstrap program,
                # which has no accelerator to hide on here).  Counts are
                # byte-identical: absorb_block keys by canonical utf-8
                # bytes, the same contract as a classic drain.  Table
                # mode engages immediately — a vocabulary that fails to
                # cover the next window's batches reverts through the
                # standard miss-fraction bar.
                from .. import faults as _faults
                from .handoff import CLASSIC_DRAIN_BYTES_PER_SLOT

                # The bootstrap replaces this window's program dispatches
                # — it keeps their fault site, so chaos schedules aimed
                # at the lowered map fire on every backend.
                _faults.check("device_dispatch")
                with _trace.span("handoff", "bootstrap-host",
                                 bytes=len(data)):
                    # The native grouping is codec work — bucketed and
                    # traced as such, so codec_fraction/critpath keep
                    # attributing the scan's host compute when the
                    # handoff swallows every emitted block.
                    with devtime.track("codec"), _trace.span(
                            "codec", "codec-window", bytes=len(data)):
                        if self.dedup:
                            blk = chunk_doc_freq(data, self.mode,
                                                 self.lower,
                                                 self.pair_values)
                        else:
                            blk = chunk_token_counts(data, self.mode,
                                                     self.lower,
                                                     self.pair_values)
                    self._absorb_or_out(
                        (blk,) if blk is not None else (), out)
                if self._handoff_live and self._hv.nslots:
                    self._hv.table_mode = True
                    if self.store is not None and blk is not None:
                        # Drain bytes the classic path would have
                        # fetched for this window, one-batch lower
                        # bound (its real fetch scales with padded
                        # TOKENS, not distinct keys).
                        self.store.count_d2h_avoided(
                            CLASSIC_DRAIN_BYTES_PER_SLOT * len(blk))
                return out
        with devtime.track("codec"):
            if self.lower:
                buf = _LOWER[buf]
            starts, lens = _token_bounds(buf, self.mode)
        n = len(starts)
        if n == 0:
            return ()
        line_id = self._line_ids(buf, starts) if self.dedup else None

        short = lens <= _SHORT_TOKEN
        long_idx = np.flatnonzero(~short)
        s_starts, s_lens, s_lines = starts, lens, line_id
        if len(long_idx):
            sidx = np.flatnonzero(short)
            s_starts, s_lens = starts[sidx], lens[sidx]
            s_lines = line_id[sidx] if line_id is not None else None
        ns = len(s_starts)

        bounds = (_batch_bounds(s_lines, ns,
                                max(1024, settings.lower_batch))
                  if ns else [])
        if bounds is None:
            # The whole-window host path recounts EVERY token, long ones
            # included — nothing else may land for this window (long
            # tokens commit only after this check passes, so they can
            # never count twice).
            self._absorb_or_out(self._host_window(win), out)
            return out

        if len(long_idx):
            blk = self._long_tokens(buf, starts, lens, line_id, long_idx)
            self._absorb_or_out((blk,), out)
        if ns == 0:
            return out

        # Double-buffered feed: build + dispatch batch i+1 while batch i's
        # program runs; drain resolves the previous dispatch only after
        # the next one is in flight (jax dispatch is async).
        pending = None
        for a, b in bounds:
            if (pending is not None and self._handoff_live
                    and not self._hv.table_mode and not self._hv.nslots):
                # Bootstrap sync: resolve the job's FIRST classic batch
                # before the next dispatch — its drain seeds the
                # vocabulary, so every remaining batch can run the cheap
                # table program.  One batch of lost overlap buys
                # table-mode for the rest of the job (jobs are only a
                # handful of batches long).
                self._resolve(buf, pending, out)
                pending = None
            nxt = self._next_batch(
                buf, s_starts[a:b], s_lens[a:b],
                s_lines[a:b] if s_lines is not None else None, out)
            if pending is not None:
                self._resolve(buf, pending, out)
            pending = nxt
        if pending is not None:
            self._resolve(buf, pending, out)
        return out

    def finish(self):
        return ()

    def finalize_handoff(self, store, n_partitions):
        """Register the job's accumulated vocabulary as per-partition
        HBM-resident refs (the plan's ``handoff="device"`` edge).
        Returns ``(blocks, {pid: [BlockRef]})`` — ``blocks`` is the
        degrade flush the caller must push through the classic combine
        path; at most one side is non-empty."""
        if self._hv is None:
            return (), {}
        return self._hv.finalize(store, n_partitions)


def device_window_sink(mapper, store=None, handoff=False, jobs=1):
    """The device window sink for a claimed mapper, or None.
    ``handoff=True`` arms the cross-stage device-resident tier (a
    pair-values scanner — an object lane with no device tier — silently
    stays on the classic emit path); ``jobs`` is the stage's concurrent
    job count, dividing the handoff budget per vocabulary."""
    params = claims(mapper)
    if params is None:
        return None
    return DeviceTokenFoldSink(params, store=store, handoff=handoff,
                               jobs=jobs)

