"""Vectorized 64-bit record hashing (dual uint32 lanes).

Replaces the reference's per-record ``hash(key) % n_partitions`` partitioner
(reference dampr/base.py:6-8 ``Splitter``) with a batched kernel: string keys become a
padded uint8 matrix hashed by a dual-lane FNV-1a scan on device; integer keys go
through a murmur-style finalizer.  Two independent 32-bit lanes (h1, h2) stand in for
a 64-bit hash without requiring global ``jax_enable_x64``:

- partition routing uses ``h1 % P`` (cheap, single lane);
- grouping sorts lexicographically on ``(h1, h2)`` via ``lax.sort(num_keys=2)``;
- host bookkeeping combines lanes into one uint64 (``combine64``).

Collisions on the full 64 bits are detected by the HashRegistry in blocks.py (exact
grouping falls back to comparing real keys), so hashing here only needs to be
uniform, not perfect.

Python-equality nuance: ``1 == 1.0 == True`` group together under the reference's
sort+groupby semantics, so integral floats and bools are canonicalized to int64
before hashing.
"""

import functools

import numpy as np

from .. import settings

_FNV_OFFSET1 = np.uint32(2166136261)
_FNV_OFFSET2 = np.uint32(0x9747B28C)
_FNV_PRIME1 = np.uint32(16777619)
_FNV_PRIME2 = np.uint32(0x85EBCA6B)

# Length padding buckets bound jit recompilations for variable-width string blocks.
_LEN_BUCKETS = (8, 16, 32, 64, 128, 256, 512, 1024)


def _len_bucket(max_len):
    for b in _LEN_BUCKETS:
        if max_len <= b:
            return b
    # Very long keys: round up to a multiple of 1024.
    return ((max_len + 1023) // 1024) * 1024


def _pow2_rows(n):
    p = 1 << max(0, (n - 1).bit_length())
    return max(p, 8)


def encode_str_keys(keys):
    """Encode a sequence of str/bytes keys as (padded uint8 [N, L], lengths int32 [N]).

    UTF-8 encodes str; bytes pass through.  L is bucketed to bound compilations.
    """
    bs = [k.encode("utf-8") if isinstance(k, str) else bytes(k) for k in keys]
    n = len(bs)
    max_len = max((len(b) for b in bs), default=1)
    L = _len_bucket(max(max_len, 1))
    mat = np.zeros((n, L), dtype=np.uint8)
    lens = np.empty(n, dtype=np.int32)
    for i, b in enumerate(bs):
        lens[i] = len(b)
        if b:
            mat[i, : len(b)] = np.frombuffer(b, dtype=np.uint8)
    return mat, lens


# ---------------------------------------------------------------------------
# numpy host path
# ---------------------------------------------------------------------------

def _fnv_numpy(mat, lens):
    n, L = mat.shape
    h1 = np.full(n, _FNV_OFFSET1, dtype=np.uint32)
    h2 = np.full(n, _FNV_OFFSET2, dtype=np.uint32)
    cols = np.arange(L, dtype=np.int32)
    with np.errstate(over="ignore"):
        for c in range(L):
            active = cols[c] < lens
            b = mat[:, c].astype(np.uint32)
            nh1 = (h1 ^ b) * _FNV_PRIME1
            nh2 = (h2 ^ b) * _FNV_PRIME2
            h1 = np.where(active, nh1, h1)
            h2 = np.where(active, nh2, h2)
    return h1, h2


def _mix_int_numpy(vals_i64):
    v = vals_i64.astype(np.uint64)
    lo = (v & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    hi = (v >> np.uint64(32)).astype(np.uint32)
    with np.errstate(over="ignore"):
        h1 = _murmur_fmix_np(lo ^ np.uint32(0x9E3779B9), hi)
        h2 = _murmur_fmix_np(lo ^ np.uint32(0x85EBCA6B), hi ^ np.uint32(0xC2B2AE35))
    return h1, h2


def _murmur_fmix_np(x, y):
    h = x
    h ^= y
    h ^= h >> np.uint32(16)
    h = (h * np.uint32(0x85EBCA6B)).astype(np.uint32)
    h ^= h >> np.uint32(13)
    h = (h * np.uint32(0xC2B2AE35)).astype(np.uint32)
    h ^= h >> np.uint32(16)
    return h


# ---------------------------------------------------------------------------
# JAX device path
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _fnv_jit():
    import jax
    import jax.numpy as jnp
    from jax import lax

    def kernel(mat, lens):
        n, L = mat.shape
        h1 = jnp.full((n,), _FNV_OFFSET1, dtype=jnp.uint32)
        h2 = jnp.full((n,), _FNV_OFFSET2, dtype=jnp.uint32)

        def body(c, hs):
            h1, h2 = hs
            active = c < lens
            b = mat[:, c].astype(jnp.uint32)
            nh1 = (h1 ^ b) * _FNV_PRIME1
            nh2 = (h2 ^ b) * _FNV_PRIME2
            return (jnp.where(active, nh1, h1), jnp.where(active, nh2, h2))

        h1, h2 = lax.fori_loop(0, L, body, (h1, h2))
        return h1, h2

    return jax.jit(kernel)


@functools.lru_cache(maxsize=None)
def _mix_int_jit():
    import jax
    import jax.numpy as jnp

    def fmix(x, y):
        h = x ^ y
        h = h ^ (h >> 16)
        h = h * jnp.uint32(0x85EBCA6B)
        h = h ^ (h >> 13)
        h = h * jnp.uint32(0xC2B2AE35)
        h = h ^ (h >> 16)
        return h

    def kernel(lo, hi):
        h1 = fmix(lo ^ jnp.uint32(0x9E3779B9), hi)
        h2 = fmix(lo ^ jnp.uint32(0x85EBCA6B), hi ^ jnp.uint32(0xC2B2AE35))
        return h1, h2

    return jax.jit(kernel)


def _use_device(n):
    return settings.use_device and n >= settings.device_min_batch


def _fnv(mat, lens):
    n = mat.shape[0]
    if not _use_device(n):
        return _fnv_numpy(mat, lens)
    np_rows = _pow2_rows(n)
    if np_rows != n:
        mat = np.pad(mat, ((0, np_rows - n), (0, 0)))
        lens = np.pad(lens, (0, np_rows - n))
    h1, h2 = _fnv_jit()(mat, lens)
    return np.asarray(h1)[:n], np.asarray(h2)[:n]


def _mix_int(vals_i64):
    n = vals_i64.shape[0]
    if not _use_device(n):
        return _mix_int_numpy(vals_i64)
    np_rows = _pow2_rows(n)
    v = vals_i64
    if np_rows != n:
        v = np.pad(v, (0, np_rows - n))
    u = v.astype(np.uint64)
    lo = (u & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    hi = (u >> np.uint64(32)).astype(np.uint32)
    h1, h2 = _mix_int_jit()(lo, hi)
    return np.asarray(h1)[:n], np.asarray(h2)[:n]


# ---------------------------------------------------------------------------
# Public entry
# ---------------------------------------------------------------------------

def _canonical_int(k):
    """Map bools / integral floats to int to mirror Python equality grouping."""
    if isinstance(k, bool):
        return int(k)
    if isinstance(k, float) and k.is_integer():
        return int(k)
    return k


def _host_hash_item(k):
    """Deterministic per-item fallback hash for keys outside the fast paths
    (tuples, frozensets, ...).  Uses Python's salted hash — stable within one
    process, which is all partition routing + in-run grouping need."""
    h = hash(k) & 0xFFFFFFFFFFFFFFFF
    return np.uint32(h & 0xFFFFFFFF), np.uint32((h >> 32) ^ (h & 0xFFFFFFFF) ^ 0x51ED2701)


def hash_keys(keys):
    """Hash a batch of keys -> (h1, h2) uint32 arrays.

    `keys` is a numpy array (numeric dtype or object) or a list.  Chooses the
    vectorized int path, the byte-matrix FNV path, or the per-item host fallback.
    """
    if isinstance(keys, np.ndarray) and keys.dtype != object:
        if np.issubdtype(keys.dtype, np.integer) or keys.dtype == np.bool_:
            return _mix_int(keys.astype(np.int64))
        if np.issubdtype(keys.dtype, np.floating):
            return _hash_float_array(keys)
        # other numeric dtypes: go through object path
        keys = keys.astype(object)

    keys = list(keys) if not isinstance(keys, np.ndarray) else keys
    n = len(keys)
    if n == 0:
        return (np.empty(0, dtype=np.uint32), np.empty(0, dtype=np.uint32))

    kinds = set()
    for k in keys:
        if isinstance(k, bool):
            kinds.add(int)
        elif isinstance(k, int):
            kinds.add(int)
        elif isinstance(k, float):
            kinds.add(int if k.is_integer() else float)
        elif isinstance(k, str):
            kinds.add(str)
        elif isinstance(k, bytes):
            kinds.add(bytes)
        else:
            kinds.add(object)
        if len(kinds) > 1:
            break

    if kinds == {int}:
        arr = np.fromiter((int(_canonical_int(k)) for k in keys), dtype=np.int64,
                          count=n)
        return _mix_int(arr)
    if kinds == {str} or kinds == {bytes}:
        mat, lens = encode_str_keys(keys)
        return _fnv(mat, lens)
    if kinds == {float}:
        arr = np.fromiter((float(k) for k in keys), dtype=np.float64, count=n)
        return _hash_float_array(arr)

    h1 = np.empty(n, dtype=np.uint32)
    h2 = np.empty(n, dtype=np.uint32)
    for i, k in enumerate(keys):
        a, b = _host_hash_item(_freeze(k))
        h1[i] = a
        h2[i] = b
    return h1, h2


def _hash_float_array(arr):
    """Float keys: integral values canonicalize to ints (Python equality);
    the rest hash on their float64 bit pattern."""
    arr64 = arr.astype(np.float64)
    integral = (arr64 == np.floor(arr64)) & np.isfinite(arr64) & (np.abs(arr64) < 2 ** 62)
    as_int = np.where(integral, arr64, 0).astype(np.int64)
    bits = arr64.view(np.int64)
    mixed_src = np.where(integral, as_int, bits)
    return _mix_int(mixed_src)


def _freeze(k):
    if isinstance(k, list):
        return tuple(_freeze(x) for x in k)
    if isinstance(k, dict):
        return tuple(sorted((kk, _freeze(vv)) for kk, vv in k.items()))
    if isinstance(k, set):
        return frozenset(k)
    return k


def combine64(h1, h2):
    """Combine the two uint32 lanes into one uint64 per record (host only)."""
    return (h1.astype(np.uint64) << np.uint64(32)) | h2.astype(np.uint64)
